"""Smoke the scalar examples as subprocesses (executable docs must run).

Mirrors the reference's examples-as-documentation role (reference:
examples/*.py); only the fast scalar examples run here — the device-loop
examples (settlement_cycle, compact_settlement, distributed_settlement,
settlement_service, streaming_settlement, batched_consensus,
fault_tolerant_service, columnar_ingest, coresident_tiebreak,
uncertainty_bands, degraded_mesh_recovery, onepass_settlement,
multitenant_serving, combinatorial_markets — the round-18
combinatorial-markets example's moment-pair sweep bit matrix,
adaptive-early-exit determinism, banded byte parity, block projection
invariants, and analytics-off byte coda live in tests/test_infer.py,
with the adaptive-vs-fixed sweep-count capture smoked through
tests/test_bench_harness.py::TestInferLeg; the round-17 multi-tenant
front-door example's
wire byte parity, robustness matrix, per-class QoS isolation, and
variance-aware shed determinism live in tests/test_net.py, with the
e2e leg smoked through tests/test_bench_harness.py::TestNetServeLeg; the
ingest example's packer parity lives in tests/test_fastpack.py and
tests/test_serve.py; the co-resident tie-break's chunk parity and fused
session in tests/test_ring.py; the uncertainty-band/graph-sweep
example's bit matrix, fused-program parity, and analytics on/off
byte-exactness coda in tests/test_analytics.py; the degraded-mesh
recovery example's membership/replay/adopt contracts and byte coda in
tests/test_cluster.py, with the real-kill multi-process version smoked
through tests/test_bench_harness.py::TestKillSoakLeg; the one-pass
settlement example's kernel/XLA bit matrix, session byte parity, and
sorted-tiebreak pins in tests/test_pallas_settle.py) each pay tens of
seconds of XLA
compilation and
are exercised through the library tests instead (streaming_settlement's
path: tests/test_overlap.py::TestSettleStream and the driver dryrun's
_dryrun_settle_stream leg; fault_tolerant_service's restart recipe:
TestSettleStreamSharded's failure cases pin the settled-count contract
it relies on).
"""

import pathlib
import subprocess
import sys

import pytest

_ROOT = pathlib.Path(__file__).resolve().parents[1]


@pytest.mark.parametrize(
    "name",
    ["basic_consensus.py", "reliability_tracking.py", "tie_breaking.py"],
)
def test_scalar_example_runs(name):
    proc = subprocess.run(
        [sys.executable, str(_ROOT / "examples" / name)],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=_ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip(), "example produced no output"
