"""Fused Pallas cycle ≡ XLA cycle, element-wise (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from bayesian_consensus_engine_tpu.ops.pallas_cycle import (
    build_pallas_cycle,
    to_slot_major,
)
from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle,
)

M, K = 1024, 16
TILE = 256


def _inputs(seed=0):
    rng = np.random.default_rng(seed)
    probs = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)
    mask = jnp.asarray(rng.random((M, K)) < 0.8)
    outcome = jnp.asarray(rng.random(M) < 0.5)
    state = MarketBlockState(
        reliability=jnp.asarray(rng.uniform(0.0, 1.0, (M, K)), dtype=jnp.float32),
        confidence=jnp.asarray(rng.uniform(0.0, 1.0, (M, K)), dtype=jnp.float32),
        updated_days=jnp.asarray(
            rng.choice([0.0, 3.0, 35.0, 500.0], (M, K)), dtype=jnp.float32
        ),
        exists=jnp.asarray(rng.random((M, K)) < 0.5),
    )
    return probs, mask, outcome, state, jnp.float32(501.0)


class TestFusedKernelEquivalence:
    def test_matches_xla_cycle(self):
        probs, mask, outcome, state, now = _inputs()
        xla = build_cycle(mesh=None, donate=False)(probs, mask, outcome, state, now)

        sm_probs, sm_mask, sm_outcome, sm_state = to_slot_major(
            probs, mask, outcome, state
        )
        pallas_cycle = build_pallas_cycle(M, K, tile_markets=TILE, interpret=True)
        new_state, consensus, confidence, tw = pallas_cycle(
            sm_probs, sm_mask, sm_outcome, sm_state, now
        )

        np.testing.assert_allclose(
            np.asarray(consensus)[0], np.asarray(xla.consensus),
            rtol=1e-6, equal_nan=True,
        )
        np.testing.assert_allclose(
            np.asarray(confidence)[0], np.asarray(xla.confidence), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(tw)[0], np.asarray(xla.total_weight), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(new_state.reliability).T,
            np.asarray(xla.state.reliability),
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(new_state.confidence).T,
            np.asarray(xla.state.confidence),
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(new_state.updated_days).T,
            np.asarray(xla.state.updated_days),
            rtol=1e-6,
        )
        np.testing.assert_array_equal(
            np.asarray(new_state.exists).T > 0, np.asarray(xla.state.exists)
        )

    def test_composes_over_steps(self):
        probs, mask, outcome, state, _now = _inputs(7)
        pallas_cycle = build_pallas_cycle(M, K, tile_markets=TILE, interpret=True)
        xla_cycle = build_cycle(mesh=None, donate=False)

        sm = to_slot_major(probs, mask, outcome, state)
        p_state = sm[3]
        x_state = state
        for step in range(3):
            t = jnp.float32(502.0 + step)
            p_state, p_cons, _, _ = pallas_cycle(sm[0], sm[1], sm[2], p_state, t)
            x_result = xla_cycle(probs, mask, outcome, x_state, t)
            x_state = x_result.state
        np.testing.assert_allclose(
            np.asarray(p_state.reliability).T,
            np.asarray(x_state.reliability),
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(p_cons)[0], np.asarray(x_result.consensus),
            rtol=1e-6, equal_nan=True,
        )

    def test_rejects_unaligned_markets(self):
        with pytest.raises(ValueError, match="not a multiple"):
            build_pallas_cycle(1000, K, tile_markets=256)

    def test_in_place_aliasing_shapes(self):
        # Output state buffers share shapes/dtypes with inputs (alias contract).
        probs, mask, outcome, state, now = _inputs(3)
        sm_probs, sm_mask, sm_outcome, sm_state = to_slot_major(
            probs, mask, outcome, state
        )
        pallas_cycle = build_pallas_cycle(M, K, tile_markets=TILE, interpret=True)
        new_state, *_ = pallas_cycle(sm_probs, sm_mask, sm_outcome, sm_state, now)
        for new, old in zip(new_state, sm_state):
            assert new.shape == old.shape and new.dtype == old.dtype
