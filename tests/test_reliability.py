"""SQLite reliability store — semantics and durability.

Mirrors the reference store coverage (reference: tests/test_reliability.py):
cold-start non-persistence, capped/clamped updates, confidence growth,
per-market isolation, sorted listing, reconnect durability, frozen records —
plus decay-on-read and the dry-run zero-write contract.
"""

import dataclasses
from datetime import datetime, timedelta, timezone
from pathlib import Path

import pytest

from bayesian_consensus_engine_tpu.utils.config import (
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
    MAX_UPDATE_STEP,
)
from bayesian_consensus_engine_tpu.state import (
    ReliabilityRecord,
    ReliabilityStore,
    SQLiteReliabilityStore,
)


# The semantic battery runs against BOTH backends: the durable SQLite store
# and the HBM tensor store must be observably interchangeable (the
# ReliabilityStore seam the TPU path is gated behind).
@pytest.fixture(params=["sqlite", "tensor"])
def store(request):
    if request.param == "sqlite":
        with SQLiteReliabilityStore(":memory:") as s:
            yield s
    else:
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        with TensorReliabilityStore() as s:
            yield s


@pytest.fixture
def file_store(tmp_path: Path):
    with SQLiteReliabilityStore(tmp_path / "rel.db") as s:
        yield s


class TestColdStart:
    def test_unseen_source_returns_defaults(self, store):
        rec = store.get_reliability("nobody", "market-1")
        assert rec.reliability == DEFAULT_RELIABILITY
        assert rec.confidence == DEFAULT_CONFIDENCE
        assert rec.updated_at == ""
        assert rec.source_id == "nobody"
        assert rec.market_id == "market-1"

    def test_cold_start_read_does_not_persist(self, store):
        store.get_reliability("nobody", "market-1")
        assert store.list_sources() == []

    def test_decayed_cold_start_still_defaults(self, store):
        rec = store.get_reliability("nobody", "market-1", apply_decay=True)
        assert rec.reliability == DEFAULT_RELIABILITY


class TestOutcomeUpdates:
    def test_correct_increases(self, store):
        rec = store.update_reliability("a", "m", outcome_correct=True)
        assert rec.reliability > DEFAULT_RELIABILITY

    def test_incorrect_decreases(self, store):
        rec = store.update_reliability("a", "m", outcome_correct=False)
        assert rec.reliability < DEFAULT_RELIABILITY

    def test_step_capped(self, store):
        rec = store.update_reliability("a", "m", outcome_correct=True)
        assert abs(rec.reliability - DEFAULT_RELIABILITY) <= MAX_UPDATE_STEP + 1e-12

    def test_exact_first_step_value(self, store):
        # raw +0.15 capped to +0.10 → 0.60; confidence 0.25 + 0.75*0.1 = 0.325
        rec = store.update_reliability("a", "m", outcome_correct=True)
        assert rec.reliability == pytest.approx(0.60)
        assert rec.confidence == pytest.approx(0.325)

    def test_clamped_to_zero(self, store):
        for _ in range(20):
            rec = store.update_reliability("a", "m", outcome_correct=False)
        assert rec.reliability >= 0.0
        assert rec.reliability == pytest.approx(0.0)

    def test_clamped_to_one(self, store):
        for _ in range(20):
            rec = store.update_reliability("a", "m", outcome_correct=True)
        assert rec.reliability <= 1.0
        assert rec.reliability == pytest.approx(1.0)

    def test_confidence_grows_monotonically_toward_one(self, store):
        prev = DEFAULT_CONFIDENCE
        for _ in range(50):
            rec = store.update_reliability("a", "m", outcome_correct=True)
            assert rec.confidence > prev or rec.confidence == pytest.approx(1.0)
            assert rec.confidence <= 1.0
            prev = rec.confidence

    def test_update_persists(self, store):
        store.update_reliability("a", "m", outcome_correct=True)
        rec = store.get_reliability("a", "m")
        assert rec.updated_at != ""
        assert rec.reliability == pytest.approx(0.60)

    def test_updates_accumulate(self, store):
        r1 = store.update_reliability("a", "m", outcome_correct=True).reliability
        r2 = store.update_reliability("a", "m", outcome_correct=True).reliability
        assert r2 > r1

    def test_per_market_isolation(self, store):
        store.update_reliability("a", "m-1", outcome_correct=True)
        store.update_reliability("a", "m-2", outcome_correct=False)
        assert store.get_reliability("a", "m-1").reliability > DEFAULT_RELIABILITY
        assert store.get_reliability("a", "m-2").reliability < DEFAULT_RELIABILITY

    def test_update_applies_to_undecayed_value(self, store):
        """Decay is read-time only; updates read the stored (undecayed) value."""
        store.update_reliability("a", "m", outcome_correct=True)  # 0.60 stored
        # Backdate the row far into the past so decayed != stored.
        old = (datetime.now(timezone.utc) - timedelta(days=300)).isoformat()
        store.put_record(ReliabilityRecord("a", "m", 0.60, 0.325, old))
        decayed = store.get_reliability("a", "m", apply_decay=True).reliability
        assert decayed < 0.60  # sanity: decay visible on read
        rec = store.update_reliability("a", "m", outcome_correct=True)
        assert rec.reliability == pytest.approx(0.70)  # 0.60 + 0.10, not decayed


class TestDryRun:
    def test_compute_update_never_writes(self, store):
        rec = store.compute_update("a", "m", outcome_correct=True)
        assert rec.reliability == pytest.approx(0.60)
        assert store.list_sources() == []

    def test_dry_run_flag_never_writes(self, store):
        rec = store.update_reliability("a", "m", outcome_correct=True, dry_run=True)
        assert rec.reliability == pytest.approx(0.60)
        assert store.list_sources() == []
        assert store.get_reliability("a", "m").updated_at == ""


class TestListSources:
    def test_empty(self, store):
        assert store.list_sources() == []

    def test_lists_all(self, store):
        store.update_reliability("src-b", "m-1", True)
        store.update_reliability("src-a", "m-2", False)
        records = store.list_sources()
        assert {r.source_id for r in records} == {"src-a", "src-b"}

    def test_filter_by_market(self, store):
        store.update_reliability("a", "m-1", True)
        store.update_reliability("a", "m-2", True)
        only = store.list_sources(market_id="m-1")
        assert len(only) == 1
        assert only[0].market_id == "m-1"

    def test_sorted_output(self, store):
        for sid in ("zed", "alpha", "mike"):
            store.update_reliability(sid, "m", True)
        ids = [r.source_id for r in store.list_sources()]
        assert ids == sorted(ids)


class TestDurability:
    def test_survives_reconnect(self, tmp_path: Path):
        db = tmp_path / "rel.db"
        with SQLiteReliabilityStore(db) as s:
            s.update_reliability("a", "m", outcome_correct=True)
        with SQLiteReliabilityStore(db) as s:
            rec = s.get_reliability("a", "m")
            assert rec.reliability > DEFAULT_RELIABILITY
            assert rec.confidence > DEFAULT_CONFIDENCE

    def test_schema_created_on_new_db(self, tmp_path: Path):
        import sqlite3

        db = tmp_path / "new.db"
        SQLiteReliabilityStore(db).close()
        conn = sqlite3.connect(db)
        row = conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name='sources'"
        ).fetchone()
        conn.close()
        assert row is not None

    def test_file_store_fixture_works(self, file_store):
        file_store.update_reliability("a", "m", True)
        assert len(file_store.list_sources()) == 1


class TestPutRows:
    ROWS = [
        ("a", "m", 0.61, 0.31, "2026-01-02T00:00:00+00:00"),
        ("b", "m", 0.42, 0.27, "2026-01-03T00:00:00+00:00"),
        ("a", "n", 0.55, 0.25, "2026-01-04T00:00:00+00:00"),
    ]

    def test_fresh_table_and_upsert_paths_agree(self, tmp_path: Path):
        """The empty-table INSERT fast path and the UPSERT path must leave
        byte-identical logical state: write fresh vs write-then-rewrite."""
        with SQLiteReliabilityStore(tmp_path / "fresh.db") as fresh:
            fresh.put_rows(self.ROWS)
            once = fresh.list_sources()
        with SQLiteReliabilityStore(tmp_path / "twice.db") as twice:
            twice.put_rows(self.ROWS)  # INSERT path (empty)
            twice.put_rows(self.ROWS)  # UPSERT path (populated)
            again = twice.list_sources()
        assert once == again
        assert [r.source_id for r in once] == ["a", "a", "b"]

    def test_duplicate_keys_in_one_batch_last_wins(self):
        """Intra-batch duplicates keep UPSERT's last-wins semantics on the
        empty-table fast path too."""
        dupes = self.ROWS + [("a", "m", 0.99, 0.5, "2026-02-01T00:00:00+00:00")]
        with SQLiteReliabilityStore(":memory:") as store:
            store.put_rows(dupes)
            rec = store.get_reliability("a", "m")
        assert rec.reliability == 0.99
        assert rec.updated_at == "2026-02-01T00:00:00+00:00"

    def test_upsert_overwrites_existing_rows(self):
        with SQLiteReliabilityStore(":memory:") as store:
            store.put_rows(self.ROWS)
            store.put_rows([("b", "m", 0.8, 0.4, "2026-03-01T00:00:00+00:00")])
            rec = store.get_reliability("b", "m")
            assert rec.reliability == 0.8
            assert len(store.list_sources()) == 3


class TestRecord:
    def test_frozen(self):
        rec = ReliabilityRecord("a", "m", 0.5, 0.25, "")
        with pytest.raises(dataclasses.FrozenInstanceError):
            rec.reliability = 0.9  # type: ignore[misc]

    def test_equality(self):
        assert ReliabilityRecord("a", "m", 0.5, 0.25, "t") == ReliabilityRecord(
            "a", "m", 0.5, 0.25, "t"
        )


class TestProtocol:
    def test_sqlite_store_satisfies_interface(self, store):
        assert isinstance(store, ReliabilityStore)
