"""cluster/ — membership views, journal merge recovery, and the round-13
resident adopt extension (band mode + the cluster host-staged relayout).

The real-kill multi-process soak lives in scripts/kill_soak.py (smoked by
tests/test_bench_harness.py::TestKillSoakLeg); this suite pins the
in-process contracts it rides on:

* :class:`~.cluster.membership.MeshView` — coordinator-free agreement:
  identical views from identical host sets, bands that tile the padded
  axis, degraded views as pure epoch bumps.
* :mod:`~.cluster.recover` — the degraded-mesh byte contract: a
  one-journal merge is bit-equal to ``replay_journal``; band journals
  merge deterministically and refuse split-brain; a live adoption equals
  the offline merge.
* the session side — ``band=`` and forced-cluster adopts take the
  RELAYOUT path (never the PR-5 teardown+rebuild) with byte parity
  against the per-batch-session stream, rebuild reasons are named, and
  ``stream.resident_fallbacks`` counts exactly the falls.
* the crash-resume degraded-factorisation contract: a journal written on
  an (A, B) mesh resumes bit-equal on a DIFFERENT factorisation of the
  surviving devices — store arrays, appended journal epochs (wall_ts
  masked), and SQLite export bytes.
"""

import random
import struct

import numpy as np
import pytest

from bayesian_consensus_engine_tpu.cluster import (
    ClusterModeUnsupported,
    MeshView,
    adopt_journal,
    replay_cluster_journals,
    store_digest,
)
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.pipeline import settle_stream
from bayesian_consensus_engine_tpu.state.journal import (
    JournalWriter,
    replay_journal,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_400.0


def _payloads(rng, markets, universe, tag=""):
    out = []
    for m in range(markets):
        n = rng.randint(1, 3)
        out.append((
            f"m{tag}-{m}",
            [
                {
                    "sourceId": f"s{rng.randrange(universe)}",
                    "probability": round(rng.random(), 6),
                }
                for _ in range(n)
            ],
        ))
    return out


def _mixed_batches(markets=24, batches=5, seed=11, tag=""):
    """Stable pairs, drift, then pair growth — refresh, relayout, ladder.

    The market COUNT stays fixed (band plans must cover exactly their
    band every batch); drift and growth happen in the (source, market)
    pair universe, which is what moves rows through the store."""
    rng = random.Random(seed)
    stable = _payloads(rng, markets, 12, tag=tag)
    out = []
    for b in range(batches):
        if b < 2:
            pays = [
                (k, [dict(s, probability=round(rng.random(), 6))
                     for s in sigs])
                for k, sigs in stable
            ]
        elif b < 4:
            pays = _payloads(rng, markets, 16, tag=tag)
        else:
            pays = [
                (
                    f"m{tag}-{m}",
                    [
                        {
                            "sourceId": f"s{rng.randrange(40)}",
                            "probability": round(rng.random(), 6),
                        }
                        for _ in range(rng.randint(3, 6))
                    ],
                )
                for m in range(markets)
            ]
        outs = [rng.random() < 0.5 for _ in pays]
        out.append((pays, outs))
    return out


def _journal_epochs_sans_clock(path):
    """Frame payloads with the wall-clock stamp (and its CRC) masked."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        fields = hdr.unpack_from(blob, off)
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = fields
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


class TestMeshView:
    def test_identical_host_sets_agree(self):
        a = MeshView(epoch=3, hosts=(2, 0, 5), devices_per_host=4)
        b = MeshView(epoch=3, hosts=(5, 2, 0), devices_per_host=4)
        assert a == b
        assert a.hosts == (0, 2, 5)
        assert a.fingerprint == b.fingerprint
        assert a.shape == (12, 1)

    def test_bands_tile_the_padded_axis(self):
        view = MeshView(epoch=0, hosts=(0, 1, 2), devices_per_host=2)
        markets = 17
        padded = view.padded_markets(markets)
        assert padded % view.markets_extent == 0
        spans = [view.band(h, markets) for h in view.hosts]
        assert [lo for lo, _ in spans] == [0, padded // 3, 2 * padded // 3]
        assert all(gm == markets for _, gm in spans)
        owned = [list(view.owned_markets(h, markets)) for h in view.hosts]
        flat = sum(owned, [])
        assert flat == list(range(markets))  # live rows, no gaps/overlap

    def test_degraded_is_an_epoch_bump_over_survivors(self):
        view = MeshView(epoch=0, hosts=(0, 1, 2), devices_per_host=2)
        degraded = view.degraded([2, 0])
        assert degraded.epoch == 1
        assert degraded.hosts == (0, 2)
        assert degraded.fingerprint != view.fingerprint
        # Survivors re-tile the whole axis between them.
        assert list(degraded.owned_markets(0, 10)) + list(
            degraded.owned_markets(2, 10)
        ) == list(range(10))
        with pytest.raises(ValueError, match="not members"):
            view.degraded([0, 7])
        with pytest.raises(ValueError, match="empty"):
            view.degraded([])

    def test_ici_shape_validation(self):
        with pytest.raises(ValueError, match="devices per"):
            MeshView(epoch=0, hosts=(0,), devices_per_host=4,
                     ici_shape=(3, 1))
        with pytest.raises(ValueError, match="duplicate"):
            MeshView(epoch=0, hosts=(1, 1), devices_per_host=1)

    def test_build_mesh_matches_view_shape(self):
        # Single-host: the local mesh over this host's devices.
        local = MeshView(epoch=0, hosts=(0,), devices_per_host=4)
        mesh = local.build_mesh()
        assert dict(mesh.shape) == {"markets": 4, "sources": 1}
        # Multi-host on one process (explicit granules over the 8 CPU
        # devices): the hybrid DCN-outer mesh, granules in sorted-host
        # order — the same factorisation MeshView.shape promises.
        multi = MeshView(epoch=0, hosts=(0, 1), devices_per_host=4,
                         ici_shape=(2, 2))
        mesh = multi.build_mesh()
        assert dict(mesh.shape) == {"markets": 4, "sources": 2}
        assert (multi.markets_extent, multi.sources_extent) == (4, 2)


def _band_stream_to_journal(tmp_path, name, tag, markets=10, batches=3,
                            seed=5):
    """One shared-nothing band: stream → journal → synced store."""
    rng = random.Random(seed)
    store = TensorReliabilityStore()
    jrnl = tmp_path / f"{name}.jrnl"
    bs = []
    for _ in range(batches):
        pays = _payloads(rng, markets, 8, tag=tag)
        bs.append((pays, [rng.random() < 0.5 for _ in pays]))
    list(settle_stream(store, bs, steps=1, now=NOW, journal=str(jrnl),
                       sync_checkpoints=True))
    store.sync()
    return store, jrnl


class TestClusterReplay:
    def test_single_journal_merge_is_bit_equal_to_replay(self, tmp_path):
        _, jrnl = _band_stream_to_journal(tmp_path, "solo", "a")
        merged = replay_cluster_journals([jrnl])
        ref, tag = replay_journal(jrnl)
        assert merged.tags == (tag,)
        assert merged.resume_index(0) == tag + 1
        # Bit-for-bit: same digest means same pair order, same value
        # columns, same ISO sidecars — the degraded-mesh byte contract's
        # foundation.
        assert store_digest(merged.store) == store_digest(ref)
        used = len(ref)
        np.testing.assert_array_equal(
            merged.store._rel[:used], ref._rel[:used]
        )
        np.testing.assert_array_equal(
            merged.store._days[:used], ref._days[:used]
        )

    def test_band_journals_merge_deterministically(self, tmp_path):
        s_a, j_a = _band_stream_to_journal(tmp_path, "a", "a", seed=5)
        s_b, j_b = _band_stream_to_journal(tmp_path, "b", "b", seed=6)
        merged = replay_cluster_journals([j_a, j_b])
        assert merged.tags == (2, 2)
        assert merged.rows == (len(s_a), len(s_b))
        got = {(r.source_id, r.market_id) for r in
               merged.store.list_sources()}
        want = {
            (r.source_id, r.market_id)
            for s in (s_a, s_b) for r in s.list_sources()
        }
        assert got == want
        again = replay_cluster_journals([j_a, j_b])
        assert store_digest(again.store) == store_digest(merged.store)
        # Order is part of the contract: callers must agree on it.
        flipped = replay_cluster_journals([j_b, j_a])
        assert store_digest(flipped.store) != store_digest(merged.store)

    def test_adopt_journal_equals_offline_merge(self, tmp_path):
        _, j_a = _band_stream_to_journal(tmp_path, "a2", "a", seed=5)
        s_b, j_b = _band_stream_to_journal(tmp_path, "b2", "b", seed=6)
        live, _ = replay_journal(j_a)
        tag, rows = adopt_journal(live, j_b)
        assert (tag, rows) == (2, len(s_b))
        merged = replay_cluster_journals([j_a, j_b])
        assert store_digest(live) == store_digest(merged.store)
        # SQLite bytes too — identical stores must export identical files.
        live.flush_to_sqlite(tmp_path / "live.db")
        merged.store.flush_to_sqlite(tmp_path / "merged.db")
        assert (tmp_path / "live.db").read_bytes() == (
            tmp_path / "merged.db"
        ).read_bytes()

    def test_overlapping_journals_are_split_brain(self, tmp_path):
        _, jrnl = _band_stream_to_journal(tmp_path, "dup", "a")
        with pytest.raises(ValueError, match="split-brain"):
            replay_cluster_journals([jrnl, jrnl])

    def test_adopted_rows_ride_the_next_epoch(self, tmp_path):
        """After adoption the survivor's own journal is self-contained:
        one more settle + epoch, and IT ALONE replays to the full store."""
        _, j_a = _band_stream_to_journal(tmp_path, "a3", "a", seed=5)
        _, j_b = _band_stream_to_journal(tmp_path, "b3", "b", seed=6)
        live, _ = replay_journal(j_a)
        adopt_journal(live, j_b)
        writer = JournalWriter(j_a, resume=True)
        rng = random.Random(9)
        pays = _payloads(rng, 6, 8, tag="a")
        list(settle_stream(
            live, [(pays, [True] * len(pays))], steps=1, now=NOW + 9,
            journal=writer, sync_checkpoints=True,
        ))
        live.sync()
        solo = replay_cluster_journals([j_a])
        assert store_digest(solo.store) == store_digest(live)


class TestClusterAdopt:
    """The round-13 retirement of the PR-5 fallback: band mode and the
    cluster (host-staged) posture adopt by RELAYOUT, byte-equal to the
    per-batch-session stream; the remaining rebuilds carry reasons."""

    def _stream(self, batches, mesh, band=None, resident=True,
                num_slots=8, monkey=None, stats=None):
        store = TensorReliabilityStore()
        stats = stats if stats is not None else []
        results = list(settle_stream(
            store, batches, steps=2, now=NOW, stats=stats,
            reuse_plans=True, mesh=mesh, band=band, num_slots=num_slots,
            resident_session=resident,
        ))
        store.sync()
        records = [
            (r.source_id, r.market_id, r.reliability, r.confidence,
             r.updated_at)
            for r in store.list_sources()
        ]
        return records, results, stats

    def test_band_mode_adopts_resident_and_matches_per_batch(self):
        batches = _mixed_batches()
        markets = max(len(p) for p, _ in batches)
        mesh = make_mesh((4, 2))
        rec_on, res_on, stats_on = self._stream(
            batches, mesh, band=(0, markets)
        )
        modes = [s["session_adopt"] for s in stats_on]
        assert modes[0] == "start"
        assert set(modes[1:]) <= {"refresh", "relayout"}  # NO rebuilds
        rec_off, res_off, _ = self._stream(
            batches, mesh, band=(0, markets), resident=False
        )
        assert rec_on == rec_off
        for a, b in zip(res_on, res_off):
            assert a.market_keys == b.market_keys
            np.testing.assert_array_equal(
                np.asarray(a.consensus), np.asarray(b.consensus)
            )

    def test_forced_cluster_path_is_byte_equal(self, monkeypatch):
        """The host-staged cluster relayout (multi-controller posture,
        forced via the _process_count seam) must produce the same bytes
        as the in-HBM device relayout AND the per-batch rebuild."""
        import bayesian_consensus_engine_tpu.pipeline as pipeline_mod

        batches = _mixed_batches(seed=13)
        markets = max(len(p) for p, _ in batches)
        mesh = make_mesh()
        rec_device, res_device, _ = self._stream(
            batches, mesh, band=(0, markets)
        )
        monkeypatch.setattr(pipeline_mod, "_process_count", lambda: 2)
        rec_cluster, res_cluster, stats = self._stream(
            batches, mesh, band=(0, markets)
        )
        modes = [s["session_adopt"] for s in stats]
        assert "relayout" in modes
        assert not any(m.startswith("rebuild") for m in modes[1:])
        assert rec_cluster == rec_device
        for a, b in zip(res_cluster, res_device):
            np.testing.assert_array_equal(
                np.asarray(a.consensus), np.asarray(b.consensus)
            )

    def test_band_change_rebuilds_with_reason(self):
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
            build_settlement_plan,
        )

        rng = random.Random(3)
        store = TensorReliabilityStore()
        mesh = make_mesh()
        pays = _payloads(rng, 10, 8)
        plan = build_settlement_plan(store, pays, num_slots=4,
                                     fingerprint=True)
        session = ShardedSettlementSession(
            store, plan, mesh, band=(0, 10)
        )
        session.settle([True] * 10, steps=1, now=NOW)
        pays2 = _payloads(rng, 12, 8, tag="x")
        plan2 = build_settlement_plan(store, pays2, num_slots=4,
                                      fingerprint=True)
        assert session.adopt(plan2, band=(0, 12)) == "rebuild:band-change"
        session.close()

    def test_backdated_entering_stamps_rebuild_with_reason(self):
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
            build_settlement_plan,
            settle,
        )

        rng = random.Random(4)
        store = TensorReliabilityStore()
        mesh = make_mesh()
        # Rows settled at an OLD day, then a session whose epoch sits
        # above it: those rows entering the resident block cannot be
        # re-expressed against the session epoch.
        old_pays = _payloads(rng, 4, 6, tag="old")
        old_plan = build_settlement_plan(store, old_pays, num_slots=4)
        settle(store, old_plan, [True] * 4, steps=1, now=NOW - 500.0)
        store.sync()
        pays = _payloads(rng, 6, 6, tag="live")
        plan = build_settlement_plan(store, pays, num_slots=4)
        session = ShardedSettlementSession(store, plan, mesh)
        session.settle([True] * 6, steps=1, now=NOW)
        # Force the session's epoch ABOVE the old stamps so the entering
        # re-expression goes non-positive.
        session._epoch0 = NOW - 0.5
        merged = build_settlement_plan(
            store, pays + old_pays, num_slots=4
        )
        assert session.adopt(merged) == "rebuild:backdated-stamps"
        session.close()

    def test_resident_fallbacks_counter(self, tmp_path):
        from bayesian_consensus_engine_tpu import obs

        batches = _mixed_batches(seed=17)
        markets = max(len(p) for p, _ in batches)
        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            self._stream(batches, make_mesh(), band=(0, markets))
        finally:
            obs.set_metrics_registry(previous)
        counters = registry.export()["counters"]
        # The whole drift/growth stream stayed resident: the retirement
        # metric reads zero.
        assert counters.get("stream.resident_fallbacks", 0) == 0
        assert counters["stream.session_adopts"] >= 1


class TestAnalyticsClusterGate:
    def _session(self, band=None):
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
            build_settlement_plan,
        )

        rng = random.Random(8)
        store = TensorReliabilityStore()
        pays = _payloads(rng, 12, 8)
        plan = build_settlement_plan(store, pays, num_slots=4,
                                     fingerprint=True)
        return ShardedSettlementSession(
            store, plan, make_mesh((4, 2)), band=band
        ), [True] * 12

    def test_band_session_serves_bands(self):
        """The PR-10 band tree extended to the banded session: same
        program, same bits as the whole-axis session on the same plan."""
        banded, outcomes = self._session(band=(0, 12))
        with banded:
            _, tb_b, bands_b, prop = banded.settle_with_analytics(
                outcomes, steps=1, now=NOW
            )
        assert prop is None
        plain, _ = self._session()
        with plain:
            _, tb_p, bands_p, _ = plain.settle_with_analytics(
                outcomes, steps=1, now=NOW
            )
        for field in ("mean", "lo", "hi", "stderr"):
            np.testing.assert_array_equal(
                np.asarray(getattr(bands_b, field)),
                np.asarray(getattr(bands_p, field)),
            )
        np.testing.assert_array_equal(
            np.asarray(tb_b.prediction), np.asarray(tb_p.prediction)
        )

    def test_graph_sweep_on_band_session_serves_with_parity(self):
        """Round 18 closes the PR-11 refusal: graph analytics on a
        band session no longer raises — it serves the same bits as the
        whole-axis session (the full byte-parity matrix lives in
        tests/test_infer.py::TestBandedGraphSession)."""
        from bayesian_consensus_engine_tpu.analytics.bands import (
            AnalyticsOptions,
        )
        from bayesian_consensus_engine_tpu.analytics.graph import (
            MarketGraph,
        )

        graph = MarketGraph.from_edges([("m-0", "m-1", 0.5)])
        options = AnalyticsOptions(graph=graph)
        banded, outcomes = self._session(band=(0, 12))
        with banded:
            _, _, bands_b, prop_b = banded.settle_with_analytics(
                outcomes, steps=1, now=NOW, analytics=options
            )
        plain, _ = self._session()
        with plain:
            _, _, bands_p, prop_p = plain.settle_with_analytics(
                outcomes, steps=1, now=NOW, analytics=options
            )
        assert prop_b is not None
        np.testing.assert_array_equal(
            np.asarray(prop_b), np.asarray(prop_p)
        )
        np.testing.assert_array_equal(
            np.asarray(bands_b.stderr), np.asarray(bands_p.stderr)
        )

    def test_multi_controller_names_the_route(self, monkeypatch):
        import bayesian_consensus_engine_tpu.pipeline as pipeline_mod

        session, outcomes = self._session()
        monkeypatch.setattr(pipeline_mod, "_process_count", lambda: 2)
        with session:
            with pytest.raises(
                ClusterModeUnsupported, match="MeshView"
            ):
                session.settle_with_analytics(outcomes, steps=1, now=NOW)


class TestDegradedFactorisationResume:
    """The crash-resume satellite: a journal written on an (A, B) mesh
    replays bit-equal onto a DIFFERENT degraded factorisation — final
    store arrays, the journal epochs appended during the resume
    (wall_ts masked), and SQLite export bytes."""

    def _crash_stream(self, tmp_path, monkeypatch, batches, mesh):
        real_flush = TensorReliabilityStore.flush_to_journal
        calls = {"n": 0}

        def broken_third(self, journal, tag=0):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("journal disk gone")
            return real_flush(self, journal, tag=tag)

        monkeypatch.setattr(
            TensorReliabilityStore, "flush_to_journal", broken_third
        )
        store = TensorReliabilityStore()
        jrnl = tmp_path / "cluster_crash.jrnl"
        stats: list = []
        writer = JournalWriter(jrnl)
        with pytest.raises(RuntimeError, match="journal disk gone"):
            for _r in settle_stream(
                store, batches, steps=2, now=NOW, checkpoint_every=1,
                stats=stats, reuse_plans=True, mesh=mesh, journal=writer,
                sync_checkpoints=True,
            ):
                pass
        writer.close()
        monkeypatch.setattr(
            TensorReliabilityStore, "flush_to_journal", real_flush
        )
        return jrnl

    def _resume(self, tmp_path, jrnl_src, name, batches, mesh):
        """Replay the crashed journal, resume the remaining batches on
        *mesh*, and return (store, journal copy, sqlite path)."""
        import shutil

        jrnl = tmp_path / f"resume_{name}.jrnl"
        shutil.copy(jrnl_src, jrnl)
        store, tag = replay_journal(jrnl)
        resume_from = tag + 1
        stats: list = []
        for _r in settle_stream(
            store, batches[resume_from:], steps=2, now=NOW + resume_from,
            checkpoint_every=1, stats=stats, reuse_plans=True, mesh=mesh,
            journal=JournalWriter(jrnl, resume=True),
            sync_checkpoints=True,
        ):
            pass
        store.sync()
        db = tmp_path / f"resume_{name}.db"
        store.flush_to_sqlite(db)
        return store, jrnl, db

    def test_degraded_resume_is_bit_equal_to_single_host(
        self, tmp_path, monkeypatch
    ):
        batches = _mixed_batches(seed=29)
        written_mesh = make_mesh()  # (8, 1): the full "cluster"
        jrnl = self._crash_stream(
            tmp_path, monkeypatch, batches, written_mesh
        )
        _store, _j, _db = None, None, None
        # Degraded factorisation: HALF the devices (the survivors),
        # markets-only — the bit-exact regime the contract is pinned in.
        import jax

        degraded_mesh = make_mesh(
            (4, 1), devices=jax.devices()[:4]
        )
        s_deg, j_deg, db_deg = self._resume(
            tmp_path, jrnl, "degraded", batches, degraded_mesh
        )
        # Single-host replay of the same journal: the flat resume.
        s_one, j_one, db_one = self._resume(
            tmp_path, jrnl, "flat", batches, None
        )
        assert s_deg.list_sources() == s_one.list_sources()
        used = len(s_deg)
        for column in ("_rel", "_conf", "_days", "_exists"):
            np.testing.assert_array_equal(
                getattr(s_deg, column)[:used],
                getattr(s_one, column)[:used],
            )
        assert _journal_epochs_sans_clock(j_deg) == (
            _journal_epochs_sans_clock(j_one)
        )
        assert db_deg.read_bytes() == db_one.read_bytes()
        assert store_digest(s_deg) == store_digest(s_one)


class TestRecoveryFlightRecorder:
    """Round-16 satellite: a dispatch failure landing WHILE cluster
    recovery (``adopt_journal``) is in progress must leave a crash
    postmortem that SHOWS the recovery in flight — the ``recovery``
    component ring holds the adoption's span chain (an ``adopt_start``
    with no ``adopt_done`` = adoption mid-replay at the failure)."""

    def _dead_band_journal(self, tmp_path):
        store = TensorReliabilityStore()
        journal = tmp_path / "dead_band.jrnl"
        list(settle_stream(
            store, _mixed_batches(markets=6, batches=2, seed=7, tag="d"),
            steps=1, now=NOW, journal=journal, checkpoint_every=1,
        ))
        return journal

    def test_dump_mid_adoption_captures_recovery_spans(
        self, tmp_path, monkeypatch
    ):
        import asyncio
        import threading

        from bayesian_consensus_engine_tpu import obs
        from bayesian_consensus_engine_tpu.cluster import recover
        from bayesian_consensus_engine_tpu.serve import ConsensusService

        dead_journal = self._dead_band_journal(tmp_path)

        # Pause the adoption mid-flight: adopt_start is recorded, the
        # replay walk blocks until released — the window in which the
        # dispatch failure fires.
        real_replay = recover._replay_into
        adopt_started = threading.Event()
        release_adopt = threading.Event()

        def paused_replay(store, path):
            adopt_started.set()
            assert release_adopt.wait(timeout=30)
            return real_replay(store, path)

        monkeypatch.setattr(recover, "_replay_into", paused_replay)

        # ...and a journal whose second epoch dies (the TestFlightRecorder
        # failure mode): the service's dispatch worker takes the flight
        # dump at the moment of failure.
        real_flush = TensorReliabilityStore.flush_to_journal_async
        calls = {"n": 0}

        def broken_second(self, journal, tag=0):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("journal disk gone")
            return real_flush(self, journal, tag=tag)

        monkeypatch.setattr(
            TensorReliabilityStore, "flush_to_journal_async", broken_second
        )

        tracer = obs.Tracer()
        previous = obs.set_tracer(tracer)
        survivor_store = TensorReliabilityStore()
        adopter = threading.Thread(
            target=recover.adopt_journal,
            args=(survivor_store, dead_journal),
            daemon=True,
        )
        try:
            adopter.start()
            assert adopt_started.wait(timeout=30)

            async def main():
                service = ConsensusService(
                    TensorReliabilityStore(), steps=1, now=NOW,
                    journal=tmp_path / "live.jrnl", checkpoint_every=1,
                    max_batch=2, max_delay_s=None,
                )
                async with service:
                    for i in range(4):
                        service.submit(
                            f"m{i}", [("s", 0.5 + 0.01 * i)], True
                        )
                    await service.drain()
                return service

            with pytest.raises(RuntimeError, match="journal disk gone"):
                asyncio.run(main())

            # The postmortem: the service's own rings PLUS the recovery
            # ring, whose chain shows the adoption STARTED and not done.
            dump = tracer.last_flight_dump
            assert dump is not None
            assert "dispatch failure" in dump["reason"]
            assert "recovery" in dump["components"]
            recovery_names = [
                e["name"] for e in dump["components"]["recovery"]
            ]
            assert recovery_names == ["adopt_start"]
            (start_event,) = dump["components"]["recovery"]
            assert start_event["args"]["journal"] == str(dead_journal)
        finally:
            release_adopt.set()
            adopter.join(timeout=30)
            obs.set_tracer(previous)

        # Once released, the adoption completes and closes its chain —
        # the full log now carries start AND done with the adopted rows.
        events = [
            e for e in tracer.events() if e["scope"] == recover.RECOVERY_SCOPE
        ]
        assert [e["name"] for e in events] == ["adopt_start", "adopt_done"]
        done = events[-1]
        assert done["args"]["rows_adopted"] == len(survivor_store)
        assert done["args"]["rows_adopted"] > 0
        assert done["args"]["tag"] == 1  # two epochs, 0-indexed tags
