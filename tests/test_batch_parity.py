"""Array path vs scalar path: property-based parity.

The scalar engine is the byte-exact contract; the batched JAX path must match
it to float64 tolerance on arbitrary inputs (and exactly on the golden
fixture under x64). This is the CPU↔TPU parity gate of SURVEY.md §7 step 3.
"""

import json
import math
import pathlib
import random

import pytest

jax = pytest.importorskip("jax")

enable_x64 = jax.enable_x64

from bayesian_consensus_engine_tpu.core import compute_consensus
from bayesian_consensus_engine_tpu.core.batch import (
    compute_batch_consensus,
    compute_consensus_jax,
    mapping_lookup,
)

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


def random_case(rng: random.Random, max_sources: int = 9):
    n = rng.randint(1, 25)
    signals = [
        {
            "sourceId": f"s-{rng.randint(0, max_sources)}",
            "probability": round(rng.random(), 6),
        }
        for _ in range(n)
    ]
    reliability = {}
    for sid in {s["sourceId"] for s in signals}:
        roll = rng.random()
        if roll < 0.4:
            reliability[sid] = {
                "reliability": round(rng.random(), 6),
                "confidence": round(rng.random(), 6),
            }
        elif roll < 0.5:
            reliability[sid] = {}  # present-but-partial: not cold-start
    return signals, (reliability or None)


def assert_documents_close(array_doc, scalar_doc, rel_tol=1e-12):
    assert array_doc["schemaVersion"] == scalar_doc["schemaVersion"]
    for key in ("consensus", "confidence"):
        a, b = array_doc[key], scalar_doc[key]
        if b is None:
            assert a is None
        else:
            assert math.isclose(a, b, rel_tol=rel_tol, abs_tol=1e-12), (key, a, b)
    assert [w["sourceId"] for w in array_doc["sourceWeights"]] == [
        w["sourceId"] for w in scalar_doc["sourceWeights"]
    ]
    for aw, bw in zip(array_doc["sourceWeights"], scalar_doc["sourceWeights"]):
        assert math.isclose(aw["weight"], bw["weight"], rel_tol=rel_tol)
        assert math.isclose(
            aw["normalizedWeight"], bw["normalizedWeight"], rel_tol=rel_tol, abs_tol=1e-12
        )
    assert math.isclose(
        array_doc["normalization"]["totalWeight"],
        scalar_doc["normalization"]["totalWeight"],
        rel_tol=rel_tol,
    )
    assert array_doc["normalization"]["sourceCount"] == scalar_doc["normalization"]["sourceCount"]
    assert array_doc["diagnostics"] == scalar_doc["diagnostics"]


class TestSingleMarketParity:
    def test_randomized_parity_x64(self):
        rng = random.Random(2026)
        with enable_x64():
            for _ in range(150):
                signals, reliability = random_case(rng)
                array_doc = compute_consensus_jax(signals, reliability)
                scalar_doc = compute_consensus(signals, reliability)
                assert_documents_close(array_doc, scalar_doc)

    def test_randomized_parity_f32_loose(self):
        rng = random.Random(7)
        for _ in range(30):
            signals, reliability = random_case(rng)
            array_doc = compute_consensus_jax(signals, reliability)
            scalar_doc = compute_consensus(signals, reliability)
            if scalar_doc["consensus"] is not None:
                assert math.isclose(
                    array_doc["consensus"], scalar_doc["consensus"], rel_tol=1e-5
                )

    def test_golden_fixture_exact_under_x64(self):
        fixture = json.loads((FIXTURES / "golden_regression.json").read_text())
        with enable_x64():
            array_doc = compute_consensus_jax(fixture["input"]["signals"])
        assert array_doc == fixture["expectedOutput"]

    def test_zero_weight_market(self):
        with enable_x64():
            doc = compute_consensus_jax(
                [{"sourceId": "a", "probability": 0.7}],
                {"a": {"reliability": 0.0, "confidence": 0.3}},
            )
        assert doc["consensus"] is None
        assert doc["confidence"] == 0.0
        assert doc["sourceWeights"][0]["normalizedWeight"] == 0.0

    def test_negative_total_weight_matches_scalar(self):
        # Out-of-domain but accepted input: the scalar engine (like the
        # reference, core.py:131) only special-cases total_weight == 0, so a
        # negative total divides through — both backends must agree.
        signals = [{"sourceId": "a", "probability": 0.7}]
        rel = {"a": {"reliability": -1.0, "confidence": 0.5}}
        with enable_x64():
            array_doc = compute_consensus_jax(signals, rel)
        scalar_doc = compute_consensus(signals, rel)
        assert array_doc["consensus"] == scalar_doc["consensus"] == pytest.approx(0.7)
        assert array_doc["normalization"]["totalWeight"] == -1.0

    def test_duplicate_signals_deduped(self):
        with enable_x64():
            doc = compute_consensus_jax(
                [
                    {"sourceId": "a", "probability": 0.2},
                    {"sourceId": "a", "probability": 0.4},
                    {"sourceId": "b", "probability": 0.9},
                ]
            )
        assert doc["consensus"] == pytest.approx(0.6)
        assert doc["diagnostics"]["sources"] == 3
        assert doc["diagnostics"]["uniqueSources"] == 2

    def test_backend_kwarg_routes_to_array_path(self):
        signals = [{"sourceId": "a", "probability": 0.6}]
        doc = compute_consensus(signals, backend="jax")
        assert doc["consensus"] == pytest.approx(0.6, rel=1e-6)
        # empty-signals stays on the scalar path regardless of backend
        assert compute_consensus([], backend="tpu")["diagnostics"]["status"] == "no_signals"

    def test_golden_fixture_exact_via_backend_dispatch_x64(self):
        # The dispatch line itself (engine.py backend= kwarg), not a direct
        # compute_consensus_jax call: under x64 the batched path reproduces
        # the golden bytes for BOTH backend aliases.
        fixture = json.loads((FIXTURES / "golden_regression.json").read_text())
        signals = fixture["input"]["signals"]
        with enable_x64():
            for backend in ("jax", "tpu"):
                assert (
                    compute_consensus(signals, backend=backend)
                    == fixture["expectedOutput"]
                ), backend

    def test_backend_unavailable_raises_not_implemented(self, monkeypatch):
        # The dispatch's ImportError → NotImplementedError fallback: a build
        # without the batched path must fail loudly, not fall back silently.
        import sys as _sys

        monkeypatch.setitem(
            _sys.modules, "bayesian_consensus_engine_tpu.core.batch", None
        )
        with pytest.raises(NotImplementedError, match="backend 'jax' requires"):
            compute_consensus(
                [{"sourceId": "a", "probability": 0.6}], backend="jax"
            )


class TestBatchedMarkets:
    def test_many_markets_one_pass(self):
        rng = random.Random(99)
        markets = []
        expected = {}
        with enable_x64():
            for m in range(40):
                signals, reliability = random_case(rng)
                mid = f"market-{m}"
                markets.append((mid, signals))
                doc = compute_consensus(signals, reliability)
                doc["marketId"] = mid
                expected[mid] = (doc, reliability)

            # Batched lookup dispatches per market id.
            tables = {mid: rel for mid, (_doc, rel) in expected.items()}

            def lookup(sid, mid):
                return mapping_lookup(tables[mid])(sid, mid)

            results = compute_batch_consensus(markets, lookup)

        assert set(results) == set(expected)
        for mid, (scalar_doc, _rel) in expected.items():
            assert_documents_close(results[mid], scalar_doc)
            assert results[mid]["marketId"] == mid

    def test_market_sweep_matches_scalar_sweep(self):
        from bayesian_consensus_engine_tpu.core.batch import (
            compute_all_consensus_batched,
        )
        from bayesian_consensus_engine_tpu.models import MarketId, MarketStore
        from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore

        rng = random.Random(5)
        markets = MarketStore()
        with SQLiteReliabilityStore(":memory:") as rel:
            for m in range(12):
                mid = MarketId(f"sweep-{m}")
                for _ in range(rng.randint(0, 6)):
                    sid = f"s{rng.randint(0, 4)}"
                    markets.add_signal(
                        mid, {"sourceId": sid, "probability": round(rng.random(), 4)}
                    )
                    if rng.random() < 0.5:
                        rel.update_reliability(sid, str(mid), rng.random() < 0.5)
                markets.get_or_create(mid)

            scalar = markets.compute_all_consensus(rel)
            with enable_x64():
                batched = compute_all_consensus_batched(markets, rel)

        assert set(scalar) == set(batched)
        for mid, scalar_doc in scalar.items():
            batched_doc = batched[mid]
            if "normalization" not in scalar_doc:  # empty-market reduced doc
                assert batched_doc == scalar_doc
                continue
            # decay-on-read runs at slightly different wall-clock instants in
            # the two sweeps; allow for that drift only.
            assert_documents_close(batched_doc, scalar_doc, rel_tol=1e-6)
            assert batched_doc["diagnostics"]["coldStartSources"] == []

    def test_empty_market_reduced_document(self):
        results = compute_batch_consensus([("empty", [])])
        assert results["empty"] == {
            "schemaVersion": "1.0.0",
            "consensus": None,
            "confidence": 0.0,
            "marketId": "empty",
        }

    def test_mixed_empty_and_live(self):
        results = compute_batch_consensus(
            [
                ("live", [{"sourceId": "a", "probability": 0.8}]),
                ("empty", []),
            ]
        )
        assert results["live"]["consensus"] == pytest.approx(0.8, rel=1e-6)
        assert "normalization" not in results["empty"]
