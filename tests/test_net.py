"""net/ + multi-tenant QoS: the round-17 network front door.

The non-negotiable contracts, in four parts:

* **Byte parity over the wire** — the same admitted-request trace served
  through :class:`~.net.server.ConsensusServer` over a real socket and
  submitted in-process through ``ConsensusService.submit`` yields
  identical results, journal epoch payloads (wall_ts masked), and
  SQLite bytes — flat AND sharded-resident. Structural (the server
  submits into the SAME coalescer); these tests keep it structural.
* **Wire robustness** — torn/truncated frames, partial writes from a
  client dying mid-frame, oversized-frame refusal, and version-mismatch
  error frames each kill ONLY the offending connection; the coalescer
  keeps serving and the journal bytes are untouched.
* **Deterministic variance-aware shedding** — the shed victim sequence
  is a pure function of (class, per-market stderr ranking, arrival
  order), pinned by a fixed trace; with no stderr known the policy IS
  the round-8 shed-oldest.
* **Per-class QoS** — each class runs its own budget, SLO accounting,
  and burn-rate monitor: one class refusing (budget or burn) never
  refuses another's traffic.
"""

import asyncio
import socket
import struct

import pytest

jax = pytest.importorskip("jax")

from bayesian_consensus_engine_tpu.net import (
    ConsensusClient,
    ConsensusServer,
)
from bayesian_consensus_engine_tpu.net import wire
from bayesian_consensus_engine_tpu.obs.health import BurnWindow
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.serve import (
    ConsensusService,
    Overloaded,
    QosClass,
    ShedError,
    shed_rank_key,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_900.0


def journal_epochs_sans_clock(path):
    """Decoded epoch frames with the wall-clock field masked (same
    helper as tests/test_serve.py)."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


def mixed_trace(width=8):
    """Hits, drift, and growth as one submission-ordered request trace
    (tests/test_serve.py's shape: every round submits exactly *width*
    distinct markets so ``max_batch=width`` seals one window per round)."""
    trace = []
    for rnd in range(2):
        for m in range(width):
            trace.append((
                f"m-{m}",
                [(f"s-{m}", 0.55 + 0.01 * rnd), (f"s-{(m + 1) % 5}", 0.40)],
                (m + rnd) % 2 == 0,
            ))
    for rnd in range(2):
        for m in range(width):
            trace.append((
                f"m-{m}",
                [(f"s-{m}", 0.35 + 0.01 * rnd), ("s-drift", 0.70)],
                (m + rnd) % 3 == 0,
            ))
    for m in range(2 * width):
        trace.append((
            f"fresh-{m}", [(f"s-{m % 5}", 0.62), (f"g-{m}", 0.48)],
            m % 2 == 1,
        ))
    return trace


def _service(store, tmp_path, name, mesh=None, width=8, **kwargs):
    kwargs.setdefault("steps", 2)
    kwargs.setdefault("now", NOW)
    kwargs.setdefault("checkpoint_every", 2)
    return ConsensusService(
        store,
        mesh=mesh,
        journal=tmp_path / f"{name}.jrnl",
        db_path=tmp_path / f"{name}.db",
        max_batch=width,
        max_delay_s=None,
        record_batches=True,
        **kwargs,
    )


def run_inprocess(store, trace, tmp_path, name, mesh=None, width=8,
                  **kwargs):
    """The in-process reference: the trace through plain ``submit``."""

    async def main():
        service = _service(store, tmp_path, name, mesh=mesh, width=width,
                           **kwargs)
        futures = []
        async with service:
            for market_id, signals, outcome in trace:
                futures.append(service.submit(market_id, signals, outcome))
            await service.drain()
        return service, [f.result() for f in futures]

    service, results = asyncio.run(main())
    store.sync()
    return service, results


def run_over_wire(store, trace, tmp_path, name, mesh=None, width=8,
                  misbehave=None, **kwargs):
    """The same trace offered by ONE pipelined blocking client over a
    real socket (submission order = wire order = the admitted trace).
    ``misbehave(port)`` runs hostile raw-socket traffic BEFORE the real
    trace — the robustness tests' injection point."""

    async def main():
        service = _service(store, tmp_path, name, mesh=mesh, width=width,
                           **kwargs)
        server = await ConsensusServer(service).start()
        loop = asyncio.get_running_loop()

        def drive():
            if misbehave is not None:
                misbehave(server.port)
            with ConsensusClient(port=server.port) as client:
                return client.submit_pipelined(
                    trace, return_exceptions=False
                )

        try:
            results = await loop.run_in_executor(None, drive)
            await service.drain()
        finally:
            await server.close()
            await service.close()
        return service, results

    service, results = asyncio.run(main())
    store.sync()
    return service, results


class TestWireCodec:
    def test_roundtrip(self):
        frame = wire.encode_request(
            "m-1", [("s-1", 0.5), {"sourceId": "s-2", "probability": 0.25}],
            True, qos_class="premium", request_id=7,
        )
        kind, length, crc = wire.decode_header(frame[:wire.HEADER.size])
        assert kind == wire.KIND_REQUEST
        payload = wire.decode_payload(frame[wire.HEADER.size:], crc)
        assert payload == {
            "id": 7, "market": "m-1",
            "signals": [["s-1", 0.5], ["s-2", 0.25]],
            "outcome": True, "class": "premium",
        }

    def test_canonical_bytes(self):
        a = wire.encode_request("m", [("s", 0.5)], False, request_id=3)
        b = wire.encode_request("m", [("s", 0.5)], False, request_id=3)
        assert a == b

    def test_bad_magic(self):
        frame = bytearray(wire.encode_frame(wire.KIND_REQUEST, {}))
        frame[0] = 0x58
        with pytest.raises(wire.BadMagic):
            wire.decode_header(bytes(frame[:wire.HEADER.size]))

    def test_version_mismatch(self):
        frame = bytearray(wire.encode_frame(wire.KIND_REQUEST, {}))
        frame[4] = wire.WIRE_VERSION + 1
        with pytest.raises(wire.VersionMismatch) as excinfo:
            wire.decode_header(bytes(frame[:wire.HEADER.size]))
        assert excinfo.value.got == wire.WIRE_VERSION + 1

    def test_oversized_refused_before_allocation(self):
        header = wire.HEADER.pack(
            wire.MAGIC, wire.WIRE_VERSION, wire.KIND_REQUEST, 0,
            wire.MAX_FRAME_BYTES + 1, 0,
        )
        with pytest.raises(wire.FrameTooLarge):
            wire.decode_header(header)

    def test_crc_mismatch(self):
        frame = bytearray(wire.encode_frame(wire.KIND_ERROR, {"code": "shed",
                                                              "message": ""}))
        frame[-1] ^= 0xFF
        _kind, length, crc = wire.decode_header(
            bytes(frame[:wire.HEADER.size])
        )
        with pytest.raises(wire.ChecksumMismatch):
            wire.decode_payload(bytes(frame[wire.HEADER.size:]), crc)

    def test_truncated_header(self):
        with pytest.raises(wire.TruncatedFrame):
            wire.decode_header(b"BC")

    def test_error_payloads_lift_to_serve_exceptions(self):
        with pytest.raises(Overloaded) as excinfo:
            wire.raise_error_payload(
                {"code": "overloaded", "message": "x",
                 "retry_after_s": 0.25, "pending": 9}
            )
        assert excinfo.value.retry_after_s == 0.25
        assert excinfo.value.pending == 9
        with pytest.raises(ShedError):
            wire.raise_error_payload({"code": "shed", "message": "x"})
        with pytest.raises(wire.WireError):
            wire.raise_error_payload({"code": "oversized", "message": "x"})


class TestWireByteParity:
    """The headline: wire-served bytes ≡ in-process bytes over the same
    admitted-request trace — across topology hits, drift, and growth."""

    @pytest.mark.parametrize("use_mesh", [False, True],
                             ids=["flat", "sharded"])
    def test_wire_equals_inprocess(self, tmp_path, use_mesh):
        trace = mixed_trace()
        mesh = make_mesh() if use_mesh else None

        wire_store = TensorReliabilityStore()
        wire_service, wire_results = run_over_wire(
            wire_store, trace, tmp_path, "wire", mesh=mesh
        )
        ref_store = TensorReliabilityStore()
        ref_service, ref_results = run_inprocess(
            ref_store, trace, tmp_path, "ref", mesh=mesh
        )

        assert [r.market_id for r in wire_results] == [
            r.market_id for r in ref_results
        ]
        assert [r.consensus for r in wire_results] == [
            r.consensus for r in ref_results
        ]
        assert [r.batch_index for r in wire_results] == [
            r.batch_index for r in ref_results
        ]
        # The coalescer saw the same trace → the same batch sequence
        # (markets + outcomes per batch; the probability columns are
        # covered bit-for-bit by the byte comparisons below)...
        assert [
            (batch[0][0], batch[1]) for batch in wire_service.batch_log
        ] == [
            (batch[0][0], batch[1]) for batch in ref_service.batch_log
        ]
        # ...and every derived byte matches.
        assert journal_epochs_sans_clock(
            tmp_path / "wire.jrnl"
        ) == journal_epochs_sans_clock(tmp_path / "ref.jrnl")
        assert (tmp_path / "wire.db").read_bytes() == (
            tmp_path / "ref.db"
        ).read_bytes()

    def test_qos_classed_trace_same_bytes(self, tmp_path):
        """Class labels route admission, never settlement: the same
        trace with classes attached settles the same bytes."""
        trace = mixed_trace()
        qos = [QosClass("premium", 3600.0, 1 << 16),
               QosClass("besteffort", 3600.0, 1 << 16)]

        plain_store = TensorReliabilityStore()
        run_inprocess(plain_store, trace, tmp_path, "plain")

        classed_store = TensorReliabilityStore()

        async def main():
            service = _service(classed_store, tmp_path, "classed", qos=qos)
            async with service:
                futures = [
                    service.submit(
                        market, signals, outcome,
                        qos_class=(
                            "premium" if i % 2 == 0 else "besteffort"
                        ),
                    )
                    for i, (market, signals, outcome) in enumerate(trace)
                ]
                await service.drain()
                return [f.result() for f in futures]

        asyncio.run(main())
        classed_store.sync()
        assert journal_epochs_sans_clock(
            tmp_path / "classed.jrnl"
        ) == journal_epochs_sans_clock(tmp_path / "plain.jrnl")
        assert (tmp_path / "classed.db").read_bytes() == (
            tmp_path / "plain.db"
        ).read_bytes()


def _raw(port):
    return socket.create_connection(("127.0.0.1", port), timeout=10.0)


def _read_error_code(sock):
    """One frame off a raw socket; returns the error payload's code."""
    header = b""
    while len(header) < wire.HEADER.size:
        chunk = sock.recv(wire.HEADER.size - len(header))
        if not chunk:
            return None
        header += chunk
    kind, length, crc = wire.decode_header(header)
    body = b""
    while len(body) < length:
        body += sock.recv(length - len(body))
    payload = wire.decode_payload(body, crc)
    assert kind == wire.KIND_ERROR
    return payload["code"]


class TestWireRobustness:
    """Hostile transport traffic: the connection dies cleanly, the
    coalescer and the journal bytes are untouched."""

    @staticmethod
    def _misbehave(port):
        # 1. Torn header: half a header, then the client dies.
        with _raw(port) as sock:
            sock.sendall(wire.MAGIC + b"\x01")
        # 2. Partial write mid-frame: a valid header claiming 64 payload
        #    bytes, 10 bytes sent, then death (the slow-client tear).
        with _raw(port) as sock:
            sock.sendall(
                wire.HEADER.pack(
                    wire.MAGIC, wire.WIRE_VERSION, wire.KIND_REQUEST, 0,
                    64, 0,
                ) + b"x" * 10
            )
        # 3. Oversized frame: refused with an explicit error frame.
        with _raw(port) as sock:
            sock.sendall(
                wire.HEADER.pack(
                    wire.MAGIC, wire.WIRE_VERSION, wire.KIND_REQUEST, 0,
                    wire.MAX_FRAME_BYTES + 1, 0,
                )
            )
            assert _read_error_code(sock) == "oversized"
            assert sock.recv(1) == b""  # ...and the connection closed
        # 4. Version mismatch: its own code, then close.
        with _raw(port) as sock:
            sock.sendall(
                wire.HEADER.pack(
                    wire.MAGIC, wire.WIRE_VERSION + 1, wire.KIND_REQUEST,
                    0, 2, 0,
                )
            )
            assert _read_error_code(sock) == "version_mismatch"
            assert sock.recv(1) == b""
        # 5. Garbage magic.
        with _raw(port) as sock:
            sock.sendall(b"HTTP/1.1 GET /\r\n" + b"\x00" * 16)
            assert _read_error_code(sock) == "bad_frame"
            assert sock.recv(1) == b""
        # 6. Corrupted payload (CRC disagrees).
        with _raw(port) as sock:
            frame = bytearray(
                wire.encode_request("m-x", [("s", 0.5)], True)
            )
            frame[-1] ^= 0xFF
            sock.sendall(bytes(frame))
            assert _read_error_code(sock) == "bad_frame"
            assert sock.recv(1) == b""
        # 7. A response frame from a "client": protocol violation.
        with _raw(port) as sock:
            sock.sendall(wire.encode_frame(wire.KIND_RESPONSE, {"id": 0}))
            assert _read_error_code(sock) == "bad_frame"
            assert sock.recv(1) == b""
        # 8. Well-framed request with a non-integer id: refused as
        #    bad_request BEFORE submit — every reply path echoes the id
        #    through int(), so discovering it at respond time would kill
        #    the reply task after the request settled and the client
        #    would never get a frame.
        with _raw(port) as sock:
            sock.sendall(
                wire.encode_frame(
                    wire.KIND_REQUEST,
                    {
                        "id": "abc", "market": "m-x",
                        "signals": [["s", 0.5]], "outcome": True,
                    },
                )
            )
            assert _read_error_code(sock) == "bad_request"

    def test_violations_leave_bytes_untouched(self, tmp_path):
        trace = mixed_trace()
        hostile_store = TensorReliabilityStore()
        service, results = run_over_wire(
            hostile_store, trace, tmp_path, "hostile",
            misbehave=self._misbehave,
        )
        assert len(results) == len(trace)
        clean_store = TensorReliabilityStore()
        run_over_wire(clean_store, trace, tmp_path, "clean")
        assert journal_epochs_sans_clock(
            tmp_path / "hostile.jrnl"
        ) == journal_epochs_sans_clock(tmp_path / "clean.jrnl")
        assert (tmp_path / "hostile.db").read_bytes() == (
            tmp_path / "clean.db"
        ).read_bytes()


class TestShedRankKey:
    def test_widest_band_first_then_arrival(self):
        ranked = sorted(
            [
                ("narrow", shed_rank_key(0.01, 0)),
                ("wide", shed_rank_key(0.4, 3)),
                ("mid", shed_rank_key(0.2, 1)),
                ("unknown-old", shed_rank_key(None, 2)),
                ("unknown-new", shed_rank_key(None, 5)),
            ],
            key=lambda pair: pair[1],
        )
        assert [name for name, _ in ranked] == [
            "wide", "mid", "narrow", "unknown-old", "unknown-new",
        ]

    def test_tie_breaks_oldest_first(self):
        assert shed_rank_key(0.3, 1) < shed_rank_key(0.3, 2)


class TestVarianceAwareShedding:
    """Acceptance: shed order is a pure function of (class, stderr
    ranking, arrival order), pinned by a fixed trace."""

    def test_fixed_trace_fixed_shed_sequence(self):
        """Budget 3; arrivals 4..6 each shed the widest pending market:
        m-wide (0.40), then m-mid (0.20), then m-narrow (0.05)."""
        first = self._collect_victims()
        second = self._collect_victims()
        assert first == ["m-wide", "m-mid", "m-narrow"]
        assert second == first  # same trace, same order, run to run

    def _collect_victims(self):
        store = TensorReliabilityStore()
        victims = []

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                qos=[QosClass("be", 3600.0, 3, policy="shed_oldest")],
            )
            service.seed_band_stderr(
                {"m-wide": 0.40, "m-mid": 0.20, "m-narrow": 0.05}
            )
            pending = {}
            for market in ("m-narrow", "m-wide", "m-mid"):
                pending[market] = service.submit(
                    market, [("s", 0.6)], True, qos_class="be"
                )
            for i in range(3):
                overflow = service.submit(
                    f"m-fresh-{i}", [("s", 0.6)], True, qos_class="be"
                )
                pending[f"m-fresh-{i}"] = overflow
                for market, future in list(pending.items()):
                    if future.done() and isinstance(
                        future.exception(), ShedError
                    ):
                        victims.append(market)
                        del pending[market]
            await service.drain()
            await service.close()

        asyncio.run(main())
        return victims

    def test_malformed_request_cannot_evict_pending(self):
        """Signal validation runs BEFORE the admission decision: a
        malformed arrival against a full shed_oldest budget refuses on
        its own defect — it must never first shed a healthy pending
        request and then fail (via the wire that ordering would let one
        bad frame kill one legitimate in-flight request per send)."""
        store = TensorReliabilityStore()

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                qos=[QosClass("be", 3600.0, 2, policy="shed_oldest")],
            )
            first = service.submit("m-a", [("s", 0.6)], True)
            second = service.submit("m-b", [("s", 0.6)], True)
            with pytest.raises(ValueError):
                service.submit("m-c", [("s", 0.6, "extra")], True)
            with pytest.raises(ValueError):
                service.submit("m-d", [("s", "not-a-prob")], True)
            # Both healthy requests are still pending — no victim was
            # taken for an arrival that could never be admitted.
            assert not first.done() and not second.done()
            snap = service.qos_snapshot()
            assert snap["be"]["counts"]["shed"] == 0
            assert snap["be"]["pending"] == 2
            await service.drain()
            await service.close()

        asyncio.run(main())

    def test_no_stderr_degrades_to_shed_oldest(self):
        store = TensorReliabilityStore()
        victims = []

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                qos=[QosClass("be", 3600.0, 2, policy="shed_oldest")],
            )
            first = service.submit("m-a", [("s", 0.6)], True)
            second = service.submit("m-b", [("s", 0.6)], True)
            service.submit("m-c", [("s", 0.6)], True)
            assert isinstance(first.exception(), ShedError)
            assert not second.done() or second.exception() is None
            victims.append("m-a")
            await service.drain()
            await service.close()

        asyncio.run(main())
        assert victims == ["m-a"]


class TestQosClasses:
    def test_validation(self):
        with pytest.raises(ValueError, match="name"):
            QosClass("bad name", 1.0, 4)
        with pytest.raises(ValueError, match="slo_s"):
            QosClass("x", 0.0, 4)
        with pytest.raises(ValueError, match="policy"):
            QosClass("x", 1.0, 4, policy="drop_all")
        with pytest.raises(ValueError, match="duplicate"):
            ConsensusService(
                TensorReliabilityStore(),
                qos=[QosClass("x", 1.0, 4), QosClass("x", 2.0, 4)],
            )

    def test_unknown_class_and_unclassed_service_raise(self):
        store = TensorReliabilityStore()

        async def main():
            service = ConsensusService(
                store, max_delay_s=None,
                qos=[QosClass("premium", 1.0, 4)],
            )
            with pytest.raises(ValueError, match="unknown QoS class"):
                service.submit("m", [("s", 0.5)], True, qos_class="nope")
            await service.close()
            unclassed = ConsensusService(store, max_delay_s=None)
            with pytest.raises(ValueError, match="declared no qos"):
                unclassed.submit("m", [("s", 0.5)], True,
                                 qos_class="premium")
            await unclassed.close()

        asyncio.run(main())

    def test_per_class_budget_is_isolated(self):
        """The best-effort budget refusing never touches premium."""
        store = TensorReliabilityStore()

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                qos=[QosClass("premium", 3600.0, 64),
                     QosClass("be", 3600.0, 2)],
            )
            service.submit("m-1", [("s", 0.6)], True, qos_class="be")
            service.submit("m-2", [("s", 0.6)], True, qos_class="be")
            with pytest.raises(Overloaded):
                service.submit("m-3", [("s", 0.6)], True, qos_class="be")
            # Premium admits freely at the same moment.
            future = service.submit(
                "m-4", [("s", 0.6)], True, qos_class="premium"
            )
            snap = service.qos_snapshot()
            assert snap["be"]["counts"]["rejected"] == 1
            assert snap["premium"]["counts"]["rejected"] == 0
            await service.drain()
            await future
            await service.close()
            return service

        service = asyncio.run(main())
        snap = service.qos_snapshot()
        assert snap["premium"]["counts"]["met"] == 1
        assert snap["be"]["counts"]["met"] == 2
        # Goodput is per class: be = 2/3 (the refusal counts against),
        # premium = 1/1.
        assert snap["premium"]["goodput_within_slo"] == 1.0
        assert abs(snap["be"]["goodput_within_slo"] - 2 / 3) < 1e-12

    def test_per_class_burn_shedding_with_probe(self):
        """A class burning its own budget refuses ITS arrivals below its
        bound (every Nth admitted as a probe); the other class and the
        service-wide bound never notice."""
        store = TensorReliabilityStore()

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                qos=[
                    QosClass("premium", 3600.0, 64),
                    QosClass(
                        "be", 3600.0, 64, shed_when_burning=True,
                        burn_probe_every=2, objective_goodput=0.5,
                        burn_windows=(BurnWindow(2, 4, 1.0),),
                    ),
                ],
            )
            monitor = service._qos_states["be"].health
            for _ in range(8):
                monitor.record("violated")
            assert monitor.burning
            outcomes = []
            for i in range(4):
                try:
                    service.submit(
                        f"m-{i}", [("s", 0.6)], True, qos_class="be"
                    )
                    outcomes.append("admitted")
                except Overloaded:
                    outcomes.append("rejected")
            # burn_probe_every=2: reject, probe, reject, probe.
            assert outcomes == [
                "rejected", "admitted", "rejected", "admitted",
            ]
            # Premium admits throughout.
            service.submit("m-p", [("s", 0.6)], True, qos_class="premium")
            await service.drain()
            await service.close()
            return service

        service = asyncio.run(main())
        snap = service.qos_snapshot()
        assert snap["be"]["counts"]["rejected"] == 2
        assert snap["premium"]["counts"]["rejected"] == 0

    def test_class_shed_keeps_aggregate_counters_consistent(self):
        """A class-scoped shed replaces its victim: the arrival is
        counted admitted ONCE (review-pass regression: consulting the
        global controller after count_shed double-counted it, so
        serve.admitted could exceed serve.requests)."""
        from bayesian_consensus_engine_tpu import obs

        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            store = TensorReliabilityStore()

            async def main():
                service = ConsensusService(
                    store, steps=1, now=NOW, max_batch=64,
                    max_delay_s=None,
                    qos=[QosClass("be", 3600.0, 2,
                                  policy="shed_oldest")],
                )
                for i in range(6):
                    service.submit(f"m-{i}", [("s", 0.6)], True)
                await service.drain()
                await service.close()

            asyncio.run(main())
            counters = registry.export()["counters"]
            assert counters["serve.requests"] == 6
            # 6 arrivals, budget 2: four sheds, every arrival admitted
            # exactly once — admitted == requests, never more.
            assert counters["serve.admitted"] == 6
            assert counters["serve.shed"] == 4
            assert counters.get("serve.rejected", 0) == 0
            assert counters["serve.qos.be.admitted"] == 6
            assert counters["serve.qos.be.shed"] == 4
        finally:
            obs.set_metrics_registry(previous)

    def test_first_declared_class_is_default(self):
        store = TensorReliabilityStore()

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                qos=[QosClass("premium", 3600.0, 64),
                     QosClass("be", 3600.0, 64)],
            )
            future = service.submit("m-1", [("s", 0.6)], True)
            await service.drain()
            await future
            await service.close()
            return service

        service = asyncio.run(main())
        snap = service.qos_snapshot()
        assert snap["premium"]["offered"] == 1
        assert snap["be"]["offered"] == 0


class TestQosLedger:
    """extras.qos → merged per-class bands, rendered and diffed."""

    @staticmethod
    def _record(counts_by_class, leg="e2e_netserve.overload"):
        return {
            "leg": leg,
            "value": 1.0,
            "unit": "s",
            "extras": {
                "qos": {
                    name: {"slo_s": slo, "counts": counts}
                    for name, (slo, counts) in counts_by_class.items()
                }
            },
        }

    def test_counts_sum_across_repeats(self):
        from bayesian_consensus_engine_tpu.obs.ledger import min_of_repeats

        records = [
            self._record({
                "premium": (0.05, {"met": 9, "violated": 1}),
                "be": (1.0, {"met": 4, "shed": 6}),
            }),
            self._record({
                "premium": (0.05, {"met": 8, "violated": 2}),
                "be": (1.0, {"met": 5, "shed": 5}),
            }),
        ]
        band = min_of_repeats(records, "e2e_netserve.overload")
        assert band["qos"]["premium"]["counts"] == {
            "met": 17, "violated": 3,
        }
        assert band["qos"]["premium"]["goodput_within_slo"] == 0.85
        assert band["qos"]["be"]["slo_violations"] == 11

    def test_vocabulary_mismatch_refuses(self):
        from bayesian_consensus_engine_tpu.obs.ledger import min_of_repeats

        records = [
            self._record({"premium": (0.05, {"met": 1})}),
            self._record({"gold": (0.05, {"met": 1})}),
        ]
        with pytest.raises(ValueError, match="vocabularies differ"):
            min_of_repeats(records, "e2e_netserve.overload")

    def test_slo_mismatch_refuses(self):
        from bayesian_consensus_engine_tpu.obs.ledger import min_of_repeats

        records = [
            self._record({"premium": (0.05, {"met": 1})}),
            self._record({"premium": (0.5, {"met": 1})}),
        ]
        with pytest.raises(ValueError, match="slo_s"):
            min_of_repeats(records, "e2e_netserve.overload")

    def test_render_and_diff_carry_class_columns(self):
        from bayesian_consensus_engine_tpu.obs.ledger import (
            diff_bands,
            render,
            render_diff,
        )

        old = [self._record({"premium": (0.05, {"met": 8, "violated": 2})})]
        new = [self._record({"premium": (0.05, {"met": 6, "violated": 4})})]
        table = render(new)
        assert "premium: goodput 60.0% slo 4" in table
        diff = diff_bands(old, new)
        entry = diff["e2e_netserve.overload"]
        assert entry["metrics"]["qos.premium.goodput"] == {
            "old": 0.8, "new": 0.6,
        }
        assert "qos.premium.goodput 0.8->0.6" in render_diff(diff)


class TestServeCli:
    """`bce-tpu serve`: the banner/summary contract, end to end over a
    real subprocess socket."""

    def test_serve_round_trip(self, tmp_path):
        import json
        import subprocess
        import sys

        proc = subprocess.Popen(
            [
                sys.executable, "-m", "bayesian_consensus_engine_tpu.cli",
                "serve", "--port", "0", "--duration", "20",
                "--qos", "premium:5.0:256",
                "--qos", "besteffort:5.0:64:shed_oldest",
                "--max-delay-ms", "1",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            banner = json.loads(proc.stdout.readline())
            assert banner["classes"] == ["premium", "besteffort"]
            with ConsensusClient(port=banner["port"]) as client:
                result = client.submit(
                    "m-1", [("s-1", 0.7)], True, qos_class="premium"
                )
                assert result.market_id == "m-1"
                assert 0.0 <= result.consensus <= 1.0
        finally:
            proc.terminate()
            proc.wait(timeout=30)

    def test_sigint_lands_the_exit_summary(self):
        """Ctrl-C in the default run-until-interrupted mode still
        drains and prints the documented per-class summary JSON —
        SIGINT routes through the stop event instead of cancelling the
        serve coroutine before the summary is built."""
        import json
        import signal as _signal
        import subprocess
        import sys

        proc = subprocess.Popen(
            [
                sys.executable, "-m", "bayesian_consensus_engine_tpu.cli",
                "serve", "--port", "0", "--duration", "0",
                "--qos", "premium:5.0:256",
                "--max-delay-ms", "1",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            banner = json.loads(proc.stdout.readline())
            with ConsensusClient(port=banner["port"]) as client:
                result = client.submit(
                    "m-1", [("s-1", 0.7)], True, qos_class="premium"
                )
                assert result.market_id == "m-1"
            proc.send_signal(_signal.SIGINT)
            stdout, _stderr = proc.communicate(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        assert proc.returncode == 0
        summary = json.loads(stdout)
        assert summary["served"]["requests"] == 1
        assert "premium" in summary["qos"]
