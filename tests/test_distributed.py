"""Multi-host plumbing on the virtual 8-device CPU mesh.

Single-process degradation must be exact: the hybrid mesh reduces to the
plain local mesh, global_block/global_market round-trip through local_view,
and the sharded cycle produces identical numbers through the distributed
assembly path as through plain device_put.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle,
    init_block_state,
    make_mesh,
)
from bayesian_consensus_engine_tpu.parallel.distributed import (
    _band_from_intervals,
    global_block,
    global_market,
    init_distributed,
    local_view,
    make_hybrid_mesh,
    process_market_rows,
)
from bayesian_consensus_engine_tpu.parallel.mesh import MARKETS_AXIS, SOURCES_AXIS

M, K = 32, 16


class TestInitDistributed:
    def test_single_process_noop(self):
        info = init_distributed()
        assert info["process_index"] == 0
        assert info["process_count"] == 1
        assert info["global_devices"] == 8

    def test_num_processes_one_noop(self):
        info = init_distributed(num_processes=1)
        assert info["process_count"] == 1

    def test_runtime_probe_api_still_public(self):
        # _runtime_already_initialized leans on jax.distributed.is_initialized;
        # fail loudly if a JAX upgrade moves it (the except-fallback would
        # otherwise silently degrade idempotence detection). JAX builds that
        # never had the probe fall back to the module's own flag by design.
        import jax

        if not hasattr(jax.distributed, "is_initialized"):
            pytest.skip(
                "this JAX has no jax.distributed.is_initialized; "
                "_runtime_already_initialized uses its own flag"
            )
        assert jax.distributed.is_initialized() is False

    def test_cluster_bringup_failure_surfaces(self):
        # The test backend is already initialised (conftest touched JAX), so
        # a genuine multi-process bring-up must FAIL LOUDLY here — silently
        # degrading to a single-process run is the bug mode this guards.
        with pytest.raises(RuntimeError):
            init_distributed(
                coordinator_address="127.0.0.1:1",
                num_processes=2,
                process_id=0,
            )


class TestBandFromIntervals:
    def test_contiguous_tiling_collapses(self):
        assert _band_from_intervals({(0, 4), (4, 8), (8, 12)}) == (0, 12)

    def test_duplicate_intervals_ok(self):
        # Replicas along the sources axis present identical row slices.
        assert _band_from_intervals({(4, 8), (4, 8)}) == (4, 8)

    def test_single_interval(self):
        assert _band_from_intervals({(16, 32)}) == (16, 32)

    def test_gap_raises(self):
        # Interleaved ownership (another process holds (4, 8)) must never
        # collapse to the hull (0, 12).
        with pytest.raises(ValueError, match="not contiguous"):
            _band_from_intervals({(0, 4), (8, 12)})

    def test_overlap_raises(self):
        with pytest.raises(ValueError, match="not contiguous"):
            _band_from_intervals({(0, 6), (4, 8)})

    def test_empty_raises(self):
        with pytest.raises(ValueError, match="owns no devices"):
            _band_from_intervals(set())


class TestHybridMesh:
    def test_default_shape(self):
        mesh = make_hybrid_mesh()
        assert mesh.shape[MARKETS_AXIS] == 8
        assert mesh.shape[SOURCES_AXIS] == 1

    def test_explicit_ici_shape(self):
        mesh = make_hybrid_mesh(ici_shape=(4, 2))
        assert mesh.shape[MARKETS_AXIS] == 4
        assert mesh.shape[SOURCES_AXIS] == 2

    def test_granule_split(self):
        # Force 2 granules of 4 devices: markets axis = 2 x ici_markets.
        mesh = make_hybrid_mesh(ici_shape=(2, 2), num_granules=2)
        assert mesh.shape[MARKETS_AXIS] == 4
        assert mesh.shape[SOURCES_AXIS] == 2

    def test_bad_ici_shape_raises(self):
        with pytest.raises(ValueError, match="devices per granule"):
            make_hybrid_mesh(ici_shape=(3, 2))


class TestGlobalArrays:
    def test_round_trip_block(self):
        mesh = make_hybrid_mesh(ici_shape=(4, 2))
        rng = np.random.default_rng(0)
        full = rng.random((M, K)).astype(np.float32)
        lo, hi = process_market_rows(M, mesh)
        assert (lo, hi) == (0, M)  # single process owns everything
        arr = global_block(full[lo:hi], mesh, M)
        assert arr.shape == (M, K)
        np.testing.assert_array_equal(local_view(arr), full)

    def test_round_trip_market_vector(self):
        mesh = make_hybrid_mesh()
        vec = np.arange(M, dtype=np.float32)
        arr = global_market(vec, mesh, M)
        np.testing.assert_array_equal(local_view(arr), vec)

    def test_cycle_through_distributed_assembly(self):
        mesh = make_hybrid_mesh(ici_shape=(4, 2))
        rng = np.random.default_rng(1)
        probs_np = rng.random((M, K)).astype(np.float32)
        mask_np = rng.random((M, K)) < 0.8
        outcome_np = rng.random(M) < 0.5

        probs = global_block(probs_np, mesh, M)
        mask = global_block(mask_np, mesh, M)
        outcome = global_market(outcome_np, mesh, M)
        cold = init_block_state(M, K)
        state = MarketBlockState(
            *(global_block(np.asarray(x), mesh, M) for x in cold)
        )
        got = build_cycle(mesh, donate=False)(
            probs, mask, outcome, state, jnp.float32(1.0)
        )

        plain = build_cycle(make_mesh((8, 1)), donate=False)(
            jnp.asarray(probs_np),
            jnp.asarray(mask_np),
            jnp.asarray(outcome_np),
            init_block_state(M, K),
            jnp.float32(1.0),
        )
        np.testing.assert_allclose(
            np.asarray(got.consensus), np.asarray(plain.consensus), rtol=2e-6
        )
        np.testing.assert_array_equal(
            local_view(got.state.reliability),
            np.asarray(plain.state.reliability),
        )

    def test_local_view_requires_shards(self):
        mesh = make_hybrid_mesh()
        arr = global_market(np.zeros(M, np.float32), mesh, M)
        assert local_view(arr).shape == (M,)
