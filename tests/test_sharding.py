"""Sharded cycle on a virtual 8-device CPU mesh.

Every mesh topology must produce the same numbers as the unsharded cycle,
and the cycle itself must preserve the scalar engine's semantics (decay on
read, update undecayed state, cold-start priors, 0.5-threshold correctness).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle,
    init_block_state,
    make_mesh,
)
from bayesian_consensus_engine_tpu.utils.config import (
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
)


M, K = 32, 16  # divisible by every mesh shape used below


def _random_inputs(seed=0):
    rng = np.random.default_rng(seed)
    probs = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)
    mask = jnp.asarray(rng.random((M, K)) < 0.7)
    outcome = jnp.asarray(rng.random(M) < 0.5)
    state = MarketBlockState(
        reliability=jnp.asarray(rng.uniform(0.1, 1.0, (M, K)), dtype=jnp.float32),
        confidence=jnp.asarray(rng.uniform(0.0, 1.0, (M, K)), dtype=jnp.float32),
        updated_days=jnp.asarray(
            rng.choice([0.0, 5.0, 40.0, 400.0], (M, K)), dtype=jnp.float32
        ),
        exists=jnp.asarray(rng.random((M, K)) < 0.6),
    )
    now = jnp.float32(401.0)
    return probs, mask, outcome, state, now


def _as_np(result):
    return jax.tree.map(np.asarray, result)


class TestMeshTopologies:
    def test_eight_devices_available(self):
        assert jax.device_count() == 8

    @pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
    def test_sharded_matches_unsharded(self, shape):
        inputs = _random_inputs()
        baseline = _as_np(build_cycle(mesh=None, donate=False)(*inputs))
        mesh = make_mesh(shape)
        sharded = _as_np(build_cycle(mesh=mesh, donate=False)(*inputs))

        np.testing.assert_allclose(
            sharded.consensus, baseline.consensus, rtol=1e-6, equal_nan=True
        )
        np.testing.assert_allclose(sharded.confidence, baseline.confidence, rtol=1e-6)
        np.testing.assert_allclose(
            sharded.total_weight, baseline.total_weight, rtol=1e-6
        )
        for field in MarketBlockState._fields:
            np.testing.assert_allclose(
                getattr(sharded.state, field),
                getattr(baseline.state, field),
                rtol=1e-6,
                err_msg=field,
            )

    def test_bad_mesh_shape_rejected(self):
        with pytest.raises(ValueError, match="needs 6 devices"):
            make_mesh((3, 2))


class TestCycleSemantics:
    def test_cold_batch_consensus_is_unweighted_mean(self):
        probs = jnp.full((4, 8), 0.7, dtype=jnp.float32)
        mask = jnp.ones((4, 8), dtype=bool)
        outcome = jnp.ones(4, dtype=bool)
        state = init_block_state(4, 8)
        result = build_cycle(donate=False)(probs, mask, outcome, state, jnp.float32(10.0))
        np.testing.assert_allclose(np.asarray(result.consensus), 0.7, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(result.confidence), DEFAULT_CONFIDENCE, rtol=1e-6)

    def test_update_moves_reliability_by_capped_step(self):
        probs = jnp.array([[0.9, 0.2]], dtype=jnp.float32)  # slot0 right, slot1 wrong
        mask = jnp.ones((1, 2), dtype=bool)
        outcome = jnp.array([True])
        state = init_block_state(1, 2)
        result = build_cycle(donate=False)(probs, mask, outcome, state, jnp.float32(1.0))
        rel = np.asarray(result.state.reliability)
        assert rel[0, 0] == pytest.approx(DEFAULT_RELIABILITY + 0.10, rel=1e-6)
        assert rel[0, 1] == pytest.approx(DEFAULT_RELIABILITY - 0.10, rel=1e-6)
        assert np.asarray(result.state.exists).all()

    def test_boundary_probability_counts_correct(self):
        # p == 0.5 predicts True (reference: market.py:299).
        probs = jnp.array([[0.5]], dtype=jnp.float32)
        mask = jnp.ones((1, 1), dtype=bool)
        state = init_block_state(1, 1)
        up = build_cycle(donate=False)(
            probs, mask, jnp.array([True]), state, jnp.float32(1.0)
        )
        assert float(up.state.reliability[0, 0]) > DEFAULT_RELIABILITY

    def test_decay_applies_to_read_not_to_update_base(self):
        # Stored 0.8 updated 30 (half-life) days ago: consensus sees 0.45,
        # but a correct outcome updates 0.8 → 0.9 (undecayed base).
        state = MarketBlockState(
            reliability=jnp.array([[0.8]], dtype=jnp.float32),
            confidence=jnp.array([[0.5]], dtype=jnp.float32),
            updated_days=jnp.array([[10.0]], dtype=jnp.float32),
            exists=jnp.array([[True]]),
        )
        probs = jnp.array([[0.9]], dtype=jnp.float32)
        mask = jnp.ones((1, 1), dtype=bool)
        result = build_cycle(donate=False)(
            probs, mask, jnp.array([True]), state, jnp.float32(40.0)
        )
        assert float(result.total_weight[0]) == pytest.approx(0.45, rel=1e-5)
        assert float(result.state.reliability[0, 0]) == pytest.approx(0.9, rel=1e-6)
        assert float(result.state.updated_days[0, 0]) == pytest.approx(40.0)

    def test_masked_slots_untouched(self):
        state = init_block_state(1, 4)
        probs = jnp.array([[0.9, 0.9, 0.9, 0.9]], dtype=jnp.float32)
        mask = jnp.array([[True, False, True, False]])
        result = build_cycle(donate=False)(
            probs, mask, jnp.array([True]), state, jnp.float32(1.0)
        )
        exists = np.asarray(result.state.exists)
        np.testing.assert_array_equal(exists, mask)
        rel = np.asarray(result.state.reliability)
        assert rel[0, 1] == DEFAULT_RELIABILITY  # untouched
        assert rel[0, 0] == pytest.approx(0.6, rel=1e-6)

    def test_zero_weight_market_nan_consensus(self):
        probs = jnp.zeros((1, 2), dtype=jnp.float32)
        mask = jnp.zeros((1, 2), dtype=bool)  # no signals at all
        state = init_block_state(1, 2)
        result = build_cycle(donate=False)(
            probs, mask, jnp.array([True]), state, jnp.float32(1.0)
        )
        assert np.isnan(float(result.consensus[0]))
        assert float(result.confidence[0]) == 0.0

    def test_cycle_composes_over_steps(self):
        """Two consecutive correct outcomes drive reliability up two steps."""
        cycle = build_cycle(donate=False)
        probs = jnp.array([[0.9]], dtype=jnp.float32)
        mask = jnp.ones((1, 1), dtype=bool)
        state = init_block_state(1, 1)
        r1 = cycle(probs, mask, jnp.array([True]), state, jnp.float32(1.0))
        r2 = cycle(probs, mask, jnp.array([True]), r1.state, jnp.float32(2.0))
        assert float(r2.state.reliability[0, 0]) == pytest.approx(0.7, rel=1e-6)
        assert float(r2.state.confidence[0, 0]) == pytest.approx(
            0.25 + 0.75 * 0.1 + (1 - 0.325) * 0.1 + 0.0, rel=1e-4
        ) or float(r2.state.confidence[0, 0]) == pytest.approx(0.3925, rel=1e-5)


class TestSlotMajorLayout:
    def test_slot_major_matches_row_major(self):
        probs, mask, outcome, state, now = _random_inputs(2)
        baseline = _as_np(build_cycle(mesh=None, donate=False)(probs, mask, outcome, state, now))
        transposed = MarketBlockState(*(x.T for x in state))
        slot = build_cycle(mesh=None, donate=False, slot_major=True)(
            probs.T, mask.T, outcome, transposed, now
        )
        slot = _as_np(slot)
        np.testing.assert_allclose(
            slot.consensus, baseline.consensus, rtol=1e-6, equal_nan=True
        )
        for field in MarketBlockState._fields:
            np.testing.assert_allclose(
                getattr(slot.state, field).T,
                getattr(baseline.state, field),
                rtol=1e-6,
                err_msg=field,
            )

    @pytest.mark.parametrize("shape", [(4, 2), (1, 8)])
    def test_slot_major_sharded(self, shape):
        probs, mask, outcome, state, now = _random_inputs(3)
        baseline = _as_np(build_cycle(mesh=None, donate=False)(probs, mask, outcome, state, now))
        mesh = make_mesh(shape)
        transposed = MarketBlockState(*(x.T for x in state))
        slot = _as_np(
            build_cycle(mesh=mesh, donate=False, slot_major=True)(
                probs.T, mask.T, outcome, transposed, now
            )
        )
        np.testing.assert_allclose(
            slot.consensus, baseline.consensus, rtol=1e-6, equal_nan=True
        )


class TestCycleLoop:
    def test_loop_equals_repeated_single_cycles(self):
        from bayesian_consensus_engine_tpu.parallel import build_cycle_loop

        probs, mask, outcome, state, _now = _random_inputs(4)
        single = build_cycle(mesh=None, donate=False)
        current = state
        for i in range(5):
            result = single(probs, mask, outcome, current, jnp.float32(100.0 + i))
            current = result.state

        loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
        transposed = MarketBlockState(*(x.T for x in state))
        loop_state, loop_consensus = loop(
            probs.T, mask.T, outcome, transposed, jnp.float32(100.0), 5
        )
        np.testing.assert_allclose(
            np.asarray(loop_consensus), np.asarray(result.consensus),
            rtol=1e-6, equal_nan=True,
        )
        for field in MarketBlockState._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(loop_state, field)).T,
                np.asarray(getattr(current, field)),
                rtol=1e-5,
                err_msg=field,
            )

    def test_sharded_loop_matches_unsharded(self):
        from bayesian_consensus_engine_tpu.parallel import build_cycle_loop

        probs, mask, outcome, state, _now = _random_inputs(5)
        transposed = MarketBlockState(*(x.T for x in state))
        unsharded = build_cycle_loop(mesh=None, slot_major=True, donate=False)(
            probs.T, mask.T, outcome, transposed, jnp.float32(50.0), 3
        )
        mesh = make_mesh((4, 2))
        sharded = build_cycle_loop(mesh=mesh, slot_major=True, donate=False)(
            probs.T, mask.T, outcome, transposed, jnp.float32(50.0), 3
        )
        np.testing.assert_allclose(
            np.asarray(sharded[1]), np.asarray(unsharded[1]),
            rtol=1e-6, equal_nan=True,
        )

    def test_exists_none_state_accepted(self):
        """A cycle's exists=None output state feeds back into loop and cycle."""
        from bayesian_consensus_engine_tpu.parallel import build_cycle_loop

        probs, mask, outcome, state, _now = _random_inputs(8)
        none_state = MarketBlockState(
            reliability=jnp.full((M, K), 0.5, jnp.float32),
            confidence=jnp.full((M, K), 0.25, jnp.float32),
            updated_days=jnp.zeros((M, K), jnp.float32),
            exists=None,
        )
        single = build_cycle(mesh=None, donate=False)
        r = single(probs, mask, outcome, none_state, jnp.float32(1.0))
        assert r.state.exists is None

        loop = build_cycle_loop(mesh=None, slot_major=False, donate=False)
        loop_state, loop_consensus = loop(
            probs, mask, outcome, r.state, jnp.float32(2.0), 2
        )
        assert loop_state.exists is None

        # Equivalent exists-carrying run produces identical numbers.
        full = MarketBlockState(
            none_state.reliability,
            none_state.confidence,
            none_state.updated_days,
            jnp.zeros((M, K), bool),
        )
        cur = single(probs, mask, outcome, full, jnp.float32(1.0)).state
        ref_state, ref_consensus = loop(probs, mask, outcome, cur, jnp.float32(2.0), 2)
        np.testing.assert_allclose(
            np.asarray(loop_consensus), np.asarray(ref_consensus),
            rtol=1e-6, equal_nan=True,
        )
        np.testing.assert_allclose(
            np.asarray(loop_state.reliability), np.asarray(ref_state.reliability),
            rtol=1e-6,
        )

        # Sharded variants accept both structures too.
        mesh = make_mesh((4, 2))
        sharded_single = build_cycle(mesh=mesh, donate=False)
        sr = sharded_single(probs, mask, outcome, none_state, jnp.float32(1.0))
        assert sr.state.exists is None
        sharded_loop = build_cycle_loop(mesh=mesh, slot_major=False, donate=False)
        ss, sc = sharded_loop(probs, mask, outcome, sr.state, jnp.float32(2.0), 2)
        assert ss.exists is None
        np.testing.assert_allclose(
            np.asarray(sc), np.asarray(loop_consensus), rtol=1e-6, equal_nan=True
        )

    def test_padded_loop_matches_unpadded(self):
        """Lane padding must not change any real market's outputs or state."""
        from bayesian_consensus_engine_tpu.parallel import (
            build_cycle_loop,
            pad_markets,
        )

        probs, mask, outcome, state, _now = _random_inputs(6)
        transposed = MarketBlockState(*(x.T for x in state))
        loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
        base_state, base_consensus = loop(
            probs.T, mask.T, outcome, transposed, jnp.float32(50.0), 3
        )

        p_probs, p_mask, p_outcome, p_state, total = pad_markets(
            probs.T, mask.T, outcome, transposed, multiple=128
        )
        assert total == 128 and p_probs.shape == (K, 128)
        pad_state, pad_consensus = loop(
            p_probs, p_mask, p_outcome, p_state, jnp.float32(50.0), 3
        )
        np.testing.assert_allclose(
            np.asarray(pad_consensus)[:M], np.asarray(base_consensus),
            rtol=1e-6, equal_nan=True,
        )
        assert np.isnan(np.asarray(pad_consensus)[M:]).all()
        for field in MarketBlockState._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(pad_state, field))[:, :M],
                np.asarray(getattr(base_state, field)),
                rtol=1e-6,
                err_msg=field,
            )


class TestDonation:
    def test_donated_state_buffer_reused(self):
        mesh = make_mesh((8, 1))
        cycle = build_cycle(mesh=mesh, donate=True)
        probs, mask, outcome, state, now = _random_inputs(1)
        from bayesian_consensus_engine_tpu.parallel import shard_block, shard_market

        state = MarketBlockState(*(shard_block(x, mesh) for x in state))
        result = cycle(
            shard_block(probs, mesh), shard_block(mask, mesh),
            shard_market(outcome, mesh), state, now,
        )
        # Donated input buffers are invalidated after the call.
        with pytest.raises(RuntimeError):
            _ = np.asarray(state.reliability)
        assert np.isfinite(np.asarray(result.state.reliability)).all()
