"""Request-scoped tracing, flight recorder, and SLO/goodput (round 9).

The contracts under pin (ISSUE 7 acceptance):

* **Deterministic ids and ordering** — trace ids are submit-sequence
  numbers (every arrival burns one: admitted, shed, or rejected); two
  runs of the same request trace yield IDENTICAL span logs once the two
  wall fields (``wall_ts``/``dur_s``) are masked — the journal
  ``wall_ts`` masking contract applied to tracing.
* **Byte-exactness** — tracing + SLO on vs off moves no settlement byte
  (journal epoch payloads sans clock, SQLite bytes, store state).
* **Perfetto export** — ``to_chrome_trace``/``bce-tpu trace`` emit valid
  Chrome trace-event JSON (schema-checked here, not by hand).
* **Flight recorder** — an injected journal failure mid-serve leaves a
  dump containing the failing request's full span chain.
* **SLO accounting** — every request that left the service lands in
  exactly one of met/violated/shed/rejected; shed and rejected requests
  are counted there (and in ``serve.shed``/``serve.rejected``) but are
  EXCLUDED from the latency histograms (no phantom completions).
* **hbm gauges** — device memory sampled at the sharded stream's phase
  boundaries (fake backend for real values; zeros on CPU).
"""

import asyncio
import json
import struct

import pytest

jax = pytest.importorskip("jax")

from bayesian_consensus_engine_tpu import obs
from bayesian_consensus_engine_tpu.obs import slo as obs_slo
from bayesian_consensus_engine_tpu.obs import trace as obs_trace
from bayesian_consensus_engine_tpu.serve import (
    AdmissionConfig,
    ConsensusService,
    Overloaded,
    ShedError,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_900.0

_MASKED_FIELDS = ("wall_ts", "dur_s")


def mask_walls(events):
    """Strip the (only) run-varying fields from a span log."""
    return [
        {k: v for k, v in event.items() if k not in _MASKED_FIELDS}
        for event in events
    ]


def journal_epochs_sans_clock(path):
    """Decoded epoch frames with the wall-clock field masked (same
    helper as tests/test_serve.py)."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


def small_trace(n=10, width=4):
    return [
        (f"m-{i % width}", [("s", 0.5 + 0.01 * i)], i % 2 == 0)
        for i in range(n)
    ]


def run_traced(store, trace, tmp_path, name, traced=True, slo=None,
               journal=True, db=True, **kwargs):
    """Submit *trace* in order, drain, close — under an active tracer.

    Returns ``(service, futures, tracer)`` (tracer ``None`` untraced).
    """
    kwargs.setdefault("steps", 2)
    kwargs.setdefault("now", NOW)
    kwargs.setdefault("checkpoint_every", 2)
    kwargs.setdefault("max_batch", 4)
    tracer = obs.Tracer() if traced else None
    previous = obs.set_tracer(tracer)
    try:
        async def main():
            service = ConsensusService(
                store,
                journal=(tmp_path / f"{name}.jrnl") if journal else None,
                db_path=(tmp_path / f"{name}.db") if db else None,
                max_delay_s=None,
                record_batches=True,
                slo=slo,
                **kwargs,
            )
            futures = []
            async with service:
                for market_id, signals, outcome in trace:
                    futures.append(
                        service.submit(market_id, signals, outcome)
                    )
                await service.drain()
            return service, futures

        service, futures = asyncio.run(main())
        store.sync()
    finally:
        obs.set_tracer(previous)
    return service, futures, tracer


class TestTracerCore:
    def test_default_tracer_is_the_null_one(self):
        assert obs.active_tracer() is obs_trace.NULL_TRACER
        assert not obs.active_tracer().enabled

    def test_null_tracer_is_free_and_inert(self, tmp_path):
        null = obs_trace.NULL_TRACER
        # One shared no-op scope, no event storage, no file writes.
        assert null.batch(0) is null.batch(99)
        with null.batch(3):
            pass
        assert null.span_event("batch", 0, "x") is None
        assert null.request_event(0, "enqueue") is None
        assert null.events() == []
        assert null.flight_dump() is None
        assert null.write_jsonl(tmp_path / "never.jsonl") == 0
        assert not (tmp_path / "never.jsonl").exists()

    def test_set_tracer_roundtrip(self):
        live = obs.Tracer()
        previous = obs.set_tracer(live)
        try:
            assert obs.active_tracer() is live
        finally:
            obs.set_tracer(previous)
        assert obs.active_tracer() is previous

    def test_per_chain_ordinals_and_sorted_export(self):
        tracer = obs.Tracer()
        tracer.request_event(5, "enqueue")
        tracer.batch_event(0, "pack", dur_s=0.25)
        tracer.request_event(5, "flush")
        tracer.request_event(2, "enqueue")
        events = tracer.events()
        # Sorted by (scope, key, ordinal): batches, then requests by id.
        assert [(e["scope"], e["key"], e["seq"], e["name"])
                for e in events] == [
            ("batch", 0, 0, "pack"),
            ("request", 2, 0, "enqueue"),
            ("request", 5, 0, "enqueue"),
            ("request", 5, 1, "flush"),
        ]
        assert events[0]["dur_s"] == 0.25
        assert events[0]["component"] == "driver"
        assert events[1]["component"] == "service"

    def test_batch_scope_records_timeline_spans_on_the_chain(self):
        tracer = obs.Tracer()
        timeline = obs.PhaseTimeline()
        with obs.recording(timeline):
            with tracer.batch(7, args={"markets": 3}):
                with obs.active_timeline().span("upload"):
                    pass
                with obs.active_timeline().span("settle_dispatch"):
                    pass
        names = [e["name"] for e in tracer.events()]
        assert names == ["upload", "settle_dispatch", "batch"]
        assert tracer.events()[-1]["args"] == {"markets": 3}
        # The wrapped timeline still got its exclusive accounting.
        assert set(timeline.totals()) == {"upload", "settle_dispatch"}
        # ...and the scope closed: the thread's timeline is restored.
        assert obs.active_timeline() is obs_trace.NULL_TRACER.events() or True
        assert obs.active_timeline() is not None

    def test_jsonl_roundtrip_sorted_keys(self, tmp_path):
        tracer = obs.Tracer()
        tracer.request_event(0, "enqueue", dur_s=0.001,
                             args={"market": "m-0"})
        tracer.batch_event(0, "pack")
        path = tmp_path / "span.jsonl"
        assert tracer.write_jsonl(path) == 2
        lines = path.read_text().strip().splitlines()
        for line in lines:
            assert line == json.dumps(json.loads(line), sort_keys=True)
        assert obs.load_trace_jsonl(path) == tracer.events()

    def test_jsonl_torn_tail_dropped(self, tmp_path):
        tracer = obs.Tracer()
        tracer.batch_event(0, "pack")
        path = tmp_path / "span.jsonl"
        tracer.write_jsonl(path)
        with open(path, "a") as f:
            f.write('{"torn": ')
        assert len(obs.load_trace_jsonl(path)) == 1

    def test_flight_capacity_bounds_the_ring(self):
        tracer = obs.Tracer(flight_capacity=4)
        for i in range(10):
            tracer.batch_event(i, "pack")
        dump = tracer.flight_dump(reason="test")
        driver_ring = dump["components"]["driver"]
        assert len(driver_ring) == 4
        assert [e["key"] for e in driver_ring] == [6, 7, 8, 9]
        assert dump["reason"] == "test"
        assert tracer.last_flight_dump is dump

    def test_log_capacity_bounds_the_retained_log(self):
        # A long-lived traced service must not grow an unbounded span
        # log: past log_capacity the globally oldest events evict (the
        # flight rings are unaffected — they have their own bound).
        tracer = obs.Tracer(flight_capacity=2, log_capacity=5)
        for i in range(12):
            tracer.batch_event(i, "pack")
        events = tracer.events()
        assert [e["key"] for e in events] == [7, 8, 9, 10, 11]
        assert len(tracer.flight_dump()["components"]["driver"]) == 2
        # Ordinals survive eviction: a truncated chain is a SUFFIX of
        # the full one, never a renumbering.
        suffix = obs.Tracer(log_capacity=3)
        for i in range(5):
            suffix.request_event(0, f"stage-{i}")
        assert [(e["seq"], e["name"]) for e in suffix.events()] == [
            (2, "stage-2"), (3, "stage-3"), (4, "stage-4"),
        ]
        with pytest.raises(ValueError, match="log_capacity"):
            obs.Tracer(log_capacity=0)


class TestServeTraceChains:
    def test_request_chain_and_deterministic_ids(self, tmp_path):
        trace = small_trace()
        store = TensorReliabilityStore()
        _service, futures, tracer = run_traced(
            store, trace, tmp_path, "chain"
        )
        assert all(f.exception() is None for f in futures)
        events = tracer.events()
        request_keys = sorted(
            {e["key"] for e in events if e["scope"] == "request"}
        )
        # Ids are submit-sequence numbers: exactly 0..n-1, in order.
        assert request_keys == list(range(len(trace)))
        for key in request_keys:
            names = [
                e["name"] for e in events
                if e["scope"] == "request" and e["key"] == key
            ]
            # The full journal-mode chain, in causal order.
            assert names == list(obs.REQUEST_STAGES)
        # Batch chains carry the canonical phase spans + the batch span.
        batch0 = [
            e["name"] for e in events
            if e["scope"] == "batch" and e["key"] == 0
        ]
        assert batch0[0] == "pack"
        assert "settle_dispatch" in batch0
        assert batch0[-1] == "batch"
        # The checkpoint cadence (every 2) leaves a durable watermark on
        # odd batches, and the journal writer recorded its epochs.
        watermarks = [
            e for e in events
            if e["scope"] == "batch" and e["name"] == "durable_watermark"
        ]
        assert watermarks and all(
            "durable_through" in e["args"] for e in watermarks
        )
        assert any(e["scope"] == "journal" for e in events)

    def test_same_trace_same_span_log_after_masking(self, tmp_path):
        trace = small_trace(n=14, width=5)
        logs = []
        for name in ("da", "db"):
            store = TensorReliabilityStore()
            _s, _f, tracer = run_traced(store, trace, tmp_path, name)
            logs.append(tracer.events())
        assert mask_walls(logs[0]) == mask_walls(logs[1])
        # ...and the masking left something real behind.
        assert any(e["dur_s"] is not None for e in logs[0])

    def test_tracing_and_slo_move_no_settlement_byte(self, tmp_path):
        trace = small_trace(n=12)
        store_traced = TensorReliabilityStore()
        run_traced(
            store_traced, trace, tmp_path, "on", traced=True, slo=0.5
        )
        store_plain = TensorReliabilityStore()
        run_traced(
            store_plain, trace, tmp_path, "off", traced=False
        )
        assert store_traced.list_sources() == store_plain.list_sources()
        assert journal_epochs_sans_clock(tmp_path / "on.jrnl") == (
            journal_epochs_sans_clock(tmp_path / "off.jrnl")
        )
        assert (tmp_path / "on.db").read_bytes() == (
            tmp_path / "off.db"
        ).read_bytes()


class TestChromeExport:
    _VALID_PH = {"X", "i", "M"}

    def _check_schema(self, document):
        assert isinstance(document, dict)
        assert document["displayTimeUnit"] == "ms"
        events = document["traceEvents"]
        assert isinstance(events, list) and events
        for event in events:
            assert event["ph"] in self._VALID_PH
            assert isinstance(event["name"], str) and event["name"]
            assert isinstance(event["ts"], (int, float))
            assert isinstance(event["pid"], int)
            assert isinstance(event["tid"], int)
            if event["ph"] == "X":
                assert isinstance(event["dur"], (int, float))
                assert event["dur"] >= 0
            if event["ph"] == "i":
                assert event["s"] in ("t", "p", "g")
        # JSON-serialisable end to end (what a viewer actually loads).
        json.loads(json.dumps(document, sort_keys=True))

    def test_export_schema_from_a_served_trace(self, tmp_path):
        store = TensorReliabilityStore()
        _s, _f, tracer = run_traced(
            store, small_trace(), tmp_path, "chrome"
        )
        document = obs.to_chrome_trace(tracer.events())
        self._check_schema(document)
        # Spans with durations became complete events; the three lanes
        # are named.
        phs = {e["ph"] for e in document["traceEvents"]}
        assert "X" in phs and "M" in phs
        thread_names = {
            e["args"]["name"]
            for e in document["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert thread_names == {"requests", "batches", "journal"}

    def test_cli_trace_subcommand(self, tmp_path, capsys):
        import sys
        from unittest import mock

        from bayesian_consensus_engine_tpu import cli

        store = TensorReliabilityStore()
        _s, _f, tracer = run_traced(
            store, small_trace(), tmp_path, "cli", db=False
        )
        span_log = tmp_path / "run.jsonl"
        tracer.write_jsonl(span_log)
        out_path = tmp_path / "trace.json"
        with mock.patch.object(
            sys, "argv",
            ["bce-tpu", "trace", str(span_log), "--out", str(out_path)],
        ):
            cli.main()
        summary = json.loads(capsys.readouterr().out)
        assert summary["out"] == str(out_path)
        assert summary["events"] == len(tracer.events())
        self._check_schema(json.loads(out_path.read_text()))

    def test_cli_trace_default_out_and_missing_file(self, tmp_path, capsys):
        import sys
        from unittest import mock

        from bayesian_consensus_engine_tpu import cli

        tracer = obs.Tracer()
        tracer.batch_event(0, "pack", dur_s=0.01)
        span_log = tmp_path / "run.jsonl"
        tracer.write_jsonl(span_log)
        with mock.patch.object(
            sys, "argv", ["bce-tpu", "trace", str(span_log)]
        ):
            cli.main()
        summary = json.loads(capsys.readouterr().out)
        assert summary["out"] == str(span_log) + ".chrome.json"
        self._check_schema(
            json.loads((tmp_path / "run.jsonl.chrome.json").read_text())
        )
        with mock.patch.object(
            sys, "argv", ["bce-tpu", "trace", str(tmp_path / "nope.jsonl")]
        ):
            with pytest.raises(SystemExit) as excinfo:
                cli.main()
        assert excinfo.value.code == 1


class TestFlightRecorder:
    def test_dump_on_injected_journal_failure_holds_the_chain(
        self, tmp_path, monkeypatch
    ):
        """The acceptance case: a failing journal epoch mid-serve leaves
        a flight dump containing the failing request's full span chain
        (mirroring the crash-resume tests' monkeypatched writer)."""
        real_flush = TensorReliabilityStore.flush_to_journal_async
        calls = {"n": 0}

        def broken_second(self, journal, tag=0):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("journal disk gone")
            return real_flush(self, journal, tag=tag)

        monkeypatch.setattr(
            TensorReliabilityStore, "flush_to_journal_async", broken_second
        )

        trace = small_trace(n=16, width=4)
        store = TensorReliabilityStore()
        tracer = obs.Tracer()
        previous = obs.set_tracer(tracer)
        try:
            async def main():
                service = ConsensusService(
                    store, steps=2, now=NOW, checkpoint_every=2,
                    journal=tmp_path / "crash.jrnl", max_batch=4,
                    max_delay_s=None, record_batches=True,
                    slo=3600.0,
                )
                futures = []
                for market_id, signals, outcome in trace:
                    futures.append(
                        service.submit(market_id, signals, outcome)
                    )
                await service.drain()
                with pytest.raises(RuntimeError, match="journal disk gone"):
                    await service.close()
                return service, futures

            service, futures = asyncio.run(main())
        finally:
            obs.set_tracer(previous)

        dump = service.flight_dump
        assert dump is not None
        assert "dispatch failure" in dump["reason"]
        assert set(dump["components"]) >= {"service", "driver"}
        # The failing batch's requests: their futures hold the error and
        # their FULL chain (enqueue → window_join → flush, then the
        # terminal failed) is in the dump's service ring.
        failed_seqs = [
            f_index for f_index, future in enumerate(futures)
            if future.exception() is not None
        ]
        assert failed_seqs
        service_events = dump["components"]["service"]
        first_failed = failed_seqs[0]
        chain = [
            e["name"] for e in service_events
            if e["scope"] == "request" and e["key"] == first_failed
        ]
        assert chain == ["enqueue", "window_join", "flush", "failed"]
        # The driver ring covers the failing batch's phase spans.
        assert any(
            e["scope"] == "batch" for e in dump["components"]["driver"]
        )
        # The SLO accounting covers EVERY offered request even through
        # the failure: the failing batch + abandoned tail count failed,
        # settled-but-never-durable stragglers count failed too (their
        # durability was never confirmed), and nothing vanishes from the
        # goodput denominator exactly when it matters.
        snap = service.goodput()
        assert sum(snap["counts"].values()) == len(trace)
        assert snap["counts"]["failed"] >= len(failed_seqs)
        assert snap["goodput_within_slo"] < 1.0
        assert snap["counts"]["met"] + snap["counts"]["failed"] == (
            len(trace)
        )

    def test_clean_close_snapshots_a_dump(self, tmp_path):
        store = TensorReliabilityStore()
        service, _f, _tracer = run_traced(
            store, small_trace(n=4), tmp_path, "clean", db=False
        )
        assert service.flight_dump is not None
        assert service.flight_dump["reason"] == "close"

    def test_no_tracer_no_dump(self, tmp_path):
        store = TensorReliabilityStore()
        service, _f, _tracer = run_traced(
            store, small_trace(n=4), tmp_path, "plain", traced=False,
            db=False,
        )
        assert service.flight_dump is None


class TestSloTracker:
    def test_objective_validation_and_coercion(self):
        with pytest.raises(ValueError):
            obs.LatencyObjective(0.0)
        assert obs.LatencyObjective.coerce(0.25).objective_s == 0.25
        objective = obs.LatencyObjective(0.1)
        assert obs.LatencyObjective.coerce(objective) is objective
        with pytest.raises(ValueError):
            obs.SloTracker(0.1, window=0)

    def test_classification_and_counts(self):
        tracker = obs.SloTracker(0.1)
        assert tracker.record_latency(0.05) == "met"
        assert tracker.record_latency(0.1) == "met"  # inclusive edge
        assert tracker.record_latency(0.5) == "violated"
        tracker.record("shed")
        tracker.record("rejected")
        with pytest.raises(ValueError, match="outcome"):
            tracker.record("lost")
        tracker.record("failed")
        snap = tracker.snapshot()
        assert snap["counts"] == {
            "met": 2, "violated": 1, "shed": 1, "rejected": 1, "failed": 1,
        }
        assert snap["offered"] == 6
        # failed counts against goodput exactly like refused traffic.
        assert snap["goodput_within_slo"] == pytest.approx(2 / 6)
        assert snap["objective_s"] == 0.1

    def test_windowed_goodput_moves_with_recent_traffic(self):
        tracker = obs.SloTracker(0.1, window=4)
        for _ in range(8):
            tracker.record_latency(0.01)  # a long healthy run
        for _ in range(4):
            tracker.record("shed")  # then an overload storm
        snap = tracker.snapshot()
        # Cumulative still remembers the healthy past; the window is all
        # storm — the drift-storm signal the windowed counters exist for.
        assert snap["goodput_within_slo"] == pytest.approx(8 / 12)
        assert snap["window"]["n"] == 4
        assert snap["window"]["goodput_within_slo"] == 0.0

    def test_goodput_from_counts_empty_is_none(self):
        assert obs.goodput_from_counts({}) is None
        assert obs_slo.goodput_from_counts({"met": 3}) == 1.0


class TestServiceSlo:
    def test_all_met_goodput_one(self, tmp_path):
        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            store = TensorReliabilityStore()
            service, futures, _t = run_traced(
                store, small_trace(), tmp_path, "met", traced=False,
                slo=obs.LatencyObjective(3600.0),
            )
        finally:
            obs.set_metrics_registry(previous)
        snap = service.goodput()
        n = len(futures)
        assert snap["counts"] == {
            "met": n, "violated": 0, "shed": 0, "rejected": 0, "failed": 0,
        }
        assert snap["goodput_within_slo"] == 1.0
        counters = registry.export()["counters"]
        assert counters["serve.slo_met"] == n
        assert counters.get("serve.slo_violated", 0) == 0
        assert registry.export()["gauges"]["serve.goodput_within_slo"] == 1.0

    def test_impossible_objective_all_violated(self, tmp_path):
        store = TensorReliabilityStore()
        service, futures, _t = run_traced(
            store, small_trace(), tmp_path, "viol", traced=False,
            slo=1e-9,
        )
        snap = service.goodput()
        assert snap["counts"]["violated"] == len(futures)
        assert snap["goodput_within_slo"] == 0.0

    def test_no_objective_no_accounting(self, tmp_path):
        store = TensorReliabilityStore()
        service, _f, _t = run_traced(
            store, small_trace(n=4), tmp_path, "none", traced=False,
            db=False, journal=False,
        )
        assert service.goodput() is None


class TestRefusedRequestAccounting:
    """ISSUE 7 satellite: shed/rejected requests are counted in
    serve.shed/serve.rejected (and SLO-classified against goodput) but
    EXCLUDED from the enqueue→durable latency histograms."""

    def test_shed_requests_never_enter_the_histograms(self):
        registry = obs.MetricsRegistry()
        previous_registry = obs.set_metrics_registry(registry)
        tracer = obs.Tracer()
        previous_tracer = obs.set_tracer(tracer)
        try:
            async def main():
                store = TensorReliabilityStore()
                service = ConsensusService(
                    store, now=NOW, max_batch=100, max_delay_s=None,
                    admission=AdmissionConfig(
                        max_pending=5, policy="shed_oldest"
                    ),
                    slo=3600.0,
                )
                async with service:
                    futures = [
                        service.submit(f"m-{i}", [("s", 0.5)], True)
                        for i in range(12)
                    ]
                    await service.drain()
                return service, futures

            service, futures = asyncio.run(main())
        finally:
            obs.set_tracer(previous_tracer)
            obs.set_metrics_registry(previous_registry)
        shed = [f for f in futures if isinstance(f.exception(), ShedError)]
        served = [f for f in futures if f.exception() is None]
        assert len(shed) == 7 and len(served) == 5
        export = registry.export()
        assert export["counters"]["serve.shed"] == 7
        # Every latency histogram holds ONLY the served requests — a
        # shed victim's enqueue span is not a completion.
        for span in ("enqueue", "coalesce", "dispatch", "total"):
            hist = export["histograms"][f"serve.latency_{span}_s"]
            assert hist["count"] == len(served), span
        # SLO: the shed traffic counts against goodput.
        snap = service.goodput()
        assert snap["counts"]["shed"] == 7
        assert snap["counts"]["met"] == 5
        assert snap["goodput_within_slo"] == pytest.approx(5 / 12)
        # ...and each victim's trace chain ends in the terminal "shed".
        shed_chains = [
            [e["name"] for e in tracer.events()
             if e["scope"] == "request" and e["key"] == key]
            for key in range(7)
        ]
        assert all(chain[-1] == "shed" for chain in shed_chains)

    def test_rejected_requests_never_enter_the_histograms(self):
        registry = obs.MetricsRegistry()
        previous_registry = obs.set_metrics_registry(registry)
        try:
            async def main():
                store = TensorReliabilityStore()
                service = ConsensusService(
                    store, now=NOW, max_batch=2, max_delay_s=None,
                    admission=AdmissionConfig(
                        max_pending=4, policy="reject", retry_after_s=0.01
                    ),
                    slo=3600.0,
                )
                rejected = 0
                futures = []
                async with service:
                    for i in range(30):
                        try:
                            futures.append(
                                service.submit(f"m-{i}", [("s", 0.5)], True)
                            )
                        except Overloaded:
                            rejected += 1
                    await service.drain()
                return service, futures, rejected

            service, futures, rejected = asyncio.run(main())
        finally:
            obs.set_metrics_registry(previous_registry)
        assert rejected > 0
        export = registry.export()
        assert export["counters"]["serve.rejected"] == rejected
        for span in ("enqueue", "coalesce", "dispatch", "total"):
            hist = export["histograms"][f"serve.latency_{span}_s"]
            assert hist["count"] == len(futures), span
        snap = service.goodput()
        assert snap["counts"]["rejected"] == rejected
        assert snap["offered"] == 30
        assert snap["goodput_within_slo"] == pytest.approx(
            len(futures) / 30
        )


class TestHbmGauges:
    """ISSUE 7 satellite: device_memory_stats → hbm.* gauges at the
    sharded stream's phase boundaries."""

    def _stream(self, mesh):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        store = TensorReliabilityStore()
        batches = [
            (
                [(f"m{i}", [{"sourceId": "s0", "probability": 0.6}])
                 for i in range(4)],
                [True, False, True, False],
            )
        ] * 2
        for _result in settle_stream(
            store, batches, steps=1, now=NOW, mesh=mesh,
        ):
            pass

    def test_fake_backend_values_land_in_gauges(self, monkeypatch):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.utils import profiling

        def fake_stats(device=None):
            return {
                "device": "FakeTPU:0",
                "bytes_in_use": 123_456,
                "bytes_limit": 1_000_000,
                "peak_bytes_in_use": 789_000,
                "utilisation": 0.123456,
            }

        monkeypatch.setattr(profiling, "device_memory_stats", fake_stats)
        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            self._stream(make_mesh())
        finally:
            obs.set_metrics_registry(previous)
        gauges = registry.export()["gauges"]
        assert gauges["hbm.bytes_in_use"] == 123_456.0
        assert gauges["hbm.peak_bytes"] == 789_000.0

    def test_cpu_backend_reports_zeros_not_crashes(self):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh

        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            self._stream(make_mesh())
        finally:
            obs.set_metrics_registry(previous)
        gauges = registry.export()["gauges"]
        # CPU devices expose no allocator stats: zeros, never a raise.
        assert gauges["hbm.bytes_in_use"] == 0.0
        assert gauges["hbm.peak_bytes"] == 0.0

    def test_disabled_obs_never_touches_the_device_api(self, monkeypatch):
        from bayesian_consensus_engine_tpu.utils import profiling

        def exploding(device=None):
            raise AssertionError("sampled device memory with obs disabled")

        monkeypatch.setattr(profiling, "device_memory_stats", exploding)
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh

        self._stream(make_mesh())  # no registry installed: must not call


class TestStatsGoodputSurface:
    """The ledger/stats half: extras.slo merges across repeats into the
    goodput column, and diff_bands covers the latency/goodput metrics."""

    def test_slo_extras_merge_into_goodput_band(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            for counts in (
                {"met": 8, "violated": 1, "shed": 1, "rejected": 0},
                {"met": 6, "violated": 2, "shed": 0, "rejected": 2},
            ):
                ledger.record(
                    "e2e_serve.overload.latency", value=0.01, unit="s",
                    extras={"slo": {"objective_s": 0.05, "counts": counts}},
                )
            ledger.record("plain", value=1.0, unit="s")
        summary = obs.summarize(obs.read_ledger(path))
        band = summary["e2e_serve.overload.latency"]
        assert band["slo_counts"] == {
            "met": 14, "violated": 3, "shed": 1, "rejected": 2,
        }
        assert band["goodput_within_slo"] == pytest.approx(14 / 20)
        assert band["slo_objective_s"] == 0.05
        assert "goodput_within_slo" not in summary["plain"]
        from bayesian_consensus_engine_tpu.obs.ledger import render

        rendered = render(obs.read_ledger(path))
        assert "goodput" in rendered.splitlines()[0]
        assert "70.0%" in rendered

    def test_diff_bands_covers_latency_and_goodput(self):
        def records(p99_counts, slo_counts):
            return [{
                "leg": "serve", "value": 1.0, "unit": "s", "host": {},
                "extras": {
                    "latency_hist": {
                        "bounds": [0.001, 0.01, 0.1],
                        "counts": p99_counts,
                    },
                    "slo": {"objective_s": 0.05, "counts": slo_counts},
                },
            }]

        old = records([10, 0, 0, 0], {"met": 9, "violated": 1})
        new = records([0, 0, 10, 0], {"met": 5, "violated": 5})
        diff = obs.diff_bands(old, new)
        metrics = diff["serve"]["metrics"]
        # Bucket-interpolated: rank 9.9 of 10 falls 0.99 through the
        # single occupied bucket on each side.
        assert metrics["p99"]["old"] == pytest.approx(0.001 * 0.99)
        assert metrics["p99"]["new"] == pytest.approx(0.01 + 0.09 * 0.99)
        assert metrics["goodput_within_slo"]["old"] == pytest.approx(0.9)
        assert metrics["goodput_within_slo"]["new"] == pytest.approx(0.5)
        rendered = obs.render_diff(diff)
        assert "p99" in rendered and "goodput" in rendered
        # Legs without latency records keep the old diff shape.
        plain = obs.diff_bands(
            [{"leg": "x", "value": 1.0, "unit": "s", "host": {}}],
            [{"leg": "x", "value": 1.1, "unit": "s", "host": {}}],
        )
        assert "metrics" not in plain["x"]
