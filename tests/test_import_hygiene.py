"""Import hygiene: layer policy is lint-enforced; the runtime probe backstops it.

The static half of this file's old job — "no module-level backend call
anywhere in the package" — now lives in graftlint (LY302), next to the
layer map (LY301) and the single layering allowlist
(``lint/config.LAYERING_ALLOWLIST``), so policy has exactly one home.
These tests pin that delegation: the package passes the LY rules, and the
allowlist stays empty (every entry is debt a reviewer must see).

The subprocess probe stays as the dynamic backstop: static analysis can
be fooled (getattr tricks, exec, a C extension touching XLA), but
``xla_bridge.backends_are_initialized()`` cannot. Multi-process bring-up
requires ``jax.distributed.initialize()`` to run before ANY
backend-touching call — a stray module-level ``jnp.something(...)``
constant breaks every cluster user (it happened: a module-level
``jnp.int32`` sentinel in ops/tiebreak.py broke the two-process suite).
"""

import pathlib
import subprocess
import sys

from bayesian_consensus_engine_tpu.lint import run as lint_run
from bayesian_consensus_engine_tpu.lint.config import (
    LAYERING_ALLOWLIST,
    PACKAGE,
)

_ROOT = pathlib.Path(__file__).resolve().parents[1]


def test_package_passes_the_layering_rules():
    """LY301 (layer map) + LY302 (import-time backend calls) over the package."""
    n_files, findings = lint_run([PACKAGE], select=("LY301", "LY302"))
    rendered = "\n".join(f.render() for f in findings)
    assert n_files > 20
    assert not findings, f"layering violations:\n{rendered}"


def test_layering_allowlist_is_empty():
    # One allowlist, and it is empty: an upward import needs a lint-config
    # diff this test makes loud, not a per-test special case.
    assert LAYERING_ALLOWLIST == frozenset()


_PROBE = """
import sys
sys.path.insert(0, {root!r})

import bayesian_consensus_engine_tpu
import bayesian_consensus_engine_tpu.core
import bayesian_consensus_engine_tpu.models
import bayesian_consensus_engine_tpu.ops
import bayesian_consensus_engine_tpu.parallel
import bayesian_consensus_engine_tpu.pipeline
import bayesian_consensus_engine_tpu.state
import bayesian_consensus_engine_tpu.utils

from jax._src import xla_bridge

assert not xla_bridge.backends_are_initialized(), (
    "importing the package initialised a JAX backend — "
    "jax.distributed.initialize() can no longer be called by users"
)
print("IMPORT_CLEAN")
"""


def test_package_import_leaves_backend_uninitialised():
    # Runs in a subprocess because the test session itself has long since
    # initialised the CPU backend.
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE.format(root=str(_ROOT))],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "IMPORT_CLEAN" in proc.stdout
