"""Importing the package must not initialise the JAX backend.

Multi-process bring-up requires ``jax.distributed.initialize()`` to run
before ANY backend-touching call (jax.devices, device_put, or creating a
jnp array at module import). A stray module-level ``jnp.something(...)``
constant anywhere in the package breaks every cluster user — this is the
regression test for exactly that (it happened: a module-level
``jnp.int32`` sentinel in ops/tiebreak.py broke the two-process suite).

Runs in a subprocess because the test session itself has long since
initialised the CPU backend.
"""

import pathlib
import subprocess
import sys

_ROOT = pathlib.Path(__file__).resolve().parents[1]

_PROBE = """
import sys
sys.path.insert(0, {root!r})

import bayesian_consensus_engine_tpu
import bayesian_consensus_engine_tpu.core
import bayesian_consensus_engine_tpu.models
import bayesian_consensus_engine_tpu.ops
import bayesian_consensus_engine_tpu.parallel
import bayesian_consensus_engine_tpu.pipeline
import bayesian_consensus_engine_tpu.state
import bayesian_consensus_engine_tpu.utils

from jax._src import xla_bridge

assert not xla_bridge.backends_are_initialized(), (
    "importing the package initialised a JAX backend — "
    "jax.distributed.initialize() can no longer be called by users"
)
print("IMPORT_CLEAN")
"""


def test_package_import_leaves_backend_uninitialised():
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE.format(root=str(_ROOT))],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "IMPORT_CLEAN" in proc.stdout
