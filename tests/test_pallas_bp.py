"""Round 19: the VMEM-resident belief-propagation kernel
(``ops/pallas_bp.py``) against the XLA sweep it replaces.

The acceptance contract (ISSUE 19):

* **Bit parity by matrix cell** — ``build_bp_sweep`` equals
  ``bp_sweep_math`` (mean, variance, iters_run, residual — all four,
  bit-for-bit) on {sparse deg-2, dense deg-8, edgeless, NaN-neighbour}
  × {point, moments} × {fixed-depth, adaptive early-exit}, in
  interpret mode on the tier-1 CPU backend, at forced multi-tile
  grids. Parity is structural (both trace
  :func:`~.ops.propagate.bp_row_mix`), so these tests are the
  regression net over the scaffolding around the shared row math: the
  Jacobi snapshot, the masked early-exit, the aliased VMEM windows.
* **Mesh-factorisation invisibility** — the gather-once kernel route
  produces the same bits as the single-shard reference on
  (4,2)/(2,4)/(8,1)/(1,8), ops level and through the routed fused
  program (same-mesh ``sweep_kernel="xla"`` vs ``"pallas"``).
* **Session byte parity** — ``settle_with_analytics`` with
  ``sweep_kernel="pallas"`` leaves every settlement artifact
  byte-identical (store digest, journal epochs sans wall clock,
  SQLite bytes) and every analytics output bit-identical.
* **Routing honesty** — ``sweep_kernel="auto"`` rides the ShapeTuner
  contract (knob ``sweep_kernel``): off → XLA without measuring; the
  ineligible shapes raise by name.
"""

import struct

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bayesian_consensus_engine_tpu.analytics import (
    AnalyticsOptions,
    MarketGraph,
)
from bayesian_consensus_engine_tpu.cluster.recover import store_digest
from bayesian_consensus_engine_tpu.infer import (
    InferenceOptions,
    propagate_beliefs,
)
from bayesian_consensus_engine_tpu.ops.pallas_bp import (
    build_bp_sweep,
    resolve_tile_sweep,
)
from bayesian_consensus_engine_tpu.ops.propagate import bp_sweep_math
from bayesian_consensus_engine_tpu.parallel._jax_compat import shard_map
from bayesian_consensus_engine_tpu.parallel.mesh import (
    MARKETS_AXIS,
    make_mesh,
)
from bayesian_consensus_engine_tpu.parallel.sharded import (
    MarketBlockState,
    build_cycle_analytics_loop,
)
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state.journal import JournalWriter
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_400.0

MESH_SHAPES = [(4, 2), (2, 4), (8, 1), (1, 8)]


def _workload(kind: str, m: int = 256, seed: int = 9):
    """Moment seeds + neighbour blocks for one parity-matrix cell."""
    rng = np.random.default_rng(seed)
    means = rng.random(m).astype(np.float32)
    variances = rng.uniform(1e-4, 0.05, m).astype(np.float32)
    if kind == "sparse_deg2":
        d = 2
        idx = rng.integers(0, m, (m, d)).astype(np.int32)
        idx[rng.random((m, d)) < 0.5] = -1
    elif kind == "dense_deg8":
        d = 8
        idx = rng.integers(0, m, (m, d)).astype(np.int32)
    elif kind == "edgeless":
        d = 4
        idx = np.full((m, d), -1, np.int32)
    elif kind == "nan_neighbour":
        d = 4
        idx = rng.integers(0, m, (m, d)).astype(np.int32)
        # NaN means AND NaN variances land on different rows, so both
        # exclusion paths (mean-finite, variance-finite) fire.
        means[::7] = np.nan
        variances[3::11] = np.nan
    else:  # pragma: no cover - test bug
        raise AssertionError(kind)
    w = rng.uniform(0.1, 1.5, idx.shape).astype(np.float32)
    return (
        jnp.asarray(means), jnp.asarray(variances),
        jnp.asarray(idx), jnp.asarray(w),
    )


def _assert_quad_equal(got, want, label):
    names = ("mean", "variance", "iters_run", "residual")
    for name, g, w in zip(names, got, want):
        if g is None or w is None:
            assert g is None and w is None, f"{label}:{name}"
            continue
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=f"{label}:{name}"
        )


WORKLOADS = ["sparse_deg2", "dense_deg8", "edgeless", "nan_neighbour"]


class TestBpKernelParityMatrix:
    """build_bp_sweep ≡ bp_sweep_math, every cell, interpret mode."""

    @pytest.mark.parametrize("workload", WORKLOADS)
    @pytest.mark.parametrize("moments", [True, False], ids=["moments", "point"])
    @pytest.mark.parametrize("tol", [None, 1e-4], ids=["fixed", "adaptive"])
    def test_bit_parity(self, workload, moments, tol):
        if not moments and tol is not None:
            pytest.skip("adaptive point sweep is not a routed config")
        means, variances, idx, w = _workload(workload)
        v_in = variances if moments else None
        want = bp_sweep_math(
            means, v_in, idx, w, damping=0.45, max_steps=16, tol=tol
        )
        sweep = build_bp_sweep(
            means.shape[0], idx.shape[1], 16,
            damping=0.45, tol=tol, moments=moments, interpret=True,
        )
        got = jax.jit(
            lambda v, s, i, wt: sweep(v, s if moments else None, i, wt)
        )(means, variances, idx, w)
        _assert_quad_equal(got, want, f"{workload}/{moments}/{tol}")

    @pytest.mark.parametrize("tile", [128, 64])
    def test_multi_tile_grids_move_no_bits(self, tile):
        # Forced small tiles: 2 and 4 tiles per sweep. The residual is
        # a sequential max over tile maxes — exact associativity is the
        # determinism argument; this pins it.
        means, variances, idx, w = _workload("dense_deg8")
        want = bp_sweep_math(
            means, variances, idx, w, damping=0.45, max_steps=16,
            tol=1e-4,
        )
        sweep = build_bp_sweep(
            means.shape[0], idx.shape[1], 16,
            damping=0.45, tol=1e-4, moments=True, tile_markets=tile,
            interpret=True,
        )
        got = jax.jit(sweep)(means, variances, idx, w)
        _assert_quad_equal(got, want, f"tile={tile}")

    def test_adaptive_early_exit_freezes_the_audit_pair(self):
        # Edgeless: the first sweep measures residual 0, every later
        # grid step must be a masked no-op — iters stays 1.
        means, variances, idx, w = _workload("edgeless")
        sweep = build_bp_sweep(
            means.shape[0], idx.shape[1], 24,
            damping=0.45, tol=1e-4, moments=True, interpret=True,
        )
        mean, var, iters, residual = jax.jit(sweep)(
            means, variances, idx, w
        )
        assert int(iters) == 1
        assert float(residual) == 0.0
        np.testing.assert_array_equal(np.asarray(mean), np.asarray(means))
        np.testing.assert_array_equal(
            np.asarray(var), np.asarray(variances)
        )


class TestKernelAcrossMeshFactorisations:
    """The gather-once route: same bits as single-shard bp_sweep_math
    on every factorisation of the markets axis."""

    def _kernel_sharded(self, mesh_shape, means, variances, idx, w, *,
                        tol, max_steps):
        mesh = make_mesh(mesh_shape)
        market = P(MARKETS_AXIS)
        sweep = build_bp_sweep(
            means.shape[0], idx.shape[1], max_steps,
            damping=0.4, tol=tol, moments=True, interpret=True,
        )

        def math(v, s, i, wt):
            # The routed program's exact shard structure: gather once,
            # run the full global launch redundantly, slice local rows.
            m_loc = v.shape[0]
            gather = lambda x: jax.lax.all_gather(
                x, MARKETS_AXIS, tiled=True
            )
            mean, var, iters, residual = sweep(
                gather(v), gather(s), gather(i), gather(wt)
            )
            start = jax.lax.axis_index(MARKETS_AXIS) * m_loc
            return (
                jax.lax.dynamic_slice(mean, (start,), (m_loc,)),
                jax.lax.dynamic_slice(var, (start,), (m_loc,)),
                iters,
                residual,
            )

        fn = shard_map(
            math, mesh=mesh,
            in_specs=(market, market, market, market),
            out_specs=(market, market, P(), P()),
            check_vma=False,
        )
        return jax.jit(fn)(means, variances, idx, w)

    @pytest.mark.parametrize("tol", [None, 1e-3])
    def test_ops_bitwise_parity_across_mesh_factorisations(self, tol):
        means, variances, idx, w = _workload("sparse_deg2", m=64)
        want = bp_sweep_math(
            means, variances, idx, w, damping=0.4, max_steps=64, tol=tol
        )
        for shape in MESH_SHAPES:
            got = self._kernel_sharded(
                shape, means, variances, idx, w, tol=tol, max_steps=64
            )
            _assert_quad_equal(got, want, f"mesh={shape}")

    @pytest.mark.parametrize("mesh_shape", MESH_SHAPES)
    def test_routed_loop_same_mesh_xla_vs_kernel(self, mesh_shape):
        # The full fused program per factorisation: swapping ONLY the
        # sweep route moves no bits anywhere in the output tuple.
        rng = np.random.default_rng(11)
        k, m, d = 8, 256, 4
        probs = jnp.asarray(rng.random((k, m)), jnp.float32)
        mask = jnp.asarray(rng.random((k, m)) < 0.9)
        outcome = jnp.asarray(rng.random(m) < 0.5)
        state = MarketBlockState(
            reliability=jnp.asarray(
                rng.uniform(0.1, 1.0, (k, m)), jnp.float32
            ),
            confidence=jnp.asarray(
                rng.uniform(0.0, 1.0, (k, m)), jnp.float32
            ),
            updated_days=jnp.zeros((k, m), jnp.float32),
            exists=jnp.asarray(rng.random((k, m)) < 0.7),
        )
        now = jnp.asarray(400.0, jnp.float32)
        nidx = jnp.asarray(rng.integers(0, m, (m, d)), jnp.int32)
        nw = jnp.asarray(rng.uniform(0.1, 1.0, (m, d)), jnp.float32)
        mesh = make_mesh(mesh_shape)

        def run(sweep_kernel):
            loop = build_cycle_analytics_loop(
                mesh, donate=False, sweep_steps=12,
                sweep_mode="moments", sweep_tol=1e-4,
                sweep_kernel=sweep_kernel,
            )
            return loop(probs, mask, outcome, state, now, 2, nidx, nw)

        want, got = run("xla"), run("pallas")
        for slot, (a, b) in enumerate(zip(want, got)):
            for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                np.testing.assert_array_equal(
                    np.asarray(la), np.asarray(lb),
                    err_msg=f"mesh={mesh_shape} slot={slot}",
                )

    def test_settle_kernel_composes_with_sweep_kernel(self):
        # One shard_map program, kernel → kernel: the one-pass settle
        # kernel feeds the BP kernel with no XLA stage between, and the
        # whole tuple still matches the all-XLA program bit-for-bit.
        rng = np.random.default_rng(13)
        k, m, d = 8, 256, 3
        probs = jnp.asarray(rng.random((k, m)), jnp.float32)
        mask = jnp.asarray(rng.random((k, m)) < 0.9)
        outcome = jnp.asarray(rng.random(m) < 0.5)
        state = MarketBlockState(
            reliability=jnp.asarray(
                rng.uniform(0.1, 1.0, (k, m)), jnp.float32
            ),
            confidence=jnp.asarray(
                rng.uniform(0.0, 1.0, (k, m)), jnp.float32
            ),
            updated_days=jnp.zeros((k, m), jnp.float32),
            exists=jnp.asarray(rng.random((k, m)) < 0.7),
        )
        now = jnp.asarray(400.0, jnp.float32)
        nidx = jnp.asarray(rng.integers(0, m, (m, d)), jnp.int32)
        nw = jnp.asarray(rng.uniform(0.1, 1.0, (m, d)), jnp.float32)
        mesh = make_mesh((8, 1))

        def run(kernel, sweep_kernel):
            loop = build_cycle_analytics_loop(
                mesh, donate=False, sweep_steps=8,
                sweep_mode="moments", sweep_tol=1e-5,
                kernel=kernel, sweep_kernel=sweep_kernel,
            )
            return loop(probs, mask, outcome, state, now, 2, nidx, nw)

        want = run("xla", "xla")
        got = run("pallas", "pallas")
        for slot, (a, b) in enumerate(zip(want, got)):
            for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                np.testing.assert_array_equal(
                    np.asarray(la), np.asarray(lb), err_msg=f"slot={slot}"
                )


def _journal_epochs_sans_clock(path):
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


_SESSION_EDGES = [
    ("m-0", "m-1", 0.5), ("m-1", "m-2", 0.7), ("m-3", "m-4", 0.4),
]


def _session_run(sweep_kernel, analytics, markets=12, seed=8):
    import random

    rng = random.Random(seed)
    payloads = []
    for m in range(markets):
        payloads.append((
            f"m-{m}",
            [
                {
                    "sourceId": f"s{rng.randrange(8)}",
                    "probability": round(rng.random(), 6),
                }
                for _ in range(rng.randint(1, 3))
            ],
        ))
    outcomes = [True] * markets
    store = TensorReliabilityStore()
    plan = build_settlement_plan(store, payloads, num_slots=4,
                                 fingerprint=True)
    session = ShardedSettlementSession(store, plan, make_mesh((4, 2)))
    with session:
        out = session.settle_with_analytics(
            outcomes, steps=1, now=NOW, analytics=analytics,
            sweep_kernel=sweep_kernel,
        )
    store.sync()
    return store, out


class TestSessionSweepKernelParity:
    """The fused session under sweep_kernel='pallas': identical
    analytics bits, identical settlement bytes."""

    @pytest.mark.parametrize(
        "analytics",
        [
            AnalyticsOptions(
                graph=MarketGraph.from_edges(
                    _SESSION_EDGES, damping=0.4, steps=4
                ),
                inference=InferenceOptions(tol=1e-6, max_steps=32),
            ),
            AnalyticsOptions(
                graph=MarketGraph.from_edges(_SESSION_EDGES, steps=3)
            ),
        ],
        ids=["moments_adaptive", "point"],
    )
    def test_session_bit_and_byte_parity(self, analytics, tmp_path):
        store_a, (res_a, tb_a, bands_a, prop_a) = _session_run(
            "xla", analytics
        )
        store_b, (res_b, tb_b, bands_b, prop_b) = _session_run(
            "pallas", analytics
        )
        np.testing.assert_array_equal(
            np.asarray(res_a.consensus), np.asarray(res_b.consensus)
        )
        np.testing.assert_array_equal(
            np.asarray(bands_a.stderr), np.asarray(bands_b.stderr)
        )
        for pa, pb in zip(
            jax.tree.leaves(prop_a), jax.tree.leaves(prop_b)
        ):
            np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
        # Byte parity on every settlement artifact: the sweep is an
        # additive analytics read — the kernel route must not move a
        # single stored byte.
        assert store_digest(store_a) == store_digest(store_b)
        for name, store in (("xla", store_a), ("pallas", store_b)):
            writer = JournalWriter(tmp_path / f"{name}.jrnl")
            store.flush_to_journal(writer)
            writer.close()
            store.flush_to_sqlite(tmp_path / f"{name}.db")
        assert _journal_epochs_sans_clock(tmp_path / "xla.jrnl") == (
            _journal_epochs_sans_clock(tmp_path / "pallas.jrnl")
        )
        assert (tmp_path / "xla.db").read_bytes() == (
            tmp_path / "pallas.db"
        ).read_bytes()

    def test_analytics_options_carry_the_knob(self):
        analytics = AnalyticsOptions(
            graph=MarketGraph.from_edges(
                _SESSION_EDGES, damping=0.4, steps=4
            ),
            inference=InferenceOptions(tol=1e-6, max_steps=16),
            sweep_kernel="pallas",
        )
        ref = AnalyticsOptions(
            graph=analytics.graph, inference=analytics.inference
        )
        _, (_, _, _, prop_k) = _session_run(None, analytics)
        _, (_, _, _, prop_x) = _session_run(None, ref)
        for pa, pb in zip(
            jax.tree.leaves(prop_k), jax.tree.leaves(prop_x)
        ):
            np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


class TestHostEntryKernel:
    def test_propagate_beliefs_kernel_parity(self):
        keys = [f"m{i}" for i in range(10)]
        graph = MarketGraph.from_edges(
            [(f"m{i}", f"m{(i + 1) % 10}", 0.8) for i in range(10)],
            steps=8, damping=0.4,
        )
        rng = np.random.default_rng(5)
        means = np.full(128, np.nan, np.float32)
        means[:10] = rng.random(10)
        variances = np.full(128, np.nan, np.float32)
        variances[:10] = rng.uniform(0.001, 0.1, 10)
        options = InferenceOptions(tol=1e-5, max_steps=16)
        want = propagate_beliefs(
            means, variances, graph, keys, 128, options=options
        )
        got = propagate_beliefs(
            means, variances, graph, keys, 128, options=options,
            kernel="pallas",
        )
        _assert_quad_equal(
            (got.mean, got.stderr, got.iters_run, got.residual),
            (want.mean, want.stderr, want.iters_run, want.residual),
            "host_entry",
        )

    def test_unknown_kernel_rejected(self):
        graph = MarketGraph.from_edges([("a", "b", 1.0)])
        with pytest.raises(ValueError, match="kernel="):
            propagate_beliefs(
                np.zeros(2, np.float32), np.ones(2, np.float32),
                graph, ["a", "b"], 2, kernel="mosaic",
            )


class TestRoutingAndBuilders:
    def test_sweep_kernel_option_validated(self):
        with pytest.raises(ValueError, match="sweep_kernel="):
            build_cycle_analytics_loop(
                make_mesh((4, 2)), sweep_steps=2, sweep_kernel="cuda"
            )

    def test_pallas_sweep_needs_a_graph(self):
        with pytest.raises(ValueError, match="no graph sweep"):
            build_cycle_analytics_loop(
                make_mesh((4, 2)), sweep_kernel="pallas"
            )

    def test_auto_without_graph_resolves_xla(self):
        # Nothing to adjudicate — the ineligible-auto convention: the
        # loop builds and never consults the tuner.
        loop = build_cycle_analytics_loop(
            make_mesh((4, 2)), sweep_kernel="auto"
        )
        assert callable(loop)

    def test_builder_rejects_bad_knobs(self):
        with pytest.raises(ValueError, match="max_steps"):
            build_bp_sweep(128, 2, 0, damping=0.5)
        with pytest.raises(ValueError, match="tol"):
            build_bp_sweep(128, 2, 2, damping=0.5, tol=0.0)
        with pytest.raises(ValueError, match="not a multiple"):
            build_bp_sweep(130, 2, 2, damping=0.5, tile_markets=64)

    def test_call_shape_matches_build_mode(self):
        sweep = build_bp_sweep(
            128, 2, 2, damping=0.5, moments=True, interpret=True
        )
        v = jnp.zeros(128, jnp.float32)
        idx = jnp.zeros((128, 2), jnp.int32)
        w = jnp.ones((128, 2), jnp.float32)
        with pytest.raises(ValueError, match="without variances"):
            sweep(v, None, idx, w)
        point = build_bp_sweep(
            128, 2, 2, damping=0.5, moments=False, interpret=True
        )
        with pytest.raises(ValueError, match="point lane"):
            point(v, v, idx, w)

    def test_tile_resolver_budget(self):
        # Small shapes take the whole axis as one tile; the resolver
        # never admits a state set over the 16 MB budget.
        assert resolve_tile_sweep(256, 8, True) == 256
        tile = resolve_tile_sweep(1024 * 512, 8, True)
        assert (1024 * 512) % tile == 0


class TestSweepKernelAutotune:
    """sweep_kernel='auto' rides the ShapeTuner contract (knob
    ``sweep_kernel``): off → XLA without measuring; on → the honesty
    guard races the kernel against the XLA default on the same clock."""

    def test_auto_resolves_through_tuner(self, monkeypatch):
        from bayesian_consensus_engine_tpu.parallel import sharded
        from bayesian_consensus_engine_tpu.utils import autotune

        seen = {}

        class FakeTuner:
            def tune(self, knob, shape_key, candidates, measure, default):
                seen.update(
                    knob=knob, shape_key=shape_key,
                    candidates=candidates, default=default,
                )
                return "pallas"

        monkeypatch.setattr(autotune, "default_tuner", lambda: FakeTuner())
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        choice = sharded._tuned_sweep_kernel(
            mesh, 16, 256, 2, 4, 8, "moments", 1e-4, 0.5,
            None, None, 6, 1.959964,
        )
        assert choice == "pallas"
        assert seen["knob"] == "sweep_kernel"
        # Graph knobs ride the key: degree/mode/tol change both raced
        # programs, so a verdict at one config never answers another.
        assert seen["shape_key"] == (
            16, 256, 2, 4, 8, "moments", 1e-4, 1, 1
        )
        assert seen["candidates"] == ["pallas"]
        assert seen["default"] == "xla"

    def test_default_off_resolves_xla_without_measuring(
        self, monkeypatch, tmp_path
    ):
        from bayesian_consensus_engine_tpu.parallel import sharded
        from bayesian_consensus_engine_tpu.utils import autotune

        monkeypatch.delenv("BCE_AUTOTUNE", raising=False)
        monkeypatch.setattr(autotune, "_default_tuner", None)
        monkeypatch.setattr(
            autotune, "_default_cache_path",
            lambda: str(tmp_path / "never.json"),
        )
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        choice = sharded._tuned_sweep_kernel(
            mesh, 16, 256, 2, 4, 8, "moments", 1e-4, 0.5,
            None, None, 6, 1.959964,
        )
        assert choice == "xla"
        assert not (tmp_path / "never.json").exists()

    def test_real_race_records_honesty_verdict(self, tmp_path):
        # A REAL (tiny-shape) race through an enabled tuner: whatever
        # wins, the cache entry must carry the default and the verdict —
        # a tuned "pallas" may only ship with beat_default=True.
        from bayesian_consensus_engine_tpu.parallel import sharded
        from bayesian_consensus_engine_tpu.utils.autotune import ShapeTuner
        from bayesian_consensus_engine_tpu.utils import autotune

        tuner = ShapeTuner(
            cache_path=str(tmp_path / "cache.json"), enabled=True
        )
        orig = autotune.default_tuner
        autotune.default_tuner = lambda: tuner
        try:
            mesh = make_mesh((1, 1), devices=jax.devices()[:1])
            choice = sharded._tuned_sweep_kernel(
                mesh, 4, 16, 1, 2, 2, "moments", None, 0.5,
                None, None, 6, 1.959964,
            )
            decision = tuner.decision(
                "sweep_kernel", (4, 16, 1, 2, 2, "moments", None, 1, 1)
            )
        finally:
            autotune.default_tuner = orig
        assert decision is not None
        assert decision["default"] == "xla"
        assert decision["choice"] == choice
        if choice == "pallas":
            assert decision["beat_default"] is True
