"""graftlint's own gate: the repo is clean, and every rule actually fires.

Two halves, both load-bearing:

* ``test_repo_is_lint_clean`` runs the full linter over the repo gate set
  inside tier-1, so a committed host-sync / determinism / layering
  violation fails the suite — the repo is self-checking.
* The fixture table seeds one minimal BAD snippet and one GOOD twin per
  rule and asserts the rule fires on exactly the bad one (``select``
  isolates each rule so e.g. an F401 on a deliberately-unused import
  cannot mask a missing LY301). A rule that silently stops matching is a
  gate that silently stopped gating.

The engine is stdlib-only (ast + symtable); nothing here touches JAX.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from bayesian_consensus_engine_tpu.lint import RULES, check_source, run
from bayesian_consensus_engine_tpu.lint import config as lint_config

_ROOT = pathlib.Path(__file__).resolve().parents[1]
PKG = lint_config.PACKAGE

#: Every devlint-era rule the migrated engine must reproduce (ISSUE 1
#: acceptance criterion) plus the three new families.
_DEVLINT_IDS = ("F401", "F541", "F811", "F821", "F841", "E711", "E712", "E722")
_NEW_FAMILY_IDS = (
    "JX101", "JX102", "JX103", "JX104", "JX105", "JX106", "JX107", "JX108",
    "JX109", "JX110",
    "DT201", "DT202", "DT203",
    "LY301", "LY302", "LY303",
    "SH401",
    "PL501",
    "AS601", "AS602", "AS603",
)


def _codes(src: str, rel: str, select=None) -> list[str]:
    return [f.rule_id for f in check_source(src, rel, select=select)]


# (rule_id, rel-path the snippet pretends to live at, bad source, good twin)
_CASES = [
    (
        "JX101",
        f"{PKG}/ops/case.py",
        "def f(x):\n    return x.sum().item()\n",
        "def f(x):\n    return x.sum()\n",
    ),
    (
        "JX102",
        f"{PKG}/ops/case.py",
        "import jax\n\n@jax.jit\ndef f(x):\n    return float(x) + 1.0\n",
        "import jax\n\n@jax.jit\ndef f(x):\n    return x + 1.0\n",
    ),
    (
        "JX103",
        f"{PKG}/parallel/case.py",
        "import jax\nimport numpy as np\n\n@jax.jit\ndef f(x):\n"
        "    return np.asarray(x)\n",
        "import jax\nimport jax.numpy as jnp\n\n@jax.jit\ndef f(x):\n"
        "    return jnp.asarray(x)\n",
    ),
    (
        "JX104",
        f"{PKG}/core/case.py",
        "import jax\n\n@jax.jit\ndef f(x):\n    print(x)\n    return x\n",
        "import jax\n\n@jax.jit\ndef f(x):\n"
        "    jax.debug.print('x={}', x)\n    return x\n",
    ),
    (
        "JX105",
        f"{PKG}/parallel/case.py",
        "import jax\n\ndef step(state, x):\n    return state + x\n\n"
        "step_fast = jax.jit(step)\n",
        "import jax\n\ndef step(state, x):\n    return state + x\n\n"
        "step_fast = jax.jit(step, donate_argnums=(0,))\n",
    ),
    (
        "JX108",
        f"{PKG}/state/case.py",  # in the package, OUTSIDE the hot paths
        "import jax\n\ndef step(state, x):\n    return state + x\n\n"
        "step_fast = jax.jit(step)\n",
        "import jax\n\ndef step(state, x):\n    return state + x\n\n"
        "step_fast = jax.jit(step, donate_argnums=(0,))\n",
    ),
    (
        "JX106",
        f"{PKG}/core/case.py",
        "import jax\n\ndef f(x, opts):\n    return x\n\n"
        "g = jax.jit(f, static_argnums=(1,))\ny = g(1, [1, 2])\n",
        "import jax\n\ndef f(x, opts):\n    return x\n\n"
        "g = jax.jit(f, static_argnums=(1,))\ny = g(1, (1, 2))\n",
    ),
    (
        # Timing window (perf_counter in scope) fenced by block_until_ready:
        # the audit fires; the scalar-fetch fence twin stays quiet, and so
        # does a fence with no stopwatch in scope (third case below).
        "JX109",
        "scripts/case.py",
        "import time\nimport jax\n\n\ndef timed(f, x):\n"
        "    start = time.perf_counter()\n"
        "    jax.block_until_ready(f(x))\n"
        "    return time.perf_counter() - start\n",
        "import time\n\n\ndef timed(f, x):\n"
        "    start = time.perf_counter()\n"
        "    float(f(x).reshape(-1)[0])\n"
        "    return time.perf_counter() - start\n",
    ),
    (
        "JX107",
        f"{PKG}/ops/case.py",
        "import jax.numpy as jnp\n\ndef f():\n    return jnp.zeros((4, 4))\n",
        "import jax.numpy as jnp\n\ndef f():\n"
        "    return jnp.zeros((4, 4), dtype=jnp.float32)\n",
    ),
    (
        "DT201",
        f"{PKG}/state/case.py",
        "def f():\n    return [x for x in {1, 2, 3}]\n",
        "def f():\n    return [x for x in sorted({1, 2, 3})]\n",
    ),
    (
        "DT202",
        f"{PKG}/ops/case.py",
        "import time\n\ndef f():\n    return time.time()\n",
        "def f(now):\n    return now\n",
    ),
    (
        "DT203",
        f"{PKG}/state/case.py",
        "import json\n\ndef f(d):\n    return json.dumps(d)\n",
        "import json\n\ndef f(d):\n    return json.dumps(d, sort_keys=True)\n",
    ),
    (
        "LY301",
        f"{PKG}/ops/case.py",
        f"from {PKG}.state import records\n",
        f"from {PKG}.utils import config\n",
    ),
    (
        # Round 12: analytics sits above ops/parallel and below
        # pipeline/serve — reaching up into the serving tier from an
        # analytics module is an upward import; building on the mesh
        # machinery below is the designed direction.
        "LY301",
        f"{PKG}/analytics/case.py",
        f"from {PKG}.serve.driver import SessionDriver\n",
        f"from {PKG}.parallel.sharded import read_phase\n"
        f"from {PKG}.ops.uncertainty import band_math\n",
    ),
    (
        # Round 13: cluster (membership views + journal recovery) sits
        # beside analytics — built on parallel's mesh machinery and
        # state's journal, orchestrated BY pipeline/serve; a cluster
        # module reaching up into the orchestration tier is an upward
        # import.
        "LY301",
        f"{PKG}/cluster/case.py",
        f"from {PKG}.pipeline import settle_stream\n",
        f"from {PKG}.parallel.distributed import make_hybrid_mesh\n"
        f"from {PKG}.state.journal import replay_journal\n",
    ),
    (
        # Round 17: net (the socket front door) shares the serve tier —
        # importing the CLI above it is an upward import; submitting
        # into serve's coalescer and raising serve's exceptions is the
        # designed direction.
        "LY301",
        f"{PKG}/net/case.py",
        f"from {PKG}.cli import build_parser\n",
        f"from {PKG}.serve.coalesce import ConsensusService\n"
        f"from {PKG}.serve.admission import Overloaded\n",
    ),
    (
        # ...and the inverse: an engine tier importing net would give a
        # kernel module a socket — the numeric rule flags it (net sits
        # at the serve tier, above every engine layer).
        "LY301",
        f"{PKG}/state/case.py",
        f"from {PKG}.net.wire import encode_frame\n",
        f"from {PKG}.core.batch import topology_fingerprint\n",
    ),
    (
        # Round 18: replay (the counterfactual replay lab) shares the
        # orchestration tier — importing the CLI above it is an upward
        # import; re-driving serve's SessionDriver and the sweep step
        # below is the designed direction.
        "LY301",
        f"{PKG}/replay/case.py",
        f"from {PKG}.cli import build_parser\n",
        f"from {PKG}.serve.driver import SessionDriver\n"
        f"from {PKG}.parallel.sharded import build_replay_sweep_step\n",
    ),
    (
        # ...and the inverse: an engine tier importing replay would let
        # a kernel re-drive the harness that re-drives it — the numeric
        # rule flags it (replay sits at the serve tier).
        "LY301",
        f"{PKG}/parallel/case.py",
        f"from {PKG}.replay.lab import replay_sweep\n",
        f"from {PKG}.ops.cycle_math import CycleParams\n",
    ),
    (
        # Round 18: infer (moment-pair BP + band partitioning + blocks)
        # sits between analytics and orchestration — importing the
        # pipeline that orchestrates it is an upward import; composing
        # analytics' graph alignment with the ops sweep math below is
        # the designed direction.
        "LY301",
        f"{PKG}/infer/case.py",
        f"from {PKG}.pipeline import settle_stream\n",
        f"from {PKG}.analytics.graph import MarketGraph\n"
        f"from {PKG}.ops.propagate import bp_sweep_math\n",
    ),
    (
        # ...and the inverse: analytics importing infer would invert
        # the composition (infer builds ON analytics' graph surface) —
        # the numeric rule flags it (infer sits a layer above).
        "LY301",
        f"{PKG}/analytics/case.py",
        f"from {PKG}.infer.bp import InferenceOptions\n",
        f"from {PKG}.ops.uncertainty import band_math\n",
    ),
    (
        "LY302",
        f"{PKG}/core/case.py",
        "import jax.numpy as jnp\n\nSENTINEL = jnp.int32(0)\n",
        "import jax.numpy as jnp\n\ndef sentinel():\n    return jnp.int32(0)\n",
    ),
    (
        # obs is layer 0, so LY301 alone would let a kernel import it —
        # LY303 is the rule that keeps pure-math layers instrumentation-
        # free (config.OBS_ALLOWED_IMPORTERS).
        "LY303",
        f"{PKG}/ops/case.py",
        f"from {PKG}.obs.timeline import active_timeline\n",
        f"from {PKG}.utils import config\n",
    ),
    (
        # Round 9: the tracing/SLO modules are obs too — LY303 confines
        # them to the orchestration layers exactly like metrics/timeline
        # (a request tracer in a kernel is a host-sync magnet).
        "LY303",
        f"{PKG}/parallel/case.py",
        f"from {PKG}.obs.trace import active_tracer\n"
        f"from {PKG}.obs.slo import SloTracker\n",
        f"from {PKG}.utils import config\n",
    ),
    (
        # Round 16: the obs READ surface (exporter/fleet/health) is
        # confined further than obs itself — pipeline may WRITE metrics
        # (the good twin) but must never read them back through the
        # exporter (write-only obs, enforced structurally).
        "LY303",
        f"{PKG}/pipeline.py",
        f"from {PKG}.obs.export import TelemetryServer\n",
        f"from {PKG}.obs.metrics import metrics_registry\n",
    ),
    (
        # Round 16: obs is stdlib-only by contract — an obs module that
        # imports numpy would drag a backend into every orchestration
        # import; stdlib (and intra-obs) imports are the good twin.
        "LY303",
        f"{PKG}/obs/case.py",
        "import numpy as np\n",
        "import json\nimport http.server\n"
        f"from {PKG}.obs.metrics import metrics_registry\n",
    ),
    (
        # A PartitionSpec axis the mesh does not define: the typo'd
        # string is flagged; the axis-constant twin is the idiom.
        "SH401",
        f"{PKG}/parallel/case.py",
        "from jax.sharding import PartitionSpec as P\n\n"
        "SPEC = P('markets', 'source')\n",
        f"from {PKG}.parallel.mesh import MARKETS_AXIS, SOURCES_AXIS\n"
        "from jax.sharding import PartitionSpec as P\n\n"
        "SPEC = P(MARKETS_AXIS, SOURCES_AXIS)\n",
    ),
    (
        # The grid floor-divides m // tile with no divisibility guard AND
        # the literal BlockSpec set (4096×4096 f32, double-buffered) blows
        # the 16 MB scoped-VMEM budget — both halves of the rule fire.
        # The good twin guards the ragged tail and tiles to a module
        # constant the checker can resolve.
        "PL501",
        f"{PKG}/ops/case.py",
        "from jax.experimental import pallas as pl\n\n\n"
        "def build(m, tile):\n"
        "    grid = (m // tile,)\n"
        "    big = pl.BlockSpec((4096, 4096), lambda i: (0, i))\n"
        "    return pl.pallas_call(None, grid=grid, in_specs=[big],\n"
        "                          out_specs=[big])\n",
        "from jax.experimental import pallas as pl\n\nTILE = 512\n\n\n"
        "def build(m, tile):\n"
        "    if m % tile:\n"
        "        raise ValueError('ragged markets axis')\n"
        "    grid = (m // tile,)\n"
        "    block = pl.BlockSpec((8, TILE), lambda i: (0, i))\n"
        "    return pl.pallas_call(None, grid=grid, in_specs=[block],\n"
        "                          out_specs=[block])\n",
    ),
    (
        # Round 14 (one-pass settlement): an output aliased onto an
        # input (``input_output_aliases``) shares the input's HBM buffer
        # and counts ONCE against the 16 MB scoped-VMEM budget. The bad
        # twin double-bills the aliased pair past the budget; the good
        # twin declares the alias and fits exactly.
        "PL501",
        f"{PKG}/ops/case.py",
        "from jax.experimental import pallas as pl\n\n\n"
        "def build():\n"
        "    grid = (4,)\n"
        "    big = pl.BlockSpec((1024, 1024), lambda i: (0, i))\n"
        "    return pl.pallas_call(None, grid=grid, in_specs=[big],\n"
        "                          out_specs=[big, big])\n",
        "from jax.experimental import pallas as pl\n\n\n"
        "def build():\n"
        "    grid = (4,)\n"
        "    big = pl.BlockSpec((1024, 1024), lambda i: (0, i))\n"
        "    return pl.pallas_call(None, grid=grid, in_specs=[big],\n"
        "                          out_specs=[big, big],\n"
        "                          input_output_aliases={0: 0})\n",
    ),
    (
        # Round 19 (VMEM-resident BP): the iteration-outer 2-D grid
        # ``(steps, m // tile)`` with constant-index full-vector state
        # windows. The bad twin floor-divides the markets axis with no
        # guard AND double-bills the launch-resident state pair (in +
        # out counted separately past the budget); the good twin guards
        # the ragged tail and declares the literal
        # ``input_output_aliases`` the in-place moment update actually
        # uses (``ops/pallas_bp.py``), so the aliased windows count
        # once and fit.
        "PL501",
        f"{PKG}/ops/case.py",
        "from jax.experimental import pallas as pl\n\n\n"
        "def build(m, tile, steps):\n"
        "    grid = (steps, m // tile)\n"
        "    state = pl.BlockSpec((1, 1048576), lambda it, t: (0, 0))\n"
        "    nb = pl.BlockSpec((2048, 8), lambda it, t: (t, 0))\n"
        "    return pl.pallas_call(None, grid=grid,\n"
        "                          in_specs=[nb, state, state],\n"
        "                          out_specs=[state, state])\n",
        "from jax.experimental import pallas as pl\n\nM_STATE = 524288\n\n\n"
        "def build(m, tile, steps):\n"
        "    if m % tile:\n"
        "        raise ValueError('markets axis must tile exactly')\n"
        "    grid = (steps, m // tile)\n"
        "    state = pl.BlockSpec((1, M_STATE), lambda it, t: (0, 0))\n"
        "    nb = pl.BlockSpec((2048, 8), lambda it, t: (t, 0))\n"
        "    return pl.pallas_call(None, grid=grid,\n"
        "                          in_specs=[nb, state, state],\n"
        "                          out_specs=[state, state],\n"
        "                          input_output_aliases={1: 0, 2: 1})\n",
    ),
    (
        # Round 20 (sources-sharded partials): a multi-output launch
        # whose state blocks alias in place through the COMPREHENSION
        # idiom ``{base + j: j for j in range(N)}`` and whose spec
        # lists use list arithmetic (``[a, b] + [block] * N``). The
        # good twin fits the budget ONLY because the evaluated alias
        # map credits the four aliased state outputs once — double-
        # billing them (the pre-round-20 undecidable fallback) would
        # read 20 MB double-buffered. The bad twin's block set is past
        # the budget even WITH the aliasing credited.
        "PL501",
        f"{PKG}/ops/case.py",
        "from jax.experimental import pallas as pl\n\nN_STATE = 4\n\n\n"
        "def build():\n"
        "    grid = (4,)\n"
        "    block = pl.BlockSpec((512, 1024), lambda i: (0, i))\n"
        "    row = pl.BlockSpec((4, 1024), lambda i: (0, i))\n"
        "    in_specs = [block, block] + [block] * N_STATE\n"
        "    out_specs = [block] * N_STATE + [row]\n"
        "    return pl.pallas_call(\n"
        "        None, grid=grid, in_specs=in_specs,\n"
        "        out_specs=out_specs,\n"
        "        input_output_aliases={2 + j: j for j in range(N_STATE)},\n"
        "    )\n",
        "from jax.experimental import pallas as pl\n\nN_STATE = 4\n\n\n"
        "def build():\n"
        "    grid = (4,)\n"
        "    block = pl.BlockSpec((256, 1024), lambda i: (0, i))\n"
        "    row = pl.BlockSpec((4, 1024), lambda i: (0, i))\n"
        "    in_specs = [block, block] + [block] * N_STATE\n"
        "    out_specs = [block] * N_STATE + [row]\n"
        "    return pl.pallas_call(\n"
        "        None, grid=grid, in_specs=in_specs,\n"
        "        out_specs=out_specs,\n"
        "        input_output_aliases={2 + j: j for j in range(N_STATE)},\n"
        "    )\n",
    ),
    (
        "F401",
        "tests/case.py",
        "import os\n\n\ndef f():\n    return 1\n",
        "import os\n\n\ndef f():\n    return os.sep\n",
    ),
    (
        "F541",
        "tests/case.py",
        "x = f'constant'\n",
        "x = f'{1}'\n",
    ),
    (
        "F811",
        "tests/case.py",
        "import os\nimport os\n\nprint(os.sep)\n",
        "import os\n\nprint(os.sep)\n",
    ),
    (
        "F821",
        "tests/case.py",
        "def f():\n    return missing_name\n",
        "def f():\n    return 1\n",
    ),
    (
        "F841",
        "tests/case.py",
        "def f():\n    y = 1\n    return 2\n",
        "def f():\n    y = 1\n    return y\n",
    ),
    (
        "E711",
        "tests/case.py",
        "def f(x):\n    return x == None\n",
        "def f(x):\n    return x is None\n",
    ),
    (
        "E712",
        "tests/case.py",
        "def f(x):\n    return x == True\n",
        "def f(x):\n    return bool(x)\n",
    ),
    (
        "E722",
        "tests/case.py",
        "def f(x):\n    try:\n        return int(x)\n    except:\n"
        "        return 0\n",
        "def f(x):\n    try:\n        return int(x)\n    except ValueError:\n"
        "        return 0\n",
    ),
    (
        # The whole-program tier's same-file shape: the helper is traced
        # through a call from the jitted entry, never wrapped itself — a
        # per-file JX102 walk cannot see it. Cross-MODULE shapes (one and
        # two hops, re-exports) live in tests/test_devlint.py's fixture
        # matrix, which drives check_source(project=…).
        "JX110",
        f"{PKG}/ops/case.py",
        "import jax\n\ndef helper(x):\n    return float(x)\n\n"
        "@jax.jit\ndef entry(x):\n    return helper(x)\n",
        "import jax\n\ndef helper(x):\n    return x * 2.0\n\n"
        "@jax.jit\ndef entry(x):\n    return helper(x)\n",
    ),
    (
        "AS601",
        f"{PKG}/net/case.py",
        "import time\n\nasync def handle():\n    time.sleep(1)\n",
        "import asyncio\n\nasync def handle():\n    await asyncio.sleep(1)\n",
    ),
    (
        "AS602",
        f"{PKG}/serve/case.py",
        "async def reply():\n    pass\n\n"
        "async def handle():\n    reply()\n",
        "async def reply():\n    pass\n\n"
        "async def handle():\n    await reply()\n",
    ),
    (
        "AS603",
        f"{PKG}/serve/case.py",
        "import asyncio\nimport threading\n\nclass C:\n"
        "    def __init__(self):\n        self.lock = threading.Lock()\n"
        "    async def go(self):\n        with self.lock:\n"
        "            await asyncio.sleep(0)\n",
        "import asyncio\n\nclass C:\n"
        "    def __init__(self):\n        self.lock = asyncio.Lock()\n"
        "    async def go(self):\n        async with self.lock:\n"
        "            await asyncio.sleep(0)\n",
    ),
]


class TestRepoClean:
    def test_repo_is_lint_clean(self):
        n_files, findings = run()
        rendered = "\n".join(f.render() for f in findings)
        assert n_files > 50, "gate set shrank — check lint/config.DEFAULT_PATHS"
        assert not findings, f"graftlint findings in the repo:\n{rendered}"

    def test_every_devlint_rule_migrated(self):
        for rule_id in _DEVLINT_IDS + _NEW_FAMILY_IDS:
            assert rule_id in RULES, f"rule {rule_id} missing from the registry"


class TestRuleFixtures:
    @pytest.mark.parametrize(
        "rule_id,rel,bad,good", _CASES, ids=[c[0] for c in _CASES]
    )
    def test_fires_on_bad_and_quiet_on_good(self, rule_id, rel, bad, good):
        assert rule_id in _codes(bad, rel, select=[rule_id]), (
            f"{rule_id} failed to fire on its seeded violation"
        )
        assert rule_id not in _codes(good, rel, select=[rule_id]), (
            f"{rule_id} false-positived on the good twin"
        )

    @pytest.mark.parametrize(
        "rule_id,rel,bad", [(c[0], c[1], c[2]) for c in _CASES],
        ids=[c[0] for c in _CASES],
    )
    def test_scoped_rules_stay_out_of_foreign_paths(self, rule_id, rel, bad):
        # A snippet outside the repo (rel=None) only sees unscoped rules:
        # path-scoped families must never leak onto arbitrary files.
        scoped = RULES[rule_id].scope is not None
        if scoped:
            assert rule_id not in _codes(bad, None, select=[rule_id])


class TestLayeringResolution:
    def test_from_package_import_segment_resolves_to_the_segment(self):
        # `from pkg import models` imports the models segment (layer 4),
        # not the root facade (layer 99) — legal from cli (layer 7).
        src = f"from {PKG} import models\n"
        assert _codes(src, f"{PKG}/cli.py", select=["LY301"]) == []

    def test_from_package_import_segment_still_layer_checked(self):
        # ...and from ops (layer 1) the same import IS an upward import.
        src = f"from {PKG} import models\n"
        assert "LY301" in _codes(src, f"{PKG}/ops/case.py", select=["LY301"])

    def test_importing_the_root_facade_is_flagged(self):
        # Nothing inside the package imports the root facade (layer 99).
        src = f"from {PKG} import SCHEMA_VERSION\n"
        assert "LY301" in _codes(src, f"{PKG}/cli.py", select=["LY301"])

    def test_obs_import_allowed_from_orchestration_layers(self):
        for src in (
            f"from {PKG}.obs.metrics import metrics_registry\n",
            f"from {PKG}.obs.trace import active_tracer\n",
            f"from {PKG}.obs.slo import LatencyObjective\n",
        ):
            for rel in (
                f"{PKG}/pipeline.py",
                f"{PKG}/serve/coalesce.py",
                f"{PKG}/state/journal.py",
                f"{PKG}/cli.py",
                # Round 12: analytics surfaces are orchestration-
                # adjacent (graph alignment, tuner resolution) — allowed;
                # the analytics KERNELS live in ops/ and stay flagged.
                f"{PKG}/analytics/bands.py",
                # Round 16: cluster recovery records recovery-scope
                # trace spans (the crash-postmortem ring) — allowed.
                f"{PKG}/cluster/recover.py",
                # Round 17: the socket front door counts connections/
                # frames/wire errors — allowed (write surface only).
                f"{PKG}/net/server.py",
            ):
                assert _codes(src, rel, select=["LY303"]) == [], (src, rel)

    def test_obs_read_surface_confined_to_serve_and_cli(self):
        # Round 16: the exporter/fleet/health READ surface — serve/cli
        # may import it; every other segment (including the otherwise
        # obs-allowed orchestration tiers) is flagged, lazy or not.
        for sub in ("export", "fleet", "health"):
            src = f"from {PKG}.obs.{sub} import anything\n"
            lazy = (
                f"def f():\n    from {PKG}.obs import {sub}\n"
                f"    return {sub}\n"
            )
            for rel in (
                f"{PKG}/serve/coalesce.py",
                f"{PKG}/cli.py",
            ):
                assert _codes(src, rel, select=["LY303"]) == [], (sub, rel)
            for rel in (
                f"{PKG}/pipeline.py",
                f"{PKG}/state/journal.py",
                f"{PKG}/analytics/bands.py",
                f"{PKG}/cluster/recover.py",
                f"{PKG}/ops/case.py",
                # Round 17: net may WRITE metrics but is not a read-
                # surface importer — the server serves requests; the
                # service's exporter serves metrics.
                f"{PKG}/net/server.py",
            ):
                for bad in (src, lazy):
                    assert "LY303" in _codes(
                        bad, rel, select=["LY303"]
                    ), (sub, rel, bad)

    def test_obs_import_flagged_from_pure_math_layers(self):
        # `from pkg import obs` and lazy in-function imports both count.
        for src in (
            f"from {PKG} import obs\n",
            f"def f():\n    from {PKG}.obs import ledger\n    return ledger\n",
        ):
            for rel in (f"{PKG}/parallel/case.py", f"{PKG}/ops/case.py"):
                assert "LY303" in _codes(src, rel, select=["LY303"]), (
                    src, rel,
                )


class TestSuppression:
    def test_blanket_noqa(self):
        src = "def f(x):\n    return x == None  # noqa\n"
        assert _codes(src, "tests/case.py") == []

    def test_id_noqa(self):
        src = "def f(x):\n    return x == None  # noqa: E711\n"
        assert "E711" not in _codes(src, "tests/case.py")

    def test_wrong_id_noqa_does_not_suppress(self):
        src = "def f(x):\n    return x == None  # noqa: F401\n"
        assert "E711" in _codes(src, "tests/case.py")


class TestFenceAudit:
    """JX109: the block_until_ready-vs-fence audit. Co-occurrence with a
    monotonic-clock read defines a timing window; a bare correctness sync
    is legitimate and stays quiet."""

    def test_bare_sync_without_stopwatch_is_quiet(self):
        src = (
            "import jax\n\n\ndef sync(x):\n"
            "    jax.block_until_ready(x)\n    return x\n"
        )
        assert _codes(src, "scripts/case.py", select=["JX109"]) == []

    def test_module_level_timing_script_is_flagged(self):
        src = (
            "import time\nimport jax\n\nstart = time.perf_counter()\n"
            "jax.block_until_ready(start)\n"
            "print(time.perf_counter() - start)\n"
        )
        assert "JX109" in _codes(src, "scripts/case.py", select=["JX109"])

    def test_second_same_named_method_still_scanned(self):
        # _all_defs dedupes by name (lookup semantics); the fence audit
        # must scan EVERY def — the violating second `run` here.
        src = (
            "import time\nimport jax\n\n\nclass A:\n    def run(self, x):\n"
            "        return x\n\n\nclass B:\n    def run(self, f, x):\n"
            "        t0 = time.perf_counter()\n"
            "        jax.block_until_ready(f(x))\n"
            "        return time.perf_counter() - t0\n"
        )
        assert "JX109" in _codes(src, "scripts/case.py", select=["JX109"])

    def test_nested_def_does_not_contaminate_module_scope(self):
        # A def nested in an `if` block is its own scope: its stopwatch
        # must not turn an unrelated module-level correctness sync into
        # a finding, and the module-level sync must not silence it.
        src = (
            "import time\nimport jax\n\n"
            "jax.block_until_ready(warmup())\n\n"
            "if True:\n    def main():\n"
            "        t0 = time.perf_counter()\n"
            "        return time.perf_counter() - t0\n"
        )
        assert _codes(src, "scripts/case.py", select=["JX109"]) == []

    def test_timed_outer_does_not_contaminate_inner_helper(self):
        # The enclosing function times something; the nested helper's
        # bare sync is a different scope and stays quiet.
        src = (
            "import time\nimport jax\n\n\ndef outer(f, x):\n"
            "    t0 = time.perf_counter()\n\n"
            "    def helper(y):\n"
            "        jax.block_until_ready(y)\n        return y\n\n"
            "    return helper(f(x)), time.perf_counter() - t0\n"
        )
        assert _codes(src, "scripts/case.py", select=["JX109"]) == []

    def test_aliased_clock_still_counts(self):
        src = (
            "import time as _time\nimport jax\n\n\ndef timed(f, x):\n"
            "    t0 = _time.monotonic()\n"
            "    jax.block_until_ready(f(x))\n"
            "    return _time.monotonic() - t0\n"
        )
        assert "JX109" in _codes(src, "scripts/case.py", select=["JX109"])

    def test_is_warning_tier(self):
        assert RULES["JX109"].severity == "warning"


class TestShardingSpecAudit:
    """SH401: PartitionSpec arguments in ``parallel/`` must resolve to the
    mesh's real axes. The vocabulary is tiny (MARKETS_AXIS/SOURCES_AXIS)
    so the checker is exact; it must accept every legal spec shape the
    repo uses (None dims, tuple dims, empty specs, attribute-qualified
    constants) and stay out of foreign paths."""

    _REL = f"{PKG}/parallel/case.py"

    def _codes(self, src):
        return [
            f.rule_id
            for f in check_source(src, self._REL, select=["SH401"])
        ]

    def test_empty_and_none_and_tuple_specs_are_legal(self):
        src = (
            f"from {PKG}.parallel.mesh import MARKETS_AXIS, SOURCES_AXIS\n"
            "from jax.sharding import PartitionSpec as P\n\n"
            "A = P()\n"
            "B = P(MARKETS_AXIS, None)\n"
            "C = P((MARKETS_AXIS, SOURCES_AXIS), None)\n"
        )
        assert self._codes(src) == []

    def test_attribute_qualified_constant_is_legal(self):
        src = (
            f"from {PKG}.parallel import mesh\n"
            "from jax.sharding import PartitionSpec\n\n"
            "SPEC = PartitionSpec(mesh.MARKETS_AXIS)\n"
        )
        assert self._codes(src) == []

    def test_literal_axis_names_are_legal(self):
        # mesh.py itself pins the constants to these strings; a doc
        # example using them directly must not be a violation.
        src = (
            "from jax.sharding import PartitionSpec as P\n\n"
            "SPEC = P('markets', 'sources')\n"
        )
        assert self._codes(src) == []

    def test_typo_string_and_unknown_name_are_flagged(self):
        src = (
            "from jax.sharding import PartitionSpec as P\n\n"
            "AXIS = 'markets'\n"
            "A = P('market')\n"      # typo'd literal
            "B = P(AXIS)\n"          # computed — unverifiable
            "C = P(AGENTS_AXIS)\n"   # unknown constant
        )
        assert self._codes(src) == ["SH401", "SH401", "SH401"]

    def test_tuple_with_one_bad_axis_is_flagged(self):
        src = (
            f"from {PKG}.parallel.mesh import MARKETS_AXIS\n"
            "from jax.sharding import PartitionSpec as P\n\n"
            "SPEC = P((MARKETS_AXIS, 'agent'), None)\n"
        )
        assert self._codes(src) == ["SH401"]

    def test_stays_out_of_non_parallel_paths(self):
        src = (
            "from jax.sharding import PartitionSpec as P\n\n"
            "SPEC = P('bogus')\n"
        )
        for rel in (f"{PKG}/ops/case.py", "scripts/case.py", None):
            assert "SH401" not in [
                f.rule_id for f in check_source(src, rel, select=["SH401"])
            ], rel


class TestCliContract:
    """The module entry point: exit codes, JSON shape, rule IDs."""

    def _run(self, *argv, cwd=None):
        return subprocess.run(
            [sys.executable, "-m", "bayesian_consensus_engine_tpu.lint", *argv],
            capture_output=True,
            text=True,
            cwd=cwd or _ROOT,
            timeout=120,
        )

    def test_exit_1_with_rule_ids_on_seeded_violation(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text(
            "import os\n\ndef f(x):\n    try:\n        return x == None\n"
            "    except:\n        return None\n"
        )
        proc = self._run(str(bad))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        for rule_id in ("F401", "E711", "E722"):
            assert rule_id in proc.stdout

    def test_exit_0_on_clean_file(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text("def f(x):\n    return x + 1\n")
        proc = self._run(str(clean))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_exit_1_on_nonexistent_path(self, tmp_path):
        # A typo'd path in a CI step must not pass as "0 findings".
        proc = self._run(str(tmp_path / "no_such_file.py"))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "E902" in proc.stdout

    def test_json_output_shape(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text("x = f'constant'\n")
        proc = self._run("--format", "json", str(bad))
        payload = json.loads(proc.stdout)
        assert payload["files"] == 1
        (finding,) = payload["findings"]
        assert finding["rule_id"] == "F541"
        assert finding["line"] == 1
        assert finding["severity"] == "error"


class TestSelectValidation:
    """Unknown ``--select`` IDs must error with near-misses, not run
    zero rules and exit 0 — the silently-green CI step bug."""

    def test_check_source_raises_with_near_miss(self):
        with pytest.raises(ValueError) as exc:
            check_source("x = 1\n", None, select=["JX9999"])
        msg = str(exc.value)
        assert "JX9999" in msg
        assert "JX1" in msg.replace("JX9999", "")  # a JX catalog near-miss

    def test_run_raises_too(self, tmp_path):
        (tmp_path / "a.py").write_text("x = 1\n")
        with pytest.raises(ValueError):
            run(["a.py"], root=tmp_path, select=["NOPE99"])

    def test_valid_select_unaffected(self):
        assert _codes("x = f'const'\n", None, select=["F541"]) == ["F541"]

    def test_cli_exits_2_with_catalog_hint(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text("def f(x):\n    return x\n")
        proc = subprocess.run(
            [
                sys.executable, "-m", "bayesian_consensus_engine_tpu.lint",
                "--select", "JX9999", str(clean),
            ],
            capture_output=True, text=True, cwd=_ROOT, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "JX9999" in proc.stderr
        assert "did you mean" in proc.stderr


class TestRunDedupe:
    """Overlapping targets lint (and count) each file exactly once."""

    def _tree(self, tmp_path):
        pkg = tmp_path / "pkg"
        sub = pkg / "sub"
        sub.mkdir(parents=True)
        (pkg / "a.py").write_text("x = f'const'\n")  # one F541 each
        (sub / "b.py").write_text("y = f'const'\n")
        return pkg

    def test_overlapping_dirs_count_once(self, tmp_path):
        self._tree(tmp_path)
        n_once, f_once = run(["pkg"], root=tmp_path)
        n_twice, f_twice = run(["pkg", "pkg/sub"], root=tmp_path)
        assert n_once == n_twice == 2
        assert [f.render() for f in f_once] == [f.render() for f in f_twice]

    def test_file_named_twice_counts_once(self, tmp_path):
        pkg = self._tree(tmp_path)
        n, findings = run(
            ["pkg/a.py", str(pkg / "a.py")], root=tmp_path
        )
        assert n == 1
        assert len(findings) == 1

    def test_e902_semantics_survive_dedupe(self, tmp_path):
        self._tree(tmp_path)
        n, findings = run(["pkg", "no_such_dir"], root=tmp_path)
        assert n == 2
        assert [f.rule_id for f in findings].count("E902") == 1


class TestProjectStatsLine:
    """`run(stats=…)` and the CLI surface the traced-set numbers, so a
    CI log shows the whole-program pass actually ran."""

    _SRC = (
        "import jax\n\ndef helper(x):\n    return x + 1\n\n"
        "@jax.jit\ndef entry(x):\n    return helper(x)\n"
    )

    def test_run_fills_stats(self, tmp_path):
        (tmp_path / "mod.py").write_text(self._SRC)
        stats: dict = {}
        run(["mod.py"], root=tmp_path, stats=stats)
        assert stats["traced_functions"] == 2  # entry + helper
        assert stats["traced_modules"] == 1

    def test_cli_prints_traced_set_line(self, tmp_path):
        mod = tmp_path / "mod.py"
        mod.write_text(self._SRC)
        proc = subprocess.run(
            [
                sys.executable, "-m", "bayesian_consensus_engine_tpu.lint",
                str(mod),
            ],
            capture_output=True, text=True, cwd=_ROOT, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "traced set: 2 functions across 1 modules" in proc.stdout

    def test_json_output_carries_stats(self, tmp_path):
        mod = tmp_path / "mod.py"
        mod.write_text(self._SRC)
        proc = subprocess.run(
            [
                sys.executable, "-m", "bayesian_consensus_engine_tpu.lint",
                "--format", "json", str(mod),
            ],
            capture_output=True, text=True, cwd=_ROOT, timeout=120,
        )
        payload = json.loads(proc.stdout)
        assert payload["stats"]["traced_functions"] == 2


class TestSeverityTiers:
    """The two-tier contract: ``error`` gates (CLI exit 1, bench/perf_lab
    refuse to measure), ``warning`` is advisory — printed everywhere,
    failing nothing."""

    _BAD_WARM = (
        "import jax\n\ndef step(state, x):\n    return state + x\n\n"
        "step_fast = jax.jit(step)\n"
    )

    def test_jx108_is_warning_tier(self):
        assert RULES["JX108"].severity == "warning"
        (finding,) = [
            f for f in check_source(
                self._BAD_WARM, f"{PKG}/state/case.py", select=["JX108"]
            )
        ]
        assert finding.severity == "warning"
        assert "[warning]" in finding.render()

    def test_same_shape_in_a_hot_path_stays_error_tier(self):
        (finding,) = check_source(
            self._BAD_WARM, f"{PKG}/core/case.py", select=["JX105", "JX108"]
        )
        assert finding.rule_id == "JX105"
        assert finding.severity == "error"

    def test_registry_rejects_unknown_severity(self):
        from bayesian_consensus_engine_tpu.lint.registry import rule

        with pytest.raises(ValueError, match="severity"):
            rule("ZZ999", name="bad-tier", rationale="x", severity="fatal")(
                lambda ctx: ()
            )

    def test_cli_exits_0_on_warnings_only(self, tmp_path, capsys,
                                          monkeypatch):
        from bayesian_consensus_engine_tpu.lint import engine

        case = tmp_path / PKG / "state" / "case.py"
        case.parent.mkdir(parents=True)
        case.write_text(self._BAD_WARM)
        monkeypatch.setattr(engine, "_repo_root", lambda: tmp_path)
        rc = engine.main(["--select", "JX108", f"{PKG}/state/case.py"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "JX108 [warning]" in out
        assert "1 warnings" in out and "0 errors" in out

    def test_bench_gate_passes_warnings_fails_errors(self, monkeypatch,
                                                     capsys):
        import bench
        from bayesian_consensus_engine_tpu import lint
        from bayesian_consensus_engine_tpu.lint.engine import Finding

        warning = Finding("x.py", 1, "JX108", "advisory", "warning")
        error = Finding("y.py", 2, "JX105", "gating", "error")

        # bench passes its cache sidecar through run(cache=…) — the stub
        # accepts and ignores it (the gate contract under test is the
        # severity split, not the cache).
        monkeypatch.setattr(lint, "run", lambda **kw: (1, [warning]))
        bench.lint_gate(skip=False)  # warnings only: the gate passes...
        assert "JX108" in capsys.readouterr().err  # ...but still prints

        monkeypatch.setattr(lint, "run", lambda **kw: (2, [warning, error]))
        with pytest.raises(SystemExit):
            bench.lint_gate(skip=False)
        err = capsys.readouterr().err
        assert "1 findings above" in err  # errors counted, warnings not


class TestDocsCatalog:
    def test_every_rule_documented(self):
        catalog = (_ROOT / "docs" / "static-analysis.md").read_text()
        for rule_id in RULES:
            assert rule_id in catalog, (
                f"rule {rule_id} missing from docs/static-analysis.md"
            )
