"""Durability journal (state/journal.py): binary epochs, replay, torn
tails, and the settle_stream(journal=) rolling tier.

The journal exists because rolling SQLite checkpoints floor near
~200-300k rows/s (the interchange format's text-PK UPSERT — measured
11.8 s of a 21.7 s stream wall on-chip, docs/round5-notes.md); an epoch
appends the same rows as raw fsynced columns. The non-negotiable
contracts pinned here: replay reproduces the store EXACTLY (values, ISO
strings, row assignment), a torn tail never corrupts — the journal is
valid through the last complete epoch and reports its watermark — and
journal mode changes nothing about the stream's results or its SQLite
interchange file.
"""

import random
import sqlite3

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from bayesian_consensus_engine_tpu.state import JournalWriter, replay_journal
from bayesian_consensus_engine_tpu.state.records import ReliabilityRecord
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)


def db_records(path):
    with sqlite3.connect(path) as conn:
        return conn.execute(
            "SELECT source_id, market_id, reliability, confidence, updated_at"
            " FROM sources ORDER BY source_id, market_id"
        ).fetchall()


def store_fingerprint(store):
    """Everything replay must reproduce: records AND row assignment."""
    store.sync()
    return (store.list_sources(), store._pairs.ids())


def seeded_store(n=40, seed=3):
    rng = random.Random(seed)
    store = TensorReliabilityStore()
    for i in range(n):
        store.put_record(
            ReliabilityRecord(
                source_id=f"src-{i % 7}",
                market_id=f"mkt-{i}",
                reliability=round(rng.random(), 6),
                confidence=round(rng.random(), 6),
                updated_at=f"2026-07-{10 + (i % 19):02d}T12:00:00+00:00",
            )
        )
    return store


class TestJournalRoundTrip:
    def test_single_epoch_replay_exact(self, tmp_path):
        store = seeded_store()
        path = tmp_path / "a.jrnl"
        with JournalWriter(path) as journal:
            rows = store.flush_to_journal(journal, tag=7)
        assert rows == len(store)
        replayed, tag = replay_journal(path)
        assert tag == 7
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_incremental_epochs_write_only_dirty(self, tmp_path):
        store = seeded_store(n=30)
        path = tmp_path / "b.jrnl"
        with JournalWriter(path) as journal:
            first = store.flush_to_journal(journal, tag=0)
            assert first == 30
            # Touch 3 rows + add 2 new pairs: the next epoch is exactly
            # those 5 rows, not a re-snapshot.
            for i in (4, 9, 11):
                store.update_reliability(f"src-{i % 7}", f"mkt-{i}", True)
            store.put_record(
                ReliabilityRecord(
                    source_id="src-new",
                    market_id="mkt-new-1",
                    reliability=0.625,
                    confidence=0.5,
                    updated_at="2026-07-31T00:00:00+00:00",
                )
            )
            store.put_record(
                ReliabilityRecord(
                    source_id="src-new",
                    market_id="mkt-new-2",
                    reliability=0.125,
                    confidence=0.75,
                    updated_at="2026-07-31T01:00:00+00:00",
                )
            )
            second = store.flush_to_journal(journal, tag=1)
            assert second == 5
            # Nothing dirty: an empty epoch is legal and cheap.
            assert store.flush_to_journal(journal, tag=2) == 0
        replayed, tag = replay_journal(path)
        assert tag == 2
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_journal_dirty_is_independent_of_sqlite_dirty(self, tmp_path):
        store = seeded_store(n=12)
        path = tmp_path / "c.jrnl"
        db = tmp_path / "c.db"
        with JournalWriter(path) as journal:
            store.flush_to_journal(journal, tag=0)
            # A journal epoch must not shrink the next SQLite flush...
            store.flush_to_sqlite(db)
            assert db_records(db) != []
            # ...and an SQLite flush must not shrink the next epoch.
            store.update_reliability("src-1", "mkt-1", False)
            store.flush_to_sqlite(db)
            assert store.flush_to_journal(journal, tag=1) == 1
        replayed, _ = replay_journal(path)
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_replayed_store_flushes_full_sqlite(self, tmp_path):
        # Replay marks rows dirty in the NEW store's lifetime, so its
        # first SQLite flush writes the complete interchange file.
        store = seeded_store(n=9)
        path = tmp_path / "d.jrnl"
        with JournalWriter(path) as journal:
            store.flush_to_journal(journal)
        replayed, _ = replay_journal(path)
        replayed.flush_to_sqlite(tmp_path / "replayed.db")
        store.flush_to_sqlite(tmp_path / "orig.db")
        assert db_records(tmp_path / "replayed.db") == db_records(
            tmp_path / "orig.db"
        )


class TestTornTail:
    def _two_epoch_journal(self, tmp_path):
        store = seeded_store(n=20)
        path = tmp_path / "torn.jrnl"
        with JournalWriter(path) as journal:
            store.flush_to_journal(journal, tag=0)
            store.update_reliability("src-2", "mkt-2", True)
            store.flush_to_journal(journal, tag=1)
        return path, store

    def test_truncated_tail_drops_last_epoch_only(self, tmp_path):
        path, store = self._two_epoch_journal(tmp_path)
        raw = path.read_bytes()
        path.write_bytes(raw[:-7])  # torn mid-CRC of epoch 1
        replayed, tag = replay_journal(path)
        assert tag == 0
        # Epoch 0's content is intact: same pairs, epoch-0 values.
        assert len(replayed) == len(store)

    def test_corrupt_byte_fails_crc_and_drops_epoch(self, tmp_path):
        path, _ = self._two_epoch_journal(tmp_path)
        raw = bytearray(path.read_bytes())
        raw[-20] ^= 0xFF  # inside epoch 1's payload
        path.write_bytes(bytes(raw))
        _, tag = replay_journal(path)
        assert tag == 0

    def test_resume_truncates_torn_tail_and_appends(self, tmp_path):
        path, store = self._two_epoch_journal(tmp_path)
        raw = path.read_bytes()
        path.write_bytes(raw[:-7])  # torn mid-CRC of epoch 1
        # Resume drops the torn epoch 1, then appends a fresh epoch whose
        # index is dense with the valid prefix.
        with JournalWriter(path, resume=True) as journal:
            assert journal.epoch_index == 1
            store._journal_dirty[:] = False
            store.update_reliability("src-3", "mkt-3", True)
            store.flush_to_journal(journal, tag=9)
        replayed, tag = replay_journal(path)
        assert tag == 9
        rec = {
            (r.source_id, r.market_id): r for r in replayed.list_sources()
        }
        live = {
            (r.source_id, r.market_id): r for r in store.list_sources()
        }
        assert rec[("src-3", "mkt-3")] == live[("src-3", "mkt-3")]

    def test_store_behind_journal_rejected(self, tmp_path):
        path, _ = self._two_epoch_journal(tmp_path)
        with JournalWriter(path, resume=True) as journal:
            with pytest.raises(ValueError, match="journal already covers"):
                TensorReliabilityStore().flush_to_journal(journal)

    def test_empty_journal_replays_to_empty_store(self, tmp_path):
        path = tmp_path / "empty.jrnl"
        JournalWriter(path).close()
        store, tag = replay_journal(path)
        assert tag is None and len(store) == 0

    def test_bad_magic_raises(self, tmp_path):
        path = tmp_path / "bad.jrnl"
        path.write_bytes(b"NOTAJRNL" + b"\x00" * 32)
        with pytest.raises(ValueError, match="magic"):
            replay_journal(path)


def random_payloads(rng, num_markets, universe=15, max_signals=5, tag=""):
    payloads = []
    for m in range(num_markets):
        n = rng.randint(1, max_signals)
        signals = [
            {
                "sourceId": f"src-{rng.randrange(universe)}",
                "probability": round(rng.random(), 6),
            }
            for _ in range(n)
        ]
        payloads.append((f"jm{tag}-{m}", signals))
    return payloads


def stream_batches(num_batches=4, markets=9, seed=61):
    rng = random.Random(seed)
    out = []
    for b in range(num_batches):
        payloads = random_payloads(rng, markets, tag=f"-b{b}")
        outcomes = [rng.random() < 0.5 for _ in range(markets)]
        out.append((payloads, outcomes))
    return out


class TestSettleStreamJournal:
    def _run(self, batches, db=None, journal=None, **kw):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, batches, steps=2, now=21_300.0, db_path=db,
                journal=journal, **kw,
            )
        )
        return store, results

    def test_journal_mode_matches_plain_stream_and_replays(self, tmp_path):
        batches = stream_batches()
        plain_store, plain_results = self._run(
            batches, db=tmp_path / "plain.db"
        )
        store, results = self._run(
            batches, db=tmp_path / "stream.db",
            journal=tmp_path / "s.jrnl", checkpoint_every=2,
        )
        for mine, ref in zip(results, plain_results):
            np.testing.assert_array_equal(mine.consensus, ref.consensus)
        # The interchange file is unchanged by journal mode.
        assert db_records(tmp_path / "stream.db") == db_records(
            tmp_path / "plain.db"
        )
        # The journal's durable truth equals the live store, watermarked
        # at the last settled batch.
        replayed, tag = replay_journal(tmp_path / "s.jrnl")
        assert tag == len(batches) - 1
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_journal_only_mode_needs_no_db(self, tmp_path):
        batches = stream_batches(num_batches=3)
        store, _ = self._run(batches, journal=tmp_path / "only.jrnl")
        replayed, tag = replay_journal(tmp_path / "only.jrnl")
        assert tag == 2
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_break_recovery_resumes_from_watermark(self, tmp_path):
        # Consumer dies after 2 of 5 batches; replay + resume from
        # tag+1 must equal the uninterrupted run exactly.
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = stream_batches(num_batches=5)
        full_store, _ = self._run(batches)

        store = TensorReliabilityStore()
        stream = settle_stream(
            store, batches, steps=2, now=21_300.0,
            journal=tmp_path / "r.jrnl",
        )
        for i, _result in enumerate(stream):
            if i == 1:
                stream.close()  # GeneratorExit -> tail epoch, tag=1
                break
        replayed, tag = replay_journal(tmp_path / "r.jrnl")
        assert tag == 1
        # Resume APPENDS to the same journal (resume=True); a bare path
        # must refuse rather than truncate durable epochs.
        with pytest.raises(ValueError, match="refusing to truncate"):
            JournalWriter(tmp_path / "r.jrnl")
        resumed = list(
            settle_stream(
                replayed, batches[tag + 1:], steps=2,
                now=21_300.0 + tag + 1,
                journal=JournalWriter(tmp_path / "r.jrnl", resume=True),
            )
        )
        assert len(resumed) == 3
        assert store_fingerprint(replayed) == store_fingerprint(full_store)
        # The appended-to journal now replays to the COMPLETE run.
        replayed2, tag2 = replay_journal(tmp_path / "r.jrnl")
        assert tag2 == 2  # resumed stream's own batch indices (0-based)
        assert store_fingerprint(replayed2) == store_fingerprint(full_store)

    def test_sharded_stream_journal_matches_flat(self, tmp_path):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh

        batches = stream_batches(num_batches=3, seed=71)
        flat_store, flat_results = self._run(batches)
        store, results = self._run(
            batches, journal=tmp_path / "m.jrnl", mesh=make_mesh(),
            checkpoint_every=2,
        )
        for mine, ref in zip(results, flat_results):
            np.testing.assert_array_equal(mine.consensus, ref.consensus)
        replayed, tag = replay_journal(tmp_path / "m.jrnl")
        assert tag == 2
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_lazy_checkpoints_rejected_with_journal(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        with pytest.raises(ValueError, match="lazy"):
            list(
                settle_stream(
                    TensorReliabilityStore(), [],
                    journal=tmp_path / "x.jrnl", lazy_checkpoints=True,
                )
            )

    def test_settle_raise_never_claims_failed_batch(self, tmp_path):
        # A batch that raises mid-settle must not be covered by the tail
        # epoch: the journal watermark stops at the last SETTLED batch.
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = stream_batches(num_batches=3, seed=81)
        bad = (batches[1][0], batches[1][1][:2])  # truncated outcomes
        store = TensorReliabilityStore()
        with pytest.raises(Exception):
            list(
                settle_stream(
                    store, [batches[0], bad, batches[2]], steps=1,
                    now=21_400.0, journal=tmp_path / "f.jrnl",
                )
            )
        _, tag = replay_journal(tmp_path / "f.jrnl")
        assert tag == 0


class TestDirectoryFsync:
    """append_epoch's durability contract covers the directory ENTRY, not
    just the file bytes: a fresh journal (and a compaction's os.replace)
    must fsync the parent directory, or a crash can unlink every epoch the
    service already reported durable (ADVICE round 5, medium)."""

    @staticmethod
    def _fsync_log(monkeypatch):
        import os as _os
        import stat as _stat

        real_fsync = _os.fsync
        log = []

        def logging_fsync(fd):
            kind = (
                "dir" if _stat.S_ISDIR(_os.fstat(fd).st_mode) else "file"
            )
            log.append(kind)
            return real_fsync(fd)

        monkeypatch.setattr(_os, "fsync", logging_fsync)
        return log

    def test_fresh_journal_fsyncs_parent_directory(self, tmp_path,
                                                   monkeypatch):
        log = self._fsync_log(monkeypatch)
        JournalWriter(tmp_path / "fresh.jrnl").close()
        assert "dir" in log, "journal creation never pinned its dir entry"

    def test_append_epoch_fsyncs_the_file(self, tmp_path, monkeypatch):
        store = seeded_store(n=4)
        journal = JournalWriter(tmp_path / "a.jrnl")
        log = self._fsync_log(monkeypatch)
        with journal:
            store.flush_to_journal(journal, tag=0)
        assert "file" in log

    def test_fsync_false_skips_both(self, tmp_path, monkeypatch):
        log = self._fsync_log(monkeypatch)
        with JournalWriter(tmp_path / "nf.jrnl", fsync=False) as journal:
            seeded_store(n=3).flush_to_journal(journal)
        assert log == []

    def test_compaction_fsyncs_directory_after_replace(self, tmp_path,
                                                       monkeypatch):
        import os as _os

        from bayesian_consensus_engine_tpu.state.journal import (
            compact_journal,
        )

        path = tmp_path / "c.jrnl"
        store = seeded_store(n=10)
        with JournalWriter(path) as journal:
            store.flush_to_journal(journal, tag=0)
            store.update_reliability("src-1", "mkt-1", True)
            store.flush_to_journal(journal, tag=1)

        events = []
        log = self._fsync_log(monkeypatch)
        real_replace = _os.replace

        def logging_replace(src, dst):
            events.append(("replace", len(log)))
            return real_replace(src, dst)

        monkeypatch.setattr(_os, "replace", logging_replace)
        compact_journal(path)
        (replace_event,) = [e for e in events if e[0] == "replace"]
        # At least one DIRECTORY fsync lands after the rename — the one
        # that pins the swapped entry against a crash-revert.
        assert "dir" in log[replace_event[1]:], (
            "os.replace was never followed by a directory fsync"
        )
        replayed, tag = replay_journal(path)
        assert tag == 1
        assert store_fingerprint(replayed) == store_fingerprint(store)


def _append_raw_frame(path, epoch_index, used_after, pair_blob, idx,
                      iso_values, tag=0):
    """Append a CRC-VALID frame with caller-controlled (possibly garbage)
    semantics — the 'CRC-of-garbage' shape a buggy writer produces."""
    import struct
    import zlib

    from bayesian_consensus_engine_tpu.state.journal import _EPOCH_HDR

    iso_blob = b"".join(
        struct.pack("<I", len(v.encode())) + v.encode() for v in iso_values
    )
    dirty = len(idx)
    header = _EPOCH_HDR.pack(
        epoch_index, used_after, len(pair_blob), dirty, len(iso_blob),
        0.0, tag,
    )
    payload = b"".join(
        (
            header,
            pair_blob,
            np.asarray(idx, np.uint64).tobytes(),
            np.full(dirty, 0.5, np.float64).tobytes(),
            np.full(dirty, 0.5, np.float64).tobytes(),
            np.zeros(dirty, np.float64).tobytes(),
            np.ones(dirty, np.uint8).tobytes(),
            iso_blob,
        )
    )
    with open(path, "ab") as f:
        f.write(payload)
        f.write(struct.pack("<I", zlib.crc32(payload)))


class TestSemanticResumeScan:
    """The resume scan must apply the SAME semantic checks replay does
    (ADVICE round 5, low): a CRC-valid but malformed epoch otherwise makes
    a resumed writer append after a frame replay stops before, surfacing
    later as a row-count mismatch in flush_to_journal."""

    def _journal_with_garbage_tail(self, tmp_path, kind):
        store = seeded_store(n=8)
        path = tmp_path / "g.jrnl"
        with JournalWriter(path) as journal:
            store.flush_to_journal(journal, tag=0)
        rows = len(store)
        if kind == "idx_out_of_bounds":
            _append_raw_frame(
                path, 1, rows, b"", [rows + 7],
                ["2026-08-01T00:00:00+00:00"], tag=1,
            )
        elif kind == "unparseable_pair_blob":
            # Claims one new pair but ships an empty blob.
            _append_raw_frame(path, 1, rows + 1, b"", [0], ["x"], tag=1)
        else:
            raise AssertionError(kind)
        return path, store, rows

    @pytest.mark.parametrize(
        "kind", ["idx_out_of_bounds", "unparseable_pair_blob"]
    )
    def test_replay_and_resume_stop_at_the_same_epoch(self, tmp_path, kind):
        path, store, rows = self._journal_with_garbage_tail(tmp_path, kind)
        replayed, tag = replay_journal(path)
        assert tag == 0  # the garbage epoch never lands
        with JournalWriter(path, resume=True) as journal:
            # Resume agrees with replay: appends AFTER epoch 0, covering
            # exactly the rows replay rebuilt — no late row-count mismatch.
            assert journal.epoch_index == 1
            assert journal.rows_covered == rows == len(replayed)
            replayed._journal_dirty[:] = False
            replayed.update_reliability("src-2", "mkt-2", True)
            assert replayed.flush_to_journal(journal, tag=5) == 1
        rere, tag = replay_journal(path)
        assert tag == 5
        assert store_fingerprint(rere) == store_fingerprint(replayed)

    def test_garbage_tail_is_truncated_by_resume(self, tmp_path):
        path, _store, _rows = self._journal_with_garbage_tail(
            tmp_path, "idx_out_of_bounds"
        )
        before = path.stat().st_size
        JournalWriter(path, resume=True).close()
        assert path.stat().st_size < before


class TestWriterValidation:
    def test_used_after_regression_rejected(self, tmp_path):
        with JournalWriter(tmp_path / "v.jrnl") as journal:
            store = seeded_store(n=4)
            store.flush_to_journal(journal)
            with pytest.raises(ValueError, match="used_after"):
                journal.append_epoch(
                    2, [], np.array([], np.int64), np.array([]),
                    np.array([]), np.array([]), np.array([], np.uint8),
                    [],
                )

    def test_column_length_mismatch_rejected(self, tmp_path):
        with JournalWriter(tmp_path / "w.jrnl") as journal:
            with pytest.raises(ValueError, match="length"):
                journal.append_epoch(
                    0, [], np.array([0], np.int64), np.array([0.5]),
                    np.array([]), np.array([0.0]), np.array([1], np.uint8),
                    ["x"],
                )


class TestCompaction:
    """compact_journal: the WAL-checkpoint answer to unbounded growth —
    one full-snapshot epoch, same replayed state, same watermark,
    atomic swap, resumable afterwards."""

    def _grown_journal(self, tmp_path, epochs=6):
        store = seeded_store(n=40)
        path = tmp_path / "grow.jrnl"
        with JournalWriter(path) as journal:
            store.flush_to_journal(journal, tag=0)
            for e in range(1, epochs):
                # Re-touch the same rows every epoch: the journal grows
                # while the live state stays 40 rows.
                for i in range(0, 40, 3):
                    store.update_reliability(
                        f"src-{i % 7}", f"mkt-{i}", bool(e % 2)
                    )
                store.flush_to_journal(journal, tag=e)
        return path, store

    def test_compaction_shrinks_and_preserves_state_and_tag(self, tmp_path):
        from bayesian_consensus_engine_tpu.state import compact_journal

        path, store = self._grown_journal(tmp_path)
        before_state, before_tag = replay_journal(path)
        before_size = path.stat().st_size
        kept = compact_journal(path)
        assert kept == len(store)
        assert path.stat().st_size < before_size
        after_state, after_tag = replay_journal(path)
        assert after_tag == before_tag == 5
        assert store_fingerprint(after_state) == store_fingerprint(
            before_state
        )

    def test_resume_after_compaction_appends(self, tmp_path):
        from bayesian_consensus_engine_tpu.state import compact_journal

        path, store = self._grown_journal(tmp_path, epochs=3)
        compact_journal(path)
        # The store's journal-dirty view belongs to the OLD journal; a
        # resumed writer starts from the compacted file's coverage.
        with JournalWriter(path, resume=True) as journal:
            assert journal.epoch_index == 1  # the snapshot epoch
            store._journal_dirty[:] = False
            store.update_reliability("src-1", "mkt-1", True)
            store.flush_to_journal(journal, tag=9)
        replayed, tag = replay_journal(path)
        assert tag == 9
        live = {(r.source_id, r.market_id): r for r in store.list_sources()}
        got = {(r.source_id, r.market_id): r for r in replayed.list_sources()}
        assert got[("src-1", "mkt-1")] == live[("src-1", "mkt-1")]

    def test_epochless_journal_compacts_to_empty_not_tag_zero(
        self, tmp_path
    ):
        # Inventing tag=0 would make a resumed service skip batch 0; an
        # epoch-less journal must stay (empty, None) through compaction.
        from bayesian_consensus_engine_tpu.state import compact_journal

        path = tmp_path / "fresh.jrnl"
        JournalWriter(path).close()
        assert compact_journal(path) == 0
        store, tag = replay_journal(path)
        assert tag is None and len(store) == 0

    def test_stale_compact_leftover_is_discarded(self, tmp_path):
        # A crash between snapshot write and rename leaves path.compact;
        # the next compaction must clean it up, not fail forever.
        from bayesian_consensus_engine_tpu.state import compact_journal

        path, store = self._grown_journal(tmp_path, epochs=2)
        stale = tmp_path / "grow.jrnl.compact"
        stale.write_bytes(b"BCEJRNL1leftover-from-a-crash")
        kept = compact_journal(path)
        assert kept == len(store)
        assert not stale.exists()
        replayed, tag = replay_journal(path)
        assert tag == 1
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_compaction_of_torn_journal_keeps_valid_prefix(self, tmp_path):
        from bayesian_consensus_engine_tpu.state import compact_journal

        path, _ = self._grown_journal(tmp_path, epochs=3)
        _, pre_tear_tag = replay_journal(path)
        assert pre_tear_tag == 2  # the tear below really drops an epoch
        raw = path.read_bytes()
        path.write_bytes(raw[:-9])  # tear the final epoch
        want_state, want_tag = replay_journal(path)  # valid prefix only
        compact_journal(path)
        got_state, got_tag = replay_journal(path)
        assert got_tag == want_tag == 1
        assert store_fingerprint(got_state) == store_fingerprint(want_state)


class TestAsyncEpochs:
    """flush_to_journal_async: snapshot-now/write-in-background epochs.

    The round-6 contracts: a stream's async journal equals the sync
    journal byte-for-byte on any clean exit; a crash (or failure) mid
    background write recovers to the last JOINED epoch, never a torn
    one; a background failure surfaces at the next join with the dirty
    rows restored so a later epoch re-covers them.
    """

    @staticmethod
    def _pin_clock(monkeypatch):
        from bayesian_consensus_engine_tpu.state import journal as jmod

        monkeypatch.setattr(jmod.time, "time", lambda: 9_876.5)

    def test_async_epochs_byte_identical_to_sync(
        self, tmp_path, monkeypatch
    ):
        self._pin_clock(monkeypatch)

        def run(async_mode):
            path = tmp_path / ("a.jrnl" if async_mode else "s.jrnl")
            store = seeded_store()
            with JournalWriter(path) as journal:
                for round_no in range(3):
                    if async_mode:
                        handle = store.flush_to_journal_async(
                            journal, tag=round_no
                        )
                        assert handle.result() >= 0
                    else:
                        store.flush_to_journal(journal, tag=round_no)
                    store.put_record(ReliabilityRecord(
                        source_id=f"src-{round_no}",
                        market_id=f"mkt-{round_no}",
                        reliability=0.6,
                        confidence=0.7,
                        updated_at="2026-08-01T00:00:00+00:00",
                    ))
            return path.read_bytes()

        assert run(async_mode=True) == run(async_mode=False)

    def test_epochs_serialise_without_explicit_joins(self, tmp_path):
        # Back-to-back async flushes: each joins its predecessor, so the
        # journal replays to the final state even though the caller never
        # joined the intermediate handles.
        store = seeded_store()
        with JournalWriter(tmp_path / "chain.jrnl") as journal:
            for round_no in range(4):
                store.update_reliability("src-1", f"mkt-{round_no}", True)
                handle = store.flush_to_journal_async(journal, tag=round_no)
            handle.result()
        replayed, tag = replay_journal(tmp_path / "chain.jrnl")
        assert tag == 3
        assert store_fingerprint(replayed) == store_fingerprint(store)

    class _TornFile:
        """Writes the first *allow* bytes then fails — a disk-full crash
        mid background append."""

        def __init__(self, real, allow):
            self._real = real
            self._allow = allow

        def write(self, data):
            chunk = data[: self._allow]
            self._real.write(chunk)
            self._allow -= len(chunk)
            if len(chunk) < len(data):
                raise OSError(28, "No space left on device")
            return len(chunk)

        def __getattr__(self, name):
            return getattr(self._real, name)

    def test_crash_mid_async_epoch_recovers_last_joined(self, tmp_path):
        path = tmp_path / "torn.jrnl"
        store = seeded_store()
        journal = JournalWriter(path)
        store.flush_to_journal_async(journal, tag=0).result()  # baseline
        durable = store_fingerprint(store)

        store.update_reliability("src-0", "mkt-1", True)
        real_file = journal._file
        journal._file = self._TornFile(real_file, allow=32)
        handle = store.flush_to_journal_async(journal, tag=1)
        with pytest.raises(OSError, match="No space"):
            handle.result()
        journal._file = real_file

        # Replay lands at the last JOINED epoch — tag 0, bit-exact —
        # whether or not the torn frame's prefix bytes hit the disk.
        replayed, tag = replay_journal(path)
        assert tag == 0
        assert store_fingerprint(replayed) == durable
        # The failed epoch's rows were re-marked dirty: the retry epoch
        # re-covers them and replay now reaches the live state.
        store.flush_to_journal(journal, tag=1)
        journal.close()
        replayed, tag = replay_journal(path)
        assert tag == 1
        assert store_fingerprint(replayed) == store_fingerprint(store)

    def test_background_failure_surfaces_at_next_flush(self, tmp_path):
        store = seeded_store()
        journal = JournalWriter(tmp_path / "fail.jrnl")
        store.flush_to_journal_async(journal, tag=0).result()
        store.update_reliability("src-0", "mkt-2", True)
        journal._file = self._TornFile(journal._file, allow=0)
        store.flush_to_journal_async(journal, tag=1)  # handle dropped
        store.update_reliability("src-1", "mkt-3", True)
        with pytest.raises(OSError, match="No space"):
            store.flush_to_journal_async(journal, tag=2)
        journal.close()

    def test_store_close_joins_inflight_epoch(self, tmp_path):
        store = seeded_store()
        journal = JournalWriter(tmp_path / "join.jrnl")
        store.flush_to_journal_async(journal, tag=0)
        store.close()  # joins; an unjoined daemon write could be lost
        journal.close()
        _, tag = replay_journal(tmp_path / "join.jrnl")
        assert tag == 0

    def test_stream_async_journal_byte_identical_to_sync(
        self, tmp_path, monkeypatch
    ):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        self._pin_clock(monkeypatch)
        batches = stream_batches(num_batches=4, seed=91)

        def run(sync):
            path = tmp_path / ("sync.jrnl" if sync else "async.jrnl")
            store = TensorReliabilityStore()
            for _result in settle_stream(
                store, batches, steps=2, now=21_300.0, journal=path,
                checkpoint_every=2, sync_checkpoints=sync,
            ):
                pass
            return path.read_bytes()

        assert run(sync=False) == run(sync=True)

    def test_delta_counters_in_metrics_dump(self, tmp_path):
        # journal.delta_rows counts rows carried by DELTA epochs (the
        # full-snapshot first epoch is excluded); interchange.delta_rows
        # counts rows upserted by incremental SQLite exports. Both land
        # in the deterministic sorted-JSON dump.
        import json

        from bayesian_consensus_engine_tpu import obs

        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            store = seeded_store()
            with JournalWriter(tmp_path / "m.jrnl") as journal:
                store.flush_to_journal(journal, tag=0)  # full snapshot
                store.update_reliability("src-0", "mkt-0", True)
                store.update_reliability("src-1", "mkt-1", False)
                store.flush_to_journal_async(journal, tag=1).result()
            db = tmp_path / "x.db"
            store.flush_to_sqlite(db)  # baseline: full export
            store.update_reliability("src-2", "mkt-2", True)
            store.flush_to_sqlite(db)  # incremental
        finally:
            obs.set_metrics_registry(previous)
        counters = json.loads(registry.to_json())["counters"]
        assert counters["journal.delta_rows"] == 2
        assert counters["interchange.delta_rows"] == 1

    def test_stream_consumer_break_joins_inflight(self, tmp_path):
        # A consumer that stops mid-stream (GeneratorExit) must still get
        # the in-flight epoch's durability resolved before the generator
        # returns — the tail either joins it or appends after it.
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = stream_batches(num_batches=4, seed=93)
        store = TensorReliabilityStore()
        stream = settle_stream(
            store, batches, steps=2, now=21_300.0,
            journal=tmp_path / "brk.jrnl", checkpoint_every=2,
        )
        for i, _result in enumerate(stream):
            if i == 1:
                stream.close()
                break
        replayed, tag = replay_journal(tmp_path / "brk.jrnl")
        assert tag == 1
        assert store_fingerprint(replayed) == store_fingerprint(store)
