"""obs/ determinism contract: the instrumentation may never move a byte.

Four pinned properties (ISSUE 3 acceptance):

* the default histogram bucket layout is FROZEN — a changed edge silently
  re-bins every historical capture;
* metric export and ledger lines are byte-stable regardless of the order
  call sites registered things in (DT203 applied to ourselves);
* disabled mode is structurally free: every lookup returns one shared
  null object (no allocation to measure, nothing to misattribute);
* enabling obs changes NOTHING the engine produces — golden fixture
  bytes, settle/settle_stream results, and SQLite checkpoint files are
  identical with obs off and fully on.
"""

import hashlib
import json
import os
import tempfile
import threading
import time

import numpy as np
import pytest

from bayesian_consensus_engine_tpu import obs
from bayesian_consensus_engine_tpu.obs import ledger as obs_ledger
from bayesian_consensus_engine_tpu.obs import metrics as obs_metrics
from bayesian_consensus_engine_tpu.obs import timeline as obs_timeline


class TestHistogramLayout:
    def test_default_bounds_pinned(self):
        # 1 µs → 100 s, 2 per decade: 17 edges, frozen. Re-deriving from
        # the closed form guards the formula; the literal endpoints guard
        # the parameters.
        bounds = obs_metrics.DEFAULT_BOUNDS
        assert len(bounds) == 17
        assert bounds[0] == 1e-6
        assert bounds[-1] == pytest.approx(100.0)
        expected = tuple(1e-6 * 10.0 ** (i / 2) for i in range(17))
        assert bounds == expected

    def test_bounds_require_whole_decade_steps(self):
        with pytest.raises(ValueError):
            obs_metrics.log_spaced_bounds(1e-3, 5e-2, 2)
        with pytest.raises(ValueError):
            obs_metrics.log_spaced_bounds(-1.0, 1.0, 2)

    def test_observe_bins_and_overflow(self):
        h = obs_metrics.Histogram(bounds=(1.0, 10.0, 100.0))
        for value in (0.5, 1.0, 5.0, 99.0, 1000.0):
            h.observe(value)
        snap = h.snapshot()
        # value <= edge lands in that bucket; past the last edge is the
        # implicit overflow bucket.
        assert snap["counts"] == [2, 1, 1, 1]
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(0.5 + 1.0 + 5.0 + 99.0 + 1000.0)

    def test_conflicting_bounds_rejected(self):
        registry = obs_metrics.MetricsRegistry()
        registry.histogram("h", bounds=(1.0, 2.0))
        with pytest.raises(ValueError, match="different"):
            registry.histogram("h", bounds=(1.0, 3.0))


class TestWireBucketLayouts:
    """Round-16 telemetry-plane layouts, frozen like the default one:
    bucket edges are schema — a changed edge silently re-bins every
    historical scrape/burn capture (ISSUE 14)."""

    def test_scrape_latency_bounds_pinned(self):
        from bayesian_consensus_engine_tpu.obs.export import (
            SCRAPE_LATENCY_BOUNDS,
        )

        # 10 µs → 10 s, 2 per decade: 13 edges. A scrape is a registry
        # snapshot + a text render — the span from a no-op handler tick
        # to a pathological fleet-size export.
        assert len(SCRAPE_LATENCY_BOUNDS) == 13
        assert SCRAPE_LATENCY_BOUNDS[0] == 1e-5
        assert SCRAPE_LATENCY_BOUNDS[-1] == pytest.approx(10.0)
        expected = tuple(1e-5 * 10.0 ** (i / 2) for i in range(13))
        assert SCRAPE_LATENCY_BOUNDS == expected

    def test_burn_rate_bounds_pinned(self):
        from bayesian_consensus_engine_tpu.obs.health import (
            BURN_RATE_BOUNDS,
        )

        # 0.01× → 1000× of budget pace, 2 per decade: 11 edges — burn 1
        # (spending exactly on budget) sits on an exact edge.
        assert len(BURN_RATE_BOUNDS) == 11
        assert BURN_RATE_BOUNDS[0] == 0.01
        assert BURN_RATE_BOUNDS[-1] == pytest.approx(1000.0)
        expected = tuple(0.01 * 10.0 ** (i / 2) for i in range(11))
        assert BURN_RATE_BOUNDS == expected


class TestHistogramQuantile:
    """Round-8 quantile surface: bucket-interpolated, EXACT when the
    rank lands on a log-bucket boundary, reproducible from counts alone
    (the stats renderer's p50/p99 come from exported snapshots)."""

    def _hist(self, values, bounds=(1.0, 10.0, 100.0)):
        h = obs_metrics.Histogram(bounds=bounds)
        for value in values:
            h.observe(value)
        return h

    def test_empty_histogram_has_no_quantile(self):
        assert self._hist([]).quantile(0.5) is None
        assert obs_metrics.NULL_REGISTRY.histogram("x").quantile(0.5) is None

    def test_exact_on_log_bucket_boundary(self):
        # 5 observations in (0,1], 5 in (1,10]: the 0.5 rank lands
        # EXACTLY on the first bucket's cumulative count → its upper
        # edge, exactly — no interpolation drift.
        h = self._hist([0.5] * 5 + [5.0] * 5)
        assert h.quantile(0.5) == 1.0
        # ...and with 5+5+10, rank 0.25 ends bucket 0, rank 0.5 ends
        # bucket 1 — each is that bucket's exact upper edge.
        h = self._hist([0.5] * 5 + [5.0] * 5 + [50.0] * 10)
        assert h.quantile(0.25) == 1.0
        assert h.quantile(0.5) == 10.0
        assert h.quantile(1.0) == 100.0

    def test_interpolates_within_a_bucket(self):
        # All 4 observations in (1, 10]: rank q falls q of the way
        # through the bucket — linear between the edges.
        h = self._hist([5.0] * 4)
        assert h.quantile(0.5) == pytest.approx(1.0 + (10.0 - 1.0) * 0.5)
        assert h.quantile(0.0) == pytest.approx(1.0)

    def test_overflow_clamps_to_last_finite_edge(self):
        h = self._hist([1000.0] * 3)
        assert h.quantile(0.99) == 100.0  # a lower bound, never invented

    def test_rejects_out_of_range_q(self):
        with pytest.raises(ValueError, match="quantile"):
            self._hist([1.0]).quantile(1.5)

    def test_snapshot_and_merged_snapshot_agree(self):
        h = self._hist([0.5, 5.0, 5.0, 50.0])
        snap = h.snapshot()
        assert obs_metrics.quantile_from_snapshot(snap, 0.99) == (
            h.quantile(0.99)
        )
        # Merging two identical snapshots (the ledger's cross-repeat
        # path) preserves every quantile: same distribution, more mass.
        merged = {
            "bounds": snap["bounds"],
            "counts": [c * 2 for c in snap["counts"]],
        }
        for q in (0.25, 0.5, 0.75, 0.99):
            assert obs_metrics.quantile_from_snapshot(merged, q) == (
                h.quantile(q)
            )

    def test_summary_names_percentiles(self):
        h = self._hist([0.5] * 5 + [5.0] * 5)
        summary = h.summary((0.5, 0.99, 0.999))
        assert summary["count"] == 10
        assert summary["p50"] == 1.0
        assert set(summary) == {"count", "sum", "p50", "p99", "p99.9"}

    def test_ledger_merges_latency_hists_into_p50_p99(self, tmp_path):
        path = tmp_path / "latency.jsonl"
        bounds = [1.0, 10.0, 100.0]
        with obs.RunLedger(path, run_id="r1") as ledger:
            for counts in ([5, 5, 0, 0], [5, 5, 0, 0]):
                ledger.record(
                    "serve.latency", value=1.0, unit="s",
                    extras={"latency_hist": {
                        "bounds": bounds, "counts": counts,
                    }},
                )
            ledger.record("plain_leg", value=2.0, unit="s")
        records = obs.read_ledger(path)
        summary = obs.summarize(records)
        # Merged counts [10, 10, 0, 0]: the q=0.5 rank (10 of 20) lands
        # exactly on bucket 0's cumulative end → its upper edge, 1.0.
        assert summary["serve.latency"]["p50"] == 1.0
        assert summary["serve.latency"]["p99"] is not None
        assert "p50" not in summary["plain_leg"]
        rendered = obs_ledger.render(records)
        header = rendered.splitlines()[0]
        assert "p50" in header and "p99" in header

    def test_ledger_peak_mem_min_across_repeats(self, tmp_path):
        # extras.hbm_peak_bytes folds to the MIN across repeats (the
        # repeat least polluted by co-resident allocations) and renders
        # as the stats table's peak_mem column; zero/absent samples (CPU
        # backends) contribute nothing.
        path = tmp_path / "mem.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            for peak in (300_000_000, 120_000_000, 0):
                ledger.record(
                    "ring.chunked", value=1.0, unit="s",
                    extras={"hbm_peak_bytes": peak},
                )
            ledger.record("plain_leg", value=2.0, unit="s")
        records = obs.read_ledger(path)
        summary = obs.summarize(records)
        assert summary["ring.chunked"]["hbm_peak_bytes"] == 120_000_000
        assert "hbm_peak_bytes" not in summary["plain_leg"]
        rendered = obs_ledger.render(records)
        assert "peak_mem" in rendered.splitlines()[0]
        assert "120MB" in rendered

    def test_ledger_hbm_read_min_across_repeats(self, tmp_path):
        # extras.hbm_read_bytes (the round-14 one-pass legs: arg + temp
        # bytes of the AOT settle program that ran — per-settle
        # bytes-read floor) folds to the MIN across repeats and renders
        # as the stats table's hbm_read column; zero/absent samples
        # contribute nothing.
        path = tmp_path / "read.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            for read in (96_000_000, 48_000_000, 0):
                ledger.record(
                    "e2e_onepass.onepass", value=1.0, unit="s",
                    extras={"hbm_read_bytes": read},
                )
            ledger.record("plain_leg", value=2.0, unit="s")
        records = obs.read_ledger(path)
        summary = obs.summarize(records)
        assert summary["e2e_onepass.onepass"]["hbm_read_bytes"] == 48_000_000
        assert "hbm_read_bytes" not in summary["plain_leg"]
        rendered = obs_ledger.render(records)
        assert "hbm_read" in rendered.splitlines()[0]
        assert "48MB" in rendered

    def test_ledger_intern_min_across_repeats(self, tmp_path):
        # extras.intern_s (the round-15 ingest/stream/serve legs:
        # seconds inside the pair-interning pass) folds to the MIN
        # across repeats and renders as the stats table's intern column.
        path = tmp_path / "intern.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            for intern_s in (0.31, 0.024):
                ledger.record(
                    "e2e_ingest_drift.drift1", value=1.0, unit="s",
                    extras={"intern_s": intern_s},
                )
            ledger.record("plain_leg", value=2.0, unit="s")
        records = obs.read_ledger(path)
        summary = obs.summarize(records)
        assert summary["e2e_ingest_drift.drift1"]["intern_s"] == 0.024
        assert "intern_s" not in summary["plain_leg"]
        rendered = obs_ledger.render(records)
        assert "intern" in rendered.splitlines()[0]

    def test_diff_bands_carries_intern_metric(self, tmp_path):
        def ledger_records(path, intern_s):
            with obs.RunLedger(path, run_id="r") as ledger:
                ledger.record(
                    "e2e_ingest_drift.drift1", value=1.0, unit="s",
                    extras={"intern_s": intern_s},
                )
            return obs.read_ledger(path)

        old = ledger_records(tmp_path / "old.jsonl", 0.3)
        new = ledger_records(tmp_path / "new.jsonl", 0.024)
        diff = obs.diff_bands(old, new)
        metric = diff["e2e_ingest_drift.drift1"]["metrics"]["intern_s"]
        assert metric == {"old": 0.3, "new": 0.024}
        rendered = obs.render_diff(diff)
        assert "intern 0.3->0.024" in rendered

    def test_diff_bands_carries_hbm_read_metric(self, tmp_path):
        def ledger_records(path, read):
            with obs.RunLedger(path, run_id="r") as ledger:
                ledger.record(
                    "e2e_onepass", value=1.0, unit="s",
                    extras={"hbm_read_bytes": read},
                )
            return obs.read_ledger(path)

        old = ledger_records(tmp_path / "old.jsonl", 200_000_000)
        new = ledger_records(tmp_path / "new.jsonl", 80_000_000)
        diff = obs.diff_bands(old, new)
        metric = diff["e2e_onepass"]["metrics"]["hbm_read_bytes"]
        assert metric == {"old": 200_000_000, "new": 80_000_000}
        rendered = obs.render_diff(diff)
        assert "hbm_read 2e+08->8e+07" in rendered

    def test_ledger_recovery_min_across_repeats(self, tmp_path):
        # extras.recovery_s (the round-13 kill-soak leg: kill → first
        # re-settled dead-band batch) folds to the MIN across repeats and
        # renders as the stats table's recovery column; legs without it
        # contribute nothing.
        path = tmp_path / "recovery.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            for value in (1.8, 0.42):
                ledger.record(
                    "e2e_kill_soak", value=value, unit="s",
                    extras={"recovery_s": value},
                )
            ledger.record("plain_leg", value=2.0, unit="s")
        records = obs.read_ledger(path)
        summary = obs.summarize(records)
        assert summary["e2e_kill_soak"]["recovery_s"] == 0.42
        assert "recovery_s" not in summary["plain_leg"]
        rendered = obs_ledger.render(records)
        assert "recovery" in rendered.splitlines()[0]

    def test_diff_bands_carries_recovery_metric(self, tmp_path):
        def ledger_records(path, value):
            with obs.RunLedger(path, run_id="r") as ledger:
                ledger.record(
                    "e2e_kill_soak", value=value, unit="s",
                    extras={"recovery_s": value},
                )
            return obs.read_ledger(path)

        old = ledger_records(tmp_path / "old.jsonl", 1.5)
        new = ledger_records(tmp_path / "new.jsonl", 0.5)
        diff = obs.diff_bands(old, new)
        assert diff["e2e_kill_soak"]["metrics"]["recovery_s"] == {
            "old": 1.5, "new": 0.5
        }
        rendered = obs.render_diff(diff)
        assert "recovery 1.5->0.5" in rendered

    def test_diff_bands_carries_peak_mem_metric(self, tmp_path):
        def ledger_records(path, peak):
            with obs.RunLedger(path, run_id="r") as ledger:
                ledger.record(
                    "ring", value=1.0, unit="s",
                    extras={"hbm_peak_bytes": peak},
                )
            return obs.read_ledger(path)

        old = ledger_records(tmp_path / "old.jsonl", 300_000_000)
        new = ledger_records(tmp_path / "new.jsonl", 90_000_000)
        diff = obs.diff_bands(old, new)
        metric = diff["ring"]["metrics"]["hbm_peak_bytes"]
        assert metric == {"old": 300_000_000, "new": 90_000_000}
        rendered = obs.render_diff(diff)
        assert "peak_mem 3e+08->9e+07" in rendered

    def test_mismatched_hist_layouts_refuse_to_merge(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            ledger.record("leg", value=1.0, extras={"latency_hist": {
                "bounds": [1.0, 10.0], "counts": [1, 1, 0],
            }})
            ledger.record("leg", value=1.0, extras={"latency_hist": {
                "bounds": [1.0, 100.0], "counts": [1, 1, 0],
            }})
        with pytest.raises(ValueError, match="layouts differ"):
            obs.summarize(obs.read_ledger(path))


class TestDeterministicExport:
    def test_byte_stable_across_registration_order(self):
        def populate(registry, names):
            for name in names:
                registry.counter(f"c.{name}").inc(3)
                registry.gauge(f"g.{name}").set(1.5)
                registry.histogram(f"h.{name}").observe(0.01)

        a = obs_metrics.MetricsRegistry()
        b = obs_metrics.MetricsRegistry()
        populate(a, ["alpha", "beta", "gamma"])
        populate(b, ["gamma", "alpha", "beta"])
        assert a.to_json().encode() == b.to_json().encode()

    def test_export_names_sorted(self):
        registry = obs_metrics.MetricsRegistry()
        registry.counter("z").inc()
        registry.counter("a").inc()
        assert list(registry.export()["counters"]) == ["a", "z"]


class TestDisabledModeIdentity:
    def test_null_registry_returns_one_shared_object(self):
        null = obs_metrics.NULL_REGISTRY
        assert null.counter("a") is null.counter("b")
        assert null.counter("a") is null.gauge("x") is null.histogram("y")
        # The no-ops really are no-ops.
        null.counter("a").inc(5)
        null.histogram("h").observe(1.0)
        assert null.export() == {
            "counters": {}, "gauges": {}, "histograms": {},
        }

    def test_default_registry_is_the_null_one(self):
        assert obs.metrics_registry() is obs_metrics.NULL_REGISTRY
        assert not obs.metrics_registry().enabled

    def test_set_registry_roundtrip(self):
        live = obs_metrics.MetricsRegistry()
        previous = obs.set_metrics_registry(live)
        try:
            assert obs.metrics_registry() is live
        finally:
            obs.set_metrics_registry(previous)
        assert obs.metrics_registry() is previous

    def test_null_timeline_span_is_one_shared_noop(self):
        null = obs_timeline.NULL_TIMELINE
        assert null.span("a") is null.span("b")
        with null.span("a"):
            pass
        assert null.totals() == {}
        assert not null.enabled

    def test_default_active_timeline_is_null(self):
        assert obs.active_timeline() is obs_timeline.NULL_TIMELINE


class TestTimeline:
    def test_recording_is_thread_local(self):
        timeline = obs.PhaseTimeline()
        seen = {}

        def worker():
            seen["timeline"] = obs.active_timeline()

        with obs.recording(timeline):
            assert obs.active_timeline() is timeline
            thread = threading.Thread(target=worker)
            thread.start()
            thread.join()
        # The worker thread saw the null timeline: overlapped worker time
        # must not enter the additive breakdown.
        assert seen["timeline"] is obs_timeline.NULL_TIMELINE
        assert obs.active_timeline() is obs_timeline.NULL_TIMELINE

    def test_nested_spans_attribute_exclusively(self):
        timeline = obs.PhaseTimeline()
        with obs.recording(timeline):
            with obs.active_timeline().span("checkpoint"):
                time.sleep(0.02)
                with obs.active_timeline().span("journal_fsync"):
                    time.sleep(0.02)
        totals = timeline.totals()
        # The outer phase excludes the nested one: both ~20 ms, and the
        # pair sums to the outer wall instead of double-counting it.
        assert totals["journal_fsync"] >= 0.015
        assert totals["checkpoint"] >= 0.015
        assert totals["checkpoint"] + totals["journal_fsync"] < 0.08

    def test_delta_reports_only_advanced_phases(self):
        before = {"pack": 1.0, "upload": 2.0}
        after = {"pack": 1.5, "upload": 2.0, "fetch": 0.25}
        assert obs.PhaseTimeline.delta(before, after) == {
            "fetch": 0.25, "pack": 0.5,
        }

    def test_canonical_phase_vocabulary(self):
        assert obs.PHASES == (
            "pack", "upload", "state_adopt", "settle_dispatch",
            "analytics", "fetch", "journal_fsync", "journal_async_wait",
            "checkpoint", "interchange_export", "replay",
        )


class TestLedger:
    def test_schema_roundtrip(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1", backend="cpu") as ledger:
            ledger.record(
                "leg_a", value=1.25, unit="s", repeat=0,
                phases={"pack": 0.5}, extras={"k": "v"},
            )
            ledger.record("leg_a", value=1.5, unit="s", repeat=1)
        first, second = obs.read_ledger(path)
        assert first["schema"] == obs_ledger.SCHEMA_VERSION
        assert first["run_id"] == "r1"
        assert first["backend"] == "cpu"
        assert (first["seq"], second["seq"]) == (0, 1)
        assert first["leg"] == "leg_a"
        assert first["value"] == 1.25
        assert first["unit"] == "s"
        assert first["repeat"] == 0
        assert first["phases"] == {"pack": 0.5}
        assert first["extras"] == {"k": "v"}
        assert "loadavg_1m" in first["host"]
        assert first["host"]["cpu_count"] == os.cpu_count()
        assert first["wall_unix_ts"] <= second["wall_unix_ts"]

    def test_record_lines_are_sorted_key_json(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            ledger.record("leg", extras={"zz": 1, "aa": 2})
        (line,) = path.read_text().strip().splitlines()
        assert line == json.dumps(json.loads(line), sort_keys=True)

    def test_append_only_across_writers(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            ledger.record("a")
        with obs.RunLedger(path, run_id="r2") as ledger:
            ledger.record("b")
        records = obs.read_ledger(path)
        assert [r["run_id"] for r in records] == ["r1", "r2"]

    def test_torn_tail_dropped_interior_garbage_raises(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            ledger.record("a")
            ledger.record("b")
        with open(path, "a") as f:
            f.write('{"torn": ')  # crash mid-append
        records = obs.read_ledger(path)
        assert [r["leg"] for r in records] == ["a", "b"]
        with open(path, "w") as f:
            f.write('{"torn": \n')
            f.write(json.dumps({"leg": "c"}) + "\n")
        with pytest.raises(ValueError, match="malformed"):
            obs.read_ledger(path)

    def test_truncated_final_record_dropped_exactly(self, tmp_path):
        # The explicit torn-tail case (ISSUE 14 satellite): a REAL
        # record cut mid-bytes — a SIGKILL between write and flush
        # boundary — must drop exactly that record, never a neighbour
        # (the appended-garbage case above exercises a different tail).
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            for i in range(3):
                ledger.record("leg", value=float(i), unit="s", repeat=i)
        lines = path.read_bytes().splitlines(keepends=True)
        assert len(lines) == 3
        path.write_bytes(
            b"".join(lines[:2]) + lines[2][: len(lines[2]) // 2]
        )
        records = obs.read_ledger(path)
        assert [r["repeat"] for r in records] == [0, 1]
        assert [r["value"] for r in records] == [0.0, 1.0]

    def test_min_of_repeats_band(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            for i, value in enumerate((2.0, 1.0, 1.5)):
                ledger.record("leg", value=value, unit="s", repeat=i)
            ledger.record("leg", value=None)  # non-numeric: ignored
        band = obs.min_of_repeats(obs.read_ledger(path), "leg")
        assert band["n"] == 3
        assert band["min"] == 1.0
        assert band["max"] == 2.0
        assert band["spread_pct"] == 100.0
        assert band["unit"] == "s"
        assert obs.min_of_repeats([], "leg") is None

    def test_summarize_and_render(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            ledger.record("b_leg", value=3.0, unit="s")
            ledger.record("a_leg", value=1.0, unit="s")
        records = obs.read_ledger(path)
        summary = obs.summarize(records)
        assert list(summary) == ["a_leg", "b_leg"]
        rendered = obs_ledger.render(records)
        assert "a_leg" in rendered and "b_leg" in rendered


class TestGoldenParityWithObsEnabled:
    """Enabling obs may not move a single output byte.

    Round 9: "fully on" includes the request tracer — ``_enable``
    installs a live :class:`~.obs.trace.Tracer` alongside the metrics
    registry and timeline, so the parity assertions below also pin the
    tracing layer's write-only contract."""

    def _enable(self):
        timeline = obs.PhaseTimeline()
        previous = obs.set_metrics_registry(obs.MetricsRegistry())
        self._tracer = obs.Tracer()
        self._previous_tracer = obs.set_tracer(self._tracer)
        return timeline, previous

    def _disable(self, previous):
        obs.set_metrics_registry(previous)
        obs.set_tracer(self._previous_tracer)

    def test_golden_fixture_bytes_with_obs_enabled(self):
        import pathlib

        from bayesian_consensus_engine_tpu.core import compute_consensus

        fixture = json.loads(
            (pathlib.Path(__file__).parent / "fixtures" /
             "golden_regression.json").read_text(encoding="utf-8")
        )
        timeline, previous = self._enable()
        try:
            with obs.recording(timeline):
                result = compute_consensus(fixture["input"]["signals"])
        finally:
            self._disable(previous)
        assert json.dumps(result, indent=2) == json.dumps(
            fixture["expectedOutput"], indent=2
        )

    def _stream(self, enabled):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        def batches():
            rng = np.random.default_rng(5)
            for b in range(3):
                payloads = [
                    (
                        f"m{b}-{i}",
                        [
                            {"sourceId": f"s{j}",
                             "probability": float(rng.random())}
                            for j in range(3)
                        ],
                    )
                    for i in range(6)
                ]
                yield payloads, (rng.random(6) < 0.5).tolist()

        store = TensorReliabilityStore()
        stats = []
        timeline, previous = (
            self._enable() if enabled else (None, None)
        )
        try:
            with tempfile.TemporaryDirectory() as tmp:
                db = os.path.join(tmp, "ckpt.db")
                journal = os.path.join(tmp, "ckpt.jrnl")
                with obs.recording(timeline):
                    results = [
                        result.by_market()
                        for result in settle_stream(
                            store, batches(), steps=2, now=21_900.0,
                            db_path=db, journal=journal,
                            checkpoint_every=2, stats=stats,
                        )
                    ]
                    store.sync()
                db_digest = hashlib.sha256(
                    open(db, "rb").read()
                ).hexdigest()
                journal_head = open(journal, "rb").read(8)
        finally:
            if enabled:
                self._disable(previous)
        return results, db_digest, journal_head, stats, timeline

    def test_settle_stream_byte_parity_and_phases(self):
        res_off, db_off, jrnl_off, stats_off, _ = self._stream(False)
        res_on, db_on, jrnl_on, stats_on, timeline = self._stream(True)
        # Bit-exact results and checkpoint BYTES, obs on vs off.
        assert res_on == res_off
        assert db_on == db_off
        assert jrnl_on == jrnl_off == b"BCEJRNL1"
        # Obs-disabled stats keep the unchanged schema; enabled stats add
        # the additive per-batch phase breakdown in canonical names.
        assert all("phases" not in s for s in stats_off)
        assert all("phases" in s for s in stats_on)
        recorded = set()
        for entry in stats_on:
            recorded |= set(entry["phases"])
            assert all(v >= 0 for v in entry["phases"].values())
        assert recorded <= set(obs.PHASES)
        assert "settle_dispatch" in recorded
        # The stream's wiring reached the state tiers too.
        totals = timeline.totals()
        assert "journal_fsync" in totals
        assert "interchange_export" in totals  # tail SQLite export
        # ...and the tracer recorded every batch's span chain (the
        # stream-side tracing wiring), without moving a byte above.
        events = self._tracer.events()
        assert {e["scope"] for e in events} >= {"batch", "journal"}
        batch_keys = {e["key"] for e in events if e["scope"] == "batch"}
        assert batch_keys == {0, 1, 2}

    def test_settle_stream_metrics_counters(self):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            rng = np.random.default_rng(7)
            payloads = [
                ("m0", [{"sourceId": "s0", "probability": 0.5}]),
                ("m1", [{"sourceId": "s1", "probability": 0.25}]),
            ]
            list(settle_stream(
                TensorReliabilityStore(),
                [(payloads, [True, False])] * 2,
                steps=1, now=21_900.0, reuse_plans=True,
            ))
            del rng
        finally:
            obs.set_metrics_registry(previous)
        export = registry.export()
        assert export["counters"]["stream.batches"] == 2
        assert export["counters"]["stream.plan_reuse_hits"] == 1
        assert export["counters"]["stream.plan_reuse_misses"] == 1
        assert export["histograms"]["stream.settle_dispatch_s"]["count"] == 2
        assert export["histograms"]["stream.plan_build_s"]["count"] >= 1


class TestLedgerDiff:
    """Cross-round band diffing (``bce-tpu stats --against``): the
    regression signal is bands that STOPPED overlapping, with direction
    reported and the verdict left to the unit's polarity."""

    @staticmethod
    def _records(leg, values, unit="s"):
        return [
            {"leg": leg, "value": v, "unit": unit, "host": {}}
            for v in values
        ]

    def test_overlapping_bands_are_a_wash(self):
        old = self._records("leg", [1.0, 2.0])
        new = self._records("leg", [1.5, 3.0])
        diff = obs.diff_bands(old, new)
        assert diff["leg"]["status"] == "overlap"

    def test_disjoint_bands_flag_direction(self):
        old = self._records("leg", [1.0, 2.0])
        up = obs.diff_bands(old, self._records("leg", [2.5, 3.0]))
        down = obs.diff_bands(old, self._records("leg", [0.25, 0.5]))
        assert up["leg"]["status"] == "shifted_up"
        assert down["leg"]["status"] == "shifted_down"
        # Bands ride along verbatim so a round note can quote the range.
        assert up["leg"]["old"]["max"] == 2.0
        assert up["leg"]["new"]["min"] == 2.5

    def test_touching_bands_still_overlap(self):
        # Shared endpoint = one value both rounds produced: not a shift.
        old = self._records("leg", [1.0, 2.0])
        new = self._records("leg", [2.0, 3.0])
        assert obs.diff_bands(old, new)["leg"]["status"] == "overlap"

    def test_one_sided_legs_reported_not_compared(self):
        old = self._records("gone", [1.0])
        new = self._records("fresh", [2.0])
        diff = obs.diff_bands(old, new)
        assert diff["gone"]["status"] == "old_only"
        assert diff["fresh"]["status"] == "new_only"

    def test_render_diff_counts_moved_legs(self):
        old = self._records("a", [1.0, 2.0]) + self._records("b", [5.0])
        new = self._records("a", [4.0, 6.0]) + self._records("b", [5.0])
        rendered = obs.render_diff(obs.diff_bands(old, new))
        assert "shifted_up" in rendered
        assert "1 leg(s) stopped overlapping" in rendered
        # An all-overlap diff says so instead of counting zero.
        calm = obs.render_diff(obs.diff_bands(old, old))
        assert "all shared legs overlap" in calm


class TestSloColumn:
    """Round-16 stats surface: the absolute offered-but-not-met count
    (``slo_violations``) rides beside the goodput fraction, sourced from
    the serve-leg records' ``extras.slo`` and diffed by ``--against``
    like ``hbm_read``."""

    @staticmethod
    def _slo_records(leg, counts_list):
        return [
            {
                "leg": leg, "value": 1.0, "unit": "s", "host": {},
                "extras": {"slo": {"objective_s": 0.05, "counts": counts}},
            }
            for counts in counts_list
        ]

    def test_violations_merge_across_repeats(self):
        records = self._slo_records(
            "e2e_serve",
            [
                {"met": 90, "violated": 5, "shed": 3, "rejected": 2,
                 "failed": 0},
                {"met": 95, "violated": 1, "shed": 0, "rejected": 0,
                 "failed": 4},
            ],
        )
        band = obs.min_of_repeats(records, "e2e_serve")
        assert band["slo_violations"] == 15  # every non-met outcome
        assert band["goodput_within_slo"] == pytest.approx(185 / 200)

    def test_render_has_slo_column(self):
        records = self._slo_records(
            "e2e_serve", [{"met": 9, "violated": 1}]
        )
        rendered = obs_ledger.render(records)
        header, row = rendered.splitlines()[:2]
        assert "slo" in header.split()
        assert " 1 " in row  # the violation count renders as an integer
        # Legs without SLO records dash the column.
        plain = obs_ledger.render(
            [{"leg": "plain", "value": 1.0, "unit": "s", "host": {}}]
        )
        assert "-" in plain.splitlines()[1]

    def test_diff_carries_slo_violations(self):
        old = self._slo_records("e2e_serve", [{"met": 99, "violated": 1}])
        new = self._slo_records("e2e_serve", [{"met": 80, "violated": 20}])
        diff = obs.diff_bands(old, new)
        metric = diff["e2e_serve"]["metrics"]["slo_violations"]
        assert (metric["old"], metric["new"]) == (1, 20)
        assert "slo 1->20" in obs.render_diff(diff)


class TestAutotuneProvenance:
    """Round-20 stats surface: kernel-bearing legs render their tuner
    verdict WITH provenance (a local ``race`` vs a loaded ``bank``),
    and ``--against`` flags a verdict FLIP — the regression that
    matters when a shipped bank drifts from what this host would
    measure."""

    @staticmethod
    def _records(leg, choice, source="race", beat=True):
        decision = {
            "choice": choice, "default": "xla", "beat_default": beat,
            "timings_s": {}, "source": source,
        }
        return [{
            "leg": leg, "value": 1.0, "unit": "s", "host": {},
            "extras": {"settle_autotune_decision": decision},
        }]

    def test_band_and_render_carry_provenance(self):
        records = self._records("pallas_ab", "pallas", source="bank")
        band = obs.min_of_repeats(records, "pallas_ab")
        verdict = band["autotune"]["settle_autotune_decision"]
        assert verdict["choice"] == "pallas"
        assert verdict["source"] == "bank"
        rendered = obs_ledger.render(records)
        assert "settle_autotune_decision: pallas (bank; beat default)" in (
            rendered
        )
        # Legs without a decision render no autotune trailer.
        plain = obs_ledger.render(
            [{"leg": "plain", "value": 1.0, "unit": "s", "host": {}}]
        )
        assert "autotune" not in plain

    def test_diff_flags_verdict_flip(self):
        old = self._records("pallas_ab", "pallas")
        new = self._records("pallas_ab", "xla", source="bank", beat=False)
        diff = obs.diff_bands(old, new)
        metric = diff["pallas_ab"]["metrics"][
            "autotune.settle_autotune_decision"
        ]
        assert (metric["old"], metric["new"]) == ("pallas", "xla")
        assert metric["verdict_flip"] is True
        assert metric["source"] == "bank"
        rendered = obs.render_diff(diff)
        assert "pallas->xla FLIP" in rendered
        # Same verdict both rounds: reported, not flagged.
        calm = obs.diff_bands(old, old)
        same = calm["pallas_ab"]["metrics"][
            "autotune.settle_autotune_decision"
        ]
        assert "verdict_flip" not in same


class TestCliStats:
    def _main(self, argv, capsys):
        import sys
        from unittest import mock

        from bayesian_consensus_engine_tpu import cli

        with mock.patch.object(sys, "argv", ["bce-tpu", *argv]):
            cli.main()
        return capsys.readouterr()

    def test_stats_renders_ledger(self, tmp_path, capsys):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            ledger.record("headline_f32", value=7000.0, unit="cycles/sec",
                          repeat=0)
            ledger.record("headline_f32", value=6800.0, unit="cycles/sec",
                          repeat=1)
        out = self._main(["stats", str(path)], capsys).out
        assert "headline_f32" in out
        assert "2 records" in out

    def test_stats_json_band(self, tmp_path, capsys):
        path = tmp_path / "run.jsonl"
        with obs.RunLedger(path, run_id="r1") as ledger:
            ledger.record("leg", value=2.0, unit="s", repeat=0)
            ledger.record("leg", value=1.0, unit="s", repeat=1)
            ledger.record("other", value=9.0, unit="s")
        out = self._main(
            ["stats", str(path), "--json", "--leg", "leg"], capsys
        ).out
        payload = json.loads(out)
        assert payload["records"] == 2
        assert payload["legs"]["leg"]["min"] == 1.0
        assert payload["legs"]["leg"]["max"] == 2.0
        assert "other" not in payload["legs"]

    def test_stats_live_scrapes_an_exporter(self, tmp_path, capsys):
        # Round 16: --live renders a running exporter's snapshot +
        # health verdict — next to the ledger bands when one is given,
        # alone otherwise (the ledger argument becomes optional).
        from bayesian_consensus_engine_tpu.obs.export import (
            TelemetryServer,
        )
        from bayesian_consensus_engine_tpu.obs.health import (
            BurnWindow,
            HealthMonitor,
        )

        registry = obs_metrics.MetricsRegistry()
        registry.counter("serve.requests").inc(41)
        monitor = HealthMonitor(
            objective_goodput=0.9, windows=(BurnWindow(2, 4, 1.0),)
        )
        for _ in range(4):
            monitor.record("violated")
        with TelemetryServer(
            registry=registry, health=monitor, host_id=2, epoch=5
        ) as server:
            out = self._main(["stats", "--live", server.url], capsys).out
            assert "live host 2 epoch 5" in out
            assert "health=burning" in out  # 503 bodies are answers
            assert "serve.requests" in out and "41" in out
            path = tmp_path / "run.jsonl"
            with obs.RunLedger(path, run_id="r1") as ledger:
                ledger.record("leg", value=1.0, unit="s")
            both = self._main(
                ["stats", str(path), "--live", server.url], capsys
            ).out
            assert "leg" in both and "live host 2" in both
            as_json = json.loads(
                self._main(
                    ["stats", "--json", "--live", server.url], capsys
                ).out
            )
            assert as_json["live"]["healthz"]["verdict"] == "burning"
            assert as_json["live"]["snapshot"]["host_id"] == 2

    def test_stats_without_ledger_or_live_errors(self, capsys):
        with pytest.raises(SystemExit):
            self._main(["stats"], capsys)

    def test_stats_missing_file_errors(self, tmp_path, capsys):
        with pytest.raises(SystemExit) as excinfo:
            self._main(["stats", str(tmp_path / "nope.jsonl")], capsys)
        assert excinfo.value.code == 1

    def _two_round_ledgers(self, tmp_path):
        old = tmp_path / "old.jsonl"
        new = tmp_path / "new.jsonl"
        with obs.RunLedger(old, run_id="r1") as ledger:
            ledger.record("slow_leg", value=1.0, unit="s", repeat=0)
            ledger.record("slow_leg", value=1.2, unit="s", repeat=1)
            ledger.record("steady", value=5.0, unit="s")
        with obs.RunLedger(new, run_id="r2") as ledger:
            ledger.record("slow_leg", value=2.0, unit="s", repeat=0)
            ledger.record("slow_leg", value=2.1, unit="s", repeat=1)
            ledger.record("steady", value=5.0, unit="s")
        return old, new

    def test_stats_against_flags_non_overlap(self, tmp_path, capsys):
        old, new = self._two_round_ledgers(tmp_path)
        out = self._main(
            ["stats", str(new), "--against", str(old)], capsys
        ).out
        assert "shifted_up" in out
        assert "1 leg(s) stopped overlapping" in out

    def test_stats_against_json(self, tmp_path, capsys):
        old, new = self._two_round_ledgers(tmp_path)
        out = self._main(
            ["stats", str(new), "--against", str(old), "--json"], capsys
        ).out
        payload = json.loads(out)
        assert payload["legs"]["slow_leg"]["status"] == "shifted_up"
        assert payload["legs"]["steady"]["status"] == "overlap"
        # --leg restricts BOTH sides of the diff.
        out = self._main(
            ["stats", str(new), "--against", str(old), "--json",
             "--leg", "steady"], capsys
        ).out
        assert set(json.loads(out)["legs"]) == {"steady"}
