"""analytics/: device-resident uncertainty bands + correlated-market
consensus (round 12).

The non-negotiable contracts, mirroring tests/test_ring.py's shape:

* **Band bit matrix** — band outputs are BIT-IDENTICAL at every
  ``chunk_slots`` setting, across mesh factorisations, and across the
  (M, K)/(K, M) layouts. Structural (the fixed balanced-tree
  accumulation in ops/uncertainty.py — chunk and shard roots are
  internal nodes of one global tree); these tests are the empirical pin.
* **Pure-additive analytics** — ``settle_with_analytics`` and the
  serving ``analytics=`` mode change NO settlement byte: results, store
  state, journal epoch payloads (wall_ts masked), and SQLite bytes are
  identical with analytics on or off — the obs on/off contract, applied
  to analytics.
* **Graph semantics** — the CSR MarketGraph is order-sensitive
  (fingerprints miss on edge reorder, the plan-reuse analogue), and the
  damped sweep is a bit-stable pure function of its inputs on any mesh.
"""

import asyncio
import struct
from functools import partial

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bayesian_consensus_engine_tpu.analytics import (
    AnalyticsOptions,
    MarketGraph,
    UncertaintyBands,
)
from bayesian_consensus_engine_tpu.analytics.bands import build_band_program
from bayesian_consensus_engine_tpu.ops.propagate import damped_sweep_math
from bayesian_consensus_engine_tpu.ops.uncertainty import (
    band_math,
    resolve_chunk_slots,
)
from bayesian_consensus_engine_tpu.parallel import MarketBlockState
from bayesian_consensus_engine_tpu.parallel._jax_compat import shard_map
from bayesian_consensus_engine_tpu.parallel.mesh import (
    MARKETS_AXIS,
    SOURCES_AXIS,
    make_mesh,
)
from bayesian_consensus_engine_tpu.parallel.sharded import (
    build_cycle_analytics_loop,
    build_cycle_loop,
    init_block_state,
)
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

M, K = 16, 32
NOW = 21_900.0


def _band_args(m, k, workload, seed=0):
    """One (M, K) band operand set for a named parity workload."""
    rng = np.random.default_rng(seed)
    probs = rng.random((m, k))
    valid = rng.random((m, k)) < 0.8
    if workload == "mask_holes":
        valid = rng.random((m, k)) < 0.5
        valid[0] = False  # a market with no signalling slot
    elif workload == "single_agent":
        valid = np.zeros((m, k), dtype=bool)
        valid[np.arange(m), rng.integers(0, k, m)] = True
    elif workload == "uniform":
        probs = np.full((m, k), 0.625)
        valid = np.ones((m, k), dtype=bool)
    else:
        assert workload == "random"
    return (
        jnp.asarray(probs, jnp.float32),
        jnp.asarray(valid),
        jnp.asarray(rng.uniform(0.1, 2.0, (m, k)), jnp.float32),
    )


def _sharded_bands(mesh_shape, chunk, args, slot_major=False):
    """Run band_math under shard_map on *mesh_shape*; numpy outputs."""
    mesh = make_mesh(mesh_shape)
    n_src = mesh.shape[SOURCES_AXIS]
    if slot_major:
        block = P(SOURCES_AXIS, MARKETS_AXIS)
        args = tuple(x.T for x in args)
    else:
        block = P(MARKETS_AXIS, SOURCES_AXIS)
    fn = shard_map(
        partial(
            band_math,
            axis_name=SOURCES_AXIS,
            axis_size=n_src,
            chunk_slots=chunk,
            agents_last=not slot_major,
        ),
        mesh=mesh,
        in_specs=(block,) * 3,
        out_specs=UncertaintyBands(*([P(MARKETS_AXIS)] * 6)),
        check_vma=False,
    )
    return jax.tree.map(np.asarray, jax.jit(fn)(*args))


class TestBandParityMatrix:
    """ISSUE-10 acceptance: bands bit-identical at every chunk setting,
    across mesh shapes, AND across layouts — for arbitrary float inputs,
    not just exactly-representable ones (the tree accumulation never
    changes its pairing; see ops/uncertainty.py)."""

    @pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (8, 1)])
    @pytest.mark.parametrize(
        "workload", ["random", "mask_holes", "single_agent", "uniform"]
    )
    def test_bit_exact_across_chunks_meshes_layouts(
        self, mesh_shape, workload
    ):
        args = _band_args(M, K, workload)
        want = _sharded_bands((8, 1), None, args)
        for chunk in (None, 1, 4, 7, K + 5):
            for slot_major in (False, True):
                got = _sharded_bands(mesh_shape, chunk, args, slot_major)
                for name, g, w in zip(want._fields, got, want):
                    np.testing.assert_array_equal(
                        g, w,
                        err_msg=(
                            f"{mesh_shape}/{workload}/chunk={chunk}/"
                            f"slot_major={slot_major}/{name}"
                        ),
                    )

    def test_chunk_resolution_is_pow2(self):
        # Every resolution divides the padded width — the tree-alignment
        # invariant the bit matrix rests on.
        assert resolve_chunk_slots(None, 24) == 32
        assert resolve_chunk_slots(7, 24) == 4
        assert resolve_chunk_slots(1, 24) == 1
        assert resolve_chunk_slots(100, 24) == 32
        assert resolve_chunk_slots(16, 16) == 16

    def test_empty_market_reports_nan_band(self):
        args = _band_args(M, K, "mask_holes")
        out = _sharded_bands((1, 8), 4, args)
        assert np.isnan(out.mean[0]) and np.isnan(out.lo[0])
        assert out.count[0] == 0 and out.n_eff[0] == 0.0

    def test_bad_chunk_string_rejected(self):
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        prog = build_band_program(mesh, chunk_slots="wide")
        state = jax.tree.map(lambda x: x.T, init_block_state(M, K))
        probs, valid, _rel = _band_args(M, K, "random")
        with pytest.raises(ValueError, match="auto"):
            prog(probs.T, valid.T, state, jnp.float32(400.0))


class TestBandNumerics:
    def test_matches_float64_reference(self):
        probs, valid, rel = _band_args(M, K, "random", seed=3)
        out = jax.jit(
            partial(band_math, axis_name=None, axis_size=1)
        )(probs, valid, rel)
        w = np.where(np.asarray(valid), np.asarray(rel), 0).astype(
            np.float64
        )
        p = np.asarray(probs, np.float64)
        mean = (w * p).sum(1) / w.sum(1)
        var = np.maximum((w * p * p).sum(1) / w.sum(1) - mean**2, 0)
        n_eff = w.sum(1) ** 2 / (w * w).sum(1)
        stderr = np.sqrt(var / n_eff)
        np.testing.assert_allclose(np.asarray(out.mean), mean, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(out.stderr), stderr, rtol=1e-4, atol=1e-7
        )
        np.testing.assert_allclose(
            np.asarray(out.n_eff), n_eff, rtol=1e-5
        )
        np.testing.assert_array_equal(
            np.asarray(out.count), np.asarray(valid).sum(1)
        )
        # The band brackets its own mean and stays in [0, 1].
        lo, hi = np.asarray(out.lo), np.asarray(out.hi)
        assert (lo <= np.asarray(out.mean) + 1e-6).all()
        assert (hi >= np.asarray(out.mean) - 1e-6).all()
        assert (lo >= 0).all() and (hi <= 1).all()

    def test_uniform_signals_have_near_zero_width(self):
        # The one-pass E[p²] − μ² form has a resolution floor of
        # ~sqrt(eps_f32)·|mean| on the stderr (cancellation under the
        # sqrt) — unanimous signals read as a band of width ≲ 1e-4, not
        # exactly zero. That floor is documented in reliability.md; what
        # must hold exactly is the clamp (no negative variance).
        probs, valid, rel = _band_args(M, K, "uniform")
        out = jax.jit(
            partial(band_math, axis_name=None, axis_size=1)
        )(probs, valid, rel)
        assert (np.asarray(out.stderr) >= 0).all()
        np.testing.assert_allclose(np.asarray(out.stderr), 0.0, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(out.lo), np.asarray(out.hi), atol=5e-4
        )


class TestBandMemoryDiet:
    """The chunk knob's working-set collapse, read from the same AOT
    ``memory_analysis()`` the bench leg reports (CPU materialises more
    than TPU, but the chunked/unchunked ratio shows either way)."""

    def test_chunked_temps_collapse_args_untouched(self):
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        m, k = 64, 1024
        rng = np.random.default_rng(9)
        probs = jnp.asarray(rng.random((k, m)), jnp.float32)
        mask = jnp.asarray(rng.random((k, m)) < 0.9)
        state = jax.tree.map(lambda x: x.T, init_block_state(m, k))
        now = jnp.asarray(400.0, jnp.float32)

        def mem(chunk):
            return build_band_program(mesh, chunk_slots=chunk).lower(
                probs, mask, state, now
            ).compile().memory_analysis()

        unchunked = mem(None)
        chunked = mem(64)
        assert (
            chunked.temp_size_in_bytes < unchunked.temp_size_in_bytes / 2
        ), (chunked.temp_size_in_bytes, unchunked.temp_size_in_bytes)
        assert (
            chunked.argument_size_in_bytes
            == unchunked.argument_size_in_bytes
        )


class TestMarketGraph:
    EDGES = [
        ("parent", "leg-a", 2.0),
        ("parent", "leg-b", 1.0),
        ("leg-a", "parent", 0.5),
    ]

    def test_csr_structure(self):
        graph = MarketGraph.from_edges(self.EDGES)
        assert graph.num_nodes == 3 and graph.num_edges == 3
        assert graph.node_ids == ["parent", "leg-a", "leg-b"]
        assert list(graph.offsets) == [0, 2, 3, 3]
        assert list(graph.indices) == [1, 2, 0]
        assert list(graph.weights) == [2.0, 1.0, 0.5]

    def test_fingerprint_order_sensitive(self):
        a = MarketGraph.from_edges(self.EDGES)
        b = MarketGraph.from_edges(self.EDGES)
        assert a.fingerprint == b.fingerprint
        reordered = MarketGraph.from_edges(
            [self.EDGES[1], self.EDGES[0], self.EDGES[2]]
        )
        assert reordered.fingerprint != a.fingerprint
        reweighted = MarketGraph.from_edges(
            [("parent", "leg-a", 2.5)] + self.EDGES[1:]
        )
        assert reweighted.fingerprint != a.fingerprint
        deeper = MarketGraph.from_edges(self.EDGES, steps=5)
        assert deeper.fingerprint != a.fingerprint

    def test_extended_fingerprint_covers_both_sides(self):
        graph = MarketGraph.from_edges(self.EDGES)
        other = MarketGraph.from_edges(self.EDGES[:2])
        topo_a, topo_b = b"topology-a", b"topology-b"
        assert graph.extended_fingerprint(topo_a) != (
            graph.extended_fingerprint(topo_b)
        )
        assert graph.extended_fingerprint(topo_a) != (
            other.extended_fingerprint(topo_a)
        )
        assert graph.extended_fingerprint(topo_a) == (
            graph.extended_fingerprint(topo_a)
        )

    def test_align_pads_and_drops_absent_markets(self):
        graph = MarketGraph.from_edges(self.EDGES)
        # leg-b absent from the batch: parent keeps only its leg-a edge.
        idx, w = graph.align(["leg-a", "parent"], padded_total=4)
        assert idx.shape == w.shape == (4, 1)
        assert idx[1, 0] == 0 and w[1, 0] == 2.0     # parent -> leg-a
        assert idx[0, 0] == 1 and w[0, 0] == 0.5     # leg-a -> parent
        assert (idx[2:] == -1).all() and (w[2:] == 0).all()

    def test_validation(self):
        with pytest.raises(ValueError, match="self-edge"):
            MarketGraph.from_edges([("a", "a", 1.0)])
        with pytest.raises(ValueError, match="weight"):
            MarketGraph.from_edges([("a", "b", 0.0)])
        with pytest.raises(ValueError, match="damping"):
            MarketGraph.from_edges([("a", "b", 1.0)], damping=1.5)
        with pytest.raises(ValueError, match="padded_total"):
            MarketGraph.from_edges([("a", "b", 1.0)]).align(
                ["a", "b"], padded_total=1
            )


class TestDampedSweep:
    def test_hand_computed_single_step(self):
        values = jnp.asarray([0.2, 0.8, 0.5], jnp.float32)
        idx = jnp.asarray([[1, 2], [-1, -1], [0, -1]], jnp.int32)
        w = jnp.asarray([[1.0, 3.0], [0.0, 0.0], [2.0, 0.0]], jnp.float32)
        out = np.asarray(
            jax.jit(
                partial(damped_sweep_math, damping=0.5, steps=1)
            )(values, idx, w)
        )
        # row 0: 0.5*0.2 + 0.5*(1*0.8 + 3*0.5)/4 = 0.1 + 0.2875
        assert out[0] == pytest.approx(0.3875, abs=1e-6)
        assert out[1] == pytest.approx(0.8)      # no edges: untouched
        assert out[2] == pytest.approx(0.5 * 0.5 + 0.5 * 0.2, abs=1e-6)

    def test_nan_neighbors_excluded_nan_rows_kept(self):
        values = jnp.asarray([np.nan, 0.4, 0.6], jnp.float32)
        idx = jnp.asarray([[1, -1], [0, 2], [-1, -1]], jnp.int32)
        w = jnp.ones((3, 2), jnp.float32)
        out = np.asarray(
            jax.jit(
                partial(damped_sweep_math, damping=0.5, steps=1)
            )(values, idx, w)
        )
        assert np.isnan(out[0])  # a NaN row never heals from neighbours
        # row 1's NaN neighbour (row 0) is excluded: only row 2 counts.
        assert out[1] == pytest.approx(0.5 * 0.4 + 0.5 * 0.6, abs=1e-6)

    def test_zero_steps_identity(self):
        values = jnp.asarray([0.2, 0.8], jnp.float32)
        idx = jnp.asarray([[1], [0]], jnp.int32)
        w = jnp.ones((2, 1), jnp.float32)
        out = damped_sweep_math(values, idx, w, damping=0.5, steps=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(values))

    @pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
    def test_sharded_matches_unsharded_bitwise(self, mesh_shape):
        rng = np.random.default_rng(4)
        m, d = 32, 3
        values = jnp.asarray(rng.random(m), jnp.float32)
        idx = jnp.asarray(rng.integers(-1, m, (m, d)), jnp.int32)
        w = jnp.asarray(rng.uniform(0.1, 2.0, (m, d)), jnp.float32)
        want = np.asarray(
            jax.jit(
                partial(damped_sweep_math, damping=0.5, steps=3)
            )(values, idx, w)
        )
        mesh = make_mesh(mesh_shape)
        fn = shard_map(
            partial(
                damped_sweep_math,
                damping=0.5, steps=3, axis_name=MARKETS_AXIS,
            ),
            mesh=mesh,
            in_specs=(
                P(MARKETS_AXIS), P(MARKETS_AXIS, None),
                P(MARKETS_AXIS, None),
            ),
            out_specs=P(MARKETS_AXIS),
            check_vma=False,
        )
        got = np.asarray(jax.jit(fn)(values, idx, w))
        np.testing.assert_array_equal(got, want)


class TestFusedAnalyticsLoop:
    """build_cycle_analytics_loop: cycles + tie-break + bands (+ sweep)
    in ONE program. The loop half must keep the plain loop's bytes —
    consensus INCLUDED (the analytics on/off parity contract leans on
    it); the bands half must equal the standalone program bitwise."""

    def _slot_major_inputs(self, seed=5):
        rng = np.random.default_rng(seed)
        m, k = 32, 16
        probs = jnp.asarray(rng.random((k, m)), jnp.float32)
        mask = jnp.asarray(rng.random((k, m)) < 0.8)
        outcome = jnp.asarray(rng.random(m) < 0.5)
        state = MarketBlockState(
            reliability=jnp.asarray(
                rng.uniform(0.1, 1.0, (k, m)), jnp.float32
            ),
            confidence=jnp.asarray(
                rng.uniform(0.0, 1.0, (k, m)), jnp.float32
            ),
            updated_days=jnp.asarray(
                rng.choice([0.0, 5.0, 400.0], (k, m)), jnp.float32
            ),
            exists=jnp.asarray(rng.random((k, m)) < 0.6),
        )
        return probs, mask, outcome, state, jnp.float32(401.0)

    @pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
    @pytest.mark.parametrize("steps", [1, 3])
    def test_fused_equals_loop_and_standalone_bands(
        self, mesh_shape, steps
    ):
        mesh = make_mesh(mesh_shape)
        probs, mask, outcome, state, now0 = self._slot_major_inputs()
        fused = build_cycle_analytics_loop(
            mesh, chunk_agents=5, chunk_slots=4, donate=False
        )
        st_f, cons_f, _tb, bands, prop = fused(
            probs, mask, outcome, state, now0, steps
        )
        assert prop is None
        st_p, cons_p = build_cycle_loop(mesh, donate=False)(
            probs, mask, outcome, state, now0, steps
        )
        # Consensus AND state bit-equal to the plain loop: the fused
        # program reuses the same loop scaffold and the analytics reads
        # share no reduction with it (pinned here; the serve analytics
        # byte-parity suite below rests on this).
        np.testing.assert_array_equal(
            np.asarray(cons_f), np.asarray(cons_p)
        )
        for got, want in zip(st_f, st_p):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

        # Bands == the standalone program fed the same resident state
        # (bit: both run band_math's tree order at the same chunk).
        standalone = build_band_program(mesh, chunk_slots=4)(
            probs, mask, state, now0
        )
        for name, got, want in zip(bands._fields, bands, standalone):
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(want), err_msg=name
            )

    def test_fused_sweep_equals_post_hoc_sweep(self):
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now0 = self._slot_major_inputs(7)
        m = probs.shape[1]
        rng = np.random.default_rng(11)
        nb_idx = jnp.asarray(rng.integers(-1, m, (m, 3)), jnp.int32)
        nb_w = jnp.asarray(rng.uniform(0.5, 1.5, (m, 3)), jnp.float32)
        fused = build_cycle_analytics_loop(
            mesh, chunk_slots=4, donate=False, damping=0.5, sweep_steps=2
        )
        _st, cons, _tb, _bands, prop = fused(
            probs, mask, outcome, state, now0, 2, nb_idx, nb_w
        )
        want = jax.jit(
            partial(damped_sweep_math, damping=0.5, steps=2)
        )(jnp.asarray(np.asarray(cons)), nb_idx, nb_w)
        np.testing.assert_allclose(
            np.asarray(prop), np.asarray(want), rtol=1e-6, equal_nan=True
        )

    def test_tiebreak_stage_optional(self):
        # with_tiebreak=False drops the ring stage from the program:
        # None in its slot, bands and the loop bytes untouched.
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now0 = self._slot_major_inputs()
        bands_only = build_cycle_analytics_loop(
            mesh, chunk_slots=4, donate=False, with_tiebreak=False
        )
        st_b, cons_b, tb, bands, _prop = bands_only(
            probs, mask, outcome, state, now0, 2
        )
        assert tb is None
        full = build_cycle_analytics_loop(
            mesh, chunk_agents=5, chunk_slots=4, donate=False
        )
        st_f, cons_f, tb_f, bands_f, _ = full(
            probs, mask, outcome, state, now0, 2
        )
        assert tb_f is not None
        np.testing.assert_array_equal(np.asarray(cons_b), np.asarray(cons_f))
        for got, want in zip(bands, bands_f):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        for got, want in zip(st_b, st_f):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_missing_graph_blocks_rejected(self):
        mesh = make_mesh((8, 1))
        probs, mask, outcome, state, now0 = self._slot_major_inputs()
        fused = build_cycle_analytics_loop(
            mesh, donate=False, sweep_steps=2
        )
        with pytest.raises(ValueError, match="neighbor"):
            fused(probs, mask, outcome, state, now0, 1)

    def test_unexpected_graph_blocks_rejected(self):
        # The symmetric mistake — neighbour blocks against a sweepless
        # program — must fail with the clear message, not a jax
        # arity/spec error from inside shard_map.
        mesh = make_mesh((8, 1))
        probs, mask, outcome, state, now0 = self._slot_major_inputs()
        sweepless = build_cycle_analytics_loop(mesh, donate=False)
        nb = jnp.zeros((probs.shape[1], 2), jnp.int32)
        with pytest.raises(ValueError, match="sweep_steps=0"):
            sweepless(
                probs, mask, outcome, state, now0, 1, nb,
                nb.astype(jnp.float32),
            )


def _market_payloads(markets=12, srcs=5, seed=7):
    rng = np.random.default_rng(seed)
    payloads = [
        (
            f"m-{i}",
            [
                {"sourceId": f"s-{j}", "probability": float(rng.random())}
                for j in range(srcs)
            ],
        )
        for i in range(markets)
    ]
    return payloads, list(rng.random(markets) < 0.5)


class TestSessionAnalytics:
    def test_settlement_bytes_equal_plain_settle(self):
        payloads, outcomes = _market_payloads()
        mesh = make_mesh()
        graph = MarketGraph.from_edges(
            [("m-0", "m-1", 1.0), ("m-1", "m-2", 0.5), ("m-3", "m-0", 2.0)]
        )
        stores = [TensorReliabilityStore() for _ in range(2)]
        plans = [
            build_settlement_plan(s, payloads, num_slots=8) for s in stores
        ]
        with ShardedSettlementSession(stores[0], plans[0], mesh) as plain:
            plain_result = plain.settle(outcomes, steps=2, now=NOW)
        with ShardedSettlementSession(stores[1], plans[1], mesh) as fused:
            result, tiebreak, bands, prop = fused.settle_with_analytics(
                outcomes, steps=2, now=NOW,
                analytics=AnalyticsOptions(graph=graph, chunk_slots=4),
            )
        # Point consensus BIT-equal (not tolerance): analytics must be
        # invisible to the settlement surface.
        np.testing.assert_array_equal(
            np.asarray(result.consensus), np.asarray(plain_result.consensus)
        )
        rows = np.arange(stores[0].live_row_count())
        for got, want in zip(
            stores[1].host_rows(rows), stores[0].host_rows(rows)
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # Analytics fields are populated and coherent.
        lo, mean, hi = (
            np.asarray(bands.lo), np.asarray(bands.mean), np.asarray(bands.hi)
        )
        assert (lo <= mean + 1e-6).all() and (mean <= hi + 1e-6).all()
        assert np.isfinite(np.asarray(prop)).all()
        assert np.asarray(tiebreak.prediction).shape == mean.shape

    def test_graph_blocks_cached_across_settles(self, monkeypatch):
        payloads, outcomes = _market_payloads()
        mesh = make_mesh()
        graph = MarketGraph.from_edges([("m-0", "m-1", 1.0)])
        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads, num_slots=8)
        calls = []
        original = MarketGraph.align

        def counting_align(self, *args, **kwargs):
            calls.append(1)
            return original(self, *args, **kwargs)

        monkeypatch.setattr(MarketGraph, "align", counting_align)
        options = AnalyticsOptions(graph=graph)
        with ShardedSettlementSession(store, plan, mesh) as session:
            session.settle_with_analytics(
                outcomes, now=NOW, analytics=options
            )
            session.settle_with_analytics(
                outcomes, now=NOW + 1, analytics=options
            )
        # Same plan topology + same graph: aligned once, reused after.
        assert len(calls) == 1

    def test_rejects_unknown_chunk_string(self):
        payloads, outcomes = _market_payloads(markets=2, srcs=2)
        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads, num_slots=4)
        with ShardedSettlementSession(store, plan, make_mesh()) as session:
            with pytest.raises(ValueError, match="standalone"):
                session.settle_with_analytics(
                    outcomes, now=NOW,
                    analytics=AnalyticsOptions(chunk_slots="auto"),
                )


def _journal_epochs_sans_clock(path):
    """Decoded epoch frames with the wall-clock field masked (same
    helper as test_serve/test_overlap)."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


def _serve_trace(width=6):
    """Hits + drift + growth, every round *width* distinct markets."""
    trace = []
    for rnd in range(2):
        for m in range(width):
            trace.append((
                f"m-{m}",
                [(f"s-{m}", 0.55 + 0.01 * rnd), (f"s-{(m + 1) % 3}", 0.4)],
                (m + rnd) % 2 == 0,
            ))
    for m in range(width):
        trace.append((
            f"fresh-{m}", [(f"s-{m % 3}", 0.62), (f"g-{m}", 0.48)],
            m % 2 == 1,
        ))
    return trace


def _run_service(tmp_path, name, analytics):
    """Submit the trace, drain, close; return (service, results)."""
    from bayesian_consensus_engine_tpu.serve import ConsensusService

    store = TensorReliabilityStore()
    trace = _serve_trace()

    async def main():
        service = ConsensusService(
            store,
            steps=2,
            now=NOW,
            mesh=make_mesh(),
            journal=tmp_path / f"{name}.jrnl",
            db_path=tmp_path / f"{name}.db",
            checkpoint_every=2,
            max_batch=6,
            max_delay_s=None,
            record_batches=True,
            analytics=analytics,
        )
        futures = []
        async with service:
            for market_id, signals, outcome in trace:
                futures.append(service.submit(market_id, signals, outcome))
            await service.drain()
        return service, [f.result() for f in futures]

    service, results = asyncio.run(main())
    store.sync()
    return service, results


class TestServeAnalyticsByteParity:
    """The acceptance contract: ``ConsensusService(analytics=...)`` on vs
    off over the same trace — batch sequence, per-request consensus,
    journal epoch payloads (wall_ts masked), and SQLite bytes all
    IDENTICAL; only the additive band fields differ (None vs values)."""

    def test_analytics_on_off_byte_parity(self, tmp_path):
        graph = MarketGraph.from_edges(
            [("m-0", "m-1", 1.0), ("m-2", "m-0", 0.5)]
        )
        svc_on, res_on = _run_service(
            tmp_path, "on", AnalyticsOptions(graph=graph)
        )
        svc_off, res_off = _run_service(tmp_path, "off", None)

        assert len(svc_on.batch_log) == len(svc_off.batch_log)
        for (cols_a, out_a), (cols_b, out_b) in zip(
            svc_on.batch_log, svc_off.batch_log
        ):
            assert cols_a[0] == cols_b[0] and out_a == out_b
        for a, b in zip(res_on, res_off):
            assert a.market_id == b.market_id
            assert a.consensus == b.consensus  # bit-equal floats
            assert a.batch_index == b.batch_index
            assert a.band_lo is not None and a.band_hi is not None
            assert a.band_lo <= a.consensus + 1e-6
            assert a.band_hi >= a.consensus - 1e-6
            assert b.band_lo is None and b.propagated is None
        assert _journal_epochs_sans_clock(tmp_path / "on.jrnl") == (
            _journal_epochs_sans_clock(tmp_path / "off.jrnl")
        )
        assert (tmp_path / "on.db").read_bytes() == (
            tmp_path / "off.db"
        ).read_bytes()

    def test_analytics_requires_resident_mesh(self):
        from bayesian_consensus_engine_tpu.serve import SessionDriver

        with pytest.raises(ValueError, match="resident"):
            SessionDriver(TensorReliabilityStore(), analytics=True)
        with pytest.raises(TypeError, match="AnalyticsOptions"):
            SessionDriver(
                TensorReliabilityStore(), mesh=make_mesh(),
                analytics="bands",
            )
