"""Scaled virtual-mesh execution (VERDICT r5 #3): the sharded north-star
band must run — and agree with the single-device loop — on a mesh whose
sources axis is REALLY split (non-singleton psum replica groups), at
shapes far past the old 16×8 toy dryrun.

``__graft_entry__.dryrun_north_star_band`` does the work (it is also the
``dryrun_multichip`` bench leg): build the (4, 2) hybrid mesh over the
8 virtual CPU devices the conftest provisions, run the production
slot-major cycle loop + the ring tie-break over it, and assert parity
with the single-device loop inside. The fast test pins the code path in
tier-1; the full ``large_k`` anchor shape (8 × 16k markets × 10k slots,
several GB of block state) runs under the ``slow`` marker and as the
production bench leg.
"""

import pathlib
import sys

import pytest

jax = pytest.importorskip("jax")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from __graft_entry__ import dryrun_north_star_band  # noqa: E402


class TestDryrunNorthStarBand:
    def test_scaled_band_parity_on_real_psum_mesh(self):
        result = dryrun_north_star_band(
            n_devices=8, markets=1_024, slots=64, steps=3
        )
        assert result["devices"] == 8
        assert result["mesh_shape"] == [4, 2]
        # The point of the exercise: the consensus reduction's psum runs
        # with real (non-singleton) replica groups — the 2-D regime the
        # projection table's claim (d) is about.
        assert result["psum_replica_groups"].startswith("real")
        # Parity vs the single-device loop was asserted INSIDE the run
        # (allclose at the documented psum re-association envelope).
        assert result["parity"].startswith("allclose")
        assert result["step_ms"] > 0
        assert result["ring_tiebreak_ms"] > 0
        assert result["per_device_band"] == "256 x 32"

    def test_shape_must_tile_the_mesh(self):
        with pytest.raises(ValueError, match="does not tile"):
            dryrun_north_star_band(n_devices=8, markets=1_023, slots=64)

    @pytest.mark.slow
    def test_full_large_k_anchor_shape(self):
        """The real thing: 8 devices × 16,384 markets × 10,000 slots —
        the ``large_k`` anchor shape whose per-device step time the
        docs/tpu-architecture.md projection table cites."""
        result = dryrun_north_star_band(
            n_devices=8, markets=16_384, slots=10_000, steps=2
        )
        assert result["per_device_band"] == "4096 x 5000"
        assert result["psum_replica_groups"].startswith("real")
        assert result["parity"].startswith("allclose")
        assert result["step_ms"] > 0
