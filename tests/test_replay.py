"""Counterfactual replay lab: the round-18 acceptance pins.

Four non-negotiable contracts:

* **Lane-0 byte contract** — re-driving a recorded journal's trace
  sidecar under the recorded config reproduces the live run's settled
  state byte-for-byte: :func:`~.cluster.recover.store_digest` AND the
  flushed SQLite file bytes, flat and sharded-resident.
* **Torn tails** — a journal cut mid-frame replays to its last joined
  epoch (the durable-tag bound, never past it); ``strict=True`` refuses
  (:class:`~.state.journal.TornTraceError`) instead of silently
  shortening the workload. Same for a trace sidecar cut mid-frame.
* **Sweep determinism** — the sweep result is a pure function of
  (trace, config set): run twice, identical ``result_digest`` and
  lane-state bytes; a sweep lane equals the same config replayed alone.
* **Bounded shed-stderr map** — the variance-aware shed ranking's
  per-market stderr map holds at most ``band_stderr_bound`` markets,
  eviction is deterministic (oldest settled-age first, ties by market
  id) and NEVER changes the shed order for live markets.
"""

import asyncio
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from bayesian_consensus_engine_tpu.cluster.recover import store_digest
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.pipeline import settle_stream
from bayesian_consensus_engine_tpu.replay import (
    RECORDED_CONFIG,
    ReplayConfig,
    load_trace,
    replay_single,
    replay_sweep,
    trace_from_batches,
)
from bayesian_consensus_engine_tpu.serve import (
    ConsensusService,
    QosClass,
    ShedError,
)
from bayesian_consensus_engine_tpu.serve.driver import drive_trace
from bayesian_consensus_engine_tpu.state.journal import (
    TornTraceError,
    read_trace,
    trace_path_for,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_900.0

# Two counterfactual lanes walking the swept knobs — a deterministic
# grid, no RNG (the sweep must be a pure function of (trace, configs)).
ALTERED = (
    ReplayConfig(half_life_days=12.0, base_learning_rate=0.05),
    ReplayConfig(max_update_step=0.04, band_z=1.25),
)


def _columnar_batches(n_batches=3, per_batch=16, seed=18):
    """A small service-shaped workload: half the keys recur across
    batches (the refresh path), half are fresh (the intern path)."""
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        counts = rng.integers(1, 5, per_batch)
        total = int(counts.sum())
        keys = [
            f"m{m}" if m % 2 == 0 else f"b{b}-m{m}"
            for m in range(per_batch)
        ]
        sids = [f"src-{v}" for v in rng.integers(0, 12, total)]
        probs = rng.random(total)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        outcomes = (rng.random(per_batch) < 0.5).tolist()
        out.append(((keys, sids, probs, offsets), outcomes))
    return out


def _record_live(tmp_path, batches, steps=2, name="live.jrnl"):
    """Run the REAL streamed service loop with journal + trace sidecar;
    returns (settled store, journal path)."""
    jrnl = str(tmp_path / name)
    store = TensorReliabilityStore()
    for _result in settle_stream(
        store, batches, steps=steps, now=NOW,
        journal=jrnl, trace=jrnl + ".trace", columnar=True,
    ):
        pass
    return store, jrnl


def _truncate(path, drop=9):
    """Cut *drop* bytes off the file's tail — mid-frame, the way a crash
    tears an append (frames are far larger than 9 bytes)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - drop)


class TestTraceSidecar:
    """The trace sidecar records the INPUTS the journal's deltas came
    from, in admitted order, replayable bit-for-bit."""

    def test_roundtrip_preserves_the_recorded_workload(self, tmp_path):
        batches = _columnar_batches()
        _store, jrnl = _record_live(tmp_path, batches)
        trace = read_trace(trace_path_for(jrnl))
        assert [b.index for b in trace] == [0, 1, 2]
        for b, ((keys, sids, probs, offsets), outcomes) in zip(
            trace, batches
        ):
            assert list(b.market_keys) == keys
            assert list(b.source_ids) == sids
            np.testing.assert_array_equal(b.probabilities, probs)
            np.testing.assert_array_equal(b.offsets, offsets)
            assert b.outcomes.tolist() == outcomes
            assert b.steps == 2
        # The stream's now=float cadence: one day per batch.
        assert [b.now_days for b in trace] == [NOW, NOW + 1, NOW + 2]

    def test_load_trace_covers_a_healthy_journal_fully(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        assert len(load_trace(jrnl)) == 3
        assert len(load_trace(jrnl, strict=True)) == 3

    def test_trace_from_batches_is_replay_equivalent(self, tmp_path):
        """A serving front end's batch log, converted in-process, drives
        the same rebuild as the recorded sidecar."""
        batches = _columnar_batches()
        live, _jrnl = _record_live(tmp_path, batches)
        trace = trace_from_batches(batches, now=NOW, steps=2)
        result = replay_sweep(trace)
        assert result.digest == store_digest(live)


class TestTornTails:
    """Satellite: torn/truncated journal tails entering the replay lab."""

    def test_journal_cut_mid_frame_replays_to_last_joined_epoch(
        self, tmp_path
    ):
        batches = _columnar_batches()
        _store, jrnl = _record_live(tmp_path, batches)
        _truncate(jrnl)
        trace = load_trace(jrnl)
        # The torn final epoch is NOT replayed: the workload stops at
        # the journal's durable tag...
        assert len(trace) == 2
        # ...and the bounded replay equals a live run that only ever saw
        # those batches — byte-for-byte.
        expect, _ = _record_live(tmp_path, batches[:2], name="short.jrnl")
        assert replay_sweep(trace).digest == store_digest(expect)

    def test_strict_refuses_a_torn_journal(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        _truncate(jrnl)
        with pytest.raises(TornTraceError, match="durable"):
            load_trace(jrnl, strict=True)
        # TornTraceError is a ValueError: pre-round-18 callers that
        # guard extraction with ValueError keep working.
        assert issubclass(TornTraceError, ValueError)

    def test_torn_trace_tail_drops_only_the_torn_frame(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        _truncate(trace_path_for(jrnl))
        assert len(read_trace(trace_path_for(jrnl))) == 2
        assert len(load_trace(jrnl)) == 2
        with pytest.raises(TornTraceError, match="mid-frame"):
            load_trace(jrnl, strict=True)


class TestLane0ByteContract:
    """Lane 0 pinned to the recorded config IS the live run."""

    def test_flat_rebuild_matches_live_digest_and_sqlite_bytes(
        self, tmp_path
    ):
        live, jrnl = _record_live(tmp_path, _columnar_batches())
        result = replay_sweep(load_trace(jrnl), ALTERED)
        assert result.digest == store_digest(live)
        # Same settled state ⇒ same checkpoint file, byte for byte.
        p_live = tmp_path / "live.db"
        p_replay = tmp_path / "replay.db"
        live.flush_to_sqlite(p_live)
        result.store.flush_to_sqlite(p_replay)
        assert p_live.read_bytes() == p_replay.read_bytes()

    def test_sharded_resident_rebuild_matches_live_digest(self, tmp_path):
        live, jrnl = _record_live(tmp_path, _columnar_batches())
        rebuilt = TensorReliabilityStore()
        drive_trace(rebuilt, load_trace(jrnl), mesh=make_mesh())
        assert store_digest(rebuilt) == store_digest(live)


class TestSweepDeterminism:
    """The sweep is a pure function of (trace, config set)."""

    def test_run_twice_identical(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        trace = load_trace(jrnl)
        first = replay_sweep(trace, ALTERED, rebuild=False)
        second = replay_sweep(trace, ALTERED, rebuild=False)
        assert first.result_digest == second.result_digest
        for a, b in zip(first.lane_state, second.lane_state):
            assert a.tobytes() == b.tobytes()

    def test_lane0_is_always_the_recorded_config(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        result = replay_sweep(load_trace(jrnl), ALTERED, rebuild=False)
        assert result.lanes[0].config == RECORDED_CONFIG
        assert len(result.lanes) == 1 + len(ALTERED)
        assert set(result.by_config()) == {RECORDED_CONFIG, *ALTERED}

    def test_altered_lanes_actually_diverge(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        result = replay_sweep(load_trace(jrnl), ALTERED, rebuild=False)
        reliability = result.lane_state[0]
        assert not np.array_equal(reliability[0], reliability[1])
        # The band_z lane reads back through the band-width metric.
        by = result.by_config()
        assert by[ALTERED[1]].band_width_sum != pytest.approx(
            by[RECORDED_CONFIG].band_width_sum
        )

    def test_replay_single_equals_the_sweep_lane(self, tmp_path):
        """The sequential baseline and the vmapped lane run the SAME
        per-lane math — K-lane batching must not change any lane."""
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        trace = load_trace(jrnl)
        sweep = replay_sweep(trace, ALTERED, rebuild=False).by_config()
        for config in (RECORDED_CONFIG,) + ALTERED:
            alone = replay_single(trace, config)
            lane = sweep[config]
            assert alone.markets_settled == lane.markets_settled
            assert alone.brier_sum == pytest.approx(
                lane.brier_sum, rel=1e-6
            )
            assert alone.band_width_sum == pytest.approx(
                lane.band_width_sum, rel=1e-6
            )


class TestSweepValidation:
    def test_empty_trace_refuses(self):
        with pytest.raises(ValueError, match="empty trace"):
            replay_sweep([])

    def test_mixed_step_counts_refuse(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        trace = load_trace(jrnl)
        trace[-1] = trace[-1]._replace(steps=trace[-1].steps + 1)
        with pytest.raises(ValueError, match="mixes step counts"):
            replay_sweep(trace, rebuild=False)

    def test_graph_lane_without_graph_refuses(self, tmp_path):
        _store, jrnl = _record_live(tmp_path, _columnar_batches())
        with pytest.raises(ValueError, match="graph_steps > 0"):
            replay_sweep(
                load_trace(jrnl),
                (ReplayConfig(graph_steps=2),),
                rebuild=False,
            )


class TestBoundedShedStderr:
    """PR-15 follow-up: the shed-ranking stderr map stops growing
    without bound, and eviction never changes the shed order for live
    markets."""

    def test_bound_must_be_positive(self):
        with pytest.raises(ValueError, match="band_stderr_bound"):
            ConsensusService(
                TensorReliabilityStore(), steps=1, now=NOW,
                band_stderr_bound=0,
            )

    def test_eviction_is_deterministic_oldest_first_ties_by_id(self):
        store = TensorReliabilityStore()
        survivors = []

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                band_stderr_bound=3,
            )
            # Three seed waves = three settled-age stamps. Nothing is
            # pending, so eviction is purely (age, market id) ascending:
            # the wave-1 pair goes first, 'w1-a' before 'w1-b'.
            service.seed_band_stderr({"w1-b": 0.2, "w1-a": 0.4})
            service.seed_band_stderr({"w2-c": 0.3})
            service.seed_band_stderr({"w3-d": 0.1, "w3-e": 0.5})
            survivors.extend(sorted(service.market_band_stderr))
            await service.drain()
            await service.close()

        asyncio.run(main())
        assert survivors == ["w2-c", "w3-d", "w3-e"]

    def test_eviction_never_changes_live_shed_order(self):
        """The satellite pin: force eviction while the live markets'
        overflow trace is in flight — the victim sequence must equal the
        unbounded run's, and only non-live entries may be evicted."""
        unbounded = self._collect_victims(bound=4096, stale=False)
        bounded = self._collect_victims(bound=3, stale=True)
        assert unbounded == ["m-wide", "m-mid", "m-narrow"]
        assert bounded == unbounded

    def _collect_victims(self, bound, stale):
        store = TensorReliabilityStore()
        victims = []

        async def main():
            service = ConsensusService(
                store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
                qos=[QosClass("be", 3600.0, 3, policy="shed_oldest")],
                band_stderr_bound=bound,
            )
            pending = {}
            for market in ("m-narrow", "m-wide", "m-mid"):
                pending[market] = service.submit(
                    market, [("s", 0.6)], True, qos_class="be"
                )
            service.seed_band_stderr(
                {"m-wide": 0.40, "m-mid": 0.20, "m-narrow": 0.05}
            )
            if stale:
                # Two younger non-live entries push the map past
                # bound=3. Live (pending) markets are NEVER evicted —
                # the stale newcomers go instead, so the ranking the
                # shed policy reads is untouched.
                service.seed_band_stderr(
                    {"z-stale-1": 0.90, "z-stale-2": 0.95}
                )
                assert sorted(service.market_band_stderr) == [
                    "m-mid", "m-narrow", "m-wide",
                ]
            for i in range(3):
                pending[f"m-fresh-{i}"] = service.submit(
                    f"m-fresh-{i}", [("s", 0.6)], True, qos_class="be"
                )
                for market, future in list(pending.items()):
                    if future.done() and isinstance(
                        future.exception(), ShedError
                    ):
                        victims.append(market)
                        del pending[market]
            await service.drain()
            await service.close()

        asyncio.run(main())
        return victims
