"""Counter-compact state (parallel/compact.py) vs the f32 loop.

The compact loop must be tolerance-equivalent to build_cycle_loop — the
f32 path itself drifts ulp-level from the f64 scalar contract, and the
counter decode replaces sequential f32 adds with closed forms, so the
bound here is a few f32 ulp (1e-6 relative), pinned by these tests over
random workloads, saturation drives, and the sharded mesh path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_compact_cycle_loop,
    build_cycle_loop,
    compact_to_block,
    init_block_state,
    init_compact_state,
    make_mesh,
)
from bayesian_consensus_engine_tpu.parallel.compact import (
    decode_confidence,
    decode_reliability,
)

M, K = 96, 8


def _workload(seed, m=M, k=K, occupancy=0.9):
    rng = np.random.default_rng(seed)
    probs = jnp.asarray(rng.random((k, m)), jnp.float32)
    mask = jnp.asarray(rng.random((k, m)) < occupancy)
    outcome = jnp.asarray(rng.random(m) < 0.5)
    return probs, mask, outcome


def _f32_state(m=M, k=K):
    return MarketBlockState(*(x.T for x in init_block_state(m, k)))


class TestDecode:
    def test_zero_counters_are_cold_start(self):
        state = init_compact_state(4, 2)
        assert np.all(np.asarray(decode_reliability(state.rel_steps)) == 0.5)
        assert np.all(np.asarray(decode_confidence(state.conf_steps)) == 0.25)

    def test_reliability_lattice(self):
        steps = jnp.arange(-5, 6, dtype=jnp.int8)
        vals = np.asarray(decode_reliability(steps))
        np.testing.assert_allclose(vals, np.arange(0.0, 1.01, 0.1), atol=1e-7)

    def test_confidence_matches_sequential_growth(self):
        # Closed form vs the scalar recurrence c' = c + (1-c)*0.1.
        c = 0.25
        for n in range(1, 60):
            c = min(1.0, c + (1.0 - c) * 0.1)
            got = float(decode_confidence(jnp.uint8(n)))
            assert got == pytest.approx(c, abs=2e-6), n


class TestLoopEquivalence:
    @pytest.mark.parametrize("steps", [1, 2, 7])
    def test_matches_f32_loop(self, steps):
        probs, mask, outcome = _workload(steps)
        f32_loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
        want_state, want_consensus = f32_loop(
            probs, mask, outcome, _f32_state(), jnp.float32(1.0), steps
        )
        compact_loop = build_compact_cycle_loop(mesh=None, donate=False)
        got_state, got_consensus = compact_loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(1.0), steps
        )
        np.testing.assert_allclose(
            np.asarray(got_consensus), np.asarray(want_consensus),
            rtol=1e-6, atol=1e-6,
        )
        decoded = compact_to_block(got_state)
        np.testing.assert_allclose(
            np.asarray(decoded.reliability), np.asarray(want_state.reliability),
            rtol=1e-6, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(decoded.confidence), np.asarray(want_state.confidence),
            rtol=1e-6, atol=1e-6,
        )
        np.testing.assert_array_equal(
            np.asarray(decoded.updated_days), np.asarray(want_state.updated_days)
        )

    def test_saturation_drive(self):
        # All-correct signals for 12 steps: reliability clamps at 1.0 and
        # stays there, exactly as the f32 clip does.
        k, m = 4, 8
        probs = jnp.full((k, m), 0.9, jnp.float32)
        mask = jnp.ones((k, m), bool)
        outcome = jnp.ones((m,), bool)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        state, _ = loop(
            probs, mask, outcome, init_compact_state(m, k), jnp.float32(1.0), 12
        )
        assert np.all(np.asarray(state.rel_steps) == 5)
        np.testing.assert_allclose(
            np.asarray(decode_reliability(state.rel_steps)), 1.0
        )
        # and back down: 3 wrong steps from saturation → 0.7
        state2, _ = loop(
            probs, mask, ~outcome, state, jnp.float32(20.0), 3
        )
        np.testing.assert_allclose(
            np.asarray(decode_reliability(state2.rel_steps)), 0.7, atol=1e-7
        )

    def test_unmasked_slots_pass_through_exactly(self):
        probs, _, outcome = _workload(3)
        mask = jnp.zeros((K, M), bool).at[: K // 2].set(True)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        state, _ = loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(5.0), 4
        )
        untouched = np.asarray(state.rel_steps)[K // 2 :]
        assert np.all(untouched == 0)
        assert np.all(np.asarray(state.conf_steps)[K // 2 :] == 0)
        assert np.all(np.asarray(state.updated_days)[K // 2 :] == 0.0)

    def test_warm_state_decays_on_step_zero(self):
        # A warm compact state entering a later loop must decay from its
        # per-slot stamps on step 0 (the amortised tensor read).
        probs, mask, outcome = _workload(9)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        warm, _ = loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(1.0), 2
        )
        f32_loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
        warm_f32, _ = f32_loop(
            probs, mask, outcome, _f32_state(), jnp.float32(1.0), 2
        )
        # 90 days later: reads are decayed identically in both paths.
        got_state, got_cons = loop(
            probs, mask, outcome, warm, jnp.float32(92.0), 1
        )
        want_state, want_cons = f32_loop(
            probs, mask, outcome, warm_f32, jnp.float32(92.0), 1
        )
        np.testing.assert_allclose(
            np.asarray(got_cons), np.asarray(want_cons), rtol=1e-6, atol=1e-6
        )

    def test_market_major_layout_matches_slot_major(self):
        # slot_major=False carries (M, K) blocks; same numbers, same
        # counters — only the layout differs.
        probs, mask, outcome = _workload(14)
        sm_loop = build_compact_cycle_loop(mesh=None, donate=False)
        sm_state, sm_cons = sm_loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(1.0), 3
        )
        mm_loop = build_compact_cycle_loop(
            mesh=None, slot_major=False, donate=False
        )
        mm_state, mm_cons = mm_loop(
            probs.T, mask.T, outcome,
            init_compact_state(M, K, slot_major=False), jnp.float32(1.0), 3,
        )
        np.testing.assert_allclose(
            np.asarray(mm_cons), np.asarray(sm_cons), rtol=1e-6, atol=1e-6
        )
        np.testing.assert_array_equal(
            np.asarray(mm_state.rel_steps).T, np.asarray(sm_state.rel_steps)
        )
        np.testing.assert_array_equal(
            np.asarray(mm_state.conf_steps).T, np.asarray(sm_state.conf_steps)
        )

    def test_zero_steps_identity(self):
        probs, mask, outcome = _workload(4)
        state = init_compact_state(M, K)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        got_state, consensus = loop(
            probs, mask, outcome, state, jnp.float32(1.0), 0
        )
        for got, want in zip(got_state, state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert not np.any(np.asarray(consensus))


class TestClosedForm:
    @pytest.mark.parametrize("steps", [1, 3, 8, 20])
    def test_advance_counters_equals_loop(self, steps):
        from bayesian_consensus_engine_tpu.parallel import advance_counters

        probs, mask, outcome = _workload(31)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        want_state, _ = loop(
            probs, mask, outcome, init_compact_state(M, K),
            jnp.float32(2.0), steps,
        )
        correct = (probs >= 0.5) == outcome[None, :]
        got = advance_counters(
            init_compact_state(M, K), mask, correct, steps, jnp.float32(2.0)
        )
        for field in got._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(got, field)),
                np.asarray(getattr(want_state, field)),
                err_msg=field,
            )

    def test_advance_from_warm_state_with_saturation(self):
        from bayesian_consensus_engine_tpu.parallel import advance_counters

        probs, mask, outcome = _workload(32)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        warm, _ = loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(1.0), 4
        )
        # 12 more identical days: many counters saturate at the clamp.
        want, _ = loop(probs, mask, outcome, warm, jnp.float32(5.0), 12)
        correct = (probs >= 0.5) == outcome[None, :]
        got = advance_counters(warm, mask, correct, 12, jnp.float32(5.0))
        for field in got._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(got, field)),
                np.asarray(getattr(want, field)),
                err_msg=field,
            )

    def test_zero_steps_is_identity(self):
        from bayesian_consensus_engine_tpu.parallel import advance_counters

        _, mask, outcome = _workload(33)
        state = init_compact_state(M, K)
        got = advance_counters(
            state, mask, jnp.zeros_like(mask), 0, jnp.float32(1.0)
        )
        assert got is state

    def test_conf_cap_saturation_matches_loop(self):
        # Counters hand-built just below the uint8 cap: the loop's guarded
        # +1 and the closed form's min(c+N, 255) must agree ACROSS the cap
        # (a wraparound in either would pass the shallower tests).
        from bayesian_consensus_engine_tpu.parallel import (
            CompactBlockState,
            advance_counters,
        )

        probs, mask, outcome = _workload(34)
        near_cap = CompactBlockState(
            rel_steps=jnp.zeros((K, M), jnp.int8),
            conf_steps=jnp.full((K, M), 250, jnp.uint8),
            updated_days=jnp.full((K, M), 3.0, jnp.float32),
        )
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        want, _ = loop(probs, mask, outcome, near_cap, jnp.float32(4.0), 10)
        correct = (probs >= 0.5) == outcome[None, :]
        got = advance_counters(near_cap, mask, correct, 10, jnp.float32(4.0))
        assert int(np.asarray(want.conf_steps).max()) == 255  # cap reached
        for field in got._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(got, field)),
                np.asarray(getattr(want, field)),
                err_msg=field,
            )


class TestCheckpoint:
    def test_compact_state_round_trips_through_orbax(self, tmp_path):
        # The checkpoint tier is pytree-generic; pin that int8/uint8
        # counter states survive save → restore bit-identically and keep
        # their dtypes (resume-from-checkpoint for the compact loop).
        pytest.importorskip("orbax.checkpoint")
        from bayesian_consensus_engine_tpu.state.checkpoint import (
            CycleCheckpointer,
        )

        probs, mask, outcome = _workload(21)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        state, _ = loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(1.0), 3
        )
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(3, state, meta={"next_now": 4.0}, force=True)
            restored, meta = ckpt.restore(like=state)
        assert meta["next_now"] == 4.0
        for got, want in zip(restored, state):
            assert np.asarray(got).dtype == np.asarray(want).dtype
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # and the resumed loop continues bit-identically
        full_state, full_cons = loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(1.0), 5
        )
        res_state, res_cons = loop(
            probs, mask, outcome, restored, jnp.float32(4.0), 2
        )
        np.testing.assert_array_equal(
            np.asarray(res_cons), np.asarray(full_cons)
        )
        for field in ("rel_steps", "conf_steps", "updated_days"):
            np.testing.assert_array_equal(
                np.asarray(getattr(res_state, field)),
                np.asarray(getattr(full_state, field)),
                err_msg=field,
            )


class TestSharded:
    @pytest.mark.parametrize("shape", [(8, 1), (2, 4)])
    def test_mesh_parity(self, shape):
        from bayesian_consensus_engine_tpu.parallel.mesh import (
            MARKETS_AXIS,
            SOURCES_AXIS,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax

        mesh = make_mesh(shape)
        probs, mask, outcome = _workload(11)
        block = NamedSharding(mesh, P(SOURCES_AXIS, MARKETS_AXIS))
        market = NamedSharding(mesh, P(MARKETS_AXIS))
        state = jax.tree.map(
            lambda x: jax.device_put(x, block), init_compact_state(M, K)
        )
        sharded_loop = build_compact_cycle_loop(mesh, donate=False)
        got_state, got_cons = sharded_loop(
            jax.device_put(probs, block),
            jax.device_put(mask, block),
            jax.device_put(outcome, market),
            state,
            jnp.float32(1.0),
            3,
        )
        plain_loop = build_compact_cycle_loop(mesh=None, donate=False)
        want_state, want_cons = plain_loop(
            probs, mask, outcome, init_compact_state(M, K), jnp.float32(1.0), 3
        )
        np.testing.assert_allclose(
            np.asarray(got_cons), np.asarray(want_cons), rtol=2e-6, atol=1e-6
        )
        np.testing.assert_array_equal(
            np.asarray(got_state.rel_steps), np.asarray(want_state.rel_steps)
        )
        np.testing.assert_array_equal(
            np.asarray(got_state.conf_steps), np.asarray(want_state.conf_steps)
        )


class TestReducedPrecisionProbs:
    """Opt-in reduced-precision probability inputs for the compact loop:
    u16 fixed point (2 bytes, ~7.6e-6 quantization) auto-decodes in the
    kernel; bf16 promotes exactly. Both equal the f32 loop run on the
    rounded inputs BITWISE — the encoding never changes the math, only
    the input resolution."""

    def _workload(self, M=512, K=8):
        import jax

        key = jax.random.PRNGKey(3)
        kp, km, ko = jax.random.split(key, 3)
        probs = jax.random.uniform(kp, (K, M), dtype=jnp.float32)
        mask = jax.random.uniform(km, (K, M)) < 0.9
        outcome = jax.random.uniform(ko, (M,)) < 0.5
        return probs, mask, outcome

    def test_u16_equals_f32_on_decoded_inputs_bitwise(self):
        from bayesian_consensus_engine_tpu.parallel.compact import (
            _decode_probs,
            encode_probs_u16,
        )

        probs, mask, outcome = self._workload()
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        encoded = encode_probs_u16(probs)
        assert encoded.dtype == jnp.uint16
        s_enc, c_enc = loop(
            encoded, mask, outcome, init_compact_state(512, 8),
            jnp.float32(1.0), 3,
        )
        s_ref, c_ref = loop(
            _decode_probs(encoded), mask, outcome, init_compact_state(512, 8),
            jnp.float32(1.0), 3,
        )
        assert c_enc.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(c_enc), np.asarray(c_ref))
        for a, b in zip(s_enc, s_ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_u16_quantization_bound_vs_f32(self):
        probs, mask, outcome = self._workload()
        from bayesian_consensus_engine_tpu.parallel.compact import (
            encode_probs_u16,
        )

        loop = build_compact_cycle_loop(mesh=None, donate=False)
        _, c_u16 = loop(
            encode_probs_u16(probs), mask, outcome,
            init_compact_state(512, 8), jnp.float32(1.0), 1,
        )
        _, c_f32 = loop(
            probs, mask, outcome, init_compact_state(512, 8),
            jnp.float32(1.0), 1,
        )
        err = np.abs(np.asarray(c_u16, np.float64) - np.asarray(c_f32, np.float64))
        # Consensus is a weighted mean of probabilities: its error is
        # bounded by the per-input quantization step (plus f32 noise).
        assert np.nanmax(err) < 2e-5, np.nanmax(err)

    def test_bf16_passthrough_promotes_exactly(self):
        probs, mask, outcome = self._workload()
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        bf16 = probs.astype(jnp.bfloat16)
        _, c_bf16 = loop(
            bf16, mask, outcome, init_compact_state(512, 8),
            jnp.float32(1.0), 2,
        )
        _, c_ref = loop(
            bf16.astype(jnp.float32), mask, outcome,
            init_compact_state(512, 8), jnp.float32(1.0), 2,
        )
        np.testing.assert_array_equal(np.asarray(c_bf16), np.asarray(c_ref))

    def test_u16_round_trips_reference_precision_grid(self):
        """Signals quoted to ~4 decimal places survive u16 encoding with
        their correctness side (p >= 0.5) intact."""
        from bayesian_consensus_engine_tpu.parallel.compact import (
            _decode_probs,
            encode_probs_u16,
        )

        grid = jnp.asarray(
            np.round(np.linspace(0.0, 1.0, 10_001), 4), jnp.float32
        )
        decoded = np.asarray(_decode_probs(encode_probs_u16(grid)))
        assert np.max(np.abs(decoded - np.asarray(grid))) <= 0.5 / 65535 + 1e-7
        np.testing.assert_array_equal(
            decoded >= 0.5, np.asarray(grid) >= 0.5
        )

    def test_u16_decode_is_not_hoisted_out_of_the_loop(self):
        """The whole point of u16 input is that the fori operand stays two
        bytes: the compiled program must not materialise a full-size f32
        decode at entry (feeding the while), and no f32 probs block may
        ride the while carry. (CPU pipeline; the TPU bench reports the
        measured effect — north_star_band.u16_probs.)"""
        import re
        from functools import partial

        import jax

        from bayesian_consensus_engine_tpu.parallel.compact import (
            _compact_loop_math,
            encode_probs_u16,
        )

        M, K, steps = 512, 8, 4
        probs, mask, outcome = self._workload(M, K)
        fn = partial(
            _compact_loop_math, steps=steps, axis_name=None, slots_axis=0
        )
        hlo = (
            jax.jit(fn)
            .lower(
                encode_probs_u16(probs), mask, outcome,
                init_compact_state(M, K), jnp.float32(1.0),
            )
            .compile()
            .as_text()
        )
        entry = hlo[hlo.index("ENTRY"):]
        # No entry-level convert may produce the f32 probs-shaped block.
        assert not re.search(
            rf"= f32\[{K},{M}\][^\n]*convert", entry
        ), "u16 decode was hoisted to entry"
        # The while carry must not include an f32 probs-shaped block.
        for line in entry.splitlines():
            if "while(" in line:
                assert f"f32[{K},{M}]" not in line.split("while(")[0], line

    def test_u16_probs_on_the_mesh_loop(self):
        """The sharded compact loop (shard_map over a 2-D mesh) must accept
        u16 probability blocks too — the north-star multi-chip shape."""
        import jax

        from bayesian_consensus_engine_tpu.parallel import make_mesh
        from bayesian_consensus_engine_tpu.parallel.compact import (
            _decode_probs,
            encode_probs_u16,
        )
        from bayesian_consensus_engine_tpu.parallel.mesh import (
            MARKETS_AXIS,
            SOURCES_AXIS,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh((4, 2))
        M, K, steps = 64, 8, 3
        probs, mask, outcome = self._workload(M, K)
        block = NamedSharding(mesh, P(SOURCES_AXIS, MARKETS_AXIS))
        market = NamedSharding(mesh, P(MARKETS_AXIS))
        encoded = jax.device_put(encode_probs_u16(probs), block)
        mask_s = jax.device_put(mask, block)
        outcome_s = jax.device_put(outcome, market)

        def sharded_state():
            return jax.tree.map(
                lambda x: jax.device_put(x, block),
                init_compact_state(M, K),
            )

        loop = build_compact_cycle_loop(mesh, donate=False)
        s_enc, c_enc = loop(
            encoded, mask_s, outcome_s, sharded_state(), jnp.float32(1.0),
            steps,
        )
        # Equals the single-device loop on the decoded inputs (2-D mesh:
        # psum partial sums re-associate — ulp tolerance, like the f32
        # sharded-vs-flat contract).
        flat = build_compact_cycle_loop(mesh=None, donate=False)
        s_ref, c_ref = flat(
            _decode_probs(encode_probs_u16(probs)), mask, outcome,
            init_compact_state(M, K), jnp.float32(1.0), steps,
        )
        np.testing.assert_allclose(
            np.asarray(c_enc, np.float32), np.asarray(c_ref, np.float32),
            rtol=2e-6, atol=1e-6,
        )
        np.testing.assert_array_equal(
            np.asarray(s_enc.rel_steps), np.asarray(s_ref.rel_steps)
        )


class TestU16Days:
    """Opt-in u16 day stamps (`init_compact_state(days_dtype=uint16)`):
    2 bytes/slot at rest instead of 4 — at the north-star band the
    2.5 GB that decides whether the f32-signal band fits one 16 GB chip
    (bench.bench_north_star_f32). Contract: integral days in [0, 65535]
    are BIT-IDENTICAL to the f32-days state on every path (u16→f32
    conversion is exact there)."""

    def test_init_dtype_and_validation(self):
        state = init_compact_state(4, 2, days_dtype=jnp.uint16)
        assert state.updated_days.dtype == jnp.uint16
        assert init_compact_state(4, 2).updated_days.dtype == jnp.float32
        with pytest.raises(ValueError, match="days_dtype"):
            init_compact_state(4, 2, days_dtype=jnp.int32)

    @pytest.mark.parametrize("steps", [1, 2, 7])
    def test_loop_bit_identical_to_f32_days(self, steps):
        probs, mask, outcome = _workload(steps + 100)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        day = jnp.float32(3.0)
        want_state, want_consensus = loop(
            probs, mask, outcome, init_compact_state(M, K), day, steps
        )
        got_state, got_consensus = loop(
            probs, mask, outcome,
            init_compact_state(M, K, days_dtype=jnp.uint16), day, steps,
        )
        np.testing.assert_array_equal(
            np.asarray(got_consensus), np.asarray(want_consensus)
        )
        np.testing.assert_array_equal(
            np.asarray(got_state.rel_steps), np.asarray(want_state.rel_steps)
        )
        np.testing.assert_array_equal(
            np.asarray(got_state.conf_steps),
            np.asarray(want_state.conf_steps),
        )
        assert got_state.updated_days.dtype == jnp.uint16
        np.testing.assert_array_equal(
            np.asarray(got_state.updated_days, dtype=np.float32),
            np.asarray(want_state.updated_days),
        )

    def test_warm_resume_and_read_time_decay_bit_identical(self):
        # A warm u16-days state entering a LATER loop must decay from its
        # per-slot stamps on step 0 exactly as the f32-days state does —
        # the one place the stored days are actually read.
        probs, mask, outcome = _workload(11)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        f32_state, _ = loop(
            probs, mask, outcome, init_compact_state(M, K),
            jnp.float32(1.0), 3,
        )
        u16_state, _ = loop(
            probs, mask, outcome,
            init_compact_state(M, K, days_dtype=jnp.uint16),
            jnp.float32(1.0), 3,
        )
        # resume 40 days later: decay has real work to do
        want_state, want_consensus = loop(
            probs, mask, outcome, f32_state, jnp.float32(43.0), 2
        )
        got_state, got_consensus = loop(
            probs, mask, outcome, u16_state, jnp.float32(43.0), 2
        )
        np.testing.assert_array_equal(
            np.asarray(got_consensus), np.asarray(want_consensus)
        )
        np.testing.assert_array_equal(
            np.asarray(got_state.rel_steps), np.asarray(want_state.rel_steps)
        )
        np.testing.assert_array_equal(
            np.asarray(got_state.updated_days, dtype=np.float32),
            np.asarray(want_state.updated_days),
        )

    def test_advance_counters_preserves_dtype_and_value(self):
        from bayesian_consensus_engine_tpu.parallel import advance_counters

        probs, mask, outcome = _workload(5)
        correct = (probs >= 0.5) == outcome[None, :]
        got = advance_counters(
            init_compact_state(M, K, days_dtype=jnp.uint16),
            mask, correct, 6, jnp.float32(10.0),
        )
        want = advance_counters(
            init_compact_state(M, K), mask, correct, 6, jnp.float32(10.0)
        )
        assert got.updated_days.dtype == jnp.uint16
        np.testing.assert_array_equal(
            np.asarray(got.updated_days, dtype=np.float32),
            np.asarray(want.updated_days),
        )
        np.testing.assert_array_equal(
            np.asarray(got.rel_steps), np.asarray(want.rel_steps)
        )

    def test_compact_to_block_returns_f32_days(self):
        state = init_compact_state(8, 4, days_dtype=jnp.uint16)
        block = compact_to_block(state)
        assert block.updated_days.dtype == jnp.float32

    def test_stamp_clips_past_the_u16_horizon_instead_of_wrapping(self):
        # 70000 would wrap to 4464 on a bare cast, making rows read as
        # ~65k days stale; the stamp must saturate at 65535 instead.
        probs, mask, outcome = _workload(17)
        loop = build_compact_cycle_loop(mesh=None, donate=False)
        state, _ = loop(
            probs, mask, outcome,
            init_compact_state(M, K, days_dtype=jnp.uint16),
            jnp.float32(70000.0), 1,
        )
        stamped = np.asarray(state.updated_days)[np.asarray(mask)]
        assert np.all(stamped == 65535)
