"""Real two-process distributed bring-up over a localhost coordinator.

Everything in test_distributed.py runs single-process; here two actual
Python processes (4 virtual CPU devices each) join one JAX runtime via
``init_distributed``, build the DCN-outer hybrid mesh with 2 granules (one
per process), assemble globally-sharded arrays from per-process market
bands, and run one settlement cycle whose cross-process collectives ride
gloo — covering the cluster branch of distributed.py and the real
multi-host semantics of ``jax.make_array_from_process_local_data``.

The reference has no distributed runtime at all (SURVEY §5); this suite is
the multi-host analogue of its subprocess CLI integration tests
(reference: tests/test_integration.py:15-23).
"""

import json
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    build_cycle,
    build_cycle_loop,
    init_block_state,
    make_mesh,
)

_ROOT = pathlib.Path(__file__).resolve().parents[1]

M, K = 16, 8
SEED = 20260730

_WORKER = """
import json, pathlib, sys

sys.path.insert(0, {root!r})

import os
_FLAG = "--xla_force_host_platform_device_count=4"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # old JAX: XLA_FLAGS above covers it
    pass

import numpy as np

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle,
    build_cycle_loop,
    init_block_state,
)
from bayesian_consensus_engine_tpu.parallel.distributed import (
    global_block,
    global_market,
    init_distributed,
    local_view,
    make_hybrid_mesh,
    process_market_rows,
)

port, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
M, K, SEED = {m}, {k}, {seed}

info = init_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
assert info["process_count"] == 2, info
assert info["local_devices"] == 4, info
assert info["global_devices"] == 8, info
# Structural idempotence: a repeat call must be a no-op, not a raise.
info2 = init_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
assert info2 == info, (info, info2)

# 2 granules (CPU devices share one slice key, so name them explicitly);
# DCN-outer markets axis, one granule per process.
mesh = make_hybrid_mesh(ici_shape=(2, 2), num_granules=2)
assert mesh.shape == {{"markets": 4, "sources": 2}}, dict(mesh.shape)

lo, hi = process_market_rows(M, mesh)
assert hi - lo == M // 2, (lo, hi)

# Both processes draw the same deterministic workload; each feeds ONLY its
# own band — no process ever materialises the other's rows on device.
rng = np.random.default_rng(SEED)
full_probs = rng.random((M, K)).astype(np.float32)
full_mask = rng.random((M, K)) < 0.8
full_outcome = rng.random(M) < 0.5

probs = global_block(full_probs[lo:hi], mesh, M)
mask = global_block(full_mask[lo:hi], mesh, M)
outcome = global_market(full_outcome[lo:hi], mesh, M)
cold = init_block_state(M, K)
state = MarketBlockState(
    *(global_block(np.asarray(x)[lo:hi], mesh, M) for x in cold)
)

result = build_cycle(mesh, donate=False)(
    probs, mask, outcome, state, np.float32(1.0)
)
jax.block_until_ready(result)

# The PRODUCTION loop shape (in-jit fori, fast scalar-stamp steps) must
# also run across the 2-process cluster; its cross-shard psum rides gloo.
loop_state = MarketBlockState(
    *(global_block(np.asarray(x)[lo:hi], mesh, M) for x in init_block_state(M, K))
)
loop_state, loop_consensus = build_cycle_loop(mesh, slot_major=False, donate=False)(
    probs, mask, outcome, loop_state, np.float32(1.0), 3
)
jax.block_until_ready(loop_consensus)

# The END-TO-END sharded settlement across the cluster: every process
# builds the same global plan (identical interning), feeds only its band,
# and absorbs back exactly its band's store rows — the one logical store,
# partitioned by market ownership.
from bayesian_consensus_engine_tpu.pipeline import (
    build_settlement_plan,
    settle_sharded,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

rng2 = np.random.default_rng(SEED + 1)
payloads = []
for m in range(M):
    n = int(rng2.integers(1, 5))
    payloads.append((
        f"market-{{m}}",
        [
            {{
                "sourceId": f"s{{int(rng2.integers(0, 6))}}",
                "probability": round(float(rng2.random()), 6),
            }}
            for _ in range(n)
        ],
    ))
settle_outcomes = (rng2.random(M) < 0.5).tolist()

settle_store = TensorReliabilityStore()
settle_plan = build_settlement_plan(settle_store, payloads)
settle_result = settle_sharded(
    settle_store, settle_plan, settle_outcomes, mesh, steps=2, now=20750.0
)

# Band-ingest leg: this process packs ONLY its own markets' payloads
# (globally-agreed num_slots) — the true multi-host ingest shape where no
# process ever sees another's signals.
from bayesian_consensus_engine_tpu.pipeline import ShardedSettlementSession

blo, bhi = process_market_rows(M, mesh)
band_payloads = payloads[blo:min(bhi, M)]
band_outcomes = settle_outcomes[blo:min(bhi, M)]
band_store = TensorReliabilityStore()
band_plan = build_settlement_plan(band_store, band_payloads, num_slots=4)
with ShardedSettlementSession(
    band_store, band_plan, mesh, band=(blo, M)
) as session:
    band_result = session.settle(band_outcomes, steps=2, now=20750.0)
band_consensus = np.asarray(band_result.consensus).tolist()
band_records = [
    [r.source_id, r.market_id, r.reliability, r.confidence, r.updated_at]
    for r in band_store.list_sources()
]

# Streamed band-mode service across the cluster: each process streams
# ONLY its own payload shard through settle_stream(mesh=, band=) with the
# globally-agreed integer num_slots — the multi-host service shape
# (prefetch + per-batch sharded sessions + deferred band gathers), three
# batches of fresh markets.
from bayesian_consensus_engine_tpu.pipeline import settle_stream

rng3 = np.random.default_rng(SEED + 2)
stream_full = []
for b in range(3):
    pays = []
    for m in range(M):
        n = int(rng3.integers(1, 4))
        pays.append((
            f"sm-b{{b}}-m{{m}}",
            [
                {{
                    "sourceId": f"t{{int(rng3.integers(0, 6))}}",
                    "probability": round(float(rng3.random()), 6),
                }}
                for _ in range(n)
            ],
        ))
    outs = (rng3.random(M) < 0.5).tolist()
    stream_full.append((pays, outs))

stream_store = TensorReliabilityStore()
stream_batches = [
    (pays[blo:min(bhi, M)], outs[blo:min(bhi, M)])
    for pays, outs in stream_full
]
# Rolling durability rides a PER-PROCESS journal (each process's store
# is its own band; there is no cross-process state to journal) — replay
# must reproduce this process's live store exactly.
from bayesian_consensus_engine_tpu.state.journal import replay_journal

stream_jrnl = str(pathlib.Path(outdir, f"stream_{{pid}}.jrnl"))
stream_stats = []
stream_results = list(settle_stream(
    stream_store, stream_batches, steps=2, now=20760.0,
    mesh=mesh, band=(blo, M), num_slots=4, journal=stream_jrnl,
    stats=stream_stats,
))
stream_store.sync()
replayed_store, stream_journal_tag = replay_journal(stream_jrnl)
stream_journal_ok = (
    replayed_store.list_sources() == stream_store.list_sources()
)

band = {{
    "pid": pid,
    "lo": lo,
    "hi": hi,
    "stream_market_keys": [r.market_keys for r in stream_results],
    "stream_consensus": [
        np.asarray(r.consensus).tolist() for r in stream_results
    ],
    "stream_records": [
        [r.source_id, r.market_id, r.reliability, r.confidence, r.updated_at]
        for r in stream_store.list_sources()
    ],
    "stream_journal_ok": stream_journal_ok,
    "stream_journal_tag": stream_journal_tag,
    "stream_adopt_modes": [s["session_adopt"] for s in stream_stats],
    "consensus": np.asarray(local_view(result.consensus)).tolist(),
    "reliability": np.asarray(local_view(result.state.reliability)).tolist(),
    "loop_consensus": np.asarray(local_view(loop_consensus)).tolist(),
    "loop_reliability": np.asarray(local_view(loop_state.reliability)).tolist(),
    "settle_market_keys": settle_result.market_keys,
    "settle_consensus": np.asarray(settle_result.consensus).tolist(),
    "settle_records": [
        [r.source_id, r.market_id, r.reliability, r.confidence, r.updated_at]
        for r in settle_store.list_sources()
    ],
    "bandplan_market_keys": band_result.market_keys,
    "bandplan_consensus": band_consensus,
    "bandplan_records": band_records,
}}
pathlib.Path(outdir, f"band_{{pid}}.json").write_text(json.dumps(band))
print("WORKER_OK", pid)
"""


M4, K4 = 17, 5  # 17 markets over 8 device columns: pads to 24, bands 6/6/5/0

_WORKER4 = """
import json, pathlib, sys

sys.path.insert(0, {root!r})

import os
_FLAG = "--xla_force_host_platform_device_count=2"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:  # old JAX: XLA_FLAGS above covers it
    pass

import numpy as np

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle_loop,
    init_block_state,
)
from bayesian_consensus_engine_tpu.parallel.distributed import (
    global_block,
    global_market,
    init_distributed,
    local_view,
    make_hybrid_mesh,
    process_market_rows,
)
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
    settle_sharded,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

port, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
M, K, SEED = {m}, {k}, {seed}
NUM_SLOTS = {num_slots}

info = init_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=4, process_id=pid
)
assert info["process_count"] == 4, info
assert info["global_devices"] == 8, info

# 4 granules x (2,1) ICI: markets extent 8, sources 1 — an off-multiple,
# >2-process tiling (VERDICT r3 #6). M=17 pads to 24; the four process
# bands cover 6/6/5/0 LIVE markets — uneven, including one process whose
# band is pure padding.
mesh = make_hybrid_mesh(ici_shape=(2, 1), num_granules=4)
assert mesh.shape == {{"markets": 8, "sources": 1}}, dict(mesh.shape)

padded = -(-M // 8) * 8
lo, hi = process_market_rows(padded, mesh)
assert hi - lo == padded // 4, (lo, hi)
live = max(0, min(hi, M) - lo)

rng = np.random.default_rng(SEED)
full_probs = rng.random((M, K)).astype(np.float32)
full_mask = rng.random((M, K)) < 0.8
full_outcome = rng.random(M) < 0.5

def band_rows(full, fill):
    padded_full = np.pad(
        full,
        ((0, padded - M),) + ((0, 0),) * (full.ndim - 1),
        constant_values=fill,
    )
    return padded_full[lo:hi]

probs = global_block(band_rows(full_probs, 0.0), mesh, padded)
mask = global_block(band_rows(full_mask, False), mesh, padded)
outcome = global_market(band_rows(full_outcome, False), mesh, padded)
state = MarketBlockState(
    *(
        global_block(np.asarray(x)[lo:hi], mesh, padded)
        for x in init_block_state(padded, K)
    )
)
loop_state, loop_consensus = build_cycle_loop(
    mesh, slot_major=False, donate=False
)(probs, mask, outcome, state, np.float32(1.0), 3)
jax.block_until_ready(loop_consensus)

rng2 = np.random.default_rng(SEED + 1)
payloads = []
for m in range(M):
    n = int(rng2.integers(1, 5))
    payloads.append((
        f"market-{{m}}",
        [
            {{
                "sourceId": f"s{{int(rng2.integers(0, 6))}}",
                "probability": round(float(rng2.random()), 6),
            }}
            for _ in range(n)
        ],
    ))
settle_outcomes = (rng2.random(M) < 0.5).tolist()

# Global-plan sharded settle: every process builds the same plan, feeds
# only its band, absorbs only its band's store rows.
settle_store = TensorReliabilityStore()
settle_plan = build_settlement_plan(settle_store, payloads)
settle_result = settle_sharded(
    settle_store, settle_plan, settle_outcomes, mesh, steps=2, now=20760.0
)

# Band-ingest leg: each process packs ONLY its own (possibly empty)
# payload shard with the globally-agreed slot height.
band_payloads = payloads[lo:min(hi, M)]
band_outcomes = settle_outcomes[lo:min(hi, M)]
band_store = TensorReliabilityStore()
band_plan = build_settlement_plan(
    band_store, band_payloads, num_slots=NUM_SLOTS
)
with ShardedSettlementSession(
    band_store, band_plan, mesh, band=(lo, M)
) as session:
    band_result = session.settle(band_outcomes, steps=2, now=20760.0)
assert len(band_result.market_keys) == live, (live, band_result.market_keys)

# Streamed band-mode service over the uneven cluster: two batches of
# fresh markets, each process streaming only its shard — including the
# pure-padding process (live=0), which streams EMPTY batches.
from bayesian_consensus_engine_tpu.pipeline import settle_stream

rng3 = np.random.default_rng(SEED + 2)
stream_full = []
for b in range(2):
    pays = []
    for m in range(M):
        n = int(rng3.integers(1, 4))
        pays.append((
            f"s4-b{{b}}-m{{m}}",
            [
                {{
                    "sourceId": f"u{{int(rng3.integers(0, 6))}}",
                    "probability": round(float(rng3.random()), 6),
                }}
                for _ in range(n)
            ],
        ))
    outs = (rng3.random(M) < 0.5).tolist()
    stream_full.append((pays, outs))

stream_store = TensorReliabilityStore()
stream_results = list(settle_stream(
    stream_store,
    [(p[lo:min(hi, M)], o[lo:min(hi, M)]) for p, o in stream_full],
    steps=2, now=20765.0, mesh=mesh, band=(lo, M), num_slots=NUM_SLOTS,
))
stream_store.sync()

band = {{
    "pid": pid,
    "lo": lo,
    "hi": hi,
    "live": live,
    "stream_market_keys": [r.market_keys for r in stream_results],
    "stream_consensus": [
        np.asarray(r.consensus).tolist() for r in stream_results
    ],
    "stream_records": [
        [r.source_id, r.market_id, r.reliability, r.confidence, r.updated_at]
        for r in stream_store.list_sources()
    ],
    "loop_consensus": np.asarray(local_view(loop_consensus)).tolist(),
    "loop_reliability": np.asarray(local_view(loop_state.reliability)).tolist(),
    "settle_market_keys": settle_result.market_keys,
    "settle_consensus": np.asarray(settle_result.consensus).tolist(),
    "settle_records": [
        [r.source_id, r.market_id, r.reliability, r.confidence, r.updated_at]
        for r in settle_store.list_sources()
    ],
    "bandplan_market_keys": band_result.market_keys,
    "bandplan_consensus": np.asarray(band_result.consensus).tolist(),
    "bandplan_records": [
        [r.source_id, r.market_id, r.reliability, r.confidence, r.updated_at]
        for r in band_store.list_sources()
    ],
}}
pathlib.Path(outdir, f"band4_{{pid}}.json").write_text(json.dumps(band))
print("WORKER_OK", pid)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def worker_bands(tmp_path_factory):
    """Run both workers to completion once; yield their band payloads."""
    tmp = tmp_path_factory.mktemp("twoproc")
    script = tmp / "worker.py"
    script.write_text(_WORKER.format(root=str(_ROOT), m=M, k=K, seed=SEED))
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(pid), str(tmp)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        if "Multiprocess computations aren't implemented" in out:
            pytest.skip(
                "this JAX's CPU backend has no multi-process collectives"
            )
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_OK {pid}" in out
    return [
        json.loads((tmp / f"band_{pid}.json").read_text()) for pid in (0, 1)
    ]


class TestTwoProcessCluster:
    def test_bands_tile_markets_axis(self, worker_bands):
        spans = sorted((b["lo"], b["hi"]) for b in worker_bands)
        assert spans == [(0, M // 2), (M // 2, M)]

    def test_band_shapes(self, worker_bands):
        for band in worker_bands:
            assert len(band["consensus"]) == M // 2
            assert np.asarray(band["reliability"]).shape == (M // 2, K)

    def test_cycle_matches_single_process(self, worker_bands):
        """The 2-process cluster computes the same numbers as one process."""
        rng = np.random.default_rng(SEED)
        probs = rng.random((M, K)).astype(np.float32)
        mask = rng.random((M, K)) < 0.8
        outcome = rng.random(M) < 0.5
        plain = build_cycle(make_mesh((8, 1)), donate=False)(
            jnp.asarray(probs),
            jnp.asarray(mask),
            jnp.asarray(outcome),
            init_block_state(M, K),
            jnp.float32(1.0),
        )
        expected_consensus = np.asarray(plain.consensus)
        expected_rel = np.asarray(plain.state.reliability)
        for band in worker_bands:
            lo, hi = band["lo"], band["hi"]
            np.testing.assert_allclose(
                np.asarray(band["consensus"], np.float32),
                expected_consensus[lo:hi],
                rtol=2e-6,
                atol=1e-6,
            )
            np.testing.assert_allclose(
                np.asarray(band["reliability"], np.float32),
                expected_rel[lo:hi],
                rtol=2e-6,
                atol=1e-6,
            )

    def test_sharded_settle_matches_single_device(self, worker_bands):
        """settle_sharded across the REAL 2-process cluster: the union of
        the two band stores equals one single-device settle — same records
        (conf/timestamps exact, rel to psum tolerance), same consensus."""
        import math

        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
            settle,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng2 = np.random.default_rng(SEED + 1)
        payloads = []
        for m in range(M):
            n = int(rng2.integers(1, 5))
            payloads.append((
                f"market-{m}",
                [
                    {
                        "sourceId": f"s{int(rng2.integers(0, 6))}",
                        "probability": round(float(rng2.random()), 6),
                    }
                    for _ in range(n)
                ],
            ))
        outcomes = (rng2.random(M) < 0.5).tolist()

        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads)
        ref = settle(store, plan, outcomes, steps=2, now=20750.0)
        ref_records = {
            (r.source_id, r.market_id): r for r in store.list_sources()
        }
        expected = dict(zip(ref.market_keys, np.asarray(ref.consensus)))

        union = {}
        keys_seen = []
        for band in worker_bands:
            for sid, mid, rel, conf, iso in band["settle_records"]:
                assert (sid, mid) not in union, "bands overlap in the store"
                union[(sid, mid)] = (rel, conf, iso)
            keys_seen.extend(band["settle_market_keys"])
            for key, value in zip(
                band["settle_market_keys"], band["settle_consensus"]
            ):
                want = expected[key]
                if math.isnan(want):
                    assert value is None or math.isnan(value)
                else:
                    assert abs(value - want) < 2e-6, key
        assert sorted(keys_seen) == sorted(ref.market_keys)
        assert set(union) == set(ref_records)
        for key, (rel, conf, iso) in union.items():
            reference = ref_records[key]
            assert abs(rel - reference.reliability) < 2e-6, key
            assert conf == reference.confidence, key  # host-replayed exactly
            assert iso == reference.updated_at, key

    def test_band_ingest_settle_matches_single_device(self, worker_bands):
        """The per-process band-plan path (each process packs ONLY its own
        payload shard; plan built with the globally-agreed num_slots) must
        reproduce the single-device settle across the real cluster."""
        import math

        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
            settle,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng2 = np.random.default_rng(SEED + 1)
        payloads = []
        for m in range(M):
            n = int(rng2.integers(1, 5))
            payloads.append((
                f"market-{m}",
                [
                    {
                        "sourceId": f"s{int(rng2.integers(0, 6))}",
                        "probability": round(float(rng2.random()), 6),
                    }
                    for _ in range(n)
                ],
            ))
        outcomes = (rng2.random(M) < 0.5).tolist()

        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads)
        ref = settle(store, plan, outcomes, steps=2, now=20750.0)
        ref_records = {
            (r.source_id, r.market_id): r for r in store.list_sources()
        }
        expected = dict(zip(ref.market_keys, np.asarray(ref.consensus)))

        union = {}
        keys_seen = []
        for band in worker_bands:
            for sid, mid, rel, conf, iso in band["bandplan_records"]:
                assert (sid, mid) not in union, "band stores overlap"
                union[(sid, mid)] = (rel, conf, iso)
            keys_seen.extend(band["bandplan_market_keys"])
            for key, value in zip(
                band["bandplan_market_keys"], band["bandplan_consensus"]
            ):
                want = expected[key]
                if math.isnan(want):
                    assert value is None or math.isnan(value)
                else:
                    assert abs(value - want) < 2e-6, key
        assert sorted(keys_seen) == sorted(ref.market_keys)
        assert set(union) == set(ref_records)
        for key, (rel, conf, iso) in union.items():
            reference = ref_records[key]
            assert abs(rel - reference.reliability) < 2e-6, key
            assert conf == reference.confidence, key
            assert iso == reference.updated_at, key

    def test_streamed_band_service_matches_flat_stream(self, worker_bands):
        """settle_stream(mesh=, band=) across the REAL 2-process cluster:
        each process streamed only its payload shard; the union of the two
        stream stores must equal a flat single-process settle_stream over
        the full batches (records: conf/timestamps exact, rel to psum
        tolerance; per-batch consensus bands match)."""
        import math

        from bayesian_consensus_engine_tpu.pipeline import settle_stream
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng3 = np.random.default_rng(SEED + 2)
        stream_full = []
        for b in range(3):
            pays = []
            for m in range(M):
                n = int(rng3.integers(1, 4))
                pays.append((
                    f"sm-b{b}-m{m}",
                    [
                        {
                            "sourceId": f"t{int(rng3.integers(0, 6))}",
                            "probability": round(float(rng3.random()), 6),
                        }
                        for _ in range(n)
                    ],
                ))
            outs = (rng3.random(M) < 0.5).tolist()
            stream_full.append((pays, outs))

        flat_store = TensorReliabilityStore()
        flat_results = list(settle_stream(
            flat_store, stream_full, steps=2, now=20760.0, num_slots=4
        ))
        flat_store.sync()
        ref_records = {
            (r.source_id, r.market_id): r for r in flat_store.list_sources()
        }
        expected = [
            dict(zip(r.market_keys, np.asarray(r.consensus)))
            for r in flat_results
        ]

        union = {}
        for band in worker_bands:
            # Each process's journal replayed to its own live band store
            # inside the worker, watermarked at the last batch.
            assert band["stream_journal_ok"] is True
            assert band["stream_journal_tag"] == 2
            # Round 13: the multi-process band stream is served RESIDENT
            # — the PR-5 teardown+rebuild fallback is retired. Fresh-
            # market drift batches adopt through the process-local
            # staged relayout, never by dropping the block.
            modes = band["stream_adopt_modes"]
            assert modes[0] == "start"
            assert not any(m.startswith("rebuild") for m in modes[1:]), (
                modes
            )
            for sid, mid, rel, conf, iso in band["stream_records"]:
                assert (sid, mid) not in union, "band stream stores overlap"
                union[(sid, mid)] = (rel, conf, iso)
            assert len(band["stream_market_keys"]) == 3  # one per batch
            for b, (keys, values) in enumerate(zip(
                band["stream_market_keys"], band["stream_consensus"]
            )):
                for key, value in zip(keys, values):
                    want = expected[b][key]
                    if math.isnan(want):
                        assert value is None or math.isnan(value)
                    else:
                        assert abs(value - want) < 2e-6, (b, key)
        assert set(union) == set(ref_records)
        for key, (rel, conf, iso) in union.items():
            reference = ref_records[key]
            assert abs(rel - reference.reliability) < 2e-6, key
            assert conf == reference.confidence, key  # host-replayed exactly
            assert iso == reference.updated_at, key

    def test_production_loop_matches_single_process(self, worker_bands):
        """build_cycle_loop (fast fori shape) across 2 processes == local."""
        rng = np.random.default_rng(SEED)
        probs = rng.random((M, K)).astype(np.float32)
        mask = rng.random((M, K)) < 0.8
        outcome = rng.random(M) < 0.5
        state, consensus = build_cycle_loop(
            make_mesh((8, 1)), slot_major=False, donate=False
        )(
            jnp.asarray(probs),
            jnp.asarray(mask),
            jnp.asarray(outcome),
            init_block_state(M, K),
            jnp.float32(1.0),
            3,
        )
        expected_consensus = np.asarray(consensus)
        expected_rel = np.asarray(state.reliability)
        for band in worker_bands:
            lo, hi = band["lo"], band["hi"]
            np.testing.assert_allclose(
                np.asarray(band["loop_consensus"], np.float32),
                expected_consensus[lo:hi],
                rtol=2e-6,
                atol=1e-6,
            )
            np.testing.assert_allclose(
                np.asarray(band["loop_reliability"], np.float32),
                expected_rel[lo:hi],
                rtol=2e-6,
                atol=1e-6,
            )


@pytest.fixture(scope="module")
def worker_bands4(tmp_path_factory):
    """Run the four uneven-band workers to completion once."""
    tmp = tmp_path_factory.mktemp("fourproc")
    script = tmp / "worker4.py"
    script.write_text(
        _WORKER4.format(root=str(_ROOT), m=M4, k=K4, seed=SEED, num_slots=4)
    )
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(pid), str(tmp)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(4)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        if "Multiprocess computations aren't implemented" in out:
            pytest.skip(
                "this JAX's CPU backend has no multi-process collectives"
            )
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_OK {pid}" in out
    return [
        json.loads((tmp / f"band4_{pid}.json").read_text())
        for pid in range(4)
    ]


class TestFourProcessUnevenCluster:
    """Off-multiple, >2-process tiling (VERDICT r3 #6): 17 markets pad to
    24 over an 8-column markets axis; the four processes own 6/6/5/0 LIVE
    markets — the general band math, a ragged final band, and a process
    whose band is pure padding, all across a real gloo cluster."""

    def test_bands_tile_contiguously_with_uneven_tail(self, worker_bands4):
        padded = -(-M4 // 8) * 8
        spans = sorted((b["lo"], b["hi"]) for b in worker_bands4)
        assert spans == [(0, 6), (6, 12), (12, 18), (18, 24)]
        assert spans[-1][1] == padded
        assert sorted(b["live"] for b in worker_bands4) == [0, 5, 6, 6]

    def test_production_loop_matches_single_process(self, worker_bands4):
        rng = np.random.default_rng(SEED)
        probs = rng.random((M4, K4)).astype(np.float32)
        mask = rng.random((M4, K4)) < 0.8
        outcome = rng.random(M4) < 0.5
        padded = -(-M4 // 8) * 8
        state, consensus = build_cycle_loop(
            make_mesh((8, 1)), slot_major=False, donate=False
        )(
            jnp.asarray(np.pad(probs, ((0, padded - M4), (0, 0)))),
            jnp.asarray(np.pad(mask, ((0, padded - M4), (0, 0)))),
            jnp.asarray(np.pad(outcome, (0, padded - M4))),
            init_block_state(padded, K4),
            jnp.float32(1.0),
            3,
        )
        expected_consensus = np.asarray(consensus)
        expected_rel = np.asarray(state.reliability)
        for band in worker_bands4:
            lo, hi = band["lo"], band["hi"]
            np.testing.assert_allclose(
                np.asarray(band["loop_consensus"], np.float32),
                expected_consensus[lo:hi],
                rtol=2e-6,
                atol=1e-6,
            )
            np.testing.assert_allclose(
                np.asarray(band["loop_reliability"], np.float32),
                expected_rel[lo:hi],
                rtol=2e-6,
                atol=1e-6,
            )

    def _union_parity(self, worker_bands4, keys_field, consensus_field,
                      records_field):
        import math

        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
            settle,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng2 = np.random.default_rng(SEED + 1)
        payloads = []
        for m in range(M4):
            n = int(rng2.integers(1, 5))
            payloads.append((
                f"market-{m}",
                [
                    {
                        "sourceId": f"s{int(rng2.integers(0, 6))}",
                        "probability": round(float(rng2.random()), 6),
                    }
                    for _ in range(n)
                ],
            ))
        outcomes = (rng2.random(M4) < 0.5).tolist()

        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads)
        ref = settle(store, plan, outcomes, steps=2, now=20760.0)
        ref_records = {
            (r.source_id, r.market_id): r for r in store.list_sources()
        }
        expected = dict(zip(ref.market_keys, np.asarray(ref.consensus)))

        union = {}
        keys_seen = []
        for band in worker_bands4:
            for sid, mid, rel, conf, iso in band[records_field]:
                assert (sid, mid) not in union, "bands overlap in the store"
                union[(sid, mid)] = (rel, conf, iso)
            keys_seen.extend(band[keys_field])
            for key, value in zip(band[keys_field], band[consensus_field]):
                want = expected[key]
                if math.isnan(want):
                    assert value is None or math.isnan(value)
                else:
                    assert abs(value - want) < 2e-6, key
        assert sorted(keys_seen) == sorted(ref.market_keys)
        assert set(union) == set(ref_records)
        for key, (rel, conf, iso) in union.items():
            reference = ref_records[key]
            assert abs(rel - reference.reliability) < 2e-6, key
            assert conf == reference.confidence, key
            assert iso == reference.updated_at, key

    def test_sharded_settle_union_matches_single_device(self, worker_bands4):
        self._union_parity(
            worker_bands4,
            "settle_market_keys",
            "settle_consensus",
            "settle_records",
        )

    def test_band_ingest_union_matches_single_device(self, worker_bands4):
        """Per-process band plans (one of them EMPTY) reproduce the
        single-device settle; the padding-only process contributes zero
        markets and zero records but still participates in the cluster."""
        self._union_parity(
            worker_bands4,
            "bandplan_market_keys",
            "bandplan_consensus",
            "bandplan_records",
        )
        empty = [b for b in worker_bands4 if b["live"] == 0]
        assert len(empty) == 1
        assert empty[0]["bandplan_market_keys"] == []
        assert empty[0]["bandplan_records"] == []

    def test_streamed_band_union_matches_flat_stream(self, worker_bands4):
        """settle_stream(mesh=, band=) across the 4-process uneven
        cluster — one process streaming EMPTY batches — must union to a
        flat single-process stream over the full batches. The cluster's
        markets axis is 1-wide on sources, so equality is EXACT."""
        import math

        from bayesian_consensus_engine_tpu.pipeline import settle_stream
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng3 = np.random.default_rng(SEED + 2)
        stream_full = []
        for b in range(2):
            pays = []
            for m in range(M4):
                n = int(rng3.integers(1, 4))
                pays.append((
                    f"s4-b{b}-m{m}",
                    [
                        {
                            "sourceId": f"u{int(rng3.integers(0, 6))}",
                            "probability": round(float(rng3.random()), 6),
                        }
                        for _ in range(n)
                    ],
                ))
            outs = (rng3.random(M4) < 0.5).tolist()
            stream_full.append((pays, outs))

        flat_store = TensorReliabilityStore()
        flat_results = list(settle_stream(
            flat_store, stream_full, steps=2, now=20765.0, num_slots=4
        ))
        flat_store.sync()
        ref_records = {
            (r.source_id, r.market_id): r for r in flat_store.list_sources()
        }
        expected = [
            dict(zip(r.market_keys, np.asarray(r.consensus)))
            for r in flat_results
        ]

        union = {}
        for band in worker_bands4:
            if band["live"] == 0:
                assert band["stream_market_keys"] == [[], []]
                assert band["stream_records"] == []
            for sid, mid, rel, conf, iso in band["stream_records"]:
                assert (sid, mid) not in union, "band stream stores overlap"
                union[(sid, mid)] = (rel, conf, iso)
            for b, (keys, values) in enumerate(zip(
                band["stream_market_keys"], band["stream_consensus"]
            )):
                for key, value in zip(keys, values):
                    want = expected[b][key]
                    if math.isnan(want):
                        assert value is None or math.isnan(value)
                    else:
                        assert value == want, (b, key)  # markets-only mesh
        assert set(union) == set(ref_records)
        for key, (rel, conf, iso) in union.items():
            reference = ref_records[key]
            assert rel == reference.reliability, key
            assert conf == reference.confidence, key
            assert iso == reference.updated_at, key
