"""Constants are a public contract — every literal is pinned.

Mirrors the reference's config test strategy (reference: tests/test_config.py:
20-103): a change to any value is a behavioural change and must fail loudly.
"""

from bayesian_consensus_engine_tpu.utils import config


class TestColdStartDefaults:
    def test_default_reliability_is_50_percent(self):
        assert config.DEFAULT_RELIABILITY == 0.50

    def test_default_confidence_is_25_percent(self):
        # The reference's docs claim 0.50 in places; the code path uses 0.25
        # (reference: config.py:18, test_config.py:24-26). Code wins.
        assert config.DEFAULT_CONFIDENCE == 0.25

    def test_defaults_are_valid_probabilities(self):
        assert 0.0 <= config.DEFAULT_RELIABILITY <= 1.0
        assert 0.0 <= config.DEFAULT_CONFIDENCE <= 1.0


class TestUpdateConstraints:
    def test_max_update_step_is_10_percent(self):
        assert config.MAX_UPDATE_STEP == 0.10

    def test_base_learning_rate_is_15_percent(self):
        # Reference hides this in reliability.py:34; we centralise it here.
        assert config.BASE_LEARNING_RATE == 0.15

    def test_confidence_growth_rate_is_10_percent(self):
        assert config.CONFIDENCE_GROWTH_RATE == 0.10

    def test_raw_step_exceeds_cap_so_cap_binds(self):
        assert config.BASE_LEARNING_RATE > config.MAX_UPDATE_STEP


class TestTieBreaking:
    def test_tie_tolerance(self):
        assert config.TIE_TOLERANCE == 1e-9
        assert config.TIE_TOLERANCE > 0


class TestDecay:
    def test_half_life_is_30_days(self):
        assert config.DECAY_HALF_LIFE_DAYS == 30

    def test_floor_is_10_percent(self):
        assert config.DECAY_MINIMUM == 0.10

    def test_floor_below_cold_start(self):
        assert config.DECAY_MINIMUM < config.DEFAULT_RELIABILITY


class TestSchema:
    def test_schema_version(self):
        assert config.SCHEMA_VERSION == "1.0.0"
        assert isinstance(config.SCHEMA_VERSION, str)


class TestValidationLimits:
    def test_limits(self):
        assert config.MIN_SOURCE_ID_LENGTH == 1
        assert config.MAX_SOURCE_ID_LENGTH == 256
        assert config.MAX_SIGNALS_PER_REQUEST == 1000
        assert config.MIN_SOURCE_ID_LENGTH < config.MAX_SOURCE_ID_LENGTH


class TestParamStructs:
    def test_update_params_mirror_constants(self):
        p = config.as_update_params()
        assert p.base_learning_rate == config.BASE_LEARNING_RATE
        assert p.max_step == config.MAX_UPDATE_STEP
        assert p.confidence_growth == config.CONFIDENCE_GROWTH_RATE

    def test_decay_params_mirror_constants(self):
        p = config.as_decay_params()
        assert p.half_life_days == config.DECAY_HALF_LIFE_DAYS
        assert p.floor == config.DECAY_MINIMUM
