"""infer/: MRF-grade adaptive belief propagation (round 18).

The non-negotiable contracts, mirroring tests/test_analytics.py's shape:

* **Moment-pair bit matrix** — ``bp_sweep_math`` is a bit-stable pure
  function of (means, variances, neighbor blocks) on every mesh
  factorisation, the point path is op-for-op the legacy fixed sweep
  (``damped_sweep_math`` delegates), and the fused session's moments
  output is bit-identical across chunk settings and the factorisations
  that keep its in-program inputs bit-equal.
* **Deterministic early-exit** — the adaptive trip count is a pure
  function of the inputs: identical on every mesh factorisation (ops
  level AND through the session), with the residual bits agreeing too.
* **Banded graph analytics** — a band session with graph+bands no
  longer raises ``ClusterModeUnsupported``: it serves the identical
  program, byte-for-byte (store digest, journal epochs sans wall
  clock, SQLite bytes) and bit-for-bit (analytics outputs) vs the
  whole-axis session; ``infer/partition.py``'s explicit-halo sweep is
  bit-equal to the whole-axis sweep on every banding (the ghost-zone
  argument).
* **Combinatorial blocks** — constraint declarations compile to graph
  edges, the post-sweep projection renormalises mutually-exclusive
  partitions to sum to 1 and clamps implication composites, and the
  whole path stays additive (the settle's bytes never move).
"""

import random
import struct

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bayesian_consensus_engine_tpu.analytics import (
    AnalyticsOptions,
    MarketGraph,
)
from bayesian_consensus_engine_tpu.cluster.recover import store_digest
from bayesian_consensus_engine_tpu.infer import (
    BandedGraph,
    InferenceOptions,
    MarketBlock,
    MarketBlocks,
    PropagatedBeliefs,
    banded_bp_sweep,
    exchange_halos,
    partition_csr,
    propagate_beliefs,
)
from bayesian_consensus_engine_tpu.ops.propagate import (
    bp_sweep_math,
    damped_sweep_math,
)
from bayesian_consensus_engine_tpu.parallel._jax_compat import shard_map
from bayesian_consensus_engine_tpu.parallel.mesh import (
    MARKETS_AXIS,
    make_mesh,
)
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state.journal import JournalWriter
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_400.0

MESH_SHAPES = [(4, 2), (2, 4), (8, 1), (1, 8)]


def _graph_blocks(m=32, degree=3, seed=5, edge_p=0.6):
    """One dense per-row neighbour block pair with -1 padding."""
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, m, (m, degree)).astype(np.int32)
    idx[rng.random((m, degree)) > edge_p] = -1
    w = rng.uniform(0.2, 1.8, (m, degree)).astype(np.float32)
    return jnp.asarray(idx), jnp.asarray(w)


def _moment_seeds(m=32, seed=6, nan_rows=()):
    rng = np.random.default_rng(seed)
    means = rng.random(m).astype(np.float32)
    variances = rng.uniform(1e-4, 0.05, m).astype(np.float32)
    for row in nan_rows:
        means[row] = np.nan
        variances[row] = np.nan
    return jnp.asarray(means), jnp.asarray(variances)


def _market_payloads(markets=12, universe=8, seed=8):
    rng = random.Random(seed)
    payloads = []
    for m in range(markets):
        n = rng.randint(1, 3)
        payloads.append((
            f"m-{m}",
            [
                {
                    "sourceId": f"s{rng.randrange(universe)}",
                    "probability": round(rng.random(), 6),
                }
                for _ in range(n)
            ],
        ))
    return payloads, [True] * markets


#: The session fixture's dependency graph: two components over the
#: twelve markets, damping/steps deliberately non-default.
_SESSION_EDGES = [
    ("m-0", "m-1", 0.5), ("m-1", "m-2", 0.7), ("m-3", "m-4", 0.4),
]


def _session_run(mesh_shape, band=None, analytics=None, markets=12):
    payloads, outcomes = _market_payloads(markets)
    store = TensorReliabilityStore()
    plan = build_settlement_plan(store, payloads, num_slots=4,
                                 fingerprint=True)
    session = ShardedSettlementSession(
        store, plan, make_mesh(mesh_shape), band=band
    )
    with session:
        out = session.settle_with_analytics(
            outcomes, steps=1, now=NOW, analytics=analytics
        )
    store.sync()
    return store, out


def _moments_options(tol=1e-6, max_steps=32, graph_edges=_SESSION_EDGES):
    graph = MarketGraph.from_edges(graph_edges, damping=0.4, steps=4)
    return AnalyticsOptions(
        graph=graph,
        inference=InferenceOptions(tol=tol, max_steps=max_steps),
    )


def _journal_epochs_sans_clock(path):
    """Decoded epoch frames with the wall-clock field masked (same
    helper as test_analytics/test_serve)."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


class TestBpSweepMath:
    def test_one_moment_step_hand_computed(self):
        # Markets 0 and 1 exchange one edge; 2 is isolated; 3 reads
        # both 0 and 1 with unequal edge weights, so the precision
        # weighting (1/var) is exercised against a by-hand mix.
        means = jnp.asarray([0.2, 0.8, 0.5, 0.5], jnp.float32)
        variances = jnp.asarray([0.04, 0.01, 0.09, 0.09], jnp.float32)
        idx = jnp.asarray(
            [[1, -1], [0, -1], [-1, -1], [0, 1]], jnp.int32
        )
        w = jnp.asarray(
            [[1.0, 0.0], [1.0, 0.0], [0.0, 0.0], [1.0, 2.0]], jnp.float32
        )
        mean, var, iters, residual = bp_sweep_math(
            means, variances, idx, w, damping=0.4, max_steps=1
        )
        lam, keep = 0.4, 0.6
        # Rows 0/1: one neighbour each — the precision cancels in the
        # mean; the variance blends keep²·own + λ²·neighbour.
        assert float(mean[0]) == pytest.approx(keep * 0.2 + lam * 0.8)
        assert float(var[0]) == pytest.approx(
            keep**2 * 0.04 + lam**2 * 0.01
        )
        assert float(mean[1]) == pytest.approx(keep * 0.8 + lam * 0.2)
        assert float(var[1]) == pytest.approx(
            keep**2 * 0.01 + lam**2 * 0.04
        )
        # Row 2: no edges — untouched.
        assert float(mean[2]) == pytest.approx(0.5)
        assert float(var[2]) == pytest.approx(0.09)
        # Row 3: precision-weighted two-neighbour mix.
        q0, q1 = 1.0 / 0.04, 2.0 / 0.01
        mix = (q0 * 0.2 + q1 * 0.8) / (q0 + q1)
        wvar = (q0**2 * 0.04 + q1**2 * 0.01) / (q0 + q1) ** 2
        assert float(mean[3]) == pytest.approx(
            keep * 0.5 + lam * mix, rel=1e-5
        )
        assert float(var[3]) == pytest.approx(
            keep**2 * 0.09 + lam**2 * wvar, rel=1e-5
        )
        assert int(iters) == 1
        assert float(residual) == pytest.approx(0.24, rel=1e-5)

    def test_point_path_is_damped_sweep(self):
        idx, w = _graph_blocks()
        means, _ = _moment_seeds(nan_rows=(3, 17))
        legacy = damped_sweep_math(
            means, idx, w, damping=0.35, steps=3
        )
        mean, var, iters, _ = bp_sweep_math(
            means, None, idx, w, damping=0.35, max_steps=3
        )
        np.testing.assert_array_equal(np.asarray(legacy), np.asarray(mean))
        assert var is None
        assert int(iters) == 3

    def test_nan_pad_and_edgeless_semantics(self):
        # Row 0 reads a NaN-mean neighbour and a finite one: the NaN is
        # excluded, not poisoning. Row 1 is itself NaN: held. Row 2
        # reads ONLY the NaN market: no finite neighbour, held. Row 3
        # reads a neighbour with NaN VARIANCE: excluded on the moments
        # path (precision undefined), so row 3 is held too.
        means = jnp.asarray([0.5, jnp.nan, 0.5, 0.7, 0.9], jnp.float32)
        variances = jnp.asarray(
            [0.01, jnp.nan, 0.01, 0.04, jnp.nan], jnp.float32
        )
        idx = jnp.asarray(
            [[1, 3], [0, -1], [1, -1], [4, -1], [-1, -1]], jnp.int32
        )
        w = jnp.ones((5, 2), jnp.float32)
        mean, var, _, _ = bp_sweep_math(
            means, variances, idx, w, damping=0.4, max_steps=1
        )
        assert float(mean[0]) == pytest.approx(0.6 * 0.5 + 0.4 * 0.7)
        assert float(var[0]) == pytest.approx(0.36 * 0.01 + 0.16 * 0.04)
        assert np.isnan(float(mean[1]))
        assert float(mean[2]) == 0.5 and float(var[2]) == pytest.approx(0.01)
        assert float(mean[3]) == pytest.approx(
            0.7
        )  # NaN-variance neighbour excluded
        # On the POINT path the same neighbour still mixes (only the
        # mean needs to be finite there).
        pmean, _, _, _ = bp_sweep_math(
            means, None, idx, w, damping=0.4, max_steps=1
        )
        assert float(pmean[3]) == pytest.approx(0.6 * 0.7 + 0.4 * 0.9)

    def test_max_steps_zero_is_identity(self):
        idx, w = _graph_blocks()
        means, variances = _moment_seeds()
        mean, var, iters, residual = bp_sweep_math(
            means, variances, idx, w, max_steps=0, tol=1e-6
        )
        np.testing.assert_array_equal(np.asarray(mean), np.asarray(means))
        np.testing.assert_array_equal(np.asarray(var), np.asarray(variances))
        assert int(iters) == 0 and float(residual) == 0.0

    def test_adaptive_early_exit_stops_under_the_bound(self):
        idx, w = _graph_blocks()
        means, variances = _moment_seeds()
        _, _, fixed_iters, _ = bp_sweep_math(
            means, variances, idx, w, damping=0.4, max_steps=128
        )
        mean, var, iters, residual = bp_sweep_math(
            means, variances, idx, w, damping=0.4, max_steps=128, tol=1e-5
        )
        assert int(fixed_iters) == 128
        assert 0 < int(iters) < 128
        assert float(residual) <= 1e-5
        # At convergence the adaptive sweep matches the full-depth one.
        full, _, _, _ = bp_sweep_math(
            means, variances, idx, w, damping=0.4, max_steps=128
        )
        np.testing.assert_allclose(
            np.asarray(mean), np.asarray(full), rtol=0, atol=1e-4
        )

    def test_adaptive_rejects_bad_knobs_in_options(self):
        with pytest.raises(ValueError, match="tol"):
            InferenceOptions(tol=0.0)
        with pytest.raises(ValueError, match="max_steps"):
            InferenceOptions(max_steps=-1)
        with pytest.raises(ValueError, match="damping"):
            InferenceOptions(damping=1.5)
        with pytest.raises(ValueError, match="moments"):
            InferenceOptions(moments=False, tol=1e-4)

    def test_propagate_beliefs_aligns_and_sweeps(self):
        graph = MarketGraph.from_edges(
            [("a", "b", 1.0), ("b", "a", 1.0)], damping=0.4, steps=8
        )
        means = jnp.asarray([0.2, 0.8, jnp.nan], jnp.float32)
        variances = jnp.asarray([0.01, 0.01, jnp.nan], jnp.float32)
        out = propagate_beliefs(
            means, variances, graph, ["a", "b", "pad"], 3,
            options=InferenceOptions(tol=1e-7, max_steps=100),
        )
        assert isinstance(out, PropagatedBeliefs)
        # The coupled pair converges toward its precision-weighted
        # midpoint; the pad row stays NaN.
        assert abs(float(out.mean[0]) - float(out.mean[1])) < 1e-4
        assert np.isnan(float(out.mean[2]))
        assert int(out.iters_run) < 100


class TestDeterminism:
    """The ISSUE-18 acceptance: trip counts and sweep bits are pure
    functions of the inputs — the mesh factorisation is invisible."""

    def _sharded(self, mesh_shape, means, variances, idx, w, *, tol,
                 max_steps):
        mesh = make_mesh(mesh_shape)
        market = P(MARKETS_AXIS)

        def math(v, s, i, wt):
            return bp_sweep_math(
                v, s, i, wt, damping=0.4, max_steps=max_steps, tol=tol,
                axis_name=MARKETS_AXIS,
            )

        fn = shard_map(
            math, mesh=mesh,
            in_specs=(market, market, market, market),
            out_specs=(market, market, P(), P()),
            check_vma=False,
        )
        return jax.jit(fn)(means, variances, idx, w)

    @pytest.mark.parametrize("tol", [None, 1e-3])
    def test_ops_bitwise_parity_across_mesh_factorisations(self, tol):
        idx, w = _graph_blocks()
        means, variances = _moment_seeds(nan_rows=(3,))
        reference = None
        for shape in MESH_SHAPES:
            mean, var, iters, residual = self._sharded(
                shape, means, variances, idx, w, tol=tol, max_steps=64
            )
            got = (
                np.asarray(mean), np.asarray(var),
                int(iters), np.asarray(residual),
            )
            if reference is None:
                reference = got
                continue
            np.testing.assert_array_equal(got[0], reference[0])
            np.testing.assert_array_equal(got[1], reference[1])
            assert got[2] == reference[2]
            np.testing.assert_array_equal(got[3], reference[3])
        if tol is not None:
            assert reference[2] < 64  # the early-exit actually fired

    def test_session_iters_identical_on_every_mesh(self):
        counts = {}
        for shape in MESH_SHAPES:
            _, (_, _, _, prop) = _session_run(
                shape, analytics=_moments_options(max_steps=64)
            )
            counts[shape] = (
                int(prop.iters_run),
                np.asarray(prop.residual).tobytes(),
            )
        assert len(set(counts.values())) == 1, counts
        assert 0 < counts[(4, 2)][0] < 64

    def test_session_moments_bitwise_across_preserving_factorisations(self):
        # (4, 2) and (2, 4) keep the fused program's in-program inputs
        # bit-equal (the pre-existing consensus parity envelope — other
        # factorisations may move the CONSENSUS bits upstream of the
        # sweep, which the sweep then faithfully propagates).
        _, (_, _, bands_a, prop_a) = _session_run(
            (4, 2), analytics=_moments_options()
        )
        _, (_, _, bands_b, prop_b) = _session_run(
            (2, 4), analytics=_moments_options()
        )
        np.testing.assert_array_equal(
            np.asarray(prop_a.mean), np.asarray(prop_b.mean)
        )
        np.testing.assert_array_equal(
            np.asarray(prop_a.stderr), np.asarray(prop_b.stderr)
        )
        np.testing.assert_array_equal(
            np.asarray(bands_a.stderr), np.asarray(bands_b.stderr)
        )

    @pytest.mark.parametrize("chunk_slots", [None, 2, "default"])
    def test_session_moments_bitwise_across_chunk_settings(
        self, chunk_slots
    ):
        base = _moments_options()
        _, (_, _, _, reference) = _session_run((4, 2), analytics=base)
        _, (_, _, _, prop) = _session_run(
            (4, 2),
            analytics=AnalyticsOptions(
                graph=base.graph, inference=base.inference,
                chunk_slots=chunk_slots,
            ),
        )
        np.testing.assert_array_equal(
            np.asarray(reference.mean), np.asarray(prop.mean)
        )
        np.testing.assert_array_equal(
            np.asarray(reference.stderr), np.asarray(prop.stderr)
        )
        assert int(reference.iters_run) == int(prop.iters_run)


class TestSessionInference:
    def test_moments_session_returns_propagated_beliefs(self):
        _, (_, _, bands, prop) = _session_run(
            (4, 2), analytics=_moments_options()
        )
        assert isinstance(prop, PropagatedBeliefs)
        stderr = np.asarray(prop.stderr)
        assert stderr.shape == np.asarray(prop.mean).shape
        assert int(prop.iters_run) > 0
        # Neighbour evidence moves the uncertainty where the graph
        # reaches and ONLY there: markets outside the graph keep their
        # band stderr bit-for-bit, while at least one connected market
        # comes out strictly tighter (a certain neighbour lends its
        # precision) — the widening direction is equally legal (a
        # near-certain market coupled to a wide one inherits doubt).
        band_stderr = np.asarray(bands.stderr)
        connected = np.zeros(12, bool)
        for i in range(5):  # _SESSION_EDGES covers m-0..m-4
            connected[i] = True
        np.testing.assert_array_equal(
            stderr[~connected], band_stderr[~connected]
        )
        finite = connected & np.isfinite(stderr) & np.isfinite(band_stderr)
        assert np.any(stderr[finite] < band_stderr[finite] - 1e-5)

    def test_point_session_keeps_legacy_output(self):
        graph = MarketGraph.from_edges(_SESSION_EDGES)
        _, (_, _, _, prop) = _session_run(
            (4, 2), analytics=AnalyticsOptions(graph=graph)
        )
        assert not isinstance(prop, PropagatedBeliefs)
        assert np.asarray(prop).shape == (12,)

    def test_inference_requires_a_graph(self):
        with pytest.raises(ValueError, match="graph"):
            _session_run(
                (4, 2),
                analytics=AnalyticsOptions(inference=InferenceOptions()),
            )

    def test_inference_and_blocks_type_checked(self):
        graph = MarketGraph.from_edges(_SESSION_EDGES)
        with pytest.raises(TypeError, match="InferenceOptions"):
            _session_run(
                (4, 2),
                analytics=AnalyticsOptions(graph=graph, inference="yes"),
            )
        with pytest.raises(TypeError, match="MarketBlocks"):
            _session_run(
                (4, 2),
                analytics=AnalyticsOptions(blocks=["m-0", "m-1"]),
            )


class TestBandedGraphSession:
    """PR 11's refusal, closed: band sessions serve graph analytics."""

    def test_banded_session_serves_graph_analytics(self):
        _, (_, _, _, prop) = _session_run(
            (4, 2), band=(0, 12), analytics=_moments_options()
        )
        assert isinstance(prop, PropagatedBeliefs)
        assert int(prop.iters_run) > 0

    def test_banded_byte_and_bit_parity_vs_whole_axis(self, tmp_path):
        store_a, (res_a, tb_a, bands_a, prop_a) = _session_run(
            (4, 2), analytics=_moments_options()
        )
        store_b, (res_b, tb_b, bands_b, prop_b) = _session_run(
            (4, 2), band=(0, 12), analytics=_moments_options()
        )
        # Bit parity on every analytics output...
        np.testing.assert_array_equal(
            np.asarray(prop_a.mean), np.asarray(prop_b.mean)
        )
        np.testing.assert_array_equal(
            np.asarray(prop_a.stderr), np.asarray(prop_b.stderr)
        )
        assert int(prop_a.iters_run) == int(prop_b.iters_run)
        np.testing.assert_array_equal(
            np.asarray(bands_a.stderr), np.asarray(bands_b.stderr)
        )
        np.testing.assert_array_equal(
            np.asarray(res_a.consensus), np.asarray(res_b.consensus)
        )
        # ...and byte parity on every settlement artifact: store
        # digest, journal epochs (wall clock masked), SQLite bytes.
        assert store_digest(store_a) == store_digest(store_b)
        for name, store in (("whole", store_a), ("band", store_b)):
            writer = JournalWriter(tmp_path / f"{name}.jrnl")
            store.flush_to_journal(writer)
            writer.close()
            store.flush_to_sqlite(tmp_path / f"{name}.db")
        assert _journal_epochs_sans_clock(tmp_path / "whole.jrnl") == (
            _journal_epochs_sans_clock(tmp_path / "band.jrnl")
        )
        assert (tmp_path / "whole.db").read_bytes() == (
            tmp_path / "band.db"
        ).read_bytes()

    def test_multi_controller_still_refuses(self, monkeypatch):
        import bayesian_consensus_engine_tpu.pipeline as pl

        monkeypatch.setattr(pl, "_process_count", lambda: 2)
        from bayesian_consensus_engine_tpu.cluster.recover import (
            ClusterModeUnsupported,
        )

        with pytest.raises(ClusterModeUnsupported, match="MeshView"):
            _session_run((4, 2), analytics=_moments_options())


class TestPartition:
    def _bandings(self, m):
        return [
            [(0, m)],
            [(0, m // 2), (m // 2, m)],
            [(0, m // 4), (m // 4, m // 2), (m // 2, m)],
        ]

    def test_partition_validates_contiguous_tiling(self):
        idx, w = _graph_blocks(m=8)
        with pytest.raises(ValueError, match="contiguously"):
            partition_csr(idx, w, [(0, 4), (5, 8)])
        with pytest.raises(ValueError, match="contiguously"):
            partition_csr(idx, w, [(0, 4), (4, 4), (4, 8)])
        with pytest.raises(ValueError, match="8 rows"):
            partition_csr(idx, w, [(0, 4)])

    def test_partition_remaps_and_counts_cross_edges(self):
        idx = jnp.asarray(
            [[1, -1], [2, -1], [0, 3], [-1, -1]], jnp.int32
        )
        w = jnp.ones((4, 2), jnp.float32)
        banded = partition_csr(idx, w, [(0, 2), (2, 4)])
        assert isinstance(banded, BandedGraph)
        # Band 0 imports row 2; band 1 imports row 0 — two cross edges.
        assert banded.cross_edges == 2
        b0, b1 = banded.blocks
        assert b0.halo.tolist() == [2]
        assert b0.halo_owner.tolist() == [1]
        assert b0.halo_local.tolist() == [0]
        # Row 1's neighbour 2 remaps onto the halo slot (size 2 + 0).
        assert b0.neighbor_idx[1, 0] == 2
        assert b1.halo.tolist() == [0]
        # Row 2's neighbours: 0 is remote (slot 2 + 0), 3 is local (1).
        assert b1.neighbor_idx[0].tolist() == [2, 1]

    def test_exchange_moves_only_halo_positions(self):
        idx = jnp.asarray(
            [[1, -1], [2, -1], [0, 3], [-1, -1]], jnp.int32
        )
        w = jnp.ones((4, 2), jnp.float32)
        banded = partition_csr(idx, w, [(0, 2), (2, 4)])
        values = [
            jnp.asarray([0.1, 0.2], jnp.float32),
            jnp.asarray([0.3, 0.4], jnp.float32),
        ]
        halos = exchange_halos(values, banded)
        assert halos[0].tolist() == [pytest.approx(0.3)]
        assert halos[1].tolist() == [pytest.approx(0.1)]

    @pytest.mark.parametrize("moments", [True, False])
    @pytest.mark.parametrize("tol", [None, 1e-5])
    def test_banded_sweep_bit_equal_to_whole_axis(self, moments, tol):
        m = 32
        idx, w = _graph_blocks(m=m)
        means, variances = _moment_seeds(m=m, nan_rows=(3,))
        if not moments:
            if tol is not None:
                pytest.skip("tol rides the moments sweep")
            variances = None
        ref_mean, ref_var, ref_iters, ref_res = bp_sweep_math(
            means, variances, idx, w, damping=0.4, max_steps=48, tol=tol
        )
        for bands in self._bandings(m):
            mean, var, iters, residual = banded_bp_sweep(
                means, variances, partition_csr(idx, w, bands),
                damping=0.4, max_steps=48, tol=tol,
            )
            np.testing.assert_array_equal(
                np.asarray(mean), np.asarray(ref_mean)
            )
            if moments:
                np.testing.assert_array_equal(
                    np.asarray(var), np.asarray(ref_var)
                )
            else:
                assert var is None
            assert int(iters) == int(ref_iters)
            np.testing.assert_array_equal(
                np.asarray(residual), np.asarray(ref_res)
            )


class TestBlocks:
    def test_block_validation(self):
        with pytest.raises(ValueError, match="kind"):
            MarketBlock("xor", ("a", "b"))
        with pytest.raises(ValueError, match="at least 2"):
            MarketBlock("implies", ("a",))
        with pytest.raises(ValueError, match="duplicate"):
            MarketBlock("mutually_exclusive", ("a", "a"))
        with pytest.raises(ValueError, match="weight"):
            MarketBlock("implies", ("a", "b"), weight=0.0)
        with pytest.raises(TypeError, match="MarketBlock"):
            MarketBlocks(["not-a-block"])

    def test_edges_compile_clique_and_chain(self):
        blocks = MarketBlocks([
            MarketBlock("mutually_exclusive", ("a", "b", "c"), weight=2.0),
            MarketBlock("implies", ("parlay", "leg1", "leg2")),
        ])
        edges = blocks.to_edges()
        # 3-clique both ways (6) + two composite↔leg pairs (4).
        assert len(edges) == 10
        assert ("a", "b", 2.0) in edges and ("b", "a", 2.0) in edges
        assert ("parlay", "leg1", 1.0) in edges
        assert ("leg1", "parlay", 1.0) in edges
        assert ("leg1", "leg2", 1.0) not in edges  # legs don't couple
        graph = blocks.to_graph(damping=0.3, steps=5)
        assert isinstance(graph, MarketGraph)
        assert graph.damping == 0.3 and graph.steps == 5

    def test_projection_renormalises_partition(self):
        blocks = MarketBlocks([
            MarketBlock("mutually_exclusive", ("a", "b", "c")),
        ])
        means = np.asarray([0.5, 0.3, 0.2, 0.9], np.float32)
        stderr = np.asarray([0.1, 0.1, 0.1, 0.2], np.float32)
        # Pre-scaled so the divisor is non-trivial.
        means[:3] *= 2.0
        out_mean, out_stderr = blocks.project(
            ["a", "b", "c", "other"], means, stderr
        )
        assert float(np.sum(out_mean[:3])) == pytest.approx(1.0)
        np.testing.assert_allclose(
            out_mean[:3], [0.5, 0.3, 0.2], rtol=1e-6
        )
        np.testing.assert_allclose(
            out_stderr[:3], np.asarray([0.1, 0.1, 0.1]) / 2.0, rtol=1e-6
        )
        # Untouched market, untouched inputs.
        assert float(out_mean[3]) == pytest.approx(0.9)
        assert float(means[0]) == pytest.approx(1.0)

    def test_projection_skips_absent_and_nonfinite(self):
        blocks = MarketBlocks([
            MarketBlock("mutually_exclusive", ("a", "b", "c")),
        ])
        means = np.asarray([0.4, np.nan], np.float32)
        out_mean, _ = blocks.project(["a", "b"], means)
        # Only one finite present member — nothing to renormalise.
        assert float(out_mean[0]) == pytest.approx(0.4)
        assert np.isnan(out_mean[1])

    def test_projection_clamps_implication_composite(self):
        blocks = MarketBlocks([
            MarketBlock("implies", ("parlay", "leg1", "leg2")),
        ])
        means = np.asarray([0.6, 0.5, 0.3], np.float32)
        stderr = np.asarray([0.1, 0.1, 0.1], np.float32)
        out_mean, out_stderr = blocks.project(
            ["parlay", "leg1", "leg2"], means, stderr
        )
        assert float(out_mean[0]) == pytest.approx(0.3)  # tightest leg
        assert float(out_stderr[0]) == pytest.approx(0.1)  # untouched
        # A composite already below its legs is left alone.
        means2 = np.asarray([0.1, 0.5, 0.3], np.float32)
        out2, _ = blocks.project(["parlay", "leg1", "leg2"], means2)
        assert float(out2[0]) == pytest.approx(0.1)

    def test_blocks_through_the_session_sum_to_one(self):
        blocks = MarketBlocks([
            MarketBlock(
                "mutually_exclusive", ("m-0", "m-1", "m-2", "m-3")
            ),
        ])
        _, (_, _, _, prop) = _session_run(
            (4, 2),
            analytics=AnalyticsOptions(
                blocks=blocks, inference=InferenceOptions()
            ),
        )
        assert isinstance(prop, PropagatedBeliefs)
        total = float(np.asarray(prop.mean)[:4].sum())
        assert total == pytest.approx(1.0, abs=1e-6)

    def test_blocks_leave_settlement_bytes_untouched(self, tmp_path):
        blocks = MarketBlocks([
            MarketBlock(
                "mutually_exclusive", ("m-0", "m-1", "m-2", "m-3")
            ),
        ])
        store_off, (res_off, *_rest) = _session_run((4, 2))
        store_on, (res_on, _, _, prop) = _session_run(
            (4, 2),
            analytics=AnalyticsOptions(
                blocks=blocks, inference=InferenceOptions()
            ),
        )
        assert prop is not None
        np.testing.assert_array_equal(
            np.asarray(res_off.consensus), np.asarray(res_on.consensus)
        )
        assert store_digest(store_off) == store_digest(store_on)
        store_off.flush_to_sqlite(tmp_path / "off.db")
        store_on.flush_to_sqlite(tmp_path / "on.db")
        assert (tmp_path / "off.db").read_bytes() == (
            tmp_path / "on.db"
        ).read_bytes()


class TestShedRankFromPropagatedStderr:
    """Neighbour evidence moves the variance-aware shed policy: the
    moments sweep's tightened stderr feeds the serve tier's ranking, so
    graph-connected markets shed LATER than the band stderr alone would
    rank them (they're better known than their own band shows)."""

    def _serve_stderr(self, analytics):
        import asyncio

        from bayesian_consensus_engine_tpu.serve import ConsensusService

        trace = []
        for rnd in range(2):
            for m in range(6):
                trace.append((
                    f"m-{m}",
                    [(f"s-{m}", 0.55 + 0.01 * rnd), (f"s-{(m + 1) % 3}", 0.4)],
                    (m + rnd) % 2 == 0,
                ))

        async def main():
            store = TensorReliabilityStore()
            service = ConsensusService(
                store, steps=2, now=NOW, mesh=make_mesh(),
                max_batch=6, max_delay_s=None, analytics=analytics,
            )
            futures = []
            async with service:
                for market_id, signals, outcome in trace:
                    futures.append(
                        service.submit(market_id, signals, outcome)
                    )
                await service.drain()
            return service, [f.result() for f in futures]

        return asyncio.run(main())

    def _shed_order(self, stderr_by_market):
        from bayesian_consensus_engine_tpu.serve.admission import (
            shed_rank_key,
        )

        markets = sorted(stderr_by_market)
        return sorted(
            markets,
            key=lambda m: shed_rank_key(
                stderr_by_market[m], markets.index(m)
            ),
        )

    def test_propagated_stderr_changes_the_shed_sequence(self):
        graph = MarketGraph.from_edges(
            [("m-0", "m-1", 0.5), ("m-1", "m-2", 0.7),
             ("m-3", "m-4", 0.4)],
            damping=0.4, steps=4,
        )
        svc_point, res_point = self._serve_stderr(
            AnalyticsOptions(graph=graph)
        )
        svc_bp, res_bp = self._serve_stderr(
            AnalyticsOptions(
                graph=graph,
                inference=InferenceOptions(tol=1e-6, max_steps=32),
            )
        )
        # The point sweep leaves the shed ranking on the band stderr:
        # the even-outcome markets (m-0/2/4) band a hair wider than the
        # odd ones, so they head the victim order, ties by arrival.
        point_order = self._shed_order(svc_point.market_band_stderr)
        assert point_order == ["m-0", "m-2", "m-4", "m-1", "m-3", "m-5"]
        # The moments sweep tightens the graph-connected markets —
        # m-0 (coupled to m-1, which couples to m-2) halves its stderr
        # twice over and drops to the BACK of the victim order, m-1 and
        # m-3 halve once, while the graph-blind m-5 rises to the front
        # block. The full sequence is pinned: neighbour evidence
        # REORDERS who sheds first.
        bp_order = self._shed_order(svc_bp.market_band_stderr)
        assert bp_order == ["m-2", "m-4", "m-5", "m-1", "m-3", "m-0"]
        assert bp_order != point_order
        assert bp_order[-1] == "m-0"  # best-connected market sheds last
        # The per-request results carry both stderrs; the propagated
        # one is tighter wherever the graph reaches.
        tightened = {
            r.market_id
            for r in res_bp
            if r.propagated_stderr is not None
            and r.band_stderr is not None
            and r.propagated_stderr < r.band_stderr - 1e-6
        }
        assert tightened  # neighbour evidence reached the serve tier
        for r in res_point:
            assert r.propagated_stderr is None
