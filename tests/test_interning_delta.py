"""Delta-pair interning (round 15): the epoch-persistent pair table and
the sharded deterministic intern pass.

The contract under test is BYTE parity: ``intern_mode="auto"`` (delta)
and ``intern_mode="full"`` (the legacy every-pair walk) must produce
identical plans, row assignment, store arrays, journal epoch payloads
(wall_ts masked — the one legitimately run-varying field), and SQLite
checkpoint bytes, across

    {stable, drift, reorder, shrink, grow}   workload shapes
  × {native, forced-fallback}                interner stacks
  × {flat, sharded-resident}                 settle paths

plus the sharded probe+commit pass against the serial intern, the
numpy/C ``delta_match_rows`` twins, the ``known_rows=`` fast path, and
the recovery rule (journal replay / ``absorb_replayed_rows`` drop the
epoch table — a stale table must MISS, never serve wrong rows).
"""

import struct

import numpy as np
import pytest

from bayesian_consensus_engine_tpu.core.batch import (
    pair_fingerprint,
    topology_fingerprint,
)
from bayesian_consensus_engine_tpu.pipeline import (
    settle_stream,
    stage_settlement_plan_columnar,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)
from bayesian_consensus_engine_tpu.utils import interning


# ---------------------------------------------------------------------------
# Workloads: columnar batches over a drifting market/source universe.
# ---------------------------------------------------------------------------


def _columnar(rng, market_ids, universe, max_signals=4):
    """One columnar batch: each market draws 1..max_signals sources
    (with replacement — duplicate signals exercise the averaging path)."""
    keys = list(market_ids)
    sids, probs, offsets = [], [], [0]
    for _ in keys:
        for _ in range(int(rng.integers(1, max_signals + 1))):
            sids.append(f"src-{int(rng.integers(0, universe))}")
            probs.append(float(rng.random()))
        offsets.append(len(sids))
    return (
        keys,
        sids,
        np.asarray(probs, dtype=np.float64),
        np.asarray(offsets, dtype=np.int64),
    )


def matrix_batches(seed=31):
    """The five-shape batch sequence: base, stable re-pack (same pair
    set, new probabilities AND new duplicate pattern), 1-market drift,
    full market reorder, shrink to a prefix, grow past the base."""
    rng = np.random.default_rng(seed)
    markets = [f"m-{i}" for i in range(24)]
    base = _columnar(rng, markets, universe=12)

    # Stable pair set with a different signal pattern: re-emit each
    # market's UNIQUE source set once (drops duplicates), new probs —
    # misses the topology fingerprint, hits the pair fingerprint.
    keys, sids, _probs, offsets = base
    stable_sids, stable_offsets = [], [0]
    for m in range(len(keys)):
        seen = dict.fromkeys(sids[offsets[m]:offsets[m + 1]])
        stable_sids.extend(sorted(seen))
        stable_offsets.append(len(stable_sids))
    stable = (
        list(keys),
        stable_sids,
        rng.random(len(stable_sids)),
        np.asarray(stable_offsets, dtype=np.int64),
    )

    drift = _columnar(rng, markets, universe=12)
    # ... but only 3 markets actually drift: splice the rest from base.
    d_keys, d_sids, d_probs, d_offsets = drift
    keep = [m for m in range(len(markets)) if m % 8 != 0]
    sids2, probs2, offsets2 = [], [], [0]
    for m in range(len(markets)):
        src = base if m in set(keep) else drift
        lo, hi = int(src[3][m]), int(src[3][m + 1])
        sids2.extend(src[1][lo:hi])
        probs2.extend(src[2][lo:hi].tolist())
        offsets2.append(len(sids2))
    drift = (
        list(markets), sids2, np.asarray(probs2),
        np.asarray(offsets2, dtype=np.int64),
    )

    perm = rng.permutation(len(markets))
    r_sids, r_probs, r_offsets = [], [], [0]
    for m in perm.tolist():
        lo, hi = int(base[3][m]), int(base[3][m + 1])
        r_sids.extend(base[1][lo:hi])
        r_probs.extend(base[2][lo:hi].tolist())
        r_offsets.append(len(r_sids))
    reorder = (
        [markets[m] for m in perm.tolist()], r_sids,
        np.asarray(r_probs), np.asarray(r_offsets, dtype=np.int64),
    )

    half = len(markets) // 2
    shrink = (
        list(markets[:half]),
        base[1][: int(base[3][half])],
        base[2][: int(base[3][half])].copy(),
        np.asarray(base[3][: half + 1], dtype=np.int64),
    )

    grown = markets + [f"m-new-{i}" for i in range(8)]
    grow = _columnar(rng, grown, universe=16)

    out = []
    for batch in (base, stable, drift, reorder, shrink, grow):
        n_markets = len(batch[0])
        out.append(
            (batch, [bool(b) for b in rng.integers(0, 2, n_markets)])
        )
    return out


def journal_epochs_sans_clock(path):
    """Decoded epoch frames with the wall-clock field (and its CRC)
    masked — the byte-comparable journal content."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4
    return epochs


def replayed_state(journal_path):
    """The durability truth a journal carries: replay it onto a fresh
    store and take the comparable host state (the PR-6 convention for
    free-running-prefetch streams, whose raw epoch membership is racy)."""
    from bayesian_consensus_engine_tpu.state.journal import replay_journal

    store, tag = replay_journal(journal_path)
    return tag, store_state(store)


def store_state(store):
    """The comparable host truth: ids in row order + value columns."""
    store.sync()
    used = len(store._pairs)
    return (
        store._pairs.ids(),
        store._rel[:used].tobytes(),
        store._conf[:used].tobytes(),
        store._days[:used].tobytes(),
        store._exists[:used].tobytes(),
        list(store._iso[:used]),
    )


# ---------------------------------------------------------------------------
# The byte-parity matrix.
# ---------------------------------------------------------------------------


class TestDeltaParityMatrix:
    """delta ≡ full across workloads × interner stacks × settle paths."""

    def _run_stream(self, tmp_path, name, intern_mode, mesh):
        from bayesian_consensus_engine_tpu.state.journal import (
            JournalWriter,
        )

        store = TensorReliabilityStore()
        db = tmp_path / f"{name}.db"
        jrnl = tmp_path / f"{name}.jrnl"
        stats = []
        results = list(
            settle_stream(
                store, matrix_batches(), steps=2, now=21_700.0,
                db_path=db, checkpoint_every=2, columnar=True,
                stats=stats, reuse_plans=True, mesh=mesh,
                journal=JournalWriter(jrnl), intern_mode=intern_mode,
            )
        )
        return store, results, db, jrnl, stats

    @pytest.mark.parametrize("fallback", [False, True],
                             ids=["native", "fallback"])
    @pytest.mark.parametrize("sharded", [False, True],
                             ids=["flat", "sharded-resident"])
    def test_delta_equals_full_bytes(self, tmp_path, monkeypatch,
                                     sharded, fallback):
        if fallback:
            monkeypatch.setenv("BCE_NO_NATIVE", "1")
        mesh = None
        if sharded:
            from bayesian_consensus_engine_tpu.parallel.mesh import (
                make_mesh,
            )

            mesh = make_mesh()  # markets-only: the bit-exact regime
        s_delta, r_delta, db_delta, j_delta, stats_delta = (
            self._run_stream(tmp_path, f"delta-{sharded}", "auto", mesh)
        )
        s_full, r_full, db_full, j_full, stats_full = (
            self._run_stream(tmp_path, f"full-{sharded}", "full", mesh)
        )
        for mine, ref in zip(r_delta, r_full):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(ref.consensus)
            )
        assert store_state(s_delta) == store_state(s_full)
        assert db_delta.read_bytes() == db_full.read_bytes()
        # Journals: compare REPLAYED state, the free-running-prefetch
        # convention (PR 6): the prefetch thread may intern batch N+1's
        # pairs before or after epoch N's snapshot depending on timing,
        # so raw epoch membership is racy on THIS surface either mode —
        # the dispatch-ordered epoch-bytes contract is pinned by
        # TestLockstepJournalBytes below.
        assert replayed_state(j_delta) == replayed_state(j_full)
        # The delta stream actually took the delta path: the drifted
        # batch interned FEWER pairs than the full walk, and the stable
        # re-pack (same pair set, new signal pattern) interned zero.
        interned_delta = [s["interned_pairs"] for s in stats_delta]
        interned_full = [s["interned_pairs"] for s in stats_full]
        assert interned_delta[0] == interned_full[0]  # cold = everything
        assert interned_delta[1] == 0  # pair-fingerprint O(1) tier
        assert 0 < interned_delta[2] < interned_full[2]  # the pair-delta
        # Reorder: the epoch table holds the DRIFT batch, so only the
        # drifted markets' pairs re-walk — still a fraction of the full
        # pass (which re-walks every pair of every market).
        assert interned_delta[3] < interned_full[3]

    @pytest.mark.parametrize("fallback", [False, True],
                             ids=["native", "fallback"])
    def test_forced_sharded_route_is_byte_identical(
        self, tmp_path, monkeypatch, fallback
    ):
        """The same matrix with the sharded probe+commit FORCED for
        every miss set (threshold 1, two workers) — the deterministic-
        merge contract at toy sizes. The fallback stack has no probe
        entry and must degrade to the serial pass, same bytes."""
        if fallback:
            monkeypatch.setenv("BCE_NO_NATIVE", "1")
        monkeypatch.setattr(interning, "SHARD_MIN_PAIRS", 1)
        monkeypatch.setenv("BCE_INTERN_WORKERS", "2")
        s_delta, r_delta, db_delta, j_delta, _ = self._run_stream(
            tmp_path, "sharded", "auto", None
        )
        monkeypatch.setattr(interning, "SHARD_MIN_PAIRS", 1 << 18)
        s_full, r_full, db_full, j_full, _ = self._run_stream(
            tmp_path, "serial", "full", None
        )
        assert store_state(s_delta) == store_state(s_full)
        assert db_delta.read_bytes() == db_full.read_bytes()
        assert replayed_state(j_delta) == replayed_state(j_full)


class TestLockstepJournalBytes:
    """The epoch-membership byte contract where it is actually promised:
    interning on the DISPATCH thread in batch order (the serve path's
    PlanCache + SessionDriver shape — no free-running prefetch), a delta
    and a full run must write byte-identical journal epochs (wall_ts
    masked), pinning "which epoch a new pair's table row lands in" as a
    pure function of the trace."""

    def _run(self, tmp_path, name, intern_mode, forced_shard,
             monkeypatch):
        from bayesian_consensus_engine_tpu.serve.driver import (
            PlanCache,
            SessionDriver,
        )
        from bayesian_consensus_engine_tpu.state.journal import (
            JournalWriter,
        )

        if forced_shard:
            monkeypatch.setattr(interning, "SHARD_MIN_PAIRS", 1)
            monkeypatch.setenv("BCE_INTERN_WORKERS", "2")
        else:
            monkeypatch.setattr(interning, "SHARD_MIN_PAIRS", 1 << 18)
        store = TensorReliabilityStore()
        jrnl = tmp_path / f"{name}.jrnl"
        cache = PlanCache(store, intern_mode=intern_mode)
        driver = SessionDriver(
            store, steps=2, journal=JournalWriter(jrnl),
            owns_journal=True, checkpoint_every=2, sync_checkpoints=True,
        )
        try:
            for i, (batch, outcomes) in enumerate(matrix_batches()):
                keys, sids, probs, offsets = batch
                plan = cache.bind(cache.stage(keys, sids, probs, offsets))
                driver.dispatch(plan, outcomes, now=21_800.0 + i)
                driver.checkpoint(i)
        finally:
            driver.finalize()
        return store, jrnl

    @pytest.mark.parametrize("fallback", [False, True],
                             ids=["native", "fallback"])
    def test_epoch_bytes_are_trace_pure(self, tmp_path, monkeypatch,
                                        fallback):
        if fallback:
            monkeypatch.setenv("BCE_NO_NATIVE", "1")
        s_delta, j_delta = self._run(
            tmp_path, "delta", "auto", True, monkeypatch
        )
        s_full, j_full = self._run(
            tmp_path, "full", "full", False, monkeypatch
        )
        assert store_state(s_delta) == store_state(s_full)
        assert journal_epochs_sans_clock(j_delta) == (
            journal_epochs_sans_clock(j_full)
        )


# ---------------------------------------------------------------------------
# Units: resolve tiers, twins, known_rows, sharded interner.
# ---------------------------------------------------------------------------


def _staged(batch, intern_mode="auto"):
    keys, sids, probs, offsets = batch
    return stage_settlement_plan_columnar(
        keys, sids, probs, offsets, intern_mode=intern_mode,
    )


class TestResolveTiers:
    def test_fingerprint_hit_is_o1_and_identical(self):
        base = matrix_batches()[0][0]
        stable = matrix_batches()[1][0]
        store = TensorReliabilityStore()
        plan0 = _staged(base).bind(store)
        assert plan0.intern_stats["interned_pairs"] > 0
        # Same pair set, different signal pattern: topology fingerprint
        # differs, pair fingerprint matches.
        s0, s1 = _staged(base), _staged(stable)
        assert topology_fingerprint(
            base[0], base[1], base[3]
        ) != topology_fingerprint(stable[0], stable[1], stable[3])
        assert s0.pair_fingerprint == s1.pair_fingerprint
        plan1 = s1.bind(store)
        assert plan1.intern_stats["fingerprint_hit"] is True
        assert plan1.intern_stats["interned_pairs"] == 0
        np.testing.assert_array_equal(plan1.slot_rows, plan0.slot_rows)

    def test_full_mode_never_consults_or_updates_the_table(self):
        base = matrix_batches()[0][0]
        store = TensorReliabilityStore()
        _staged(base, intern_mode="full").bind(store)
        assert store._pair_epoch is None
        plan = _staged(base).bind(store)
        # First delta bind on a full-warmed store: everything re-walks
        # the interner (all hits — no new rows), nothing was cached.
        assert plan.intern_stats["matched_pairs"] == 0

    @pytest.mark.parametrize("native", [None, False])
    def test_trailing_empty_market_does_not_split_the_check(self, native):
        """Regression (round-15 review): a zero-pair market at the END of
        the batch must not truncate the previous market's match segment.
        Batch {m0: [a,b,c], m1: []} with m0's LAST source drifted — the
        drifted pair sits exactly where the old clamped reduceat dropped
        it, so m0 must MISS (all −1), never serve the stale row."""
        po = np.array([0, 3, 3], np.int64)  # m0: 3 pairs, m1: empty
        pr_old = np.array([0, 1, 2], np.int32)   # a, b, c
        pr_new = np.array([0, 1, 3], np.int32)   # a, b, z — last pair drifts
        rows_old = np.array([10, 11, 12], np.int32)
        got = interning.delta_match_rows(
            None, pr_new, po, pr_old, po, None, rows_old, native=native,
        )
        np.testing.assert_array_equal(got, [-1, -1, -1])
        # And the unchanged batch still matches whole.
        same = interning.delta_match_rows(
            None, pr_old, po, pr_old, po, None, rows_old, native=native,
        )
        np.testing.assert_array_equal(same, rows_old)

    def test_trailing_empty_market_end_to_end_parity(self, monkeypatch):
        """The full reproduction through bind, on the forced-fallback
        (numpy-twin) stack: the drifted pair must intern a NEW row, byte-
        equal to the full-mode oracle."""
        monkeypatch.setenv("BCE_NO_NATIVE", "1")
        base = (["m0", "m1"], ["a", "b", "c"],
                np.array([0.2, 0.4, 0.6]), np.array([0, 3, 3], np.int64))
        drifted = (["m0", "m1"], ["a", "b", "z"],
                   np.array([0.3, 0.5, 0.7]), np.array([0, 3, 3], np.int64))
        store = TensorReliabilityStore()
        _staged(base).bind(store)
        plan_delta = _staged(drifted).bind(store)
        oracle = TensorReliabilityStore()
        _staged(base, intern_mode="full").bind(oracle)
        plan_full = _staged(drifted, intern_mode="full").bind(oracle)
        np.testing.assert_array_equal(
            plan_delta.slot_rows, plan_full.slot_rows
        )
        assert store._pairs.ids() == oracle._pairs.ids()

    @pytest.mark.parametrize("native", [None, False])
    def test_empty_epoch_table_misses_everything(self, native):
        """Regression (round-15 review): a zero-market epoch table must
        return all-miss like the C pass, not IndexError in the twin."""
        got = interning.delta_match_rows(
            None,
            np.array([0, 1], np.int32),      # one market, two pairs
            np.array([0, 2], np.int64),
            np.empty(0, np.int32), np.array([0], np.int64),
            np.array([-1], np.int64),        # prev_of: nothing maps
            np.empty(0, np.int32),
            native=native,
        )
        np.testing.assert_array_equal(got, [-1, -1])

    def test_empty_then_nonempty_batch_on_fallback(self, monkeypatch):
        """End-to-end: seed the table with an EMPTY batch on the forced-
        fallback stack, then bind a real one — must resolve (all-miss),
        byte-equal to full mode."""
        monkeypatch.setenv("BCE_NO_NATIVE", "1")
        empty = ([], [], np.empty(0), np.array([0], np.int64))
        real = (["m0"], ["a", "b"], np.array([0.1, 0.9]),
                np.array([0, 2], np.int64))
        store = TensorReliabilityStore()
        _staged(empty).bind(store)
        plan = _staged(real).bind(store)
        oracle = TensorReliabilityStore()
        _staged(empty, intern_mode="full").bind(oracle)
        ref = _staged(real, intern_mode="full").bind(oracle)
        np.testing.assert_array_equal(plan.slot_rows, ref.slot_rows)
        assert store._pairs.ids() == oracle._pairs.ids()

    @pytest.mark.parametrize("native", [None, False])
    def test_delta_match_twins_agree(self, native):
        rng = np.random.default_rng(7)
        for _ in range(20):
            m_old = int(rng.integers(1, 9))
            m_new = int(rng.integers(1, 9))
            counts_old = rng.integers(0, 4, m_old)
            counts_new = rng.integers(0, 4, m_new)
            po = np.concatenate([[0], np.cumsum(counts_old)]).astype(
                np.int64
            )
            pn = np.concatenate([[0], np.cumsum(counts_new)]).astype(
                np.int64
            )
            pr_old = rng.integers(0, 6, int(po[-1])).astype(np.int32)
            pr_new = rng.integers(0, 6, int(pn[-1])).astype(np.int32)
            rows_old = np.arange(int(po[-1]), dtype=np.int32) + 100
            prev_of = rng.integers(-1, m_old, m_new).astype(np.int64)
            rank_map = rng.integers(-1, 6, 6).astype(np.int32)
            got = interning.delta_match_rows(
                rank_map, pr_new, pn, pr_old, po, prev_of, rows_old,
                native=native,
            )
            ref = interning.delta_match_rows(
                rank_map, pr_new, pn, pr_old, po, prev_of, rows_old,
                native=False,
            )
            np.testing.assert_array_equal(got, ref)
            # Spot-check semantics per market against a scalar oracle.
            for m in range(m_new):
                lo, hi = int(pn[m]), int(pn[m + 1])
                pm = int(prev_of[m])
                want_match = 0 <= pm < m_old
                if want_match:
                    plo, phi = int(po[pm]), int(po[pm + 1])
                    want_match = (phi - plo == hi - lo) and all(
                        0 <= int(pr_new[k]) < 6
                        and rank_map[int(pr_new[k])]
                        == pr_old[plo + (k - lo)]
                        for k in range(lo, hi)
                    )
                if want_match:
                    assert (got[lo:hi] >= 0).all()
                else:
                    assert (got[lo:hi] == -1).all()


class TestShardedInterner:
    def _columns(self, n_pairs, n_src=12, n_mkt=40, seed=3):
        rng = np.random.default_rng(seed)
        src_table = [f"s{i}" for i in range(n_src)]
        mkt_table = [f"m{i}" for i in range(n_mkt)]
        return (
            src_table,
            rng.integers(0, n_src, n_pairs).astype(np.int32),
            mkt_table,
            rng.integers(0, n_mkt, n_pairs).astype(np.int32),
        )

    def test_sharded_equals_serial_rows_and_table(self):
        pytest.importorskip(
            "bayesian_consensus_engine_tpu._native.internmap"
        )
        cols = self._columns(4096)
        a = interning.make_pair_interner()
        b = interning.make_pair_interner()
        if not interning.probe_supported(a):
            pytest.skip("probe entry points not built")
        serial = np.asarray(a.intern_arrays_indexed(*cols))
        sharded = b.intern_indexed_sharded(*cols, workers=3)
        np.testing.assert_array_equal(serial, sharded)
        assert a.ids() == b.ids()
        # Warm re-probe: all hits, nothing committed, same rows.
        again = b.intern_indexed_sharded(*cols, workers=2)
        np.testing.assert_array_equal(serial, again)

    def test_probe_then_commit_split(self):
        pytest.importorskip(
            "bayesian_consensus_engine_tpu._native.internmap"
        )
        cols = self._columns(512)
        interner = interning.make_pair_interner()
        if not interning.probe_supported(interner):
            pytest.skip("probe entry points not built")
        # Pre-intern a prefix so the probe sees hits AND misses.
        prefix = tuple(c[:200] if isinstance(c, np.ndarray) else c
                       for c in cols)
        interner.intern_arrays_indexed(*prefix)
        rows, hashes, slots, cap = interner.probe_pairs_sharded(
            *cols, workers=2
        )
        miss_mask = rows < 0
        assert miss_mask.any() and (~miss_mask).any()
        committed = interner.commit_probed(*cols, rows, hashes, slots, cap)
        assert committed == int(miss_mask.sum())
        reference = interning.make_pair_interner()
        np.testing.assert_array_equal(
            rows, np.asarray(reference.intern_arrays_indexed(*cols))
        )


class TestKnownRows:
    def test_known_rows_fast_path(self):
        store = TensorReliabilityStore()
        sources = ["a", "b", "a", "c"]
        markets = ["m", "m", "n", "n"]
        full = store.rows_for_arrays(sources, markets)
        partial = np.array([full[0], -1, -1, -1], np.int32)
        again = store.rows_for_arrays(
            sources, markets, known_rows=partial
        )
        np.testing.assert_array_equal(again, full)
        # Pair-tuple surface rides the same path.
        pairs = list(zip(sources, markets))
        np.testing.assert_array_equal(
            store.rows_for_pairs(pairs, known_rows=full), full
        )

    def test_known_rows_assigns_in_batch_order(self):
        reference = TensorReliabilityStore()
        ref_rows = reference.rows_for_arrays(
            ["a", "b", "c"], ["m", "m", "m"]
        )
        store = TensorReliabilityStore()
        rows = store.rows_for_arrays(
            ["a", "b", "c"], ["m", "m", "m"],
            known_rows=np.array([-1, -1, -1], np.int32),
        )
        np.testing.assert_array_equal(rows, ref_rows)
        assert store._pairs.ids() == reference._pairs.ids()

    def test_known_rows_rejects_lookup_mode(self):
        store = TensorReliabilityStore()
        with pytest.raises(ValueError, match="allocate=False"):
            store.rows_for_arrays(
                ["a"], ["m"], allocate=False,
                known_rows=np.array([-1], np.int32),
            )


class TestRecoveryInvalidation:
    """Adoption/replay intern outside the bind trace: the epoch table
    must DROP, and a post-recovery delta bind must re-witness (miss),
    producing the same bytes as a full bind."""

    def _warm(self):
        store = TensorReliabilityStore()
        base = matrix_batches()[0][0]
        _staged(base).bind(store)
        assert store._pair_epoch is not None
        return store, base

    def test_absorb_replayed_rows_drops_the_table(self):
        store, _ = self._warm()
        rows = store.rows_for_arrays(["x"], ["y"])
        store.absorb_replayed_rows(
            rows, np.array([0.7]), np.array([0.6]),
            np.array([20_000.0]), np.array([True]),
            ["2024-09-30T00:00:00+00:00"],
        )
        assert store._pair_epoch is None

    def test_journal_replay_drops_the_table(self):
        """The replay hook (`_apply_journal_epoch` — what
        ``replay_journal`` and the cluster merge drive) interns outside
        the bind trace: the warmed table must drop."""
        replayed, base = self._warm()
        assert replayed._pair_epoch is not None
        replayed._apply_journal_epoch(
            len(replayed._pairs) + 1,
            [("zz", "qq")],
            np.array([len(replayed._pairs)], dtype=np.int64),
            np.array([0.5]), np.array([0.5]),
            np.array([20_100.0]), np.array([True]),
            ["2024-01-01T00:00:00+00:00"],
        )
        assert replayed._pair_epoch is None

    def test_post_adoption_delta_bind_matches_full(self):
        """After an adoption-shaped mutation, the next delta bind misses
        (cold table) and still produces full-pass bytes."""
        store, base = self._warm()
        rows = store.rows_for_arrays(["adopted-src"], ["adopted-mkt"])
        store.absorb_replayed_rows(
            rows, np.array([0.9]), np.array([0.8]),
            np.array([20_050.0]), np.array([True]),
            ["2024-11-30T00:00:00+00:00"],
        )
        drift = matrix_batches()[2][0]
        plan_delta = _staged(drift).bind(store)
        assert plan_delta.intern_stats["matched_pairs"] == 0  # re-witness
        reference = TensorReliabilityStore()
        _staged(base, intern_mode="full").bind(reference)
        ref_rows = reference.rows_for_arrays(
            ["adopted-src"], ["adopted-mkt"]
        )
        reference.absorb_replayed_rows(
            ref_rows, np.array([0.9]), np.array([0.8]),
            np.array([20_050.0]), np.array([True]),
            ["2024-11-30T00:00:00+00:00"],
        )
        plan_full = _staged(drift, intern_mode="full").bind(reference)
        np.testing.assert_array_equal(
            plan_delta.slot_rows, plan_full.slot_rows
        )
        assert store._pairs.ids() == reference._pairs.ids()


class TestPairFingerprint:
    def test_reorder_misses(self):
        base = matrix_batches()[0][0]
        reorder = matrix_batches()[3][0]
        assert _staged(base).pair_fingerprint != (
            _staged(reorder).pair_fingerprint
        )

    def test_full_mode_skips_the_digest(self):
        base = matrix_batches()[0][0]
        assert _staged(base, intern_mode="full").pair_fingerprint is None

    def test_rejects_unknown_mode(self):
        base = matrix_batches()[0][0]
        with pytest.raises(ValueError, match="intern_mode"):
            _staged(base, intern_mode="wat")

    def test_tables_are_length_delimited(self):
        # ("ab","c") vs ("a","bc") must not collide through the joined
        # table bytes.
        fp1 = pair_fingerprint(
            ["m"], ["ab", "c"], np.array([0, 0], np.int32),
            np.array([0, 1], np.int32), np.array([0, 2], np.int64),
        )
        fp2 = pair_fingerprint(
            ["m"], ["a", "bc"], np.array([0, 0], np.int32),
            np.array([0, 1], np.int32), np.array([0, 2], np.int64),
        )
        assert fp1 != fp2
