"""Shape tuner (utils/autotune.py) + its knob wirings.

The contract VERDICT r3 #8 asked for: a measured-once-per-shape tuner,
behind a flag, DEFAULT OFF, numbers unchanged when off. These tests pin
exactly that — the off path never measures and returns the caller's
default; the on path measures each candidate once, persists the winner,
and answers from cache forever after.
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from bayesian_consensus_engine_tpu.utils.autotune import ShapeTuner


class TestShapeTuner:
    def _tuner(self, tmp_path, enabled=True):
        return ShapeTuner(
            cache_path=str(tmp_path / "tune.json"),
            enabled=enabled,
            device_kind="test-device",
        )

    def test_disabled_returns_default_without_measuring(self, tmp_path):
        calls = []
        tuner = self._tuner(tmp_path, enabled=False)
        choice = tuner.tune(
            "knob", (8, 16), [1, 2, 3], lambda c: calls.append(c) or 1.0, 2
        )
        assert choice == 2
        assert calls == []
        assert not (tmp_path / "tune.json").exists()

    def test_default_off_via_env(self, monkeypatch, tmp_path):
        monkeypatch.delenv("BCE_AUTOTUNE", raising=False)
        tuner = ShapeTuner(cache_path=str(tmp_path / "t.json"))
        assert not tuner.enabled

    def test_measures_once_and_caches(self, tmp_path):
        calls = []

        def measure(candidate):
            calls.append(candidate)
            return {1: 3.0, 2: 1.0, 3: 2.0}[candidate]

        tuner = self._tuner(tmp_path)
        assert tuner.tune("knob", (8, 16), [1, 2, 3], measure, 1) == 2
        assert calls == [1, 2, 3]
        # Second ask: answered from cache, zero measurements.
        assert tuner.tune("knob", (8, 16), [1, 2, 3], measure, 1) == 2
        assert calls == [1, 2, 3]

    def test_cache_persists_across_instances(self, tmp_path):
        self._tuner(tmp_path).tune(
            "knob", (4,), [10, 20], {10: 2.0, 20: 1.0}.__getitem__, 10
        )
        fresh = self._tuner(tmp_path)
        choice = fresh.tune(
            "knob", (4,), [10, 20], lambda c: pytest.fail("measured"), 10
        )
        assert choice == 20

    def test_distinct_shapes_and_knobs_tune_independently(self, tmp_path):
        tuner = self._tuner(tmp_path)
        assert tuner.tune("a", (1,), [1, 2], {1: 1.0, 2: 2.0}.__getitem__, 2) == 1
        assert tuner.tune("a", (2,), [1, 2], {1: 2.0, 2: 1.0}.__getitem__, 1) == 2
        assert tuner.tune("b", (1,), [1, 2], {1: 5.0, 2: 1.0}.__getitem__, 1) == 2

    def test_failing_candidates_are_skipped(self, tmp_path):
        def measure(candidate):
            if candidate == 1:
                raise RuntimeError("over the VMEM budget")
            return float(candidate)

        tuner = self._tuner(tmp_path)
        assert tuner.tune("knob", (1,), [1, 2, 3], measure, 1) == 2

    def test_all_candidates_failing_returns_default(self, tmp_path):
        def measure(candidate):
            raise RuntimeError("no backend")

        tuner = self._tuner(tmp_path)
        assert tuner.tune("knob", (1,), [1, 2], measure, 7) == 7

    def test_stale_cached_choice_remeasures(self, tmp_path):
        tuner = self._tuner(tmp_path)
        tuner.tune("knob", (1,), [1, 2], {1: 1.0, 2: 2.0}.__getitem__, 2)
        # The cached winner (1) is no longer a candidate: re-measure.
        choice = tuner.tune("knob", (1,), [4, 8], {4: 2.0, 8: 1.0}.__getitem__, 4)
        assert choice == 8

    def test_cache_key_includes_device_kind(self, tmp_path):
        path = tmp_path / "tune.json"
        ShapeTuner(cache_path=str(path), enabled=True, device_kind="kindA").tune(
            "knob", (1,), [1, 2], {1: 1.0, 2: 2.0}.__getitem__, 2
        )
        payload = json.loads(path.read_text())
        assert all("kindA" in key for key in payload)


class TestPallasTileWiring:
    def test_auto_resolves_through_tuner(self, monkeypatch, tmp_path):
        from bayesian_consensus_engine_tpu.ops import pallas_cycle
        from bayesian_consensus_engine_tpu.utils import autotune

        seen = {}

        class FakeTuner:
            def tune(self, knob, shape_key, candidates, measure, default):
                seen.update(
                    knob=knob, shape_key=shape_key, candidates=candidates
                )
                return 1024

        monkeypatch.setattr(autotune, "default_tuner", lambda: FakeTuner())
        call = pallas_cycle.build_pallas_cycle(
            2048, 8, tile_markets="auto", interpret=True
        )
        assert seen["knob"] == "pallas_tile"
        assert seen["shape_key"] == (2048, 8)
        assert seen["candidates"] == [512, 1024, 2048]
        # The returned callable was built at the tuned tile: a run works.
        km = np.zeros((8, 2048), np.float32)
        m1 = np.zeros((1, 2048), np.float32)
        state = pallas_cycle.SlotMajorState(
            km + 0.5, km + 0.25, km * 0.0, km * 0.0
        )
        _state, consensus, _conf, _w = call(km + 0.5, km + 1.0, m1, state, 1.0)
        assert consensus.shape == (1, 2048)

    def test_default_off_keeps_recorded_tile(self, monkeypatch, tmp_path):
        """With the flag off, "auto" must resolve to the recorded default
        and never measure — numbers unchanged when off."""
        from bayesian_consensus_engine_tpu.ops import pallas_cycle
        from bayesian_consensus_engine_tpu.utils import autotune

        monkeypatch.delenv("BCE_AUTOTUNE", raising=False)
        monkeypatch.setattr(autotune, "_default_tuner", None)
        monkeypatch.setattr(
            autotune, "_default_cache_path",
            lambda: str(tmp_path / "never.json"),
        )
        tile = pallas_cycle._tuned_tile(2048, 8)
        assert tile == pallas_cycle.DEFAULT_TILE_M
        assert not (tmp_path / "never.json").exists()

    def test_auto_total_when_no_standard_tile_divides(self, monkeypatch):
        """"auto" must resolve for ANY M (review finding): when no standard
        tile divides M, the fallback is M itself — one tile."""
        from bayesian_consensus_engine_tpu.ops import pallas_cycle
        from bayesian_consensus_engine_tpu.utils import autotune

        monkeypatch.setattr(
            autotune, "_default_tuner",
            autotune.ShapeTuner(enabled=False, device_kind="t"),
        )
        call = pallas_cycle.build_pallas_cycle(
            384, 8, tile_markets="auto", interpret=True
        )
        km = np.zeros((8, 384), np.float32)
        m1 = np.zeros((1, 384), np.float32)
        state = pallas_cycle.SlotMajorState(
            km + 0.5, km + 0.25, km * 0.0, km * 0.0
        )
        _state, consensus, _c, _w = call(km + 0.5, km + 1.0, m1, state, 1.0)
        assert consensus.shape == (1, 384)


class TestTimeBestOf:
    def test_warmup_calls_are_untimed(self):
        from bayesian_consensus_engine_tpu.utils.autotune import time_best_of

        calls = []

        def run():
            calls.append(len(calls))

        best = time_best_of(run, repeats=2, warmup=3)
        assert len(calls) == 5  # 3 warmup + 2 timed
        assert best >= 0.0

    def test_warmup_default_zero(self):
        from bayesian_consensus_engine_tpu.utils.autotune import time_best_of

        calls = []
        time_best_of(lambda: calls.append(1), repeats=2)
        assert len(calls) == 2


class TestRingChunkWiring:
    """chunk_agents="auto" (parallel/ring.py) routes through the same
    ShapeTuner contract as the Pallas tile: off → the recorded default
    without measuring; on → the honesty guard races candidates against
    the default and records the verdict the bench leg reports."""

    def test_auto_resolves_through_tuner(self, monkeypatch):
        from bayesian_consensus_engine_tpu.parallel import ring
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.utils import autotune

        seen = {}

        class FakeTuner:
            def tune(self, knob, shape_key, candidates, measure, default):
                seen.update(
                    knob=knob, shape_key=shape_key,
                    candidates=candidates, default=default,
                )
                return 4

        monkeypatch.setattr(autotune, "default_tuner", lambda: FakeTuner())
        mesh = make_mesh((1, 8))
        chunk = ring._tuned_chunk_agents(mesh, 6, (16, 80_000))
        assert chunk == 4
        assert seen["knob"] == "ring_chunk_agents"
        assert seen["shape_key"] == (16, 80_000, 1, 8)
        assert seen["default"] == ring.DEFAULT_CHUNK_AGENTS
        # Every standard width under the 10k shard + the unchunked shard
        # width itself ride the race (the default is measured by tune()).
        assert seen["candidates"] == [128, 256, 512, 2048, 10_000]

    def test_tiny_shard_short_circuits_to_default(self, monkeypatch):
        # a_loc = 32/8 = 4: nothing to race (every candidate clamps to
        # the default) — resolve without ever constructing a tuner.
        from bayesian_consensus_engine_tpu.parallel import ring
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.utils import autotune

        def boom():
            raise AssertionError("tuner must not be constructed")

        monkeypatch.setattr(autotune, "default_tuner", boom)
        assert ring._tuned_chunk_agents(make_mesh((1, 8)), 6, (16, 32)) == 4

    def test_default_off_keeps_recorded_chunk(self, monkeypatch, tmp_path):
        from bayesian_consensus_engine_tpu.parallel import ring
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.utils import autotune

        monkeypatch.delenv("BCE_AUTOTUNE", raising=False)
        monkeypatch.setattr(autotune, "_default_tuner", None)
        monkeypatch.setattr(
            autotune, "_default_cache_path",
            lambda: str(tmp_path / "never.json"),
        )
        mesh = make_mesh((1, 8))
        chunk = ring._tuned_chunk_agents(mesh, 6, (64, 80_000))
        assert chunk == ring.DEFAULT_CHUNK_AGENTS
        assert not (tmp_path / "never.json").exists()


class TestBandChunkWiring:
    """chunk_slots="auto" (analytics/bands.py) rides the same ShapeTuner
    contract as the ring chunk: its own knob and shape key, candidates
    clamped to the shard's slot width, the recorded default raced by the
    honesty guard."""

    def test_auto_resolves_through_tuner(self, monkeypatch):
        from bayesian_consensus_engine_tpu.analytics import bands
        from bayesian_consensus_engine_tpu.ops.uncertainty import (
            DEFAULT_CHUNK_SLOTS,
        )
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.utils import autotune

        seen = {}

        class FakeTuner:
            def tune(self, knob, shape_key, candidates, measure, default):
                seen.update(
                    knob=knob, shape_key=shape_key,
                    candidates=candidates, default=default,
                )
                return 8

        monkeypatch.setattr(autotune, "default_tuner", lambda: FakeTuner())
        mesh = make_mesh((1, 8))
        chunk = bands._tuned_chunk_slots(mesh, 1.96, (80_000, 16))
        assert chunk == 8
        assert seen["knob"] == "band_chunk_slots"
        assert seen["shape_key"] == (80_000, 16, 1, 8)
        assert seen["default"] == DEFAULT_CHUNK_SLOTS
        assert seen["candidates"] == [128, 256, 512, 2048, 10_000]

    def test_tiny_shard_short_circuits_to_default(self, monkeypatch):
        from bayesian_consensus_engine_tpu.analytics import bands
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.utils import autotune

        def boom():
            raise AssertionError("tuner must not be constructed")

        monkeypatch.setattr(autotune, "default_tuner", boom)
        assert bands._tuned_chunk_slots(
            make_mesh((1, 8)), 1.96, (32, 16)
        ) == 4

    def test_enabled_tunes_races_default_and_runs(self, monkeypatch,
                                                  tmp_path):
        """End-to-end: a real (tiny) measured tune through the honesty
        guard — the verdict records the default raced on the same clock,
        and the resolved build runs and matches the unchunked output."""
        import jax

        from bayesian_consensus_engine_tpu.parallel import ring
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.utils import autotune

        tuner = autotune.ShapeTuner(
            cache_path=str(tmp_path / "ring.json"), enabled=True,
            device_kind="test-device",
        )
        monkeypatch.setattr(autotune, "_default_tuner", tuner)
        monkeypatch.setattr(ring, "_CHUNK_CANDIDATES", (2,))
        mesh = make_mesh((1, 2), devices=jax.devices()[:2])
        m, a = 8, 16
        fn = ring.build_ring_tiebreak(mesh, chunk_agents="auto")
        rng = np.random.default_rng(2)
        args = tuple(
            jax.numpy.asarray(x)
            for x in (
                rng.choice([0.25, 0.5, 0.75], (m, a)).astype(np.float32),
                rng.uniform(0.5, 2.0, (m, a)).astype(np.float32),
                rng.uniform(0, 1, (m, a)).astype(np.float32),
                rng.uniform(0, 1, (m, a)).astype(np.float32),
                rng.random((m, a)) < 0.9,
            )
        )
        got = fn(*args)
        decision = tuner.decision("ring_chunk_agents", (m, a, 1, 2))
        assert decision is not None
        # The shard width is 8, so the default clamps to it; the guard
        # recorded it raced on the same clock as the candidates.
        assert decision["default"] == 8
        assert str(decision["choice"]) in decision["timings_s"]
        assert str(decision["default"]) in decision["timings_s"]
        want = ring.build_ring_tiebreak(mesh, chunk_agents=None)(*args)
        for name, g, w in zip(got._fields, got, want):
            np.testing.assert_array_equal(
                np.asarray(g), np.asarray(w), err_msg=name
            )


class TestSlotBucket:
    def test_bucket_pads_to_sublane_multiple(self):
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        payloads = [
            (
                f"m-{m}",
                [
                    {"sourceId": f"s-{i}", "probability": 0.5}
                    for i in range(count)
                ],
            )
            for m, count in enumerate([1, 3, 5])
        ]
        plan = build_settlement_plan(
            TensorReliabilityStore(), payloads, num_slots="bucket"
        )
        assert plan.num_slots == 8  # natural K=5 → next multiple of 8
        # Two batches with different natural K land in the same bucket —
        # the point: one compiled settle program per bucket.
        plan2 = build_settlement_plan(
            TensorReliabilityStore(), payloads[:2], num_slots="bucket"
        )
        assert plan2.num_slots == plan.num_slots

    def test_bucket_settle_matches_natural_k_state(self):
        import random

        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
            settle,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng = random.Random(5)
        payloads = [
            (
                f"m-{m}",
                [
                    {
                        "sourceId": f"s-{rng.randrange(9)}",
                        "probability": round(rng.random(), 6),
                    }
                    for _ in range(rng.randint(1, 5))
                ],
            )
            for m in range(12)
        ]
        outcomes = [rng.random() < 0.5 for _ in range(12)]

        natural = TensorReliabilityStore()
        settle(
            natural,
            build_settlement_plan(natural, payloads),
            outcomes,
            steps=2,
            now=20_910.0,
        )
        natural.sync()

        bucketed = TensorReliabilityStore()
        settle(
            bucketed,
            build_settlement_plan(bucketed, payloads, num_slots="bucket"),
            outcomes,
            steps=2,
            now=20_910.0,
        )
        bucketed.sync()

        # State updates are quantised (±0.1 lattice) — identical records;
        # consensus may move ≤1 ulp (documented), checked via allclose.
        assert bucketed.list_sources() == natural.list_sources()


class TestSlotValidation:
    def test_unknown_num_slots_string_rejected_clearly(self):
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        with pytest.raises(ValueError, match="only supported string"):
            build_settlement_plan(
                TensorReliabilityStore(),
                [("m", [{"sourceId": "s", "probability": 0.5}])],
                num_slots="buckets",
            )


    def test_unknown_tile_string_rejected_clearly(self):
        from bayesian_consensus_engine_tpu.ops import pallas_cycle

        with pytest.raises(ValueError, match="only supported string"):
            pallas_cycle.build_pallas_cycle(1024, 8, tile_markets="Auto")


class TestMalformedCache:
    def test_malformed_cache_entry_remeasures(self, tmp_path):
        """A valid-JSON but wrong-schema cache entry must re-measure, not
        crash (cache is an optimisation only)."""
        path = tmp_path / "tune.json"
        tuner = ShapeTuner(
            cache_path=str(path), enabled=True, device_kind="k"
        )
        key = tuner._key("knob", (1,))
        path.write_text(json.dumps({key: {}}))
        choice = tuner.tune(
            "knob", (1,), [1, 2], {1: 2.0, 2: 1.0}.__getitem__, 1
        )
        assert choice == 2


class TestHonestyGuard:
    """A tuned value ships only when it BEATS the default on the same
    clock (VERDICT r5 #9): a rigged timer that makes every candidate
    slower than — or equal to — the default must leave the default in
    the cache, never a noise-ordered "winner"."""

    def _tuner(self, tmp_path):
        return ShapeTuner(
            cache_path=str(tmp_path / "honest.json"),
            enabled=True,
            device_kind="test-device",
        )

    def test_loser_candidates_record_the_default(self, tmp_path):
        # Rigged clock: the default (512) is fastest; the "tuned"
        # candidates all lose. Pre-guard, argmin over candidates-only
        # would have shipped 1024 without ever timing 512.
        clock = {512: 1.0, 1024: 2.0, 2048: 3.0}
        tuner = self._tuner(tmp_path)
        assert tuner.tune(
            "tile", (8, 8), [1024, 2048], clock.__getitem__, 512
        ) == 512
        entry = tuner.decision("tile", (8, 8))
        assert entry["choice"] == 512
        assert entry["default"] == 512
        assert entry["beat_default"] is False
        assert set(entry["timings_s"]) == {"512", "1024", "2048"}
        # Cached verdict answers without re-measuring (default is not in
        # the candidate list — the cached-default validity path).
        assert tuner.tune(
            "tile", (8, 8), [1024, 2048],
            lambda c: pytest.fail("re-measured"), 512,
        ) == 512

    def test_tie_ships_the_default(self, tmp_path):
        tuner = self._tuner(tmp_path)
        assert tuner.tune(
            "tile", (4,), [1, 2], {1: 1.0, 2: 1.0}.__getitem__, 1
        ) == 1
        assert tuner.decision("tile", (4,))["beat_default"] is False

    def test_winner_still_ships_and_records_the_win(self, tmp_path):
        tuner = self._tuner(tmp_path)
        assert tuner.tune(
            "tile", (4,), [1, 2], {1: 2.0, 2: 1.0}.__getitem__, 1
        ) == 2
        entry = tuner.decision("tile", (4,))
        assert entry["beat_default"] is True and entry["choice"] == 2

    def test_default_measured_even_when_not_a_candidate(self, tmp_path):
        measured = []

        def clock(candidate):
            measured.append(candidate)
            return {7: 0.5, 1: 1.0, 2: 2.0}[candidate]

        tuner = self._tuner(tmp_path)
        assert tuner.tune("tile", (2,), [1, 2], clock, 7) == 7
        assert 7 in measured

    def test_infeasible_default_ships_the_argmin(self, tmp_path):
        def clock(candidate):
            if candidate == 7:
                raise RuntimeError("default tile does not divide")
            return float(candidate)

        tuner = self._tuner(tmp_path)
        assert tuner.tune("tile", (3,), [1, 2], clock, 7) == 1
        assert tuner.decision("tile", (3,))["beat_default"] is True

    def test_pre_guard_cache_entry_is_remeasured(self, tmp_path):
        """An old-schema cache entry (argmin winner, no recorded default
        verdict) must NOT answer: it was never raced against the default
        — the exact failure the guard exists for."""
        import json as _json

        path = tmp_path / "honest.json"
        tuner = self._tuner(tmp_path)
        key = tuner._key("tile", (9,))
        path.write_text(_json.dumps(
            {key: {"choice": 1024, "timings_s": {"1024": 1.0}}}
        ))
        clock = {512: 1.0, 1024: 2.0}
        fresh = self._tuner(tmp_path)
        assert fresh.tune(
            "tile", (9,), [1024], clock.__getitem__, 512
        ) == 512
        assert fresh.decision("tile", (9,))["beat_default"] is False

    def test_cached_verdict_for_other_default_is_remeasured(self, tmp_path):
        tuner = self._tuner(tmp_path)
        assert tuner.tune(
            "tile", (11,), [1, 2], {1: 2.0, 2: 1.0}.__getitem__, 1
        ) == 2
        # Same knob+shape, different DEFAULT: the recorded race does not
        # apply — re-measure against the new default.
        assert tuner.tune(
            "tile", (11,), [1, 2], {1: 2.0, 2: 1.0, 3: 0.5}.__getitem__, 3
        ) == 3


class TestAutotuneBank:
    """The shippable bank (round 20): adjudicated verdicts exported on
    host A serve on host B of the same device generation WITHOUT a
    re-race; any drift — schema, default, generation — falls through to
    the pre-bank behaviour; a merge never silently picks a side on a
    verdict flip."""

    KIND = "TPU v5e"

    def _raced_cache(self, tmp_path):
        """Race one shape on 'host A' and return its cache path."""
        tuner = ShapeTuner(
            cache_path=str(tmp_path / "hostA.json"),
            enabled=True,
            device_kind=self.KIND,
        )
        choice = tuner.tune(
            "settle_kernel", (16, 256, 2), ["pallas"],
            {"pallas": 1.0, "xla": 2.0}.__getitem__, "xla",
        )
        assert choice == "pallas"
        return tuner._cache_path

    def test_export_load_serves_without_rerace(self, tmp_path):
        from bayesian_consensus_engine_tpu.utils.autotune import export_bank

        bank = export_bank(self._raced_cache(tmp_path))
        assert bank["schema"] == "bce-autotune-bank/v1"
        (entry,) = bank["entries"]
        assert entry["generation"] == "tpu-v5e"
        assert entry["beat_default"] is True
        assert entry["timings_s"] == {"pallas": 1.0, "xla": 2.0}

        # "Host B": tuner OFF (BCE_AUTOTUNE unset posture), fresh cache,
        # same generation. The bank is its own opt-in: the verdict
        # serves, and a measure that would raise proves no re-race ran.
        def never(_candidate):
            raise AssertionError("banked verdict must not re-race")

        host_b = ShapeTuner(
            cache_path=str(tmp_path / "hostB.json"),
            enabled=False,
            device_kind=self.KIND,
            bank=bank,
        )
        assert host_b.tune(
            "settle_kernel", (16, 256, 2), ["pallas"], never, "xla"
        ) == "pallas"
        decision = host_b.decision("settle_kernel", (16, 256, 2))
        assert decision["choice"] == "pallas"
        assert decision["source"] == "bank"

    def test_bank_loads_from_path_and_env(self, tmp_path, monkeypatch):
        from bayesian_consensus_engine_tpu.utils.autotune import export_bank

        bank = export_bank(self._raced_cache(tmp_path))
        path = tmp_path / "v5e.bank.json"
        path.write_text(json.dumps(bank))

        by_path = ShapeTuner(
            cache_path=str(tmp_path / "b1.json"), enabled=False,
            device_kind=self.KIND, bank=str(path),
        )
        assert by_path.tune(
            "settle_kernel", (16, 256, 2), ["pallas"], None, "xla"
        ) == "pallas"

        monkeypatch.setenv("BCE_AUTOTUNE_BANK", str(path))
        by_env = ShapeTuner(
            cache_path=str(tmp_path / "b2.json"), enabled=False,
            device_kind=self.KIND,
        )
        assert by_env.tune(
            "settle_kernel", (16, 256, 2), ["pallas"], None, "xla"
        ) == "pallas"

    def test_drifted_default_falls_through(self, tmp_path):
        from bayesian_consensus_engine_tpu.utils.autotune import export_bank

        bank = export_bank(self._raced_cache(tmp_path))
        tuner = ShapeTuner(
            cache_path=str(tmp_path / "drift.json"), enabled=True,
            device_kind=self.KIND, bank=bank,
        )
        # Caller's default moved since the bank was recorded: the banked
        # adjudication (vs "xla") does not answer for "fused" — the
        # honesty guard re-races against the NEW default.
        calls = []

        def clock(candidate):
            calls.append(candidate)
            return {"pallas": 2.0, "fused": 1.0}[candidate]

        assert tuner.tune(
            "settle_kernel", (16, 256, 2), ["pallas"], clock, "fused"
        ) == "fused"
        assert sorted(calls) == ["fused", "pallas"]

    def test_other_generation_falls_through(self, tmp_path):
        from bayesian_consensus_engine_tpu.utils.autotune import export_bank

        bank = export_bank(self._raced_cache(tmp_path))
        other = ShapeTuner(
            cache_path=str(tmp_path / "other.json"), enabled=False,
            device_kind="TPU v4", bank=bank,
        )
        # A v5e verdict never answers for v4: disabled + no applicable
        # bank entry → the caller's default, measure untouched.
        assert other.tune(
            "settle_kernel", (16, 256, 2), ["pallas"], None, "xla"
        ) == "xla"

    def test_schema_drift_ignores_bank_whole(self, tmp_path):
        from bayesian_consensus_engine_tpu.utils.autotune import (
            export_bank,
            load_bank,
        )

        bank = export_bank(self._raced_cache(tmp_path))
        bank["schema"] = "bce-autotune-bank/v0"
        assert load_bank(bank) is None
        tuner = ShapeTuner(
            cache_path=str(tmp_path / "drifted.json"), enabled=False,
            device_kind=self.KIND, bank=bank,
        )
        assert tuner.tune(
            "settle_kernel", (16, 256, 2), ["pallas"], None, "xla"
        ) == "xla"

    def test_validate_bank_catches_drift(self):
        from bayesian_consensus_engine_tpu.utils.autotune import validate_bank

        entry = {
            "knob": "settle_kernel", "shape_key": [4], "generation":
            "tpu-v5e", "choice": "pallas", "default": "xla",
            "beat_default": True, "timings_s": {"pallas": 1.0},
        }
        good = {"schema": "bce-autotune-bank/v1", "entries": [entry]}
        assert validate_bank(good) == []
        assert validate_bank({"schema": "???", "entries": []})
        assert validate_bank(
            {"schema": "bce-autotune-bank/v1", "entries": [
                {k: v for k, v in entry.items() if k != "default"}
            ]}
        )
        assert validate_bank(
            {"schema": "bce-autotune-bank/v1", "entries": [entry, entry]}
        )  # duplicate identity
        assert validate_bank(
            {"schema": "bce-autotune-bank/v1", "entries": [
                dict(entry, generation="TPU v5e")  # un-normalised
            ]}
        )

    def test_merge_keeps_better_evidence_and_refuses_flips(self, tmp_path):
        from bayesian_consensus_engine_tpu.utils.autotune import merge_banks

        entry = {
            "knob": "settle_kernel", "shape_key": [4], "generation":
            "tpu-v5e", "choice": "pallas", "default": "xla",
            "beat_default": True, "timings_s": {"pallas": 1.0, "xla": 2.0},
        }
        faster = dict(entry, timings_s={"pallas": 0.5, "xla": 2.0})
        a = {"schema": "bce-autotune-bank/v1", "entries": [entry]}
        b = {"schema": "bce-autotune-bank/v1", "entries": [faster]}
        merged = merge_banks(a, b)
        (kept,) = merged["entries"]
        assert kept["timings_s"]["pallas"] == 0.5

        flip = dict(entry, choice="xla", beat_default=False)
        c = {"schema": "bce-autotune-bank/v1", "entries": [flip]}
        with pytest.raises(ValueError, match="verdict flip"):
            merge_banks(a, c)
