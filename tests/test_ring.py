"""Ring / all-to-all source parallelism on the virtual 8-device CPU mesh.

The ring cycle must agree with the psum cycle and the unsharded cycle; the
explicit ppermute ring-allreduce must agree with psum; the ring tie-break
must agree with the scalar ``DeterministicTieBreaker`` on every metric it
reports (winner, density, max reliability, resolution label, group count,
confidence variance).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bayesian_consensus_engine_tpu.parallel._jax_compat import shard_map

from bayesian_consensus_engine_tpu.models.tiebreak import (
    AgentSignal,
    DeterministicTieBreaker,
)
from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle,
    make_mesh,
)
from bayesian_consensus_engine_tpu.parallel.mesh import (
    MARKETS_AXIS,
    SOURCES_AXIS,
)
from bayesian_consensus_engine_tpu.parallel.ring import (
    REDUCE_SPEC,
    UPDATE_SPEC,
    build_ring_cycle,
    build_ring_cycle_loop,
    build_ring_tiebreak,
    reshard,
    ring_allreduce,
)

M, K = 32, 16


def _random_inputs(seed=0):
    rng = np.random.default_rng(seed)
    probs = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)
    mask = jnp.asarray(rng.random((M, K)) < 0.7)
    outcome = jnp.asarray(rng.random(M) < 0.5)
    state = MarketBlockState(
        reliability=jnp.asarray(rng.uniform(0.1, 1.0, (M, K)), dtype=jnp.float32),
        confidence=jnp.asarray(rng.uniform(0.0, 1.0, (M, K)), dtype=jnp.float32),
        updated_days=jnp.asarray(
            rng.choice([0.0, 5.0, 40.0, 400.0], (M, K)), dtype=jnp.float32
        ),
        exists=jnp.asarray(rng.random((M, K)) < 0.6),
    )
    now = jnp.float32(401.0)
    return probs, mask, outcome, state, now


class TestRingAllreduce:
    @pytest.mark.parametrize("s_axis", [2, 4, 8])
    def test_matches_psum(self, s_axis):
        mesh = make_mesh((8 // s_axis, s_axis))
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)

        def via_ring(x):
            return ring_allreduce(jnp.sum(x, axis=-1), SOURCES_AXIS, s_axis)

        def via_psum(x):
            return jax.lax.psum(jnp.sum(x, axis=-1), SOURCES_AXIS)

        spec = P(MARKETS_AXIS, SOURCES_AXIS)
        out_spec = P(MARKETS_AXIS)
        ring = shard_map(
            via_ring, mesh=mesh, in_specs=spec, out_specs=out_spec, check_vma=False
        )
        psum = shard_map(via_psum, mesh=mesh, in_specs=spec, out_specs=out_spec)
        np.testing.assert_allclose(
            np.asarray(ring(x)), np.asarray(psum(x)), rtol=1e-6
        )

    def test_single_shard_identity(self):
        mesh = make_mesh((8, 1))

        def f(x):
            return ring_allreduce(x, SOURCES_AXIS, 1)

        fn = shard_map(
            f,
            mesh=mesh,
            in_specs=P(MARKETS_AXIS, SOURCES_AXIS),
            out_specs=P(MARKETS_AXIS, SOURCES_AXIS),
            check_vma=False,
        )
        x = jnp.arange(M * K, dtype=jnp.float32).reshape(M, K)
        np.testing.assert_array_equal(np.asarray(fn(x)), np.asarray(x))


class TestRingCycle:
    @pytest.mark.parametrize("shape", [(1, 8), (2, 4), (4, 2)])
    @pytest.mark.parametrize("chunk_slots", [None, 3, 8])
    def test_matches_psum_cycle(self, shape, chunk_slots):
        mesh = make_mesh(shape)
        inputs = _random_inputs()
        baseline = build_cycle(make_mesh((8, 1)), donate=False)(*inputs)
        ring = build_ring_cycle(mesh, chunk_slots=chunk_slots, donate=False)(*inputs)

        np.testing.assert_allclose(
            np.asarray(ring.consensus),
            np.asarray(baseline.consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(ring.confidence),
            np.asarray(baseline.confidence),
            rtol=2e-6,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(ring.total_weight),
            np.asarray(baseline.total_weight),
            rtol=2e-6,
        )
        # The update phase is elementwise and order-independent: exact.
        for got, want in zip(ring.state, baseline.state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_exists_none_reduced_carry(self):
        # The cycle loop's reduced carry (exists=None, cold slots already at
        # the defaults) must run through the ring cycle and match the psum
        # cycle on the same state.
        from bayesian_consensus_engine_tpu.utils.config import (
            DEFAULT_CONFIDENCE,
            DEFAULT_RELIABILITY,
        )

        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now = _random_inputs()
        reduced = MarketBlockState(
            reliability=jnp.where(state.exists, state.reliability, DEFAULT_RELIABILITY),
            confidence=jnp.where(state.exists, state.confidence, DEFAULT_CONFIDENCE),
            updated_days=jnp.where(state.exists, state.updated_days, 0.0),
            exists=None,
        )
        baseline = build_cycle(make_mesh((8, 1)), donate=False)(
            probs, mask, outcome, reduced, now
        )
        ring = build_ring_cycle(mesh, chunk_slots=4, donate=False)(
            probs, mask, outcome, reduced, now
        )
        np.testing.assert_allclose(
            np.asarray(ring.consensus),
            np.asarray(baseline.consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        assert ring.state.exists is None
        for got, want in zip(ring.state[:3], baseline.state[:3]):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_no_signals_market(self):
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now = _random_inputs()
        mask = mask.at[0].set(False)
        result = build_ring_cycle(mesh, donate=False)(
            probs, mask, outcome, state, now
        )
        out = np.asarray(result.consensus)
        assert np.isnan(out[0])
        assert np.asarray(result.total_weight)[0] == 0.0


class TestRingCycleLoop:
    @pytest.mark.parametrize("shape", [(1, 8), (2, 4)])
    @pytest.mark.parametrize("chunk_slots", [None, 5])
    def test_matches_chained_single_cycles(self, shape, chunk_slots):
        mesh = make_mesh(shape)
        probs, mask, outcome, state, _ = _random_inputs(seed=7)
        now0 = jnp.float32(401.0)
        steps = 3

        single = build_cycle(make_mesh((8, 1)), donate=False)
        want_state = state
        for i in range(steps):
            result = single(probs, mask, outcome, want_state, now0 + i)
            want_state, want_consensus = result.state, result.consensus

        loop = build_ring_cycle_loop(mesh, chunk_slots=chunk_slots, donate=False)
        got_state, got_consensus = loop(probs, mask, outcome, state, now0, steps)

        np.testing.assert_allclose(
            np.asarray(got_consensus),
            np.asarray(want_consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        # Reductions feed nothing back into the state: updates stay exact.
        for got, want in zip(got_state, want_state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_exists_none_carry(self):
        from bayesian_consensus_engine_tpu.utils.config import (
            DEFAULT_CONFIDENCE,
            DEFAULT_RELIABILITY,
        )

        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, _ = _random_inputs(seed=8)
        reduced = MarketBlockState(
            reliability=jnp.where(state.exists, state.reliability, DEFAULT_RELIABILITY),
            confidence=jnp.where(state.exists, state.confidence, DEFAULT_CONFIDENCE),
            updated_days=jnp.where(state.exists, state.updated_days, 0.0),
            exists=None,
        )
        now0 = jnp.float32(401.0)
        single = build_cycle(make_mesh((8, 1)), donate=False)
        want_state = reduced
        for i in range(2):
            result = single(probs, mask, outcome, want_state, now0 + i)
            want_state, want_consensus = result.state, result.consensus

        loop = build_ring_cycle_loop(mesh, chunk_slots=6, donate=False)
        got_state, got_consensus = loop(probs, mask, outcome, reduced, now0, 2)
        assert got_state.exists is None
        np.testing.assert_allclose(
            np.asarray(got_consensus),
            np.asarray(want_consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        for got, want in zip(got_state[:3], want_state[:3]):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_zero_steps_identity(self):
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now = _random_inputs(seed=9)
        loop = build_ring_cycle_loop(mesh, donate=False)
        got_state, consensus = loop(probs, mask, outcome, state, now, 0)
        for got, want in zip(got_state, state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert not np.any(np.asarray(consensus))

    def test_resume_matches_uninterrupted(self):
        # The shared fast-loop scaffold's bit-identity contract holds for
        # the ring loop too: 3+2 resumed == 5 uninterrupted, bit-for-bit
        # (the single-trip-fori hazard the scaffold guards against —
        # see run_fast_loop in parallel/sharded.py).
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, _ = _random_inputs(seed=10)
        loop = build_ring_cycle_loop(mesh, chunk_slots=6, donate=False)
        full_state, full_cons = loop(
            probs, mask, outcome, state, jnp.float32(10.0), 5
        )
        mid_state, _ = loop(probs, mask, outcome, state, jnp.float32(10.0), 3)
        res_state, res_cons = loop(
            probs, mask, outcome, mid_state, jnp.float32(13.0), 2
        )
        np.testing.assert_array_equal(
            np.asarray(res_cons), np.asarray(full_cons)
        )
        for got, want in zip(res_state, full_state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestReshard:
    def test_round_trip_and_layouts(self):
        mesh = make_mesh((2, 4))
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)
        x_reduce = reshard(x, mesh, REDUCE_SPEC)
        x_update = reshard(x_reduce, mesh, UPDATE_SPEC)
        assert x_update.sharding.spec == UPDATE_SPEC
        back = reshard(x_update, mesh, REDUCE_SPEC)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    def test_update_layout_fully_splits_markets(self):
        mesh = make_mesh((2, 4))
        x = jnp.zeros((M, K), dtype=jnp.float32)
        x_update = reshard(x, mesh, UPDATE_SPEC)
        shard_shapes = {s.data.shape for s in x_update.addressable_shards}
        assert shard_shapes == {(M // 8, K)}


def _scalar_resolve(agents):
    pred, diag = DeterministicTieBreaker().resolve(agents)
    return pred, diag


_LABELS = {0: "unanimous", 1: "weight_density", 2: "prediction_value_smallest"}


class TestRingTieBreak:
    def _run_one(self, agents, mesh, a_total=16):
        """One market row, padded to *a_total* agent lanes."""
        n = len(agents)
        pad = a_total - n
        pred = jnp.asarray(
            [[a.prediction for a in agents] + [0.0] * pad], dtype=jnp.float32
        )
        weight = jnp.asarray(
            [[a.weight for a in agents] + [0.0] * pad], dtype=jnp.float32
        )
        conf = jnp.asarray(
            [[a.confidence for a in agents] + [0.0] * pad], dtype=jnp.float32
        )
        rel = jnp.asarray(
            [[a.reliability_score for a in agents] + [0.0] * pad],
            dtype=jnp.float32,
        )
        valid = jnp.asarray([[True] * n + [False] * pad])
        # markets axis of size 1 → mesh (1, 8): all devices on agents.
        result = build_ring_tiebreak(mesh)(pred, weight, conf, rel, valid)
        return jax.tree.map(lambda x: np.asarray(x)[0], result)

    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh((1, 8))

    def test_density_winner(self, mesh):
        agents = [
            AgentSignal("a", 0.7, 0.9, weight=2.0, reliability_score=0.8),
            AgentSignal("b", 0.7, 0.8, weight=2.0, reliability_score=0.6),
            AgentSignal("c", 0.3, 0.7, weight=1.0, reliability_score=0.9),
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert _LABELS[int(got.resolved_by)] == want_diag.tie_resolved_by
        assert int(got.num_groups) == len(want_diag.groups)
        assert got.confidence_variance == pytest.approx(
            want_diag.confidence_variance, abs=1e-5
        )

    def test_reliability_breaks_density_tie_labeled_density(self, mesh):
        # Quirk #6: decision falls to max_reliability, label stays
        # weight_density.
        agents = [
            AgentSignal("a", 0.6, 0.5, weight=1.0, reliability_score=0.9),
            AgentSignal("b", 0.4, 0.5, weight=1.0, reliability_score=0.2),
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert want_diag.tie_resolved_by == "weight_density"
        assert _LABELS[int(got.resolved_by)] == "weight_density"

    def test_full_tie_smallest_prediction(self, mesh):
        agents = [
            AgentSignal("a", 0.8, 0.5, weight=1.0, reliability_score=0.5),
            AgentSignal("b", 0.2, 0.5, weight=1.0, reliability_score=0.5),
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert want_pred == 0.2
        assert got.prediction == pytest.approx(0.2, abs=1e-6)
        assert want_diag.tie_resolved_by == "prediction_value_smallest"
        assert _LABELS[int(got.resolved_by)] == "prediction_value_smallest"

    def test_unanimous(self, mesh):
        agents = [
            AgentSignal("a", 0.55, 0.5, weight=1.0, reliability_score=0.5),
            AgentSignal("b", 0.55, 0.9, weight=3.0, reliability_score=0.7),
        ]
        _, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert want_diag.tie_resolved_by == "unanimous"
        assert _LABELS[int(got.resolved_by)] == "unanimous"
        assert int(got.num_groups) == 1

    @pytest.mark.parametrize("seed", range(5))
    def test_random_parity_with_scalar(self, mesh, seed):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(2, 16))
        # Predictions on a coarse grid: decimal-exact at precision 6, and
        # coarse enough to actually form groups.
        agents = [
            AgentSignal(
                f"a{i}",
                float(rng.choice([0.1, 0.25, 0.5, 0.75, 0.9])),
                float(rng.uniform(0, 1)),
                weight=float(rng.uniform(0.1, 3.0)),
                reliability_score=float(rng.uniform(0, 1)),
            )
            for i in range(n)
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert int(got.num_groups) == len(want_diag.groups)
        want_group = want_diag.groups[round(want_pred, 6)]
        assert got.weight_density == pytest.approx(
            want_group["weight_density"], abs=1e-3
        )
        assert got.max_reliability == pytest.approx(
            want_group["max_reliability"], abs=1e-3
        )
        assert got.confidence_variance == pytest.approx(
            want_diag.confidence_variance, abs=1e-4
        )

    def test_big_batch_many_markets(self, mesh):
        # (M markets × 64 agents) batched tie-break, agents ring-sharded.
        rng = np.random.default_rng(42)
        m, a = 16, 64
        grid = np.array([0.1, 0.3, 0.5, 0.7, 0.9])
        pred = jnp.asarray(rng.choice(grid, (m, a)), dtype=jnp.float32)
        weight = jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), dtype=jnp.float32)
        conf = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        rel = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        valid = jnp.asarray(rng.random((m, a)) < 0.9)

        result = build_ring_tiebreak(mesh)(pred, weight, conf, rel, valid)
        self._assert_rows_match_scalar(result, pred, weight, conf, rel, valid, m, a)

    def test_origin_buffer_shrinks_with_markets_sharding(self):
        """Pin the documented at-scale memory mitigation (ring.py origin
        buffer): per shard the buffer is f32[ring, M_loc, A_loc], so moving
        devices from the agents axis to the markets axis shrinks it — (2,4)
        carries HALF the per-device origin bytes of (1,8) at the same global
        shape. Checked against the actual lowered program, not the docstring.

        (CPU ``memory_analysis`` is deliberately NOT used here: the CPU
        lowering materialises the pairwise compare as an O(M·A²) temp that
        TPU fuses away — bench.py's on-chip ``ring_compiled_temp_mb`` is the
        hardware number — so its totals say nothing about the TPU buffer.)
        """
        m, a = 1024, 4096
        rng = np.random.default_rng(47)
        grid = np.array([0.2, 0.4, 0.6, 0.8])
        args = (
            jnp.asarray(rng.choice(grid, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.random((m, a)) < 0.9),
        )

        def assert_origin_buffer(mesh_shape):
            # The pin IS the token-presence check: the per-shard buffer of
            # shape (ring, M_loc, A_loc) must appear in the lowered program.
            # 8×1024×512 at (1,8) vs 4×512×1024 at (2,4): the byte halving
            # follows arithmetically from the pinned shapes.
            ring = mesh_shape[1]
            m_loc, a_loc = m // mesh_shape[0], a // mesh_shape[1]
            text = build_ring_tiebreak(make_mesh(mesh_shape)).lower(*args).as_text()
            token = f"{ring}x{m_loc}x{a_loc}xf32"
            assert token in text, token

        assert_origin_buffer((1, 8))
        assert_origin_buffer((2, 4))

    def test_markets_axis_sharded_too(self):
        # (2, 4) mesh: the markets axis of the tie-break shard_map is
        # actually sharded — the configuration the 10k-agent scale docstring
        # recommends (origin buffer shrinks with M_loc).
        mesh24 = make_mesh((2, 4))
        rng = np.random.default_rng(43)
        m, a = 16, 32
        grid = np.array([0.2, 0.4, 0.6, 0.8])
        pred = jnp.asarray(rng.choice(grid, (m, a)), dtype=jnp.float32)
        weight = jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), dtype=jnp.float32)
        conf = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        rel = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        valid = jnp.asarray(rng.random((m, a)) < 0.9)

        result = build_ring_tiebreak(mesh24)(pred, weight, conf, rel, valid)
        self._assert_rows_match_scalar(result, pred, weight, conf, rel, valid, m, a)

    @staticmethod
    def _assert_rows_match_scalar(result, pred, weight, conf, rel, valid, m, a):
        breaker = DeterministicTieBreaker()
        for row in range(m):
            agents = [
                AgentSignal(
                    f"s{j}",
                    float(pred[row, j]),
                    float(conf[row, j]),
                    weight=float(weight[row, j]),
                    reliability_score=float(rel[row, j]),
                )
                for j in range(a)
                if bool(valid[row, j])
            ]
            if not agents:
                continue
            want_pred, want_diag = breaker.resolve(agents)
            assert np.asarray(result.prediction)[row] == pytest.approx(
                want_pred, abs=1e-6
            ), f"row {row}"
            assert (
                _LABELS[int(np.asarray(result.resolved_by)[row])]
                == want_diag.tie_resolved_by
            ), f"row {row}"
