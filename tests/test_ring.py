"""Ring / all-to-all source parallelism on the virtual 8-device CPU mesh.

The ring cycle must agree with the psum cycle and the unsharded cycle; the
explicit ppermute ring-allreduce must agree with psum; the ring tie-break
must agree with the scalar ``DeterministicTieBreaker`` on every metric it
reports (winner, density, max reliability, resolution label, group count,
confidence variance).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bayesian_consensus_engine_tpu.parallel._jax_compat import shard_map

from bayesian_consensus_engine_tpu.models.tiebreak import (
    AgentSignal,
    DeterministicTieBreaker,
)
from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle,
    make_mesh,
)
from bayesian_consensus_engine_tpu.parallel.mesh import (
    MARKETS_AXIS,
    SOURCES_AXIS,
)
from bayesian_consensus_engine_tpu.parallel.ring import (
    REDUCE_SPEC,
    UPDATE_SPEC,
    build_ring_cycle,
    build_ring_cycle_loop,
    build_ring_tiebreak,
    reshard,
    ring_allreduce,
)

M, K = 32, 16


def _random_inputs(seed=0):
    rng = np.random.default_rng(seed)
    probs = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)
    mask = jnp.asarray(rng.random((M, K)) < 0.7)
    outcome = jnp.asarray(rng.random(M) < 0.5)
    state = MarketBlockState(
        reliability=jnp.asarray(rng.uniform(0.1, 1.0, (M, K)), dtype=jnp.float32),
        confidence=jnp.asarray(rng.uniform(0.0, 1.0, (M, K)), dtype=jnp.float32),
        updated_days=jnp.asarray(
            rng.choice([0.0, 5.0, 40.0, 400.0], (M, K)), dtype=jnp.float32
        ),
        exists=jnp.asarray(rng.random((M, K)) < 0.6),
    )
    now = jnp.float32(401.0)
    return probs, mask, outcome, state, now


class TestRingAllreduce:
    @pytest.mark.parametrize("s_axis", [2, 4, 8])
    def test_matches_psum(self, s_axis):
        mesh = make_mesh((8 // s_axis, s_axis))
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)

        def via_ring(x):
            return ring_allreduce(jnp.sum(x, axis=-1), SOURCES_AXIS, s_axis)

        def via_psum(x):
            return jax.lax.psum(jnp.sum(x, axis=-1), SOURCES_AXIS)

        spec = P(MARKETS_AXIS, SOURCES_AXIS)
        out_spec = P(MARKETS_AXIS)
        ring = shard_map(
            via_ring, mesh=mesh, in_specs=spec, out_specs=out_spec, check_vma=False
        )
        psum = shard_map(via_psum, mesh=mesh, in_specs=spec, out_specs=out_spec)
        np.testing.assert_allclose(
            np.asarray(ring(x)), np.asarray(psum(x)), rtol=1e-6
        )

    def test_single_shard_identity(self):
        mesh = make_mesh((8, 1))

        def f(x):
            return ring_allreduce(x, SOURCES_AXIS, 1)

        fn = shard_map(
            f,
            mesh=mesh,
            in_specs=P(MARKETS_AXIS, SOURCES_AXIS),
            out_specs=P(MARKETS_AXIS, SOURCES_AXIS),
            check_vma=False,
        )
        x = jnp.arange(M * K, dtype=jnp.float32).reshape(M, K)
        np.testing.assert_array_equal(np.asarray(fn(x)), np.asarray(x))


class TestRingCycle:
    @pytest.mark.parametrize("shape", [(1, 8), (2, 4), (4, 2)])
    @pytest.mark.parametrize("chunk_slots", [None, 3, 8])
    def test_matches_psum_cycle(self, shape, chunk_slots):
        mesh = make_mesh(shape)
        inputs = _random_inputs()
        baseline = build_cycle(make_mesh((8, 1)), donate=False)(*inputs)
        ring = build_ring_cycle(mesh, chunk_slots=chunk_slots, donate=False)(*inputs)

        np.testing.assert_allclose(
            np.asarray(ring.consensus),
            np.asarray(baseline.consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(ring.confidence),
            np.asarray(baseline.confidence),
            rtol=2e-6,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(ring.total_weight),
            np.asarray(baseline.total_weight),
            rtol=2e-6,
        )
        # The update phase is elementwise and order-independent: exact.
        for got, want in zip(ring.state, baseline.state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_exists_none_reduced_carry(self):
        # The cycle loop's reduced carry (exists=None, cold slots already at
        # the defaults) must run through the ring cycle and match the psum
        # cycle on the same state.
        from bayesian_consensus_engine_tpu.utils.config import (
            DEFAULT_CONFIDENCE,
            DEFAULT_RELIABILITY,
        )

        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now = _random_inputs()
        reduced = MarketBlockState(
            reliability=jnp.where(state.exists, state.reliability, DEFAULT_RELIABILITY),
            confidence=jnp.where(state.exists, state.confidence, DEFAULT_CONFIDENCE),
            updated_days=jnp.where(state.exists, state.updated_days, 0.0),
            exists=None,
        )
        baseline = build_cycle(make_mesh((8, 1)), donate=False)(
            probs, mask, outcome, reduced, now
        )
        ring = build_ring_cycle(mesh, chunk_slots=4, donate=False)(
            probs, mask, outcome, reduced, now
        )
        np.testing.assert_allclose(
            np.asarray(ring.consensus),
            np.asarray(baseline.consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        assert ring.state.exists is None
        for got, want in zip(ring.state[:3], baseline.state[:3]):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_no_signals_market(self):
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now = _random_inputs()
        mask = mask.at[0].set(False)
        result = build_ring_cycle(mesh, donate=False)(
            probs, mask, outcome, state, now
        )
        out = np.asarray(result.consensus)
        assert np.isnan(out[0])
        assert np.asarray(result.total_weight)[0] == 0.0


class TestRingCycleLoop:
    @pytest.mark.parametrize("shape", [(1, 8), (2, 4)])
    @pytest.mark.parametrize("chunk_slots", [None, 5])
    def test_matches_chained_single_cycles(self, shape, chunk_slots):
        mesh = make_mesh(shape)
        probs, mask, outcome, state, _ = _random_inputs(seed=7)
        now0 = jnp.float32(401.0)
        steps = 3

        single = build_cycle(make_mesh((8, 1)), donate=False)
        want_state = state
        for i in range(steps):
            result = single(probs, mask, outcome, want_state, now0 + i)
            want_state, want_consensus = result.state, result.consensus

        loop = build_ring_cycle_loop(mesh, chunk_slots=chunk_slots, donate=False)
        got_state, got_consensus = loop(probs, mask, outcome, state, now0, steps)

        np.testing.assert_allclose(
            np.asarray(got_consensus),
            np.asarray(want_consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        # Reductions feed nothing back into the state: updates stay exact.
        for got, want in zip(got_state, want_state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_exists_none_carry(self):
        from bayesian_consensus_engine_tpu.utils.config import (
            DEFAULT_CONFIDENCE,
            DEFAULT_RELIABILITY,
        )

        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, _ = _random_inputs(seed=8)
        reduced = MarketBlockState(
            reliability=jnp.where(state.exists, state.reliability, DEFAULT_RELIABILITY),
            confidence=jnp.where(state.exists, state.confidence, DEFAULT_CONFIDENCE),
            updated_days=jnp.where(state.exists, state.updated_days, 0.0),
            exists=None,
        )
        now0 = jnp.float32(401.0)
        single = build_cycle(make_mesh((8, 1)), donate=False)
        want_state = reduced
        for i in range(2):
            result = single(probs, mask, outcome, want_state, now0 + i)
            want_state, want_consensus = result.state, result.consensus

        loop = build_ring_cycle_loop(mesh, chunk_slots=6, donate=False)
        got_state, got_consensus = loop(probs, mask, outcome, reduced, now0, 2)
        assert got_state.exists is None
        np.testing.assert_allclose(
            np.asarray(got_consensus),
            np.asarray(want_consensus),
            rtol=2e-6,
            atol=1e-6,
        )
        for got, want in zip(got_state[:3], want_state[:3]):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_zero_steps_identity(self):
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, now = _random_inputs(seed=9)
        loop = build_ring_cycle_loop(mesh, donate=False)
        got_state, consensus = loop(probs, mask, outcome, state, now, 0)
        for got, want in zip(got_state, state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert not np.any(np.asarray(consensus))

    def test_resume_matches_uninterrupted(self):
        # The shared fast-loop scaffold's bit-identity contract holds for
        # the ring loop too: 3+2 resumed == 5 uninterrupted, bit-for-bit
        # (the single-trip-fori hazard the scaffold guards against —
        # see run_fast_loop in parallel/sharded.py).
        mesh = make_mesh((2, 4))
        probs, mask, outcome, state, _ = _random_inputs(seed=10)
        loop = build_ring_cycle_loop(mesh, chunk_slots=6, donate=False)
        full_state, full_cons = loop(
            probs, mask, outcome, state, jnp.float32(10.0), 5
        )
        mid_state, _ = loop(probs, mask, outcome, state, jnp.float32(10.0), 3)
        res_state, res_cons = loop(
            probs, mask, outcome, mid_state, jnp.float32(13.0), 2
        )
        np.testing.assert_array_equal(
            np.asarray(res_cons), np.asarray(full_cons)
        )
        for got, want in zip(res_state, full_state):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestReshard:
    def test_round_trip_and_layouts(self):
        mesh = make_mesh((2, 4))
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.random((M, K)), dtype=jnp.float32)
        x_reduce = reshard(x, mesh, REDUCE_SPEC)
        x_update = reshard(x_reduce, mesh, UPDATE_SPEC)
        assert x_update.sharding.spec == UPDATE_SPEC
        back = reshard(x_update, mesh, REDUCE_SPEC)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    def test_update_layout_fully_splits_markets(self):
        mesh = make_mesh((2, 4))
        x = jnp.zeros((M, K), dtype=jnp.float32)
        x_update = reshard(x, mesh, UPDATE_SPEC)
        shard_shapes = {s.data.shape for s in x_update.addressable_shards}
        assert shard_shapes == {(M // 8, K)}


def _scalar_resolve(agents):
    pred, diag = DeterministicTieBreaker().resolve(agents)
    return pred, diag


_LABELS = {0: "unanimous", 1: "weight_density", 2: "prediction_value_smallest"}


class TestRingTieBreak:
    def _run_one(self, agents, mesh, a_total=16):
        """One market row, padded to *a_total* agent lanes."""
        n = len(agents)
        pad = a_total - n
        pred = jnp.asarray(
            [[a.prediction for a in agents] + [0.0] * pad], dtype=jnp.float32
        )
        weight = jnp.asarray(
            [[a.weight for a in agents] + [0.0] * pad], dtype=jnp.float32
        )
        conf = jnp.asarray(
            [[a.confidence for a in agents] + [0.0] * pad], dtype=jnp.float32
        )
        rel = jnp.asarray(
            [[a.reliability_score for a in agents] + [0.0] * pad],
            dtype=jnp.float32,
        )
        valid = jnp.asarray([[True] * n + [False] * pad])
        # markets axis of size 1 → mesh (1, 8): all devices on agents.
        result = build_ring_tiebreak(mesh)(pred, weight, conf, rel, valid)
        return jax.tree.map(lambda x: np.asarray(x)[0], result)

    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh((1, 8))

    def test_density_winner(self, mesh):
        agents = [
            AgentSignal("a", 0.7, 0.9, weight=2.0, reliability_score=0.8),
            AgentSignal("b", 0.7, 0.8, weight=2.0, reliability_score=0.6),
            AgentSignal("c", 0.3, 0.7, weight=1.0, reliability_score=0.9),
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert _LABELS[int(got.resolved_by)] == want_diag.tie_resolved_by
        assert int(got.num_groups) == len(want_diag.groups)
        assert got.confidence_variance == pytest.approx(
            want_diag.confidence_variance, abs=1e-5
        )

    def test_reliability_breaks_density_tie_labeled_density(self, mesh):
        # Quirk #6: decision falls to max_reliability, label stays
        # weight_density.
        agents = [
            AgentSignal("a", 0.6, 0.5, weight=1.0, reliability_score=0.9),
            AgentSignal("b", 0.4, 0.5, weight=1.0, reliability_score=0.2),
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert want_diag.tie_resolved_by == "weight_density"
        assert _LABELS[int(got.resolved_by)] == "weight_density"

    def test_full_tie_smallest_prediction(self, mesh):
        agents = [
            AgentSignal("a", 0.8, 0.5, weight=1.0, reliability_score=0.5),
            AgentSignal("b", 0.2, 0.5, weight=1.0, reliability_score=0.5),
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert want_pred == 0.2
        assert got.prediction == pytest.approx(0.2, abs=1e-6)
        assert want_diag.tie_resolved_by == "prediction_value_smallest"
        assert _LABELS[int(got.resolved_by)] == "prediction_value_smallest"

    def test_unanimous(self, mesh):
        agents = [
            AgentSignal("a", 0.55, 0.5, weight=1.0, reliability_score=0.5),
            AgentSignal("b", 0.55, 0.9, weight=3.0, reliability_score=0.7),
        ]
        _, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert want_diag.tie_resolved_by == "unanimous"
        assert _LABELS[int(got.resolved_by)] == "unanimous"
        assert int(got.num_groups) == 1

    @pytest.mark.parametrize("seed", range(5))
    def test_random_parity_with_scalar(self, mesh, seed):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(2, 16))
        # Predictions on a coarse grid: decimal-exact at precision 6, and
        # coarse enough to actually form groups.
        agents = [
            AgentSignal(
                f"a{i}",
                float(rng.choice([0.1, 0.25, 0.5, 0.75, 0.9])),
                float(rng.uniform(0, 1)),
                weight=float(rng.uniform(0.1, 3.0)),
                reliability_score=float(rng.uniform(0, 1)),
            )
            for i in range(n)
        ]
        want_pred, want_diag = _scalar_resolve(list(agents))
        got = self._run_one(agents, mesh)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert int(got.num_groups) == len(want_diag.groups)
        want_group = want_diag.groups[round(want_pred, 6)]
        assert got.weight_density == pytest.approx(
            want_group["weight_density"], abs=1e-3
        )
        assert got.max_reliability == pytest.approx(
            want_group["max_reliability"], abs=1e-3
        )
        assert got.confidence_variance == pytest.approx(
            want_diag.confidence_variance, abs=1e-4
        )

    def test_big_batch_many_markets(self, mesh):
        # (M markets × 64 agents) batched tie-break, agents ring-sharded.
        rng = np.random.default_rng(42)
        m, a = 16, 64
        grid = np.array([0.1, 0.3, 0.5, 0.7, 0.9])
        pred = jnp.asarray(rng.choice(grid, (m, a)), dtype=jnp.float32)
        weight = jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), dtype=jnp.float32)
        conf = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        rel = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        valid = jnp.asarray(rng.random((m, a)) < 0.9)

        result = build_ring_tiebreak(mesh)(pred, weight, conf, rel, valid)
        self._assert_rows_match_scalar(result, pred, weight, conf, rel, valid, m, a)

    def test_origin_buffer_shrinks_with_markets_sharding(self):
        """Pin the documented at-scale memory mitigation (ring.py origin
        buffer): per shard the buffer is f32[ring, M_loc, A_loc], so moving
        devices from the agents axis to the markets axis shrinks it — (2,4)
        carries HALF the per-device origin bytes of (1,8) at the same global
        shape. Checked against the actual lowered program, not the docstring.

        (CPU ``memory_analysis`` is deliberately NOT used here: the CPU
        lowering materialises the pairwise compare as an O(M·A²) temp that
        TPU fuses away — bench.py's on-chip ``ring_compiled_temp_mb`` is the
        hardware number — so its totals say nothing about the TPU buffer.)
        """
        m, a = 1024, 4096
        rng = np.random.default_rng(47)
        grid = np.array([0.2, 0.4, 0.6, 0.8])
        args = (
            jnp.asarray(rng.choice(grid, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32),
            jnp.asarray(rng.random((m, a)) < 0.9),
        )

        def assert_origin_buffer(mesh_shape):
            # The pin IS the token-presence check: the per-shard buffer of
            # shape (ring, M_loc, A_loc) must appear in the lowered program.
            # 8×1024×512 at (1,8) vs 4×512×1024 at (2,4): the byte halving
            # follows arithmetically from the pinned shapes.
            ring = mesh_shape[1]
            m_loc, a_loc = m // mesh_shape[0], a // mesh_shape[1]
            text = build_ring_tiebreak(make_mesh(mesh_shape)).lower(*args).as_text()
            token = f"{ring}x{m_loc}x{a_loc}xf32"
            assert token in text, token

        assert_origin_buffer((1, 8))
        assert_origin_buffer((2, 4))

    def test_markets_axis_sharded_too(self):
        # (2, 4) mesh: the markets axis of the tie-break shard_map is
        # actually sharded — the configuration the 10k-agent scale docstring
        # recommends (origin buffer shrinks with M_loc).
        mesh24 = make_mesh((2, 4))
        rng = np.random.default_rng(43)
        m, a = 16, 32
        grid = np.array([0.2, 0.4, 0.6, 0.8])
        pred = jnp.asarray(rng.choice(grid, (m, a)), dtype=jnp.float32)
        weight = jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), dtype=jnp.float32)
        conf = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        rel = jnp.asarray(rng.uniform(0, 1, (m, a)), dtype=jnp.float32)
        valid = jnp.asarray(rng.random((m, a)) < 0.9)

        result = build_ring_tiebreak(mesh24)(pred, weight, conf, rel, valid)
        self._assert_rows_match_scalar(result, pred, weight, conf, rel, valid, m, a)

    @staticmethod
    def _assert_rows_match_scalar(result, pred, weight, conf, rel, valid, m, a):
        breaker = DeterministicTieBreaker()
        for row in range(m):
            agents = [
                AgentSignal(
                    f"s{j}",
                    float(pred[row, j]),
                    float(conf[row, j]),
                    weight=float(weight[row, j]),
                    reliability_score=float(rel[row, j]),
                )
                for j in range(a)
                if bool(valid[row, j])
            ]
            if not agents:
                continue
            want_pred, want_diag = breaker.resolve(agents)
            assert np.asarray(result.prediction)[row] == pytest.approx(
                want_pred, abs=1e-6
            ), f"row {row}"
            assert (
                _LABELS[int(np.asarray(result.resolved_by)[row])]
                == want_diag.tie_resolved_by
            ), f"row {row}"


# ---------------------------------------------------------------------------
# Round 11: the chunked memory diet.
# ---------------------------------------------------------------------------


def _tb_args(m, a, workload, seed=0):
    """One (M, A) tie-break operand set for a named parity workload."""
    rng = np.random.default_rng(seed)
    grid = np.array([0.1, 0.25, 0.5, 0.75, 0.9])
    pred = rng.choice(grid, (m, a))
    valid = rng.random((m, a)) < 0.8
    if workload == "mask_holes":
        # Dense hole pattern incl. fully-invalid rows (padding markets).
        valid = rng.random((m, a)) < 0.5
        valid[0] = False
    elif workload == "all_tied":
        # Every agent in one group per market: unanimous everywhere.
        pred = np.broadcast_to(rng.choice(grid, (m, 1)), (m, a)).copy()
        valid = np.ones((m, a), dtype=bool)
    elif workload == "single_agent":
        # Exactly one valid agent per market: groups of size one.
        valid = np.zeros((m, a), dtype=bool)
        valid[np.arange(m), rng.integers(0, a, m)] = True
    else:
        assert workload == "random"
    return (
        jnp.asarray(pred, jnp.float32),
        jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (m, a)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (m, a)), jnp.float32),
        jnp.asarray(valid),
    )


class TestChunkedParityMatrix:
    """ISSUE-9 acceptance: chunked output BIT-EQUAL to unchunked, across
    chunk sizes (1, a ragged 7, an exact divisor, wider-than-the-shard) ×
    degenerate workloads, on agents-sharded AND markets-sharded meshes.
    The guarantees this leans on are structural (ops/tiebreak.py module
    comment): group sums never change their reduction expression with the
    chunk width, and the winner fold is selection-only over a total
    order — these tests are the empirical pin."""

    M, A = 16, 32

    @pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (8, 1)])
    @pytest.mark.parametrize(
        "workload", ["random", "mask_holes", "all_tied", "single_agent"]
    )
    def test_bit_exact_across_chunk_sizes(self, mesh_shape, workload):
        mesh = make_mesh(mesh_shape)
        args = _tb_args(self.M, self.A, workload)
        want = jax.tree.map(
            np.asarray, build_ring_tiebreak(mesh)(*args)
        )
        a_loc = self.A // mesh_shape[1]
        for chunk in (1, 7, a_loc // 2 or 1, self.A + 5):
            got = jax.tree.map(
                np.asarray,
                build_ring_tiebreak(mesh, chunk_agents=chunk)(*args),
            )
            for name, g, w in zip(want._fields, got, want):
                np.testing.assert_array_equal(
                    g, w, err_msg=f"{mesh_shape}/{workload}/chunk={chunk}/{name}"
                )

    def test_chunked_still_matches_scalar(self):
        # The chunked path through the full scalar-parity gauntlet (the
        # bit-exact-vs-unchunked matrix alone would be vacuous if both
        # were wrong together).
        mesh = make_mesh((1, 8))
        args = _tb_args(self.M, self.A, "random", seed=3)
        result = build_ring_tiebreak(mesh, chunk_agents=3)(*args)
        TestRingTieBreak._assert_rows_match_scalar(
            result, *[np.asarray(x) for x in args], self.M, self.A
        )

    def test_empty_market_reports_inf_prediction(self):
        # A row with no valid agent is padding: inf prediction, -inf
        # metrics, unanimous label, zero groups (the unchunked path's
        # historical behaviour, now explicit).
        mesh = make_mesh((1, 8))
        args = _tb_args(self.M, self.A, "mask_holes")
        result = build_ring_tiebreak(mesh, chunk_agents=4)(*args)
        assert np.asarray(result.prediction)[0] == np.inf
        assert np.asarray(result.weight_density)[0] == -np.inf
        assert int(np.asarray(result.resolved_by)[0]) == 0
        assert int(np.asarray(result.num_groups)[0]) == 0

    def test_bad_chunk_string_rejected(self):
        mesh = make_mesh((1, 8))
        args = _tb_args(self.M, self.A, "random")
        with pytest.raises(ValueError, match="auto"):
            build_ring_tiebreak(mesh, chunk_agents="wide")(*args)


class TestRingMemoryDiet:
    """The compile-temps ceiling, read from the same AOT
    ``memory_analysis()`` the bench leg reports. CPU lowering materialises
    the per-chunk compare mask (TPU fuses it — the on-chip numbers in the
    bench leg are the acceptance capture), so the tier-1 assertion is the
    structural one: chunked temps collapse relative to unchunked by ~the
    chunk fraction, and stay under an absolute ceiling scaled for the CPU
    materialisation."""

    def _mem(self, mesh, args, chunk):
        lowered = build_ring_tiebreak(mesh, chunk_agents=chunk).lower(*args)
        return lowered.compile().memory_analysis()

    def test_chunked_temps_collapse(self):
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        m, a = 64, 1024
        args = _tb_args(m, a, "random", seed=9)
        unchunked = self._mem(mesh, args, None)
        chunked = self._mem(mesh, args, 64)
        assert (
            chunked.temp_size_in_bytes
            < unchunked.temp_size_in_bytes / 8
        ), (chunked.temp_size_in_bytes, unchunked.temp_size_in_bytes)
        # Absolute ceiling: per-chunk mask (m·chunk·a bool) + stats, with
        # ~4× headroom for XLA bookkeeping — the diet holds even where the
        # compare mask materialises.
        assert chunked.temp_size_in_bytes <= 24 * 1024 * 1024
        # Argument blocks are untouched by the diet (same five operands).
        assert (
            chunked.argument_size_in_bytes
            == unchunked.argument_size_in_bytes
        )

    @pytest.mark.slow
    def test_stress_shape_compile_temps(self):
        # The full 2048×10k ISSUE shape, compile-only (running it needs a
        # TPU; the bench leg carries the on-chip capture). The unchunked
        # program's temps at this shape are catastrophic on any backend —
        # the chunked program must be at least an order of magnitude
        # smaller.
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        args = _tb_args(2048, 10_000, "random", seed=11)
        chunked = self._mem(mesh, args, 1024)
        unchunked = self._mem(mesh, args, None)
        assert (
            chunked.temp_size_in_bytes
            < unchunked.temp_size_in_bytes / 8
        )


class TestFusedCycleTieBreak:
    """build_cycle_tiebreak_loop: consensus+update+tie-break in ONE
    program against one resident block. The loop half must keep the plain
    loop's semantics; the tie-break half must equal the standalone ring
    path fed the same decayed read view."""

    def _slot_major_inputs(self, seed=5):
        from bayesian_consensus_engine_tpu.parallel import MarketBlockState

        rng = np.random.default_rng(seed)
        m, k = 32, 16
        # Exactly-representable values: the standalone path reduces the
        # agents axis in (M, A) layout, the fused one in (K, M) — equal
        # sums need exactly-representable weights (a 1-ulp association
        # difference between layouts is legal; within a layout the chunk
        # matrix is the bit-exact contract).
        grid = np.array([0.125, 0.25, 0.5, 0.75, 0.875])
        probs = jnp.asarray(rng.choice(grid, (k, m)), jnp.float32)
        mask = jnp.asarray(rng.random((k, m)) < 0.8)
        outcome = jnp.asarray(rng.random(m) < 0.5)
        state = MarketBlockState(
            reliability=jnp.asarray(
                rng.choice([0.25, 0.5, 0.625, 0.75], (k, m)), jnp.float32
            ),
            confidence=jnp.asarray(
                rng.choice([0.25, 0.5, 0.75], (k, m)), jnp.float32
            ),
            updated_days=jnp.zeros((k, m), jnp.float32),
            exists=jnp.asarray(rng.random((k, m)) < 0.6),
        )
        return probs, mask, outcome, state, jnp.float32(401.0)

    @pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
    def test_fused_equals_loop_plus_standalone(self, mesh_shape):
        from bayesian_consensus_engine_tpu.parallel import (
            build_cycle_loop,
            build_cycle_tiebreak_loop,
        )
        from bayesian_consensus_engine_tpu.parallel.sharded import read_phase

        mesh = make_mesh(mesh_shape)
        probs, mask, outcome, state, now0 = self._slot_major_inputs()
        fused = build_cycle_tiebreak_loop(mesh, chunk_agents=5, donate=False)
        st_f, cons_f, tiebreak = fused(probs, mask, outcome, state, now0, 3)
        st_p, cons_p = build_cycle_loop(mesh, donate=False)(
            probs, mask, outcome, state, now0, 3
        )
        np.testing.assert_allclose(
            np.asarray(cons_f), np.asarray(cons_p), rtol=2e-6, atol=1e-6
        )
        for got, want in zip(st_f, st_p):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

        # The tie-break half: slot-major fused output == standalone (M, A)
        # path fed the same pre-update decayed read (weight = read_rel).
        read_rel, read_conf = read_phase(state, now0)
        standalone = build_ring_tiebreak(mesh, chunk_agents=5)(
            probs.T, read_rel.T, read_conf.T, read_rel.T, mask.T
        )
        for name, got, want in zip(
            tiebreak._fields, tiebreak, standalone
        ):
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(want), err_msg=name
            )

    def test_session_rejects_unknown_chunk_string(self):
        # "auto" is the STANDALONE builder's knob; the session entry must
        # refuse it with a pointer, not die as int('auto') mid-trace.
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
            build_settlement_plan,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        store = TensorReliabilityStore()
        plan = build_settlement_plan(
            store, [("m-0", [{"sourceId": "s-0", "probability": 0.5}])],
            num_slots=4,
        )
        with ShardedSettlementSession(store, plan, make_mesh()) as session:
            with pytest.raises(ValueError, match="build_ring_tiebreak"):
                session.settle_with_tiebreak(
                    [True], now=21_900.0, chunk_agents="auto"
                )

    def test_session_settle_with_tiebreak(self):
        """The co-resident session entry: settlement bytes equal a plain
        settle's, and the tie-break diagnoses the batch against the
        scalar contract (cold store: every agent at the cold-start
        reliability, so ties resolve on the smallest prediction)."""
        from bayesian_consensus_engine_tpu.models.tiebreak import (
            AgentSignal,
            DeterministicTieBreaker,
        )
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
            build_settlement_plan,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )
        from bayesian_consensus_engine_tpu.utils.config import (
            DEFAULT_CONFIDENCE,
            DEFAULT_RELIABILITY,
        )

        rng = np.random.default_rng(7)
        grid = np.array([0.125, 0.25, 0.5, 0.75, 0.875])
        markets, srcs = 12, 5
        payloads = [
            (
                f"m-{i}",
                [
                    {
                        "sourceId": f"s-{j}",
                        "probability": float(rng.choice(grid)),
                    }
                    for j in range(srcs)
                ],
            )
            for i in range(markets)
        ]
        outcomes = list(rng.random(markets) < 0.5)
        mesh = make_mesh()

        stores = [TensorReliabilityStore() for _ in range(2)]
        plans = [
            build_settlement_plan(s, payloads, num_slots=8) for s in stores
        ]
        with ShardedSettlementSession(stores[0], plans[0], mesh) as plain:
            plain_result = plain.settle(outcomes, steps=2, now=21_900.0)
        with ShardedSettlementSession(stores[1], plans[1], mesh) as fused:
            fused_result, tiebreak = fused.settle_with_tiebreak(
                outcomes, steps=2, now=21_900.0, chunk_agents=3
            )

        np.testing.assert_allclose(
            np.asarray(fused_result.consensus),
            np.asarray(plain_result.consensus),
            rtol=2e-6,
        )
        # Settlement state bytes: the fused entry shares settle's commit
        # path, and the elementwise update stays exact across programs.
        rows = np.arange(stores[0].live_row_count())
        for got, want in zip(
            stores[1].host_rows(rows), stores[0].host_rows(rows)
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

        # Tie-break vs scalar: a cold store reads every signalling slot at
        # the cold-start defaults.
        breaker = DeterministicTieBreaker()
        for row, (_key, slot_payloads) in enumerate(payloads):
            agents = [
                AgentSignal(
                    s["sourceId"],
                    s["probability"],
                    DEFAULT_CONFIDENCE,
                    weight=DEFAULT_RELIABILITY,
                    reliability_score=DEFAULT_RELIABILITY,
                )
                for s in slot_payloads
            ]
            want_pred, want_diag = breaker.resolve(agents)
            assert np.asarray(tiebreak.prediction)[row] == pytest.approx(
                want_pred, abs=1e-6
            ), f"market {row}"
            assert (
                _LABELS[int(np.asarray(tiebreak.resolved_by)[row])]
                == want_diag.tie_resolved_by
            ), f"market {row}"
            assert int(np.asarray(tiebreak.num_groups)[row]) == len(
                want_diag.groups
            )
