"""Test harness configuration.

Force JAX onto the host CPU with 8 virtual devices so mesh/sharding tests
exercise real multi-device code paths without TPU hardware — the TPU analogue
of the reference's use of SQLite ":memory:" for hermetic store tests
(reference: tests/test_reliability.py:24-29).

NOTE: env-var overrides (JAX_PLATFORMS / XLA_FLAGS) do NOT work here: this
machine's ``sitecustomize`` imports jax at interpreter startup with
JAX_PLATFORMS=axon already set, so the only effective override is
``jax.config.update`` before the first backend use. TPU float64 emulation is
inexact; the f64 parity gates REQUIRE the real CPU backend.
"""

import sys
import pathlib

import os

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

# Persist XLA compiles across pytest runs: the suite compiles hundreds of
# small programs and host-CPU XLA time dominates its wall clock. The CPU
# backend's executable serialization is well-supported (unlike the tunneled
# TPU plugin, where this stays off — see bench.py). Best-effort.
try:
    _cache_dir = os.path.expanduser("~/.cache/bce_jax_test_cache")
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
except Exception:
    pass

# Make the repo root importable when tests run without an installed package.
_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_ROOT))

# Best-effort build of the native ingest packer so a fresh checkout exercises
# the C path too (tests skip it gracefully if no compiler is available).
if not list((_ROOT / "bayesian_consensus_engine_tpu" / "_native").glob("fastpack*.so")):
    try:
        import importlib.util

        _spec = importlib.util.spec_from_file_location(
            "native_build", _ROOT / "native" / "build.py"
        )
        _module = importlib.util.module_from_spec(_spec)
        _spec.loader.exec_module(_module)
        _module.build()
    except Exception:
        pass
