"""Test harness configuration.

Force JAX onto the host CPU with 8 virtual devices so mesh/sharding tests
exercise real multi-device code paths without TPU hardware — the TPU analogue
of the reference's use of SQLite ":memory:" for hermetic store tests
(reference: tests/test_reliability.py:24-29).

NOTE: env-var overrides (JAX_PLATFORMS / XLA_FLAGS) do NOT work here: this
machine's ``sitecustomize`` imports jax at interpreter startup with
JAX_PLATFORMS=axon already set, so the only effective override is
``jax.config.update`` before the first backend use. TPU float64 emulation is
inexact; the f64 parity gates REQUIRE the real CPU backend.
"""

import sys
import pathlib

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

# Make the repo root importable when tests run without an installed package.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
