"""Test harness configuration.

Force JAX onto the host CPU with 8 virtual devices so mesh/sharding tests
exercise real multi-device code paths without TPU hardware — the TPU analogue
of the reference's use of SQLite ":memory:" for hermetic store tests
(reference: tests/test_reliability.py:24-29).

NOTE: env-var overrides (JAX_PLATFORMS / XLA_FLAGS) may not take effect when a
``sitecustomize`` imports jax at interpreter startup, so prefer
``jax.config.update`` before the first backend use and fall back to env vars
for JAX versions that lack the config knob. TPU float64 emulation is inexact;
the f64 parity gates REQUIRE the real CPU backend.
"""

import os

# Belt and braces for the device-count override: newer JAX exposes
# ``jax_num_cpu_devices``; older releases only honor the XLA flag, which must
# be in the environment BEFORE the first ``import jax`` in this process.
_XLA_DEVICE_FLAG = "--xla_force_host_platform_device_count=8"
if _XLA_DEVICE_FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _XLA_DEVICE_FLAG
    ).strip()

import sys
import pathlib

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Old JAX: no such option — the XLA_FLAGS fallback above covers it,
    # provided jax was first imported in this process after we set it.
    pass

if not hasattr(jax, "enable_x64"):
    # The top-level alias landed after 0.4.37; the experimental context
    # manager is the same object on every version we support.
    from jax.experimental import enable_x64 as _enable_x64

    jax.enable_x64 = _enable_x64

# NO persistent compilation cache here, deliberately. It was tried (to cut
# host-CPU XLA compile time, which dominates suite wall clock) and reverted:
# on this host an executable RELOADED from the cache contracts
# ``c + (1 - c) * g`` into an FMA while a fresh compile does not, so the
# second pytest run differed from the first by 1 ulp and the bit-exact
# settlement parity gates (test_pipeline.py) failed only on warm caches.
# Byte-exact determinism is the paper's headline contract; a cache that
# changes output bytes between runs is not an optimisation.

# Make the repo root importable when tests run without an installed package.
_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_ROOT))

# Best-effort build of the native ingest packer so a fresh checkout exercises
# the C path too (tests skip it gracefully if no compiler is available).
if not list((_ROOT / "bayesian_consensus_engine_tpu" / "_native").glob("fastpack*.so")):
    try:
        import importlib.util

        _spec = importlib.util.spec_from_file_location(
            "native_build", _ROOT / "native" / "build.py"
        )
        _module = importlib.util.module_from_spec(_spec)
        _spec.loader.exec_module(_module)
        _module.build()
    except Exception:
        pass
