"""Test harness configuration.

Force JAX onto the host CPU with 8 virtual devices BEFORE jax is imported
anywhere, so mesh/sharding tests exercise real multi-device code paths
without TPU hardware — the TPU analogue of the reference's use of SQLite
":memory:" for hermetic store tests (reference: tests/test_reliability.py:24-29).
"""

import os
import sys
import pathlib

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Make the repo root importable when tests run without an installed package.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
