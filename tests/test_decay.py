"""Half-life decay math — scalar path.

Pin the same behaviours the reference pins (reference: tests/test_decay.py):
factor values at 0/1/2 half-lives, floor clamping, timestamp parsing edge
cases (None/empty/invalid/naive/future), and the combined helper.
"""

from datetime import datetime, timedelta, timezone

import pytest

from bayesian_consensus_engine_tpu.state.decay import (
    apply_reliability_decay,
    compute_decay_factor,
    days_since_update,
    decay_reliability_if_needed,
)


class TestDecayFactor:
    def test_zero_elapsed_is_one(self):
        assert compute_decay_factor(0) == 1.0

    def test_negative_elapsed_is_one(self):
        assert compute_decay_factor(-5) == 1.0

    def test_one_half_life(self):
        assert compute_decay_factor(30) == pytest.approx(0.5)

    def test_two_half_lives(self):
        assert compute_decay_factor(60) == pytest.approx(0.25)

    def test_custom_half_life(self):
        assert compute_decay_factor(7, half_life_days=7) == pytest.approx(0.5)

    def test_monotonically_decreasing(self):
        values = [compute_decay_factor(t) for t in (0, 1, 10, 30, 90, 365)]
        assert values == sorted(values, reverse=True)

    def test_always_in_unit_interval(self):
        for t in (0.001, 1, 100, 10000):
            assert 0.0 < compute_decay_factor(t) <= 1.0


class TestApplyDecay:
    def test_no_elapsed_no_change(self):
        assert apply_reliability_decay(0.8, 0) == 0.8

    def test_one_half_life_midpoint_to_floor(self):
        # 0.1 + (0.8 - 0.1) * 0.5 = 0.45
        assert apply_reliability_decay(0.8, 30, min_reliability=0.1) == pytest.approx(0.45)

    def test_very_old_hits_floor(self):
        assert apply_reliability_decay(0.8, 100000, min_reliability=0.1) == pytest.approx(0.1)

    def test_never_below_floor(self):
        for t in (1, 30, 365, 100000):
            assert apply_reliability_decay(0.9, t) >= 0.10

    def test_value_already_at_floor_stays(self):
        assert apply_reliability_decay(0.10, 500) == pytest.approx(0.10)

    def test_value_below_floor_pulled_up_to_floor(self):
        # floor + (0.05-0.1)*factor < floor → clamped to floor
        assert apply_reliability_decay(0.05, 30) == 0.10

    def test_clamped_to_one(self):
        assert apply_reliability_decay(1.0, 0.0001) <= 1.0


class TestDaysSinceUpdate:
    def test_none_is_zero(self):
        assert days_since_update(None) == 0.0

    def test_empty_string_is_zero(self):
        assert days_since_update("") == 0.0

    def test_invalid_timestamp_is_zero(self):
        assert days_since_update("not-a-timestamp") == 0.0

    def test_datetime_object(self):
        now = datetime(2026, 1, 31, tzinfo=timezone.utc)
        then = datetime(2026, 1, 1, tzinfo=timezone.utc)
        assert days_since_update(then, now=now) == pytest.approx(30.0)

    def test_iso_string(self):
        now = datetime(2026, 1, 2, tzinfo=timezone.utc)
        assert days_since_update("2026-01-01T00:00:00+00:00", now=now) == pytest.approx(1.0)

    def test_naive_timestamp_assumed_utc(self):
        now = datetime(2026, 1, 2, tzinfo=timezone.utc)
        assert days_since_update("2026-01-01T00:00:00", now=now) == pytest.approx(1.0)

    def test_future_timestamp_clamped_to_zero(self):
        now = datetime(2026, 1, 1, tzinfo=timezone.utc)
        assert days_since_update("2026-06-01T00:00:00+00:00", now=now) == 0.0

    def test_fractional_days(self):
        now = datetime(2026, 1, 1, 12, 0, 0, tzinfo=timezone.utc)
        assert days_since_update("2026-01-01T00:00:00+00:00", now=now) == pytest.approx(0.5)


class TestDecayIfNeeded:
    def test_cold_start_not_decayed(self):
        assert decay_reliability_if_needed(0.8, None) == (0.8, False)

    def test_same_instant_not_decayed(self):
        now = datetime.now(timezone.utc)
        value, was_decayed = decay_reliability_if_needed(0.8, now, now=now)
        assert value == 0.8
        assert was_decayed is False

    def test_old_update_decayed(self):
        now = datetime.now(timezone.utc)
        stamp = (now - timedelta(days=30)).isoformat()
        value, was_decayed = decay_reliability_if_needed(0.8, stamp, now=now)
        assert was_decayed is True
        assert value == pytest.approx(0.45, abs=1e-6)

    def test_matches_reference_implementation(self):
        """Cross-check the full scalar decay pipeline against the reference."""
        import sys

        sys.path.insert(0, "/root/reference/src")
        try:
            from bayesian_engine import decay as ref
        except ImportError:
            pytest.skip("reference not mounted")
        finally:
            sys.path.remove("/root/reference/src")

        now = datetime(2026, 7, 1, tzinfo=timezone.utc)
        for rel in (0.0, 0.05, 0.1, 0.3, 0.5, 0.77, 1.0):
            for days in (0, 0.5, 1, 29.9, 30, 60, 365, 9999):
                stamp = (now - timedelta(days=days)).isoformat()
                assert days_since_update(stamp, now=now) == ref.days_since_update(
                    stamp, now=now
                )
                assert apply_reliability_decay(rel, days) == ref.apply_reliability_decay(
                    rel, days
                )
