"""Byte-level parity against the reference implementation itself.

The golden fixture pins one known payload; this suite drives RANDOMIZED
payloads through both engines and asserts byte-identical JSON — the
strongest form of the parity contract. Runs only where the reference
checkout is mounted (skipped elsewhere, e.g. public CI).

The reference is UNTRUSTED third-party content: it is imported and
executed for output comparison only.
"""

import json
import pathlib
import random
import sys

import pytest

_REFERENCE_SRC = pathlib.Path("/root/reference/src")

pytestmark = pytest.mark.skipif(
    not _REFERENCE_SRC.is_dir(), reason="reference checkout not mounted"
)


@pytest.fixture(scope="module")
def reference_engine():
    sys.path.insert(0, str(_REFERENCE_SRC))
    try:
        from bayesian_engine.core import (  # type: ignore[import-not-found]
            ValidationError,
            compute_consensus,
            validate_input_payload,
        )

        yield compute_consensus, validate_input_payload, ValidationError
    finally:
        sys.path.remove(str(_REFERENCE_SRC))


def _random_case(rng: random.Random):
    n = rng.randint(0, 12)
    signals = [
        {
            "sourceId": f"s{rng.randint(0, 5)}",
            "probability": round(rng.random(), 6),
        }
        for _ in range(n)
    ]
    reliability = {
        f"s{i}": {
            "reliability": round(rng.random(), 6),
            "confidence": round(rng.random(), 6),
        }
        for i in range(6)
        if rng.random() < 0.7
    }
    return signals, (reliability or None)


class TestConsensusParity:
    def test_randomized_byte_identical(self, reference_engine):
        from bayesian_consensus_engine_tpu.core.engine import compute_consensus

        ref_cc, _, _ = reference_engine
        rng = random.Random(20260730)
        for trial in range(300):
            signals, reliability = _random_case(rng)
            want = ref_cc(signals, reliability)
            got = compute_consensus(signals, reliability)
            assert json.dumps(got, sort_keys=True) == json.dumps(
                want, sort_keys=True
            ), f"trial {trial}: {signals} {reliability}"

    def test_validation_messages_identical(self, reference_engine):
        from bayesian_consensus_engine_tpu.core.validate import (
            ValidationError,
            validate_input_payload,
        )

        _, ref_validate, RefValidationError = reference_engine
        bad_payloads = [
            {},
            {"schemaVersion": "2.0.0"},
            {"schemaVersion": "1.0.0"},
            {"schemaVersion": "1.0.0", "marketId": ""},
            {"schemaVersion": "1.0.0", "marketId": "m"},
            {"schemaVersion": "1.0.0", "marketId": "m", "signals": "nope"},
            {
                "schemaVersion": "1.0.0",
                "marketId": "m",
                "signals": [{"sourceId": "", "probability": 0.5}],
            },
            {
                "schemaVersion": "1.0.0",
                "marketId": "m",
                "signals": [{"sourceId": "a", "probability": 1.5}],
            },
            {
                "schemaVersion": "1.0.0",
                "marketId": "m",
                "signals": [{"sourceId": "a"}],
            },
        ]
        for payload in bad_payloads:
            with pytest.raises(RefValidationError) as ref_exc:
                ref_validate(payload)
            with pytest.raises(ValidationError) as our_exc:
                validate_input_payload(payload)
            assert str(our_exc.value) == str(ref_exc.value), payload

    def test_tiebreak_resolution_identical(self, reference_engine):
        """Randomized agent panels through both tie-breakers."""
        from bayesian_engine.tiebreak import (  # type: ignore[import-not-found]
            AgentSignal as RefAgent,
            DeterministicTieBreaker as RefBreaker,
        )

        from bayesian_consensus_engine_tpu.models.tiebreak import (
            AgentSignal,
            DeterministicTieBreaker,
        )

        rng = random.Random(99)
        ours, theirs = DeterministicTieBreaker(), RefBreaker()
        for trial in range(150):
            n = rng.randint(1, 10)
            spec = [
                (
                    f"a{i}",
                    rng.choice([0.1, 0.25, 0.5, 0.75, 0.9]),
                    round(rng.random(), 6),
                    round(rng.uniform(0.1, 3.0), 6),
                    round(rng.random(), 6),
                )
                for i in range(n)
            ]
            my_pred, my_diag = ours.resolve(
                [
                    AgentSignal(a, p, c, weight=w, reliability_score=r)
                    for a, p, c, w, r in spec
                ]
            )
            ref_pred, ref_diag = theirs.resolve(
                [
                    RefAgent(a, p, c, weight=w, reliability_score=r)
                    for a, p, c, w, r in spec
                ]
            )
            assert my_pred == ref_pred, trial
            assert my_diag.tie_resolved_by == ref_diag.tie_resolved_by, trial
            assert my_diag.method == ref_diag.method, trial
            assert my_diag.groups == ref_diag.groups, trial
            assert (
                my_diag.confidence_variance == ref_diag.confidence_variance
            ), trial

    def test_decay_math_identical(self, reference_engine):
        """Randomized decay inputs through both decay modules."""
        from bayesian_engine import decay as ref_decay  # type: ignore[import-not-found]

        from bayesian_consensus_engine_tpu.state import decay as our_decay

        rng = random.Random(5)
        for _ in range(300):
            elapsed = rng.uniform(-5, 400)
            rel = round(rng.random(), 6)
            assert our_decay.compute_decay_factor(
                elapsed
            ) == ref_decay.compute_decay_factor(elapsed)
            assert our_decay.apply_reliability_decay(
                rel, elapsed
            ) == ref_decay.apply_reliability_decay(rel, elapsed)

    def test_namespaced_fallback_chain_identical(self, reference_engine):
        """market → domain → global → cold-start walks match step for step."""
        from bayesian_engine.reliability_abstraction import (  # type: ignore[import-not-found]
            NamespacedReliabilityStore as RefNamespaced,
        )

        from bayesian_consensus_engine_tpu.state.namespaced import (
            NamespacedReliabilityStore,
        )

        rng = random.Random(21)
        ours = NamespacedReliabilityStore(":memory:")
        theirs = RefNamespaced(":memory:")
        # Mixed writes across namespaces, then chain walks.
        for _ in range(120):
            sid = f"s{rng.randint(0, 3)}"
            mid = f"m{rng.randint(0, 2)}"
            domain = rng.choice([None, "crypto", "sports"])
            if rng.random() < 0.5:
                correct = rng.random() < 0.5
                also_global = rng.random() < 0.3
                for target in (ours, theirs):
                    target.update_reliability(
                        sid,
                        outcome_correct=correct,
                        market_id=mid,
                        domain=domain,
                        update_global=also_global,
                    )
            mine = ours.get_reliability(sid, market_id=mid, domain=domain)
            ref = theirs.get_reliability(sid, market_id=mid, domain=domain)
            # Decay-on-read runs at each store's own wall-clock instant;
            # the microseconds between the two calls skew the factor ~1e-10.
            assert mine.reliability == pytest.approx(
                ref.reliability, abs=1e-6
            ), (sid, mid, domain)
            assert mine.confidence == ref.confidence
            assert mine.namespace_value == ref.namespace_value
            assert mine.is_fallback == ref.is_fallback
        ours.close()
        theirs.close()

    def test_update_trajectory_identical(self, reference_engine, tmp_path):
        """Drive both stores through the same outcome sequence."""
        from bayesian_engine.reliability import (  # type: ignore[import-not-found]
            SQLiteReliabilityStore as RefStore,
        )

        from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore

        rng = random.Random(7)
        ours = SQLiteReliabilityStore(":memory:")
        theirs = RefStore(":memory:")
        for _ in range(200):
            sid = f"s{rng.randint(0, 4)}"
            mid = f"m{rng.randint(0, 2)}"
            correct = rng.random() < 0.5
            mine = ours.update_reliability(sid, mid, correct)
            ref = theirs.update_reliability(sid, mid, correct)
            assert mine.reliability == ref.reliability, (sid, mid)
            assert mine.confidence == ref.confidence, (sid, mid)
        ours.close()
        theirs.close()
