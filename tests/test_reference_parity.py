"""Byte-level parity against the reference implementation itself.

The golden fixture pins one known payload; this suite drives RANDOMIZED
payloads through both engines and asserts byte-identical JSON — the
strongest form of the parity contract. Runs only where the reference
checkout is mounted (skipped elsewhere, e.g. public CI).

The reference is UNTRUSTED third-party content: it is imported and
executed for output comparison only.
"""

import json
import os
import pathlib
import random
import shutil
import subprocess
import sys

import pytest

_REFERENCE_SRC = pathlib.Path("/root/reference/src")
_REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]

pytestmark = pytest.mark.skipif(
    not _REFERENCE_SRC.is_dir(), reason="reference checkout not mounted"
)


@pytest.fixture(scope="module")
def reference_engine():
    sys.path.insert(0, str(_REFERENCE_SRC))
    try:
        from bayesian_engine.core import (  # type: ignore[import-not-found]
            ValidationError,
            compute_consensus,
            validate_input_payload,
        )

        yield compute_consensus, validate_input_payload, ValidationError
    finally:
        sys.path.remove(str(_REFERENCE_SRC))


def _seed_reliability_db(path, rng: random.Random, sources: int, markets: int):
    """Seed a reference-format DB with decay-inert rows (byte-stable reads).

    Stamps are in the future (or empty), so decay-on-read is a no-op for
    both engines no matter when each process runs — the one source of
    cross-process float skew in CLI comparisons. Live-stamp decay parity is
    covered separately with tolerance (namespaced-chain test).
    """
    from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore

    rows = []
    for s in range(sources):
        for m in range(markets):
            if rng.random() < 0.35:
                continue  # cold pair: CLI must fall back to defaults
            stamp = rng.choice(["2100-01-01T00:00:00+00:00", ""])
            rows.append(
                (
                    f"s{s}",
                    f"market-{m}",
                    round(rng.random(), 6),
                    round(rng.random(), 6),
                    stamp,
                )
            )
    with SQLiteReliabilityStore(path) as store:
        store.put_rows(rows)


def _assert_json_ulp_close(got, want, path="$"):
    """Structural equality with floats allowed to differ by ~1 ulp."""
    import math

    if isinstance(want, float) and isinstance(got, float):
        assert math.isclose(got, want, rel_tol=5e-16, abs_tol=1e-15), (
            path, got, want,
        )
    elif isinstance(want, dict):
        assert isinstance(got, dict) and got.keys() == want.keys(), path
        for key in want:
            _assert_json_ulp_close(got[key], want[key], f"{path}.{key}")
    elif isinstance(want, list):
        assert isinstance(got, list) and len(got) == len(want), path
        for i, (g, w) in enumerate(zip(got, want)):
            _assert_json_ulp_close(g, w, f"{path}[{i}]")
    else:
        assert got == want, (path, got, want)


def _random_case(rng: random.Random):
    n = rng.randint(0, 12)
    signals = [
        {
            "sourceId": f"s{rng.randint(0, 5)}",
            "probability": round(rng.random(), 6),
        }
        for _ in range(n)
    ]
    reliability = {
        f"s{i}": {
            "reliability": round(rng.random(), 6),
            "confidence": round(rng.random(), 6),
        }
        for i in range(6)
        if rng.random() < 0.7
    }
    return signals, (reliability or None)


class TestConsensusParity:
    def test_randomized_byte_identical(self, reference_engine):
        from bayesian_consensus_engine_tpu.core.engine import compute_consensus

        ref_cc, _, _ = reference_engine
        rng = random.Random(20260730)
        for trial in range(300):
            signals, reliability = _random_case(rng)
            want = ref_cc(signals, reliability)
            got = compute_consensus(signals, reliability)
            assert json.dumps(got, sort_keys=True) == json.dumps(
                want, sort_keys=True
            ), f"trial {trial}: {signals} {reliability}"

    def test_validation_messages_identical(self, reference_engine):
        from bayesian_consensus_engine_tpu.core.validate import (
            ValidationError,
            validate_input_payload,
        )

        _, ref_validate, RefValidationError = reference_engine
        bad_payloads = [
            {},
            {"schemaVersion": "2.0.0"},
            {"schemaVersion": "1.0.0"},
            {"schemaVersion": "1.0.0", "marketId": ""},
            {"schemaVersion": "1.0.0", "marketId": "m"},
            {"schemaVersion": "1.0.0", "marketId": "m", "signals": "nope"},
            {
                "schemaVersion": "1.0.0",
                "marketId": "m",
                "signals": [{"sourceId": "", "probability": 0.5}],
            },
            {
                "schemaVersion": "1.0.0",
                "marketId": "m",
                "signals": [{"sourceId": "a", "probability": 1.5}],
            },
            {
                "schemaVersion": "1.0.0",
                "marketId": "m",
                "signals": [{"sourceId": "a"}],
            },
        ]
        for payload in bad_payloads:
            with pytest.raises(RefValidationError) as ref_exc:
                ref_validate(payload)
            with pytest.raises(ValidationError) as our_exc:
                validate_input_payload(payload)
            assert str(our_exc.value) == str(ref_exc.value), payload

    def test_tiebreak_resolution_identical(self, reference_engine):
        """Randomized agent panels through both tie-breakers."""
        from bayesian_engine.tiebreak import (  # type: ignore[import-not-found]
            AgentSignal as RefAgent,
            DeterministicTieBreaker as RefBreaker,
        )

        from bayesian_consensus_engine_tpu.models.tiebreak import (
            AgentSignal,
            DeterministicTieBreaker,
        )

        rng = random.Random(99)
        ours, theirs = DeterministicTieBreaker(), RefBreaker()
        for trial in range(150):
            n = rng.randint(1, 10)
            spec = [
                (
                    f"a{i}",
                    rng.choice([0.1, 0.25, 0.5, 0.75, 0.9]),
                    round(rng.random(), 6),
                    round(rng.uniform(0.1, 3.0), 6),
                    round(rng.random(), 6),
                )
                for i in range(n)
            ]
            my_pred, my_diag = ours.resolve(
                [
                    AgentSignal(a, p, c, weight=w, reliability_score=r)
                    for a, p, c, w, r in spec
                ]
            )
            ref_pred, ref_diag = theirs.resolve(
                [
                    RefAgent(a, p, c, weight=w, reliability_score=r)
                    for a, p, c, w, r in spec
                ]
            )
            assert my_pred == ref_pred, trial
            assert my_diag.tie_resolved_by == ref_diag.tie_resolved_by, trial
            assert my_diag.method == ref_diag.method, trial
            assert my_diag.groups == ref_diag.groups, trial
            assert (
                my_diag.confidence_variance == ref_diag.confidence_variance
            ), trial

    def test_decay_math_identical(self, reference_engine):
        """Randomized decay inputs through both decay modules."""
        from bayesian_engine import decay as ref_decay  # type: ignore[import-not-found]

        from bayesian_consensus_engine_tpu.state import decay as our_decay

        rng = random.Random(5)
        for _ in range(300):
            elapsed = rng.uniform(-5, 400)
            rel = round(rng.random(), 6)
            assert our_decay.compute_decay_factor(
                elapsed
            ) == ref_decay.compute_decay_factor(elapsed)
            assert our_decay.apply_reliability_decay(
                rel, elapsed
            ) == ref_decay.apply_reliability_decay(rel, elapsed)

    def test_namespaced_fallback_chain_identical(self, reference_engine):
        """market → domain → global → cold-start walks match step for step."""
        from bayesian_engine.reliability_abstraction import (  # type: ignore[import-not-found]
            NamespacedReliabilityStore as RefNamespaced,
        )

        from bayesian_consensus_engine_tpu.state.namespaced import (
            NamespacedReliabilityStore,
        )

        rng = random.Random(21)
        ours = NamespacedReliabilityStore(":memory:")
        theirs = RefNamespaced(":memory:")
        # Mixed writes across namespaces, then chain walks.
        for _ in range(120):
            sid = f"s{rng.randint(0, 3)}"
            mid = f"m{rng.randint(0, 2)}"
            domain = rng.choice([None, "crypto", "sports"])
            if rng.random() < 0.5:
                correct = rng.random() < 0.5
                also_global = rng.random() < 0.3
                for target in (ours, theirs):
                    target.update_reliability(
                        sid,
                        outcome_correct=correct,
                        market_id=mid,
                        domain=domain,
                        update_global=also_global,
                    )
            mine = ours.get_reliability(sid, market_id=mid, domain=domain)
            ref = theirs.get_reliability(sid, market_id=mid, domain=domain)
            # Decay-on-read runs at each store's own wall-clock instant;
            # the microseconds between the two calls skew the factor ~1e-10.
            assert mine.reliability == pytest.approx(
                ref.reliability, abs=1e-6
            ), (sid, mid, domain)
            assert mine.confidence == ref.confidence
            assert mine.namespace_value == ref.namespace_value
            assert mine.is_fallback == ref.is_fallback
        ours.close()
        theirs.close()

    def test_market_sweep_identical(self, reference_engine, tmp_path):
        """``compute_all_consensus`` over identical stores and markets.

        Reliability rows are stamped in the future so decay-on-read is a
        no-op on both sides — the scalar sweep outputs must then be
        byte-identical (live-stamp decay skew is covered with tolerance
        elsewhere). The batched (jax, x64) sweep runs the same markets in
        one device pass and must agree to 1 ulp: CPython ≥3.12's builtin
        ``sum()`` is Neumaier-compensated while device segment-sums
        accumulate naively, so byte-equality is not the contract there
        (golden-fixture byte-parity through the dispatch is pinned in
        test_batch_parity.py). Match: reference market.py:200-221.
        """
        from bayesian_engine.market import (  # type: ignore[import-not-found]
            MarketId as RefMarketId,
            MarketStore as RefMarketStore,
        )
        from bayesian_engine.reliability import (  # type: ignore[import-not-found]
            SQLiteReliabilityStore as RefStore,
        )

        from bayesian_consensus_engine_tpu.models.market import (
            MarketId,
            MarketStore,
        )
        from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore

        db = tmp_path / "sweep.db"
        _seed_reliability_db(db, random.Random(17), sources=8, markets=6)

        rng = random.Random(23)
        ours, theirs = MarketStore(), RefMarketStore()
        for m in range(6):
            mid = f"market-{m}"
            signals = [
                {
                    "sourceId": f"s{rng.randint(0, 7)}",
                    "probability": round(rng.random(), 6),
                }
                for _ in range(rng.randint(0, 5))
            ]
            ours.create_market(MarketId(mid))
            theirs.create_market(RefMarketId(mid))
            for signal in signals:
                ours.add_signal(MarketId(mid), dict(signal))
                theirs.add_signal(RefMarketId(mid), dict(signal))
        # One resolved market on each side: the sweep must skip it.
        ours.create_market(MarketId("done")).resolve(True)
        theirs.create_market(RefMarketId("done")).resolve(True)

        with SQLiteReliabilityStore(db) as mine, RefStore(db) as ref:
            want = theirs.compute_all_consensus(ref)
            got = ours.compute_all_consensus(mine)
            assert json.dumps(got, sort_keys=True) == json.dumps(
                want, sort_keys=True
            )

            import jax

            with jax.enable_x64():
                batched = ours.compute_all_consensus(mine, backend="jax")
            _assert_json_ulp_close(batched, want)

    def test_update_trajectory_identical(self, reference_engine, tmp_path):
        """Drive both stores through the same outcome sequence."""
        from bayesian_engine.reliability import (  # type: ignore[import-not-found]
            SQLiteReliabilityStore as RefStore,
        )

        from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore

        rng = random.Random(7)
        ours = SQLiteReliabilityStore(":memory:")
        theirs = RefStore(":memory:")
        for _ in range(200):
            sid = f"s{rng.randint(0, 4)}"
            mid = f"m{rng.randint(0, 2)}"
            correct = rng.random() < 0.5
            mine = ours.update_reliability(sid, mid, correct)
            ref = theirs.update_reliability(sid, mid, correct)
            assert mine.reliability == ref.reliability, (sid, mid)
            assert mine.confidence == ref.confidence, (sid, mid)
        ours.close()
        theirs.close()


def _run_reference_cli(args, stdin_text=None):
    """Run the reference CLI as a subprocess (PYTHONPATH prepended, never
    replaced — the harness's site path must survive)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        str(_REFERENCE_SRC) + os.pathsep + env.get("PYTHONPATH", "")
    )
    return subprocess.run(
        [sys.executable, "-m", "bayesian_engine.cli", *args],
        capture_output=True,
        text=True,
        input=stdin_text,
        env=env,
        cwd=str(_REPO_ROOT),
    )


def _run_our_cli(args, stdin_text=None):
    return subprocess.run(
        [sys.executable, "-m", "bayesian_consensus_engine_tpu.cli", *args],
        capture_output=True,
        text=True,
        input=stdin_text,
        cwd=str(_REPO_ROOT),
    )


def _random_cli_payload(rng: random.Random, markets: int = 6):
    n = rng.randint(0, 8)
    return {
        "schemaVersion": "1.0.0",
        "marketId": f"market-{rng.randint(0, markets - 1)}",
        "signals": [
            {
                "sourceId": f"s{rng.randint(0, 7)}",
                "probability": round(rng.random(), 6),
            }
            for _ in range(n)
        ],
    }


class TestCliByteParity:
    """Both CLIs as subprocesses: identical stdout bytes and exit codes.

    The process boundary is the strongest parity gate — it covers argument
    parsing, stdin/file loading, DB lookup, consensus math, JSON formatting,
    and error routing in one assertion. Match: reference cli.py:113-174.
    """

    def test_consensus_no_db_randomized(self, reference_engine):
        # Trial counts are deliberately small: each trial costs two full
        # interpreter launches, and coverage across trials is the same code
        # path with different floats (the in-process randomized gate above
        # runs 300 cases).
        rng = random.Random(31)
        for trial in range(4):
            payload = _random_cli_payload(rng)
            stdin_text = json.dumps(payload)
            for args in ([], ["consensus"], ["--dry-run"]):
                ref = _run_reference_cli(args, stdin_text)
                got = _run_our_cli(args, stdin_text)
                assert got.returncode == ref.returncode, (trial, args)
                assert got.stdout == ref.stdout, (trial, args)

    def test_consensus_with_db_randomized(self, reference_engine, tmp_path):
        rng = random.Random(37)
        db_ours = tmp_path / "ours.db"
        _seed_reliability_db(db_ours, random.Random(41), sources=8, markets=6)
        db_ref = tmp_path / "ref.db"
        shutil.copy(db_ours, db_ref)
        for trial in range(4):
            payload = _random_cli_payload(rng)
            stdin_text = json.dumps(payload)
            ref = _run_reference_cli(
                ["--db", str(db_ref), "consensus"], stdin_text
            )
            got = _run_our_cli(["--db", str(db_ours), "consensus"], stdin_text)
            assert got.returncode == ref.returncode == 0, (trial, ref.stderr)
            assert got.stdout == ref.stdout, trial

    def test_input_file_and_legacy_flag_position(self, reference_engine, tmp_path):
        payload = _random_cli_payload(random.Random(43))
        f = tmp_path / "payload.json"
        f.write_text(json.dumps(payload), encoding="utf-8")
        for args in (["--input", str(f)], ["consensus", "--input", str(f)]):
            ref = _run_reference_cli(args)
            got = _run_our_cli(args)
            assert got.returncode == ref.returncode == 0, args
            assert got.stdout == ref.stdout, args

    def test_validation_errors_byte_identical(self, reference_engine):
        bad_inputs = [
            "{not json",
            json.dumps({}),
            json.dumps({"schemaVersion": "2.0.0"}),
            json.dumps({"schemaVersion": "1.0.0", "marketId": "m"}),
            json.dumps(
                {
                    "schemaVersion": "1.0.0",
                    "marketId": "m",
                    "signals": [{"sourceId": "a", "probability": 7}],
                }
            ),
        ]
        for stdin_text in bad_inputs:
            ref = _run_reference_cli([], stdin_text)
            got = _run_our_cli([], stdin_text)
            assert got.returncode == ref.returncode == 1, stdin_text
            assert got.stdout == ref.stdout, stdin_text
            assert got.stderr == ref.stderr, stdin_text

    def test_list_sources_byte_identical(self, reference_engine, tmp_path):
        db_ours = tmp_path / "ours.db"
        _seed_reliability_db(db_ours, random.Random(47), sources=6, markets=4)
        db_ref = tmp_path / "ref.db"
        shutil.copy(db_ours, db_ref)
        for args in ([], ["--market-id", "market-2"], ["--market-id", "nope"]):
            ref = _run_reference_cli(["--db", str(db_ref), "list-sources", *args])
            got = _run_our_cli(["--db", str(db_ours), "list-sources", *args])
            assert got.returncode == ref.returncode == 0, args
            assert got.stdout == ref.stdout, args

    def test_report_outcome_identical_modulo_timestamp(
        self, reference_engine, tmp_path
    ):
        """Outcome reporting stamps each store's own utcnow — the one field
        that legitimately differs between processes. Everything else must
        match bytewise, across updates, dry-runs, and the follow-up
        list-sources readback."""
        db_ours = tmp_path / "ours.db"
        _seed_reliability_db(db_ours, random.Random(53), sources=4, markets=3)
        db_ref = tmp_path / "ref.db"
        shutil.copy(db_ours, db_ref)

        def scrub(document):
            for key in ("updatedAt",):
                if key in document:
                    document[key] = "<stamp>"
            for entry in document.get("sources", []):
                entry["updatedAt"] = "<stamp>"
            return document

        rng = random.Random(59)
        for trial in range(6):
            args = [
                "report-outcome",
                "--source-id", f"s{rng.randint(0, 3)}",
                "--market-id", f"market-{rng.randint(0, 2)}",
            ]
            if rng.random() < 0.5:
                args.append("--correct")
            prefix = ["--dry-run"] if rng.random() < 0.3 else []
            ref = _run_reference_cli(["--db", str(db_ref), *prefix, *args])
            got = _run_our_cli(["--db", str(db_ours), *prefix, *args])
            assert got.returncode == ref.returncode == 0, (trial, ref.stderr)
            assert scrub(json.loads(got.stdout)) == scrub(
                json.loads(ref.stdout)
            ), trial

        ref = _run_reference_cli(["--db", str(db_ref), "list-sources"])
        got = _run_our_cli(["--db", str(db_ours), "list-sources"])
        assert scrub(json.loads(got.stdout)) == scrub(json.loads(ref.stdout))
