"""Golden regression + simulation datasets — the bit-exact parity gate.

The golden fixture stores input AND the full expected output document; the
scalar engine must reproduce it exactly (reference pattern:
tests/test_golden_fixtures.py:48-70, fixture consensus 0.6966666666666667).
Simulation fixtures exercise agreement / polarization / outlier scenarios.
"""

import json
import pathlib

import pytest

from bayesian_consensus_engine_tpu.core import (
    SCHEMA_VERSION,
    compute_consensus,
    validate_input_payload,
)

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

SIM_NAMES = [
    "sim_uniform_agreement.json",
    "sim_polarized_split.json",
    "sim_single_outlier.json",
]


def _load(name: str) -> dict:
    return json.loads((FIXTURES / name).read_text(encoding="utf-8"))


class TestGoldenRegression:
    def test_exact_output_match(self):
        fixture = _load("golden_regression.json")
        validate_input_payload(fixture["input"])
        result = compute_consensus(fixture["input"]["signals"])
        assert result == fixture["expectedOutput"], (
            "Golden regression mismatch:\n"
            + json.dumps(result, indent=2)
        )

    def test_byte_exact_json_serialization(self):
        """Stronger than dict equality: serialized bytes match too."""
        fixture = _load("golden_regression.json")
        result = compute_consensus(fixture["input"]["signals"])
        assert json.dumps(result, indent=2) == json.dumps(
            fixture["expectedOutput"], indent=2
        )

    def test_deterministic_across_runs(self):
        fixture = _load("golden_regression.json")
        signals = fixture["input"]["signals"]
        outputs = [compute_consensus(signals) for _ in range(10)]
        assert all(o == outputs[0] for o in outputs[1:])

    def test_fixture_schema_version_matches_code(self):
        fixture = _load("golden_regression.json")
        assert fixture["input"]["schemaVersion"] == SCHEMA_VERSION
        assert fixture["expectedOutput"]["schemaVersion"] == SCHEMA_VERSION


class TestSimulationDatasets:
    @pytest.fixture(params=SIM_NAMES)
    def sim(self, request) -> dict:
        return _load(request.param)

    def test_passes_validation(self, sim):
        validate_input_payload(sim["input"])

    def test_output_well_formed(self, sim):
        result = compute_consensus(sim["input"]["signals"])
        for key in (
            "schemaVersion",
            "consensus",
            "confidence",
            "sourceWeights",
            "normalization",
            "diagnostics",
        ):
            assert key in result
        assert result["schemaVersion"] == SCHEMA_VERSION

    def test_json_round_trip(self, sim):
        result = compute_consensus(sim["input"]["signals"])
        assert json.loads(json.dumps(result)) == result

    def test_deterministic(self, sim):
        signals = sim["input"]["signals"]
        assert compute_consensus(signals) == compute_consensus(signals)


class TestScenarioSemantics:
    def test_uniform_agreement_converges_near_cluster(self):
        sim = _load("sim_uniform_agreement.json")
        result = compute_consensus(sim["input"]["signals"])
        assert 0.78 <= result["consensus"] <= 0.82

    def test_polarized_split_lands_between_camps(self):
        sim = _load("sim_polarized_split.json")
        result = compute_consensus(sim["input"]["signals"])
        assert 0.15 < result["consensus"] < 0.85

    def test_single_outlier_drags_mean_down(self):
        sim = _load("sim_single_outlier.json")
        result = compute_consensus(sim["input"]["signals"])
        # 4 sources ~0.60 + one 0.05 outlier, equal weights → ~0.492
        assert result["consensus"] == pytest.approx(
            (0.60 + 0.62 + 0.58 + 0.61 + 0.05) / 5
        )


class TestGoldenWithObsEnabled:
    """The golden bytes with observability fully ON (ISSUE 3 acceptance).

    obs (metrics registry + phase timeline) is write-only host
    instrumentation; enabling it may not move a single output byte. The
    deeper settle/settle_stream + checkpoint-byte parity lives in
    tests/test_obs.py; this pins the user-visible fixture contract in
    the same file that pins it for the disabled default.
    """

    def test_exact_output_match_with_obs_enabled(self):
        from bayesian_consensus_engine_tpu import obs

        fixture = _load("golden_regression.json")
        timeline = obs.PhaseTimeline()
        previous = obs.set_metrics_registry(obs.MetricsRegistry())
        try:
            with obs.recording(timeline):
                result = compute_consensus(fixture["input"]["signals"])
        finally:
            obs.set_metrics_registry(previous)
        assert json.dumps(result, indent=2) == json.dumps(
            fixture["expectedOutput"], indent=2
        )


class TestFixtureIntegrity:
    """Every fixture file must be valid JSON with required meta keys."""

    @pytest.fixture(params=["golden_regression.json"] + SIM_NAMES)
    def fixture(self, request) -> dict:
        return _load(request.param)

    def test_has_meta(self, fixture):
        assert "description" in fixture
        assert "schemaVersion" in fixture
        assert "input" in fixture

    def test_input_validates(self, fixture):
        validate_input_payload(fixture["input"])
