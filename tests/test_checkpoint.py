"""Checkpoint/resume of the device-resident cycle state.

The resume contract mirrors the reference's durability test (reference:
tests/test_reliability.py:208-231 — write, reopen, read back): snapshot the
HBM pytree mid-loop, "crash", restore, and the continued run must produce
numbers identical to an uninterrupted one.
"""

import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle_loop,
    init_block_state,
    make_mesh,
    shard_block,
    shard_market,
)
from bayesian_consensus_engine_tpu.state.checkpoint import CycleCheckpointer

M, K = 32, 8


def _inputs(seed=0):
    rng = np.random.default_rng(seed)
    probs = jnp.asarray(rng.random((M, K)), jnp.float32)
    mask = jnp.asarray(rng.random((M, K)) < 0.8)
    outcome = jnp.asarray(rng.random(M) < 0.5)
    return probs, mask, outcome


class TestSaveRestore:
    def test_round_trip_state_and_meta(self, tmp_path):
        state = init_block_state(M, K)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            assert ckpt.latest_step() is None
            assert ckpt.save(0, state, meta={"now_days": 12.5, "note": "t0"})
            restored, meta = ckpt.restore()
        assert meta == {"now_days": 12.5, "note": "t0"}
        for field in MarketBlockState._fields:
            np.testing.assert_array_equal(
                np.asarray(restored[field]), np.asarray(getattr(state, field)),
                err_msg=field,
            )

    def test_restore_like_preserves_structure_and_dtype(self, tmp_path):
        state = init_block_state(M, K)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(3, state)
            restored, _ = ckpt.restore(like=state)
        assert isinstance(restored, MarketBlockState)
        assert restored.reliability.dtype == jnp.float32
        assert restored.exists.dtype == jnp.bool_

    def test_missing_checkpoint_raises(self, tmp_path):
        with CycleCheckpointer(tmp_path / "empty") as ckpt:
            with pytest.raises(FileNotFoundError):
                ckpt.restore()

    def test_retention_prunes_old_steps(self, tmp_path):
        state = init_block_state(4, 2)
        with CycleCheckpointer(tmp_path / "ckpt", max_to_keep=2) as ckpt:
            for step in (1, 2, 3, 4):
                ckpt.save(step, state)
            assert ckpt.latest_step() == 4
            assert ckpt.all_steps() == [3, 4]

    def test_exists_none_carry_round_trips(self, tmp_path):
        full = init_block_state(M, K)
        state = MarketBlockState(
            full.reliability, full.confidence, full.updated_days, None
        )
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(0, state)
            restored, _ = ckpt.restore(like=state)
        assert isinstance(restored, MarketBlockState)
        assert restored.exists is None
        np.testing.assert_array_equal(
            np.asarray(restored.reliability), np.asarray(state.reliability)
        )


class TestResumeEquivalence:
    def test_resume_matches_uninterrupted_run(self, tmp_path):
        probs, mask, outcome, = _inputs(1)
        loop = build_cycle_loop(mesh=None, slot_major=False, donate=False)
        state0 = init_block_state(M, K)

        # Uninterrupted: 5 consecutive daily cycles.
        full_state, full_consensus = loop(
            probs, mask, outcome, state0, jnp.float32(10.0), 5
        )

        # Interrupted: 3 cycles, checkpoint, "crash", restore, 2 more.
        mid_state, _ = loop(probs, mask, outcome, state0, jnp.float32(10.0), 3)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(3, mid_state, meta={"next_now": 13.0})
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            restored, meta = ckpt.restore(like=mid_state)
        resumed_state, resumed_consensus = loop(
            probs, mask, outcome, restored, jnp.float32(meta["next_now"]), 2
        )

        np.testing.assert_array_equal(
            np.asarray(resumed_consensus), np.asarray(full_consensus)
        )
        for field in MarketBlockState._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(resumed_state, field)),
                np.asarray(getattr(full_state, field)),
                err_msg=field,
            )


class TestStoreCheckpoint:
    def test_store_round_trip_bit_identical(self, tmp_path):
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        store = TensorReliabilityStore()
        store.update_reliability("alpha", "m1", outcome_correct=True)
        store.update_reliability("beta", "m1", outcome_correct=False)
        store.update_reliability("alpha", "m2", outcome_correct=True)
        before = store.list_sources()

        store.save_checkpoint(tmp_path / "store_ckpt")
        loaded = TensorReliabilityStore.load_checkpoint(tmp_path / "store_ckpt")
        after = loaded.list_sources()

        assert after == before  # exact f64 values + ISO strings round-trip
        # Cold-start reads behave identically post-restore.
        rec = loaded.get_reliability("never-seen", "m1")
        assert rec.reliability == store.get_reliability("never-seen", "m1").reliability

    def test_store_checkpoint_then_device_cycle(self, tmp_path):
        """Restore → device_state → cycle → absorb keeps working."""
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        store = TensorReliabilityStore()
        store.update_reliability("a", "m", outcome_correct=True)
        store.save_checkpoint(tmp_path / "ckpt")
        loaded = TensorReliabilityStore.load_checkpoint(tmp_path / "ckpt")
        state, epoch0 = loaded.device_state()
        assert bool(np.asarray(state.exists).any())
        loaded.absorb(state, epoch0)
        assert loaded.list_sources() == store.list_sources()


class TestShardedCheckpoint:
    def test_restore_onto_mesh_sharding(self, tmp_path):
        """`like` with sharded arrays restores shards placed on the mesh."""
        mesh = make_mesh((4, 2))
        state = MarketBlockState(
            *(shard_block(x, mesh) for x in init_block_state(M, K))
        )
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(0, state)
            restored, _ = ckpt.restore(like=state)
        assert restored.reliability.sharding == state.reliability.sharding
        np.testing.assert_array_equal(
            np.asarray(restored.reliability), np.asarray(state.reliability)
        )

    def test_sharded_loop_resume(self, tmp_path):
        probs, mask, outcome = _inputs(2)
        mesh = make_mesh((8, 1))
        loop = build_cycle_loop(mesh=mesh, slot_major=False, donate=False)
        sharded = MarketBlockState(
            *(shard_block(x, mesh) for x in init_block_state(M, K))
        )
        p, m_, o = shard_block(probs, mesh), shard_block(mask, mesh), shard_market(outcome, mesh)

        full_state, full_consensus = loop(p, m_, o, sharded, jnp.float32(1.0), 4)
        mid_state, _ = loop(p, m_, o, sharded, jnp.float32(1.0), 2)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(2, mid_state)
            restored, _ = ckpt.restore(like=mid_state)
        resumed_state, resumed_consensus = loop(p, m_, o, restored, jnp.float32(3.0), 2)
        np.testing.assert_array_equal(
            np.asarray(resumed_consensus), np.asarray(full_consensus)
        )
        np.testing.assert_array_equal(
            np.asarray(resumed_state.reliability), np.asarray(full_state.reliability)
        )
