"""Checkpoint/resume of the device-resident cycle state.

The resume contract mirrors the reference's durability test (reference:
tests/test_reliability.py:208-231 — write, reopen, read back): snapshot the
HBM pytree mid-loop, "crash", restore, and the continued run must produce
numbers identical to an uninterrupted one.
"""

import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle_loop,
    init_block_state,
    make_mesh,
    shard_block,
    shard_market,
)
from bayesian_consensus_engine_tpu.state.checkpoint import CycleCheckpointer

M, K = 32, 8


def _inputs(seed=0):
    rng = np.random.default_rng(seed)
    probs = jnp.asarray(rng.random((M, K)), jnp.float32)
    mask = jnp.asarray(rng.random((M, K)) < 0.8)
    outcome = jnp.asarray(rng.random(M) < 0.5)
    return probs, mask, outcome


class TestSaveRestore:
    def test_round_trip_state_and_meta(self, tmp_path):
        state = init_block_state(M, K)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            assert ckpt.latest_step() is None
            assert ckpt.save(0, state, meta={"now_days": 12.5, "note": "t0"})
            restored, meta = ckpt.restore()
        assert meta == {"now_days": 12.5, "note": "t0"}
        for field in MarketBlockState._fields:
            np.testing.assert_array_equal(
                np.asarray(restored[field]), np.asarray(getattr(state, field)),
                err_msg=field,
            )

    def test_restore_like_preserves_structure_and_dtype(self, tmp_path):
        state = init_block_state(M, K)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(3, state)
            restored, _ = ckpt.restore(like=state)
        assert isinstance(restored, MarketBlockState)
        assert restored.reliability.dtype == jnp.float32
        assert restored.exists.dtype == jnp.bool_

    def test_missing_checkpoint_raises(self, tmp_path):
        with CycleCheckpointer(tmp_path / "empty") as ckpt:
            with pytest.raises(FileNotFoundError):
                ckpt.restore()

    def test_retention_prunes_old_steps(self, tmp_path):
        state = init_block_state(4, 2)
        with CycleCheckpointer(tmp_path / "ckpt", max_to_keep=2) as ckpt:
            for step in (1, 2, 3, 4):
                ckpt.save(step, state)
            assert ckpt.latest_step() == 4
            assert ckpt.all_steps() == [3, 4]

    def test_exists_none_carry_round_trips(self, tmp_path):
        full = init_block_state(M, K)
        state = MarketBlockState(
            full.reliability, full.confidence, full.updated_days, None
        )
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(0, state)
            restored, _ = ckpt.restore(like=state)
        assert isinstance(restored, MarketBlockState)
        assert restored.exists is None
        np.testing.assert_array_equal(
            np.asarray(restored.reliability), np.asarray(state.reliability)
        )


class TestResumeEquivalence:
    def test_resume_matches_uninterrupted_run(self, tmp_path):
        probs, mask, outcome, = _inputs(1)
        loop = build_cycle_loop(mesh=None, slot_major=False, donate=False)
        state0 = init_block_state(M, K)

        # Uninterrupted: 5 consecutive daily cycles.
        full_state, full_consensus = loop(
            probs, mask, outcome, state0, jnp.float32(10.0), 5
        )

        # Interrupted: 3 cycles, checkpoint, "crash", restore, 2 more.
        mid_state, _ = loop(probs, mask, outcome, state0, jnp.float32(10.0), 3)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(3, mid_state, meta={"next_now": 13.0})
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            restored, meta = ckpt.restore(like=mid_state)
        resumed_state, resumed_consensus = loop(
            probs, mask, outcome, restored, jnp.float32(meta["next_now"]), 2
        )

        np.testing.assert_array_equal(
            np.asarray(resumed_consensus), np.asarray(full_consensus)
        )
        for field in MarketBlockState._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(resumed_state, field)),
                np.asarray(getattr(full_state, field)),
                err_msg=field,
            )


class TestStoreCheckpoint:
    def test_store_round_trip_bit_identical(self, tmp_path):
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        store = TensorReliabilityStore()
        store.update_reliability("alpha", "m1", outcome_correct=True)
        store.update_reliability("beta", "m1", outcome_correct=False)
        store.update_reliability("alpha", "m2", outcome_correct=True)
        before = store.list_sources()

        store.save_checkpoint(tmp_path / "store_ckpt")
        loaded = TensorReliabilityStore.load_checkpoint(tmp_path / "store_ckpt")
        after = loaded.list_sources()

        assert after == before  # exact f64 values + ISO strings round-trip
        # Cold-start reads behave identically post-restore.
        rec = loaded.get_reliability("never-seen", "m1")
        assert rec.reliability == store.get_reliability("never-seen", "m1").reliability

    def test_store_checkpoint_then_device_cycle(self, tmp_path):
        """Restore → device_state → cycle → absorb keeps working."""
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        store = TensorReliabilityStore()
        store.update_reliability("a", "m", outcome_correct=True)
        store.save_checkpoint(tmp_path / "ckpt")
        loaded = TensorReliabilityStore.load_checkpoint(tmp_path / "ckpt")
        state, epoch0 = loaded.device_state()
        assert bool(np.asarray(state.exists).any())
        loaded.absorb(state, epoch0)
        assert loaded.list_sources() == store.list_sources()


class TestShardedCheckpoint:
    def test_restore_onto_mesh_sharding(self, tmp_path):
        """`like` with sharded arrays restores shards placed on the mesh."""
        mesh = make_mesh((4, 2))
        state = MarketBlockState(
            *(shard_block(x, mesh) for x in init_block_state(M, K))
        )
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(0, state)
            restored, _ = ckpt.restore(like=state)
        assert restored.reliability.sharding == state.reliability.sharding
        np.testing.assert_array_equal(
            np.asarray(restored.reliability), np.asarray(state.reliability)
        )

    def test_sharded_loop_resume(self, tmp_path):
        probs, mask, outcome = _inputs(2)
        mesh = make_mesh((8, 1))
        loop = build_cycle_loop(mesh=mesh, slot_major=False, donate=False)
        sharded = MarketBlockState(
            *(shard_block(x, mesh) for x in init_block_state(M, K))
        )
        p, m_, o = shard_block(probs, mesh), shard_block(mask, mesh), shard_market(outcome, mesh)

        full_state, full_consensus = loop(p, m_, o, sharded, jnp.float32(1.0), 4)
        mid_state, _ = loop(p, m_, o, sharded, jnp.float32(1.0), 2)
        with CycleCheckpointer(tmp_path / "ckpt") as ckpt:
            ckpt.save(2, mid_state)
            restored, _ = ckpt.restore(like=mid_state)
        resumed_state, resumed_consensus = loop(p, m_, o, restored, jnp.float32(3.0), 2)
        np.testing.assert_array_equal(
            np.asarray(resumed_consensus), np.asarray(full_consensus)
        )
        np.testing.assert_array_equal(
            np.asarray(resumed_state.reliability), np.asarray(full_state.reliability)
        )


class TestPreemptionMidSession:
    """Kill/resume while a settle chain holds DEFERRED state (VERDICT r3 #4).

    Mid-chain, the store's truth is split: pending device state + sync
    recipes (reliabilities still on device behind a lazy gather, stamps/
    existence closed-form, confidences host-replayed). A preemption-safe
    snapshot at that point must capture all of it — ``save_checkpoint``
    forces the sync — and a fresh process restoring the snapshot must
    finish the chain bit-identically to an uninterrupted run.
    """

    def _fixture(self, seed=61, markets=24):
        import random

        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng = random.Random(seed)
        payloads = []
        for m in range(markets):
            n = rng.randint(1, 5)
            signals = [
                {
                    "sourceId": f"src-{rng.randrange(11)}",
                    "probability": round(rng.random(), 6),
                }
                for _ in range(n)
            ]
            payloads.append((f"market-{m}", signals))
        outcomes = [rng.random() < 0.5 for _ in range(markets)]
        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads)
        return store, plan, payloads, outcomes, build_settlement_plan

    def test_kill_resume_mid_sharded_session(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        mesh = make_mesh((4, 2))
        days = [20850.0, 20851.0, 20852.0]

        # Uninterrupted chain: three settles through one session.
        store_u, plan_u, payloads, outcomes, build_plan = self._fixture()
        with ShardedSettlementSession(store_u, plan_u, mesh) as sess:
            for day in days:
                expected = sess.settle(outcomes, steps=2, now=day)
        expected_consensus = np.asarray(expected.consensus)
        expected_records = store_u.list_sources()

        # Interrupted: two settles, snapshot MID-SESSION (pending device
        # truth + sync recipes outstanding), then the process "dies" —
        # the session is abandoned, never closed/synced.
        store_i = TensorReliabilityStore()
        plan_i = build_plan(store_i, payloads)
        session = ShardedSettlementSession(store_i, plan_i, mesh)
        for day in days[:2]:
            session.settle(outcomes, steps=2, now=day)
        assert store_i._pending_sync  # the deferred state is really there
        store_i.save_checkpoint(tmp_path / "preempt")
        del session, store_i  # kill -9: no close(), no sync()

        # Fresh process: restore, rebuild the plan (row assignment is part
        # of the snapshot, so the plan binds), finish the chain.
        store_r = TensorReliabilityStore.load_checkpoint(tmp_path / "preempt")
        plan_r = build_plan(store_r, payloads)
        with ShardedSettlementSession(store_r, plan_r, mesh) as sess:
            resumed = sess.settle(outcomes, steps=2, now=days[2])

        np.testing.assert_array_equal(
            np.asarray(resumed.consensus), expected_consensus
        )
        assert store_r.list_sources() == expected_records

    def test_kill_resume_mid_flat_settle_chain(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import settle
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        days = [20860.0, 20861.0, 20862.0]

        store_u, plan_u, payloads, outcomes, build_plan = self._fixture(seed=67)
        for day in days:
            expected = settle(store_u, plan_u, outcomes, steps=2, now=day)
        expected_consensus = np.asarray(expected.consensus)
        store_u.sync()
        expected_records = store_u.list_sources()

        store_i = TensorReliabilityStore()
        plan_i = build_plan(store_i, payloads)
        for day in days[:2]:
            settle(store_i, plan_i, outcomes, steps=2, now=day)
        assert store_i._pending is not None  # deferred device truth held
        store_i.save_checkpoint(tmp_path / "preempt")
        del store_i  # kill -9 mid-chain

        store_r = TensorReliabilityStore.load_checkpoint(tmp_path / "preempt")
        plan_r = build_plan(store_r, payloads)
        resumed = settle(store_r, plan_r, outcomes, steps=2, now=days[2])
        store_r.sync()

        np.testing.assert_array_equal(
            np.asarray(resumed.consensus), expected_consensus
        )
        assert store_r.list_sources() == expected_records
