"""One-pass settlement kernel (round 14): the interpret-mode bit oracle.

The non-negotiable contract: ``build_cycle_analytics_loop(kernel="pallas")``
— the Pallas kernel computing consensus + tie-break + band moments in one
HBM sweep per tile — is BIT-IDENTICAL to the multi-pass XLA fused program
on the tier-1 CPU backend, across chunk settings, mesh factorisations
(markets-sharded AND, since round 20, sources-sharded: each shard's
kernel emits partials merged by a deterministic cross-device stage),
workloads, and step counts. The parity is structural (the kernel body
traces the same layer-1 functions — ops/cycle_math, ring_tiebreak_math,
band_sums — the XLA program traces under shard_map); these tests are the
empirical pin, mirroring tests/test_ring.py / test_analytics.py.

Also here: the sorted tie-break through the fused session surface
(``settle_with_analytics(tiebreak="sorted")``, the PR-9 follow-up) pinned
byte-equal to the ring path on exactly-representable weights, and the
``settle_kernel`` honesty-guard wiring (``kernel="auto"``).
"""

import struct

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.analytics import AnalyticsOptions
from bayesian_consensus_engine_tpu.ops.cycle_math import MarketBlockState
from bayesian_consensus_engine_tpu.ops.pallas_settle import (
    build_onepass_settle,
    resolve_tile_markets,
)
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.parallel.sharded import (
    build_cycle_analytics_loop,
    init_block_state,
)
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state import JournalWriter
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

M, K = 64, 16
NOW = 21_900.0


def _inputs(workload, seed=0, m=M, k=K):
    """Slot-major (K, M) operand set for a named parity workload."""
    rng = np.random.default_rng(seed)
    probs = rng.random((k, m))
    valid = rng.random((k, m)) < 0.8
    if workload == "mask_holes":
        valid = rng.random((k, m)) < 0.5
        valid[:, 0] = False  # a market with no signalling slot
    elif workload == "single_agent":
        valid = np.zeros((k, m), dtype=bool)
        valid[rng.integers(0, k, m), np.arange(m)] = True
    elif workload == "all_tied":
        # Every agent lands in one quantised group per market.
        probs = np.full((k, m), 0.625)
        valid = np.ones((k, m), dtype=bool)
    else:
        assert workload == "random"
    state = MarketBlockState(
        reliability=jnp.asarray(rng.uniform(0.1, 1.0, (k, m)), jnp.float32),
        confidence=jnp.asarray(rng.uniform(0.0, 1.0, (k, m)), jnp.float32),
        updated_days=jnp.asarray(
            rng.choice([0.0, 5.0, 400.0], (k, m)), jnp.float32
        ),
        exists=jnp.asarray(rng.random((k, m)) < 0.6),
    )
    return (
        jnp.asarray(probs, jnp.float32),
        jnp.asarray(valid),
        jnp.asarray(rng.random(m) < 0.5),
        state,
        jnp.float32(401.0),
    )


def _assert_all_equal(got, want, label=""):
    """Bit-equality over the full 4-tuple (state, consensus, tb, bands)."""
    st_g, cons_g, tb_g, bands_g = got
    st_w, cons_w, tb_w, bands_w = want
    pairs = [("consensus", cons_g, cons_w)]
    pairs += [
        (f"state.{n}", getattr(st_g, n), getattr(st_w, n))
        for n in st_w._fields
    ]
    pairs += [
        (f"tb.{n}", getattr(tb_g, n), getattr(tb_w, n))
        for n in tb_w._fields
    ]
    pairs += [
        (f"bands.{n}", getattr(bands_g, n), getattr(bands_w, n))
        for n in bands_w._fields
    ]
    for name, a, b in pairs:
        a, b = np.asarray(a), np.asarray(b)
        assert np.array_equal(
            a, b, equal_nan=(a.dtype.kind == "f")
        ), f"{label}/{name} not bit-equal"


def _run(mesh, kernel, args, steps, chunk_agents, chunk_slots):
    loop = build_cycle_analytics_loop(
        mesh, chunk_agents=chunk_agents, chunk_slots=chunk_slots,
        donate=False, kernel=kernel,
    )
    st, cons, tb, bands, _ = loop(*args, steps)
    return st, cons, tb, bands


class TestOnepassParityMatrix:
    """ISSUE-12 acceptance (extended by round 20 to 2-D meshes): the
    one-pass kernel bit-identical to the multi-pass XLA fused program —
    store tensors, consensus, tie-break, bands — at every chunk setting,
    across mesh factorisations (including sources-sharded, where the
    kernel emits per-shard partials and the cross-device merge must not
    move a bit) and step counts, in interpret mode on the tier-1
    backend."""

    @pytest.mark.parametrize(
        "mesh_shape", [(1, 1), (8, 1), (4, 2), (2, 4), (1, 8)]
    )
    @pytest.mark.parametrize(
        "workload", ["random", "mask_holes", "all_tied", "single_agent"]
    )
    def test_bit_exact_vs_xla_program(self, mesh_shape, workload):
        args = _inputs(workload)
        mesh = make_mesh(
            mesh_shape, devices=jax.devices()[: mesh_shape[0] * mesh_shape[1]]
        )
        for steps, chunks in [(1, (5, 4)), (3, (None, None)), (3, (5, 4))]:
            want = _run(mesh, "xla", args, steps, *chunks)
            got = _run(mesh, "pallas", args, steps, *chunks)
            _assert_all_equal(
                got, want,
                label=f"{mesh_shape}/{workload}/steps={steps}/chunks={chunks}",
            )

    def test_multi_tile_grid_bit_exact(self):
        # The standalone builder at an explicit sub-shape tile: tiling
        # the markets axis must not move a bit (every reduction runs
        # over the K axis only).
        m, k = 256, 16
        probs, mask, outcome, state, now0 = _inputs("random", seed=3, m=m)
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        want = _run(
            mesh, "xla", (probs, mask, outcome, state, now0), 2, 5, 4
        )
        onepass = build_onepass_settle(
            m, k, 2, chunk_agents=5, chunk_slots=4, tile_markets=64,
            interpret=True,
        )
        got = jax.jit(lambda *a: onepass(*a))(
            probs, mask, outcome, state, now0
        )
        _assert_all_equal(got, want, label="tile=64")

    def test_empty_market_rows_pin(self):
        # RingTieBreakResult's empty-row convention survives the kernel:
        # prediction=+inf, group metrics -inf; bands report NaN/0.
        args = _inputs("mask_holes", seed=1)
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        _st, _cons, tb, bands = _run(mesh, "pallas", args, 1, None, None)
        assert np.asarray(tb.prediction)[0] == np.inf
        assert np.asarray(tb.weight_density)[0] == -np.inf
        assert np.asarray(tb.max_reliability)[0] == -np.inf
        assert np.isnan(np.asarray(bands.mean)[0])
        assert np.asarray(bands.count)[0] == 0
        assert np.asarray(bands.n_eff)[0] == 0.0

    def test_masked_pad_lanes_exact_passthrough(self):
        # Fully-masked markets (the lane-padding shape) keep their state
        # bit-identical — padded columns must stay cold through the
        # in-place aliased update.
        probs, mask, outcome, state, now0 = _inputs("random", seed=9)
        mask = np.array(mask)
        mask[:, M // 2:] = False  # the pad half
        mask = jnp.asarray(mask)
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        st, _cons, _tb, _bands = _run(
            mesh, "pallas", (probs, mask, outcome, state, now0), 2, None,
            None,
        )
        for name in ("reliability", "confidence", "updated_days", "exists"):
            got = np.asarray(getattr(st, name))[:, M // 2:]
            want = np.asarray(getattr(state, name))[:, M // 2:]
            assert np.array_equal(got, want), name

    def test_graph_sweep_rides_the_kernel_path(self):
        m, k = 128, 8
        probs, mask, outcome, state, now0 = _inputs(
            "random", seed=4, m=m, k=k
        )
        rng = np.random.default_rng(9)
        nb_idx = jnp.asarray(rng.integers(-1, m, (m, 3)), jnp.int32)
        nb_w = jnp.asarray(rng.uniform(0.5, 1.5, (m, 3)), jnp.float32)
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        want = build_cycle_analytics_loop(
            mesh, donate=False, sweep_steps=2
        )(probs, mask, outcome, state, now0, 2, nb_idx, nb_w)
        got = build_cycle_analytics_loop(
            mesh, donate=False, sweep_steps=2, kernel="pallas"
        )(probs, mask, outcome, state, now0, 2, nb_idx, nb_w)
        np.testing.assert_array_equal(
            np.asarray(got[4]), np.asarray(want[4])
        )


class TestOnepassRouting:
    """The kernel routing contract: clear errors where the kernel cannot
    serve, silent XLA fallback only for kernel='auto'."""

    def test_sources_sharded_mesh_served(self):
        # Round 20: kernel="pallas" on a sources-sharded mesh is a
        # served route (per-shard partials + cross-device merge), no
        # longer a build-time ValueError.
        mesh = make_mesh((1, 8))
        loop = build_cycle_analytics_loop(mesh, kernel="pallas",
                                          donate=False)
        st, cons, tb, bands, _ = loop(*_inputs("random", seed=6), 1)
        assert np.isfinite(np.asarray(cons)).all()

    def test_sources_sharded_zero_steps_rejected(self):
        # The one genuinely unsupported combination left on the 2-D
        # route: the partials kernel emits RAW last-step consensus sums,
        # and a zero-step program's zero consensus is not representable
        # as sums. The refusal names the route and the fix.
        mesh = make_mesh((1, 8))
        loop = build_cycle_analytics_loop(mesh, kernel="pallas",
                                          donate=False)
        args = _inputs("random", seed=6)
        with pytest.raises(ValueError, match="steps=0 on a"):
            loop(*args, 0)
        # auto degrades to the XLA program instead of refusing.
        auto = build_cycle_analytics_loop(mesh, kernel="auto",
                                          donate=False)
        st, cons, tb, bands, _ = auto(*args, 0)
        assert np.isfinite(np.asarray(cons)).all()

    def test_stage_off_rejected(self):
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="one sweep"):
            build_cycle_analytics_loop(
                mesh, kernel="pallas", with_bands=False
            )
        with pytest.raises(ValueError, match="one sweep"):
            build_cycle_analytics_loop(
                mesh, kernel="pallas", tiebreak_kind="sorted"
            )

    def test_auto_falls_back_where_ineligible(self):
        # auto on a sources-sharded mesh resolves through the tuner
        # like any other shape (round 20 made the route raceable);
        # with BCE_AUTOTUNE unset the tuner is off and XLA ships.
        mesh = make_mesh((1, 8))
        loop = build_cycle_analytics_loop(mesh, kernel="auto", donate=False)
        args = _inputs("random", seed=2)
        st, cons, tb, bands, _ = loop(*args, 1)
        assert np.isfinite(np.asarray(cons)).all()

    def test_unknown_kernel_rejected(self):
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="kernel="):
            build_cycle_analytics_loop(mesh, kernel="mosaic")

    def test_non_f32_state_rejected(self):
        onepass = build_onepass_settle(8, 2, 1, interpret=True)
        state = MarketBlockState(
            reliability=jnp.zeros((2, 8), jnp.float16),
            confidence=jnp.zeros((2, 8), jnp.float16),
            updated_days=jnp.zeros((2, 8), jnp.float16),
            exists=jnp.zeros((2, 8), bool),
        )
        with pytest.raises(ValueError, match="float32"):
            onepass(
                jnp.zeros((2, 8), jnp.float32),
                jnp.ones((2, 8), bool),
                jnp.zeros(8, bool),
                state,
                1.0,
            )

    def test_ragged_tile_rejected(self):
        with pytest.raises(ValueError, match="not a multiple"):
            build_onepass_settle(100, 4, 1, tile_markets=64)

    def test_tile_resolution_respects_vmem_budget(self):
        # Small K: big tiles fit. Large K: the tile shrinks so the
        # double-buffered block set stays inside the 16 MB budget.
        assert resolve_tile_markets(1_048_576, 16) == 2048
        tile = resolve_tile_markets(16_384, 10_000)
        assert tile * 10_000 * 4 * 11 * 2 <= 16 * 1024 * 1024 or (
            tile == 16_384
        )


def _grid_payloads(markets=12, srcs=5, seed=7):
    """Exactly-representable probabilities on the tie-break's quantised
    grid; a cold store reads uniform default weights — the byte-parity
    regime the ring/sorted comparison is pinned on."""
    rng = np.random.default_rng(seed)
    grid = np.round(np.linspace(0.05, 0.95, 19), 6)
    payloads = [
        (
            f"m-{i}",
            [
                {"sourceId": f"s-{j}", "probability": float(rng.choice(grid))}
                for j in range(srcs)
            ],
        )
        for i in range(markets)
    ]
    return payloads, list(rng.random(markets) < 0.5)


class TestSortedTiebreak:
    """The PR-9 follow-up: the sort-based grouping kernel through the
    same fused session surface, byte-parity-pinned against the ring
    path on exactly-representable weights."""

    def test_fused_sorted_equals_ring_on_representable_weights(self):
        rng = np.random.default_rng(2)
        grid = np.round(np.linspace(0.05, 0.95, 19), 6)
        m, k = 64, 8
        probs = jnp.asarray(rng.choice(grid, (k, m)), jnp.float32)
        mask = jnp.asarray(rng.random((k, m)) < 0.8)
        outcome = jnp.asarray(rng.random(m) < 0.5)
        # Cold state: every slot reads the default reliability and
        # confidence — exactly-representable weights, uniform conf (so
        # even the two variance expressions agree exactly).
        state = jax.tree.map(lambda x: x.T, init_block_state(m, k))
        mesh = make_mesh((2, 1), devices=jax.devices()[:2])
        now0 = jnp.float32(400.0)
        ring = build_cycle_analytics_loop(mesh, donate=False)
        srt = build_cycle_analytics_loop(
            mesh, donate=False, tiebreak_kind="sorted"
        )
        tb_r = ring(probs, mask, outcome, state, now0, 1)[2]
        tb_s = srt(probs, mask, outcome, state, now0, 1)[2]
        for name in tb_r._fields:
            a = np.asarray(getattr(tb_s, name))
            b = np.asarray(getattr(tb_r, name))
            np.testing.assert_array_equal(a, b, err_msg=name)

    def test_empty_rows_keep_each_kernels_convention(self):
        # Documented divergence: batched reports NaN/0 for empty rows,
        # the ring path ±inf — conventions, not disagreements.
        rng = np.random.default_rng(3)
        m, k = 16, 4
        probs = jnp.asarray(rng.random((k, m)), jnp.float32)
        mask_np = rng.random((k, m)) < 0.7
        mask_np[:, 0] = False
        mask = jnp.asarray(mask_np)
        outcome = jnp.asarray(rng.random(m) < 0.5)
        state = jax.tree.map(lambda x: x.T, init_block_state(m, k))
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        now0 = jnp.float32(400.0)
        tb_r = build_cycle_analytics_loop(mesh, donate=False)(
            probs, mask, outcome, state, now0, 1
        )[2]
        tb_s = build_cycle_analytics_loop(
            mesh, donate=False, tiebreak_kind="sorted"
        )(probs, mask, outcome, state, now0, 1)[2]
        assert np.asarray(tb_r.prediction)[0] == np.inf
        assert np.isnan(np.asarray(tb_s.prediction)[0])
        assert np.asarray(tb_s.weight_density)[0] == 0.0

    def test_sorted_rejected_on_sources_sharded_mesh(self):
        mesh = make_mesh((1, 8))
        with pytest.raises(ValueError, match="sorted"):
            build_cycle_analytics_loop(mesh, tiebreak_kind="sorted")

    def test_session_surface_sorted(self):
        payloads, outcomes = _grid_payloads()
        stores = [TensorReliabilityStore() for _ in range(2)]
        plans = [
            build_settlement_plan(s, payloads, num_slots=8) for s in stores
        ]
        mesh = make_mesh()
        with ShardedSettlementSession(stores[0], plans[0], mesh) as ring:
            _res_r, tb_r, _b, _p = ring.settle_with_analytics(
                outcomes, now=NOW, analytics=AnalyticsOptions(chunk_slots=4)
            )
        with ShardedSettlementSession(stores[1], plans[1], mesh) as srt:
            _res_s, tb_s, _b, _p = srt.settle_with_analytics(
                outcomes, now=NOW,
                analytics=AnalyticsOptions(chunk_slots=4, tiebreak="sorted"),
            )
        for name in ("prediction", "weight_density", "max_reliability",
                     "resolved_by", "num_groups", "confidence_variance"):
            np.testing.assert_array_equal(
                np.asarray(getattr(tb_s, name)),
                np.asarray(getattr(tb_r, name)),
                err_msg=name,
            )
        # Settlement bytes untouched by the tie-break flavour.
        rows = np.arange(stores[0].live_row_count())
        for got, want in zip(
            stores[1].host_rows(rows), stores[0].host_rows(rows)
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_unknown_tiebreak_option_rejected(self):
        payloads, outcomes = _grid_payloads(markets=2, srcs=2)
        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads, num_slots=4)
        with ShardedSettlementSession(store, plan, make_mesh()) as session:
            with pytest.raises(ValueError, match="sorted"):
                session.settle_with_analytics(
                    outcomes, now=NOW,
                    analytics=AnalyticsOptions(tiebreak="quantised"),
                )


class TestSessionKernelParity:
    """``settle_with_analytics(kernel="pallas")`` byte-equal to the XLA
    default over CHAINED settles on the resident session — store rows,
    consensus, tie-break, bands (the donation/aliasing path included)."""

    def _run(self, kernel):
        payloads, outcomes = _grid_payloads(markets=10, srcs=4, seed=5)
        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads, num_slots=8)
        options = AnalyticsOptions(chunk_slots=4, chunk_agents=3)
        with ShardedSettlementSession(store, plan, make_mesh()) as session:
            session.settle_with_analytics(
                outcomes, steps=2, now=NOW, analytics=options, kernel=kernel
            )
            out = session.settle_with_analytics(
                outcomes, steps=2, now=NOW + 1, analytics=options,
                kernel=kernel,
            )
        rows = np.arange(store.live_row_count())
        return out, [np.asarray(x) for x in store.host_rows(rows)]

    def test_store_and_outputs_bit_equal(self):
        (res_x, tb_x, bands_x, _), rows_x = self._run("xla")
        (res_p, tb_p, bands_p, _), rows_p = self._run("pallas")
        for i, (a, b) in enumerate(zip(rows_p, rows_x)):
            np.testing.assert_array_equal(a, b, err_msg=f"store array {i}")
        np.testing.assert_array_equal(
            np.asarray(res_p.consensus), np.asarray(res_x.consensus)
        )
        for name in tb_x._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(tb_p, name)),
                np.asarray(getattr(tb_x, name)),
                err_msg=f"tb.{name}",
            )
        for name in bands_x._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(bands_p, name)),
                np.asarray(getattr(bands_x, name)),
                err_msg=f"bands.{name}",
            )


def _journal_epochs_sans_clock(path):
    """Decoded epoch frames with the wall-clock field masked (same
    helper as test_serve/test_analytics)."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


class TestShardedSessionByteParity:
    """Round-20 acceptance: the partials route through the FUSED session
    surface on a sources-sharded ``(2, 4)`` mesh — store digest (every
    live row), journal epoch payloads (wall_ts masked), and SQLite
    bytes all byte-equal to the XLA default over chained banded
    settles. Settlement is durable state; the kernel may not move a
    byte of it."""

    def _run(self, kernel, tmp_path):
        payloads, outcomes = _grid_payloads(markets=10, srcs=4, seed=5)
        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads, num_slots=8)
        options = AnalyticsOptions(chunk_slots=4, chunk_agents=3)
        mesh = make_mesh((2, 4))
        with ShardedSettlementSession(store, plan, mesh) as session:
            session.settle_with_analytics(
                outcomes, steps=2, now=NOW, analytics=options, kernel=kernel
            )
            session.settle_with_analytics(
                outcomes, steps=2, now=NOW + 1, analytics=options,
                kernel=kernel,
            )
        jrnl = tmp_path / f"{kernel}.jrnl"
        with JournalWriter(jrnl) as journal:
            store.flush_to_journal(journal, tag=1)
        db = tmp_path / f"{kernel}.db"
        store.flush_to_sqlite(db)
        return store, jrnl, db

    def test_store_journal_sqlite_byte_equal(self, tmp_path):
        store_x, jrnl_x, db_x = self._run("xla", tmp_path)
        store_p, jrnl_p, db_p = self._run("pallas", tmp_path)
        rows = np.arange(store_x.live_row_count())
        for i, (a, b) in enumerate(
            zip(store_p.host_rows(rows), store_x.host_rows(rows))
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"store array {i}"
            )
        assert _journal_epochs_sans_clock(jrnl_p) == (
            _journal_epochs_sans_clock(jrnl_x)
        )
        assert db_p.read_bytes() == db_x.read_bytes()


class TestSettleKernelAutotune:
    """kernel="auto" rides the ShapeTuner contract (knob
    ``settle_kernel``): off → XLA without measuring; on → the honesty
    guard races the kernel against the XLA default on the same clock."""

    def test_auto_resolves_through_tuner(self, monkeypatch):
        from bayesian_consensus_engine_tpu.parallel import sharded
        from bayesian_consensus_engine_tpu.utils import autotune

        seen = {}

        class FakeTuner:
            def tune(self, knob, shape_key, candidates, measure, default):
                seen.update(
                    knob=knob, shape_key=shape_key,
                    candidates=candidates, default=default,
                )
                return "pallas"

        monkeypatch.setattr(autotune, "default_tuner", lambda: FakeTuner())
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        choice = sharded._tuned_settle_kernel(
            mesh, 16, 256, 2, None, None, 6, 1.959964
        )
        assert choice == "pallas"
        assert seen["knob"] == "settle_kernel"
        # Chunk knobs ride the key: a verdict raced at one chunk config
        # must never answer for another (the programs differ).
        assert seen["shape_key"] == (16, 256, 2, None, None, 1, 1)
        assert seen["candidates"] == ["pallas"]
        assert seen["default"] == "xla"

    def test_default_off_resolves_xla_without_measuring(
        self, monkeypatch, tmp_path
    ):
        from bayesian_consensus_engine_tpu.parallel import sharded
        from bayesian_consensus_engine_tpu.utils import autotune

        monkeypatch.delenv("BCE_AUTOTUNE", raising=False)
        monkeypatch.setattr(autotune, "_default_tuner", None)
        monkeypatch.setattr(
            autotune, "_default_cache_path",
            lambda: str(tmp_path / "never.json"),
        )
        mesh = make_mesh((1, 1), devices=jax.devices()[:1])
        choice = sharded._tuned_settle_kernel(
            mesh, 16, 256, 2, None, None, 6, 1.959964
        )
        assert choice == "xla"
        assert not (tmp_path / "never.json").exists()

    def test_real_race_records_honesty_verdict(self, tmp_path):
        # A REAL (tiny-shape) race through an enabled tuner: whatever
        # wins, the cache entry must carry the default and the verdict —
        # a tuned "pallas" may only ship with beat_default=True.
        from bayesian_consensus_engine_tpu.parallel import sharded
        from bayesian_consensus_engine_tpu.utils.autotune import ShapeTuner
        from bayesian_consensus_engine_tpu.utils import autotune

        tuner = ShapeTuner(
            cache_path=str(tmp_path / "cache.json"), enabled=True
        )
        orig = autotune.default_tuner
        autotune.default_tuner = lambda: tuner
        try:
            mesh = make_mesh((1, 1), devices=jax.devices()[:1])
            choice = sharded._tuned_settle_kernel(
                mesh, 4, 16, 1, None, None, 6, 1.959964
            )
            decision = tuner.decision(
                "settle_kernel", (4, 16, 1, None, None, 1, 1)
            )
        finally:
            autotune.default_tuner = orig
        assert decision is not None
        assert decision["default"] == "xla"
        assert decision["choice"] == choice
        if choice == "pallas":
            assert decision["beat_default"] is True
