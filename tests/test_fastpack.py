"""Native ingest packer ≡ pure-Python packer, plus build tooling.

Round 10 grows this into the PACKER-PARITY MATRIX: the object packer,
the native columnar grouping pass, its numpy twin, and the zero-copy
coded intake are all driven over the same edge-case workloads and must
produce byte-identical plans and fingerprints — plus a forced-fallback
subprocess lane (``BCE_NO_NATIVE=1``) proving the pure-Python twin stack
(packers AND interner) still matches the native build bit-for-bit, so
the twins can never rot unexercised.
"""

import hashlib
import json
import os
import random
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from bayesian_consensus_engine_tpu.core import batch as batch_mod
from bayesian_consensus_engine_tpu.core.batch import (
    SourceCodes,
    columns_from_payloads,
    encode_source_ids,
    mapping_lookup,
    pack_markets,
    topology_fingerprint,
)

needs_native = pytest.mark.skipif(
    batch_mod._fastpack is None,
    reason="native fastpack not built (python native/build.py)",
)

needs_columnar_native = pytest.mark.skipif(
    not batch_mod._columnar_native_available(),
    reason="columnar fastpack not built (python native/build.py)",
)


def _random_markets(seed=0, num_markets=25):
    rng = random.Random(seed)
    markets = []
    for m in range(num_markets):
        signals = [
            {
                "sourceId": f"src-{rng.randint(0, 7)}",
                "probability": round(rng.random(), 6),
            }
            for _ in range(rng.randint(0, 12))
        ]
        markets.append((f"market-{m}", signals))
    return markets


@needs_native
class TestNativePythonEquivalence:
    def test_identical_packing(self):
        markets = _random_markets()
        rel = {f"src-{i}": {"reliability": 0.1 * i, "confidence": 0.05 * i}
               for i in range(5)}
        lookup = mapping_lookup(rel)
        native = pack_markets(markets, lookup, native=True)
        python = pack_markets(markets, lookup, native=False)

        assert native.market_keys == python.market_keys
        assert native.pair_source_ids == python.pair_source_ids
        np.testing.assert_array_equal(native.pair_market, python.pair_market)
        np.testing.assert_array_equal(native.flat_probs, python.flat_probs)
        np.testing.assert_array_equal(native.flat_pair, python.flat_pair)
        np.testing.assert_array_equal(
            native.signals_per_market, python.signals_per_market
        )
        np.testing.assert_array_equal(native.pair_offsets, python.pair_offsets)
        np.testing.assert_array_equal(
            native.pair_reliability, python.pair_reliability
        )
        np.testing.assert_array_equal(
            native.pair_confidence, python.pair_confidence
        )
        np.testing.assert_array_equal(native.pair_known, python.pair_known)

    def test_empty_and_single(self):
        for markets in ([], [("only", [])], [("one", [{"sourceId": "a", "probability": 1.0}])]):
            native = pack_markets(markets, native=True)
            python = pack_markets(markets, native=False)
            assert native.pair_source_ids == python.pair_source_ids
            np.testing.assert_array_equal(native.pair_offsets, python.pair_offsets)

    def test_duplicate_heavy(self):
        markets = [
            ("m", [{"sourceId": "a", "probability": p} for p in (0.1, 0.2, 0.3)]
                  + [{"sourceId": "b", "probability": 0.9}])
        ]
        native = pack_markets(markets, native=True)
        assert native.pair_source_ids == ["a", "b"]
        np.testing.assert_array_equal(native.flat_pair, [0, 0, 0, 1])

    def test_native_used_by_default_when_built(self):
        # auto-detect prefers the native path when the extension is present
        assert batch_mod._fastpack is not None

    def test_faster_than_python(self):
        import time

        markets = _random_markets(seed=1, num_markets=2000)
        # Warm both paths, then take best-of-3: a single-shot wall-clock
        # comparison flakes on loaded CI runners (one scheduler stall can
        # exceed any fixed margin).
        pack_markets(markets, native=True)
        pack_markets(markets, native=False)

        def best_of(native):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                pack_markets(markets, native=native)
                best = min(best, time.perf_counter() - t0)
            return best

        native_dt, python_dt = best_of(True), best_of(False)
        # Non-regression guard only (real gain is ~1.3x; wide margin for CI
        # noise — this catches the native path becoming pathologically slow,
        # not small perf drift).
        assert native_dt < python_dt * 2.0, (native_dt, python_dt)


# ---------------------------------------------------------------------------
# Packer-parity matrix: every intake, same bytes.
# ---------------------------------------------------------------------------

def _edge_payloads(name):
    """Edge-case workloads the matrix runs every intake over."""
    if name == "dup_signals":
        # Duplicate sources within one market: averaging order is the
        # float contract (left-to-right per pair).
        return [
            ("m0", [
                {"sourceId": "a", "probability": 0.1},
                {"sourceId": "b", "probability": 0.9},
                {"sourceId": "a", "probability": 0.3},
                {"sourceId": "a", "probability": 0.70000001},
            ]),
            ("m1", [{"sourceId": "b", "probability": 0.5}]),
        ]
    if name == "empty_market":
        # A zero-signal market between live ones: offsets carry an
        # equal consecutive pair; slot height comes from its neighbours.
        return [
            ("m0", [{"sourceId": "x", "probability": 0.25}]),
            ("empty", []),
            ("m2", [
                {"sourceId": "y", "probability": 0.75},
                {"sourceId": "x", "probability": 0.5},
            ]),
        ]
    if name == "extreme_probs":
        # 0/1 probabilities: the consensus edge values must survive the
        # accumulate bit-for-bit.
        return [
            ("m0", [
                {"sourceId": "s0", "probability": 0.0},
                {"sourceId": "s1", "probability": 1.0},
                {"sourceId": "s0", "probability": 1.0},
                {"sourceId": "s2", "probability": 0.0},
            ]),
        ]
    assert name == "random"
    rng = random.Random(11)
    return [
        (
            f"market-{m}",
            [
                {
                    "sourceId": f"src-{rng.randint(0, 30)}",
                    "probability": rng.random(),
                }
                for _ in range(rng.randint(0, 9))
            ],
        )
        for m in range(40)
    ]


def _plan_signature(plan):
    """Everything observable about a plan, as comparable bytes."""
    return (
        tuple(plan.market_keys),
        plan.slot_rows.tobytes(),
        plan.probs.tobytes(),
        plan.mask.tobytes(),
        plan.signals_per_market.tobytes(),
        plan.binding,
        plan.fingerprint,
    )


def _build_by_intake(intake, payloads):
    from bayesian_consensus_engine_tpu.pipeline import (
        build_settlement_plan,
        build_settlement_plan_columnar,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    store = TensorReliabilityStore()
    if intake == "object":
        return build_settlement_plan(store, payloads, fingerprint=True)
    keys, sids, probs, offsets = columns_from_payloads(
        payloads, native=False
    )
    if intake == "zero_copy":
        sids = encode_source_ids(sids)
    native = {"columnar_native": True, "zero_copy": None,
              "columnar_python": False}[intake]
    return build_settlement_plan_columnar(
        store, keys, sids, probs, offsets, fingerprint=True, native=native
    )


INTAKES = ("object", "columnar_native", "columnar_python", "zero_copy")
EDGES = ("random", "dup_signals", "empty_market", "extreme_probs")


@needs_columnar_native
class TestPackerParityMatrix:
    """Every intake × every edge workload → byte-identical plans."""

    @pytest.mark.parametrize("edge", EDGES)
    @pytest.mark.parametrize("intake", INTAKES[1:])
    def test_intake_matches_object_path(self, edge, intake):
        payloads = _edge_payloads(edge)
        reference = _plan_signature(_build_by_intake("object", payloads))
        assert _plan_signature(_build_by_intake(intake, payloads)) == reference

    def test_reorder_misses_fingerprint_on_every_intake(self):
        payloads = _edge_payloads("dup_signals")
        keys, sids, probs, offsets = columns_from_payloads(
            payloads, native=False
        )
        base_string = topology_fingerprint(keys, sids, offsets)
        base_coded = topology_fingerprint(
            keys, encode_source_ids(sids), offsets
        )
        assert base_string == base_coded
        # Swap two same-market signals with DISTINCT ids: source order
        # within a market is a float-summation contract, so the digest
        # MUST move (a reordered batch may never be served by a
        # probability-only refresh).
        swapped = list(sids)
        assert swapped[0] != swapped[1]
        swapped[0], swapped[1] = swapped[1], swapped[0]
        assert topology_fingerprint(keys, swapped, offsets) != base_string
        assert (
            topology_fingerprint(keys, encode_source_ids(swapped), offsets)
            != base_coded
        )

    def test_zero_copy_codes_need_not_be_first_seen(self):
        # Any consistent (codes, table) encoding is legal — only the
        # decoded column matters. Reverse the table, remap the codes.
        payloads = _edge_payloads("random")
        keys, sids, probs, offsets = columns_from_payloads(
            payloads, native=False
        )
        canonical = encode_source_ids(sids)
        table = list(reversed(canonical.table))
        remap = {sid: i for i, sid in enumerate(table)}
        scrambled = SourceCodes(
            np.asarray([remap[s] for s in sids], np.int32), table
        )
        assert (
            topology_fingerprint(keys, scrambled, offsets)
            == topology_fingerprint(keys, sids, offsets)
        )
        ref = _plan_signature(_build_by_intake("object", payloads))
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan_columnar,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        plan = build_settlement_plan_columnar(
            TensorReliabilityStore(), keys, scrambled, probs, offsets,
            fingerprint=True,
        )
        assert _plan_signature(plan) == ref

    def test_source_codes_validation(self):
        with pytest.raises(ValueError, match="unique"):
            SourceCodes(np.asarray([0, 1], np.int32), ["a", "a"])
        with pytest.raises(ValueError, match="empty table"):
            SourceCodes(np.asarray([0], np.int32), [])
        # Out-of-range codes are rejected AT CONSTRUCTION: a negative
        # code would wrap through Python/numpy negative indexing into a
        # silently aliased fingerprint (a wrong-topology reuse hit).
        with pytest.raises(ValueError, match="out of table range"):
            SourceCodes(np.asarray([5], np.int32), ["a"])
        with pytest.raises(ValueError, match="out of table range"):
            SourceCodes(np.asarray([-1], np.int32), ["a", "b"])
        from bayesian_consensus_engine_tpu.pipeline import (
            stage_settlement_plan_columnar,
        )

        # The builder re-checks (codes are mutable numpy state): a
        # post-construction mutation cannot sneak past the stage.
        bad = SourceCodes(np.asarray([0], np.int32), ["a"])
        bad.codes[0] = 5
        with pytest.raises(ValueError, match="out of table range"):
            stage_settlement_plan_columnar(
                ["m"], bad, np.asarray([0.5]), np.asarray([0, 1], np.int64)
            )

    def test_group_columns_rejects_short_offsets(self):
        # A terminal offset short of the signal count must error in BOTH
        # twins (the C pass would otherwise drop the tail and return
        # uninitialized signal->pair entries).
        from bayesian_consensus_engine_tpu.core.batch import group_columns

        codes = np.asarray([0, 1, 0], np.int32)
        rank = np.asarray([0, 1], np.int32)
        offsets = np.asarray([0, 2], np.int64)  # covers 2 of 3 signals
        probs = np.asarray([0.1, 0.2, 0.3])
        with pytest.raises(ValueError):
            group_columns(codes, rank, offsets, probs, native=True)
        with pytest.raises(ValueError):
            group_columns(codes, rank, offsets, probs, native=False)

    def test_twins_reject_negative_indices_alike(self):
        # Negative codes/pair indices: numpy would silently WRAP them
        # (negative indexing) where C raises — both twins must error.
        from bayesian_consensus_engine_tpu.core.batch import (
            group_columns,
            pair_accumulate,
        )

        codes = np.asarray([-1], np.int32)
        rank = np.asarray([0, 1], np.int32)
        offsets = np.asarray([0, 1], np.int64)
        probs = np.asarray([0.5])
        for native in (True, False):
            with pytest.raises(IndexError):
                group_columns(codes, rank, offsets, probs, native=native)
            with pytest.raises(IndexError):
                pair_accumulate(
                    np.asarray([-1], np.int64), probs, 2, native=native
                )

    def test_no_native_env_flips_auto_detection(self, monkeypatch):
        assert batch_mod._columnar_native_available()
        assert batch_mod._object_native_available()
        monkeypatch.setenv("BCE_NO_NATIVE", "1")
        # A RUNTIME env change flips the whole auto-detected stack (no
        # half-native hybrid): fastpack auto-detection and the interner
        # consult the same knob per call.
        assert not batch_mod._columnar_native_available()
        assert not batch_mod._object_native_available()
        from bayesian_consensus_engine_tpu.utils.interning import (
            _load_internmap,
        )

        assert _load_internmap() is None

    def test_stage_then_bind_equals_one_shot_build(self):
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan_columnar,
            stage_settlement_plan_columnar,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        payloads = _edge_payloads("random")
        keys, sids, probs, offsets = columns_from_payloads(
            payloads, native=False
        )
        one_shot = build_settlement_plan_columnar(
            TensorReliabilityStore(), keys, sids, probs, offsets,
            fingerprint=True,
        )
        staged = stage_settlement_plan_columnar(
            keys, sids, probs, offsets, fingerprint=True
        )
        plan = staged.bind(TensorReliabilityStore())
        assert _plan_signature(plan) == _plan_signature(one_shot)

    def test_columns_from_payloads_native_matches_python(self):
        for edge in EDGES:
            payloads = _edge_payloads(edge)
            k0, s0, p0, o0 = columns_from_payloads(payloads, native=False)
            k1, s1, p1, o1 = columns_from_payloads(payloads, native=True)
            assert k1 == k0 and s1 == s0
            np.testing.assert_array_equal(p1, p0)
            np.testing.assert_array_equal(o1, o0)


# ---------------------------------------------------------------------------
# Forced-fallback lane: BCE_NO_NATIVE=1 ≡ native build, bit for bit.
# ---------------------------------------------------------------------------

_FALLBACK_SCRIPT = textwrap.dedent(
    """
    import hashlib, json, sys

    import numpy as np

    from bayesian_consensus_engine_tpu.core import batch as batch_mod
    from bayesian_consensus_engine_tpu.utils.interning import _load_internmap

    # The knob must actually have forced every native path off.
    assert batch_mod._fastpack is None, "fastpack not gated"
    assert _load_internmap() is None, "internmap not gated"

    from bayesian_consensus_engine_tpu.pipeline import (
        build_settlement_plan,
        build_settlement_plan_columnar,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    payloads = [tuple(p) for p in json.load(open(sys.argv[1]))]
    keys = [m for m, _ in payloads]
    sids, probs, offsets = [], [], [0]
    for _m, signals in payloads:
        for s in signals:
            sids.append(s["sourceId"])
            probs.append(s["probability"])
        offsets.append(len(sids))
    probs = np.asarray(probs, np.float64)
    offsets = np.asarray(offsets, np.int64)

    for plan in (
        build_settlement_plan(
            TensorReliabilityStore(), payloads, fingerprint=True
        ),
        build_settlement_plan_columnar(
            TensorReliabilityStore(), keys, sids, probs, offsets,
            fingerprint=True,
        ),
    ):
        digest = hashlib.blake2b(digest_size=16)
        digest.update(plan.slot_rows.tobytes())
        digest.update(plan.probs.tobytes())
        digest.update(plan.mask.tobytes())
        digest.update(repr(plan.binding).encode())
        digest.update(plan.fingerprint)
        print(digest.hexdigest())
    """
)


class TestForcedFallbackLane:
    """``BCE_NO_NATIVE=1`` — the CI lane that keeps the twins honest."""

    def test_pure_python_stack_matches_this_process(self, tmp_path):
        payloads = _edge_payloads("random") + _edge_payloads("dup_signals")
        keys = [f"{i}:{m}" for i, (m, _s) in enumerate(payloads)]
        payloads = [
            (key, signals) for key, (_m, signals) in zip(keys, payloads)
        ]
        payload_file = tmp_path / "payloads.json"
        payload_file.write_text(json.dumps(payloads))

        env = dict(os.environ)
        env["BCE_NO_NATIVE"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _FALLBACK_SCRIPT, str(payload_file)],
            capture_output=True, text=True, env=env, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lane_digests = proc.stdout.split()
        assert len(lane_digests) == 2

        # The same builds in THIS (native-enabled) process must match.
        expected = []
        for plan in (
            _build_by_intake("object", payloads),
            _build_by_intake("columnar_python", payloads),
        ):
            digest = hashlib.blake2b(digest_size=16)
            digest.update(plan.slot_rows.tobytes())
            digest.update(plan.probs.tobytes())
            digest.update(plan.mask.tobytes())
            digest.update(repr(plan.binding).encode())
            digest.update(plan.fingerprint)
            expected.append(digest.hexdigest())
        assert lane_digests == expected


_DELTA_FALLBACK_SCRIPT = textwrap.dedent(
    """
    import hashlib, json, sys

    import numpy as np

    from bayesian_consensus_engine_tpu.core import batch as batch_mod
    from bayesian_consensus_engine_tpu.utils.interning import _load_internmap

    assert batch_mod._fastpack is None, "fastpack not gated"
    assert _load_internmap() is None, "internmap not gated"

    from bayesian_consensus_engine_tpu.pipeline import (
        stage_settlement_plan_columnar,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    batches = json.load(open(sys.argv[1]))
    store = TensorReliabilityStore()
    for keys, sids, probs, offsets in batches:
        plan = stage_settlement_plan_columnar(
            keys, sids, np.asarray(probs, np.float64),
            np.asarray(offsets, np.int64), intern_mode="auto",
        ).bind(store)
        digest = hashlib.blake2b(digest_size=16)
        digest.update(plan.slot_rows.tobytes())
        digest.update(plan.probs.tobytes())
        digest.update(plan.mask.tobytes())
        digest.update(repr(plan.binding).encode())
        print(digest.hexdigest())
    table = hashlib.blake2b(digest_size=16)
    for pair in store._pairs.ids():
        table.update(repr(pair).encode())
    print(table.hexdigest())
    """
)


class TestDeltaForcedFallbackLane:
    """``BCE_NO_NATIVE=1`` over the DELTA-INTERNING chain (round 15): a
    base + drifted + reordered batch sequence bound through the epoch-
    persistent pair table on the pure-Python twins must produce plans,
    row assignment, and pair-table contents byte-identical to this
    process's native builds — including the sharded probe+commit route,
    forced here at toy size."""

    def _batches(self):
        rng = np.random.default_rng(17)
        markets = [f"mk-{i}" for i in range(12)]
        base_sids, base_offsets = [], [0]
        for _ in markets:
            for _ in range(int(rng.integers(1, 4))):
                base_sids.append(f"s-{int(rng.integers(0, 8))}")
            base_offsets.append(len(base_sids))
        base = (markets, base_sids,
                rng.random(len(base_sids)).tolist(), base_offsets)
        # Drift: re-draw the last market's sources.
        d_sids = list(base_sids[: base_offsets[-2]]) + ["s-drift"]
        d_offsets = base_offsets[:-1] + [len(d_sids)]
        drift = (markets, d_sids,
                 rng.random(len(d_sids)).tolist(), d_offsets)
        # Reorder: reversed market order, spliced from base.
        r_sids, r_offsets = [], [0]
        for m in reversed(range(len(markets))):
            r_sids.extend(base_sids[base_offsets[m]:base_offsets[m + 1]])
            r_offsets.append(len(r_sids))
        reorder = (list(reversed(markets)), r_sids,
                   rng.random(len(r_sids)).tolist(), r_offsets)
        return [base, drift, reorder]

    def test_delta_twin_matches_native_sharded(self, tmp_path,
                                               monkeypatch):
        import hashlib as _hashlib

        batches = self._batches()
        batch_file = tmp_path / "batches.json"
        batch_file.write_text(json.dumps(batches))

        env = dict(os.environ)
        env["BCE_NO_NATIVE"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _DELTA_FALLBACK_SCRIPT,
             str(batch_file)],
            capture_output=True, text=True, env=env, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lane_digests = proc.stdout.split()
        assert len(lane_digests) == len(batches) + 1

        # This process: native delta chain with the sharded probe+commit
        # route FORCED for every miss set.
        from bayesian_consensus_engine_tpu.pipeline import (
            stage_settlement_plan_columnar,
        )
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )
        from bayesian_consensus_engine_tpu.utils import interning

        monkeypatch.setattr(interning, "SHARD_MIN_PAIRS", 1)
        monkeypatch.setenv("BCE_INTERN_WORKERS", "2")
        store = TensorReliabilityStore()
        expected = []
        for keys, sids, probs, offsets in batches:
            plan = stage_settlement_plan_columnar(
                keys, sids, np.asarray(probs, np.float64),
                np.asarray(offsets, np.int64), intern_mode="auto",
            ).bind(store)
            digest = _hashlib.blake2b(digest_size=16)
            digest.update(plan.slot_rows.tobytes())
            digest.update(plan.probs.tobytes())
            digest.update(plan.mask.tobytes())
            digest.update(repr(plan.binding).encode())
            expected.append(digest.hexdigest())
        table = _hashlib.blake2b(digest_size=16)
        for pair in store._pairs.ids():
            table.update(repr(pair).encode())
        expected.append(table.hexdigest())
        assert lane_digests == expected


class TestFallback:
    def test_python_path_always_available(self):
        markets = _random_markets(seed=2)
        packed = pack_markets(markets, native=False)
        assert packed.num_markets == len(markets)

    def test_force_native_without_build_raises(self, monkeypatch):
        monkeypatch.setattr(batch_mod, "_fastpack", None)
        with pytest.raises(RuntimeError, match="native packer requested"):
            pack_markets(_random_markets(), native=True)

    def test_build_script_importable(self):
        import importlib.util
        import pathlib

        spec = importlib.util.spec_from_file_location(
            "native_build",
            pathlib.Path(__file__).parents[1] / "native" / "build.py",
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        assert callable(module.build)
