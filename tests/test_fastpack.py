"""Native ingest packer ≡ pure-Python packer, plus build tooling."""

import random

import numpy as np
import pytest

from bayesian_consensus_engine_tpu.core import batch as batch_mod
from bayesian_consensus_engine_tpu.core.batch import mapping_lookup, pack_markets

needs_native = pytest.mark.skipif(
    batch_mod._fastpack is None,
    reason="native fastpack not built (python native/build.py)",
)


def _random_markets(seed=0, num_markets=25):
    rng = random.Random(seed)
    markets = []
    for m in range(num_markets):
        signals = [
            {
                "sourceId": f"src-{rng.randint(0, 7)}",
                "probability": round(rng.random(), 6),
            }
            for _ in range(rng.randint(0, 12))
        ]
        markets.append((f"market-{m}", signals))
    return markets


@needs_native
class TestNativePythonEquivalence:
    def test_identical_packing(self):
        markets = _random_markets()
        rel = {f"src-{i}": {"reliability": 0.1 * i, "confidence": 0.05 * i}
               for i in range(5)}
        lookup = mapping_lookup(rel)
        native = pack_markets(markets, lookup, native=True)
        python = pack_markets(markets, lookup, native=False)

        assert native.market_keys == python.market_keys
        assert native.pair_source_ids == python.pair_source_ids
        np.testing.assert_array_equal(native.pair_market, python.pair_market)
        np.testing.assert_array_equal(native.flat_probs, python.flat_probs)
        np.testing.assert_array_equal(native.flat_pair, python.flat_pair)
        np.testing.assert_array_equal(
            native.signals_per_market, python.signals_per_market
        )
        np.testing.assert_array_equal(native.pair_offsets, python.pair_offsets)
        np.testing.assert_array_equal(
            native.pair_reliability, python.pair_reliability
        )
        np.testing.assert_array_equal(
            native.pair_confidence, python.pair_confidence
        )
        np.testing.assert_array_equal(native.pair_known, python.pair_known)

    def test_empty_and_single(self):
        for markets in ([], [("only", [])], [("one", [{"sourceId": "a", "probability": 1.0}])]):
            native = pack_markets(markets, native=True)
            python = pack_markets(markets, native=False)
            assert native.pair_source_ids == python.pair_source_ids
            np.testing.assert_array_equal(native.pair_offsets, python.pair_offsets)

    def test_duplicate_heavy(self):
        markets = [
            ("m", [{"sourceId": "a", "probability": p} for p in (0.1, 0.2, 0.3)]
                  + [{"sourceId": "b", "probability": 0.9}])
        ]
        native = pack_markets(markets, native=True)
        assert native.pair_source_ids == ["a", "b"]
        np.testing.assert_array_equal(native.flat_pair, [0, 0, 0, 1])

    def test_native_used_by_default_when_built(self):
        # auto-detect prefers the native path when the extension is present
        assert batch_mod._fastpack is not None

    def test_faster_than_python(self):
        import time

        markets = _random_markets(seed=1, num_markets=2000)
        # Warm both paths, then take best-of-3: a single-shot wall-clock
        # comparison flakes on loaded CI runners (one scheduler stall can
        # exceed any fixed margin).
        pack_markets(markets, native=True)
        pack_markets(markets, native=False)

        def best_of(native):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                pack_markets(markets, native=native)
                best = min(best, time.perf_counter() - t0)
            return best

        native_dt, python_dt = best_of(True), best_of(False)
        # Non-regression guard only (real gain is ~1.3x; wide margin for CI
        # noise — this catches the native path becoming pathologically slow,
        # not small perf drift).
        assert native_dt < python_dt * 2.0, (native_dt, python_dt)


class TestFallback:
    def test_python_path_always_available(self):
        markets = _random_markets(seed=2)
        packed = pack_markets(markets, native=False)
        assert packed.num_markets == len(markets)

    def test_force_native_without_build_raises(self, monkeypatch):
        monkeypatch.setattr(batch_mod, "_fastpack", None)
        with pytest.raises(RuntimeError, match="native packer requested"):
            pack_markets(_random_markets(), native=True)

    def test_build_script_importable(self):
        import importlib.util
        import pathlib

        spec = importlib.util.spec_from_file_location(
            "native_build",
            pathlib.Path(__file__).parents[1] / "native" / "build.py",
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        assert callable(module.build)
