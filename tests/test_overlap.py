"""Host-boundary overlap: background checkpoints + prefetched plan builds.

Round 3 measured the e2e pipeline's legs running strictly serially —
ingest, settle, and flush each leaving either the chip or the host idle.
Round 4 overlaps them: ``flush_to_sqlite_async`` snapshots synchronously
and writes the SQLite transaction on a background thread (GIL released in
the native writer), and ``PlanPrefetcher`` builds plan N+1 on a worker
thread while plan N settles. These tests pin the non-negotiable part:
overlap must change WALL CLOCK ONLY — results, store state, and
checkpoint files must be exactly what the serial path produces.
"""

import random
import sqlite3
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from bayesian_consensus_engine_tpu.pipeline import (
    PlanPrefetcher,
    build_settlement_plan,
    settle,
    settle_stream,
)
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.state.records import ReliabilityRecord
from bayesian_consensus_engine_tpu.state.tensor_store import TensorReliabilityStore


def random_payloads(rng, num_markets, universe=40, max_signals=5, tag=""):
    payloads = []
    for m in range(num_markets):
        n = rng.randint(1, max_signals)
        signals = [
            {
                "sourceId": f"src-{rng.randrange(universe)}",
                "probability": round(rng.random(), 6),
            }
            for _ in range(n)
        ]
        payloads.append((f"market{tag}-{m}", signals))
    return payloads


def seeded_store(n=25):
    store = TensorReliabilityStore()
    for i in range(n):
        store.put_record(
            ReliabilityRecord(
                source_id=f"src-{i}",
                market_id=f"mkt-{i % 4}",
                reliability=0.5 + 0.01 * (i % 9),
                confidence=0.25 + 0.01 * (i % 7),
                updated_at=f"2026-07-{10 + i % 19:02d}T12:00:00+00:00",
            )
        )
    return store


def bump(store, source_id, market_id, rel=0.77):
    """A deterministic dirty-making mutation (update_reliability stamps
    wall-clock now, which can't be compared across two stores)."""
    store.put_record(
        ReliabilityRecord(
            source_id=source_id,
            market_id=market_id,
            reliability=rel,
            confidence=0.4,
            updated_at="2026-07-29T09:00:00+00:00",
        )
    )


def db_records(path):
    with sqlite3.connect(path) as conn:
        return conn.execute(
            "SELECT source_id, market_id, reliability, confidence, updated_at"
            " FROM sources ORDER BY source_id, market_id"
        ).fetchall()


class TestAsyncFlush:
    def test_matches_sync_flush(self, tmp_path):
        sync_db = tmp_path / "sync.db"
        async_db = tmp_path / "async.db"
        seeded_store().flush_to_sqlite(sync_db)
        handle = seeded_store().flush_to_sqlite_async(async_db)
        assert handle.result() == 25
        assert handle.done()
        assert db_records(async_db) == db_records(sync_db)

    def test_incremental_async_writes_only_dirty(self, tmp_path):
        db = tmp_path / "ckpt.db"
        store = seeded_store()
        store.flush_to_sqlite_async(db).result()
        bump(store, "src-3", "mkt-3")
        bump(store, "src-7", "mkt-3", rel=0.11)
        handle = store.flush_to_sqlite_async(db)
        assert handle.result() == 2
        # The file reflects the updates and still holds every row.
        twin = seeded_store()
        bump(twin, "src-3", "mkt-3")
        bump(twin, "src-7", "mkt-3", rel=0.11)
        expect = tmp_path / "expect.db"
        twin.flush_to_sqlite(expect)
        assert db_records(db) == db_records(expect)

    def test_failed_write_rolls_back_bookkeeping(self, tmp_path, monkeypatch):
        db = tmp_path / "ckpt.db"
        store = seeded_store()
        store.flush_to_sqlite(db)
        bump(store, "src-5", "mkt-1")

        def broken_writer(*args, **kwargs):
            def writer():
                raise RuntimeError("disk on fire")

            return writer

        monkeypatch.setattr(store, "_build_snapshot_writer", broken_writer)
        handle = store.flush_to_sqlite_async(db)
        with pytest.raises(RuntimeError, match="disk on fire"):
            handle.result()
        monkeypatch.undo()
        # The failed flush re-marked its rows dirty and restored the
        # target, so the retry still covers the update incrementally.
        assert store.flush_to_sqlite(db) == 1
        twin = seeded_store()
        bump(twin, "src-5", "mkt-1")
        expect = tmp_path / "expect.db"
        twin.flush_to_sqlite(expect)
        assert db_records(db) == db_records(expect)

    def test_prior_failure_surfaces_on_next_flush(self, tmp_path, monkeypatch):
        db = tmp_path / "ckpt.db"
        store = seeded_store()

        def broken_writer(*args, **kwargs):
            def writer():
                raise RuntimeError("transient outage")

            return writer

        monkeypatch.setattr(store, "_build_snapshot_writer", broken_writer)
        store.flush_to_sqlite_async(db)  # handle dropped: service crashed
        monkeypatch.undo()
        with pytest.raises(RuntimeError, match="transient outage"):
            store.flush_to_sqlite(db)
        # The retry after the surfaced failure writes the full checkpoint.
        assert store.flush_to_sqlite(db) == 25
        expect = tmp_path / "expect.db"
        seeded_store().flush_to_sqlite(expect)
        assert db_records(db) == db_records(expect)

    def test_flushes_serialise_never_interleave(self, tmp_path):
        db = tmp_path / "ckpt.db"
        store = seeded_store()
        first = store.flush_to_sqlite_async(db)
        bump(store, "src-1", "mkt-1")
        # Starting the second flush joins the first — by the time it
        # snapshots, the file holds the full checkpoint to delta against.
        second = store.flush_to_sqlite_async(db)
        assert first.done()
        assert second.result() == 1
        twin = seeded_store()
        bump(twin, "src-1", "mkt-1")
        expect = tmp_path / "expect.db"
        twin.flush_to_sqlite(expect)
        assert db_records(db) == db_records(expect)

    def test_mutations_after_snapshot_do_not_leak_into_checkpoint(
        self, tmp_path, monkeypatch
    ):
        """The checkpoint is the state AS OF the call, not of the join."""
        db = tmp_path / "ckpt.db"
        store = seeded_store()
        gate = threading.Event()
        real_builder = store._build_snapshot_writer

        def gated_builder(*args, **kwargs):
            writer = real_builder(*args, **kwargs)

            def slow_writer():
                gate.wait(timeout=30)
                return writer()

            return slow_writer

        monkeypatch.setattr(store, "_build_snapshot_writer", gated_builder)
        handle = store.flush_to_sqlite_async(db)
        # Mutate AFTER the snapshot, while the write is still gated.
        bump(store, "src-2", "mkt-2")
        gate.set()
        assert handle.result() == 25
        expect = tmp_path / "expect.db"
        seeded_store().flush_to_sqlite(expect)
        assert db_records(db) == db_records(expect)
        # ...and the mutation is still pending for the NEXT checkpoint.
        assert store.flush_to_sqlite(db) == 1

    def test_memory_target(self):
        handle = seeded_store().flush_to_sqlite_async(":memory:")
        assert handle.result() == 25


def serial_plans_and_settle(payload_batches, outcome_batches, steps=2):
    store = TensorReliabilityStore()
    plans, results = [], []
    for payloads, outcomes in zip(payload_batches, outcome_batches):
        plan = build_settlement_plan(store, payloads)
        plans.append(plan)
        results.append(
            settle(store, plan, outcomes, steps=steps, now=20_300.0)
        )
    store.sync()
    return store, plans, results


class TestPlanPrefetcher:
    def _batches(self, num_batches=4, markets=17):
        rng = random.Random(99)
        payload_batches = [
            random_payloads(rng, markets, tag=f"-b{b}")
            for b in range(num_batches)
        ]
        outcome_batches = [
            [rng.random() < 0.5 for _ in range(markets)]
            for _ in range(num_batches)
        ]
        return payload_batches, outcome_batches

    def test_prefetched_settles_match_serial(self):
        payload_batches, outcome_batches = self._batches()
        serial_store, serial_plans, serial_results = serial_plans_and_settle(
            payload_batches, outcome_batches
        )

        store = TensorReliabilityStore()
        results = []
        with PlanPrefetcher(store, payload_batches) as plans:
            for plan, serial_plan, outcomes in zip(
                plans, serial_plans, outcome_batches
            ):
                # Identical row assignment, block content, and probes.
                assert np.array_equal(plan.slot_rows, serial_plan.slot_rows)
                assert np.array_equal(plan.probs, serial_plan.probs)
                assert np.array_equal(plan.mask, serial_plan.mask)
                assert plan.binding == serial_plan.binding
                results.append(
                    settle(store, plan, outcomes, steps=2, now=20_300.0)
                )
        store.sync()
        for mine, serial in zip(results, serial_results):
            assert np.array_equal(
                mine.consensus, serial.consensus, equal_nan=True
            )
        assert np.array_equal(
            store._rel[: len(store)], serial_store._rel[: len(serial_store)]
        )
        assert np.array_equal(
            store._days[: len(store)], serial_store._days[: len(serial_store)]
        )

    def test_columnar_mode_matches_dict_mode(self):
        payload_batches, _ = self._batches(num_batches=2)

        def to_columns(payloads):
            keys = [market_id for market_id, _ in payloads]
            source_ids, probs, offsets = [], [], [0]
            for _, signals in payloads:
                for signal in signals:
                    source_ids.append(signal["sourceId"])
                    probs.append(signal["probability"])
                offsets.append(len(source_ids))
            return (
                keys,
                source_ids,
                np.asarray(probs, dtype=np.float64),
                np.asarray(offsets, dtype=np.int64),
            )

        dict_store = TensorReliabilityStore()
        dict_plans = [
            build_settlement_plan(dict_store, payloads)
            for payloads in payload_batches
        ]
        col_store = TensorReliabilityStore()
        with PlanPrefetcher(
            col_store,
            [to_columns(p) for p in payload_batches],
            columnar=True,
        ) as plans:
            for plan, expect in zip(plans, dict_plans):
                assert np.array_equal(plan.slot_rows, expect.slot_rows)
                assert np.array_equal(plan.probs, expect.probs)

    def test_build_error_raises_on_next(self):
        store = TensorReliabilityStore()
        good = [("m-1", [{"sourceId": "s", "probability": 0.5}])]
        bad = [
            ("dup", [{"sourceId": "s", "probability": 0.5}]),
            ("dup", [{"sourceId": "t", "probability": 0.5}]),
        ]
        with PlanPrefetcher(store, [good, bad, good]) as plans:
            assert next(plans).market_keys == ["m-1"]
            with pytest.raises(ValueError, match="duplicate market ids"):
                next(plans)
            # The stream terminates after an error; later batches dropped.
            with pytest.raises(StopIteration):
                next(plans)

    def test_close_mid_stream_joins_worker(self):
        store = TensorReliabilityStore()
        rng = random.Random(1)
        batches = [random_payloads(rng, 5, tag=f"-c{b}") for b in range(50)]
        prefetcher = PlanPrefetcher(store, batches, depth=1)
        next(prefetcher)
        prefetcher.close()
        assert not prefetcher._worker.is_alive()

    def test_worker_overlaps_with_consumer(self):
        """The worker genuinely builds ahead: with depth=2, by the time the
        consumer finishes a slow pass over plan N, plan N+1 is already
        waiting (queue non-empty) — the build ran DURING the slow pass."""
        store = TensorReliabilityStore()
        rng = random.Random(2)
        batches = [random_payloads(rng, 40, tag=f"-o{b}") for b in range(3)]
        with PlanPrefetcher(store, batches, depth=2) as plans:
            next(plans)
            deadline = time.monotonic() + 30.0
            while plans._queue.empty() and time.monotonic() < deadline:
                time.sleep(0.005)  # the "slow consumer" leg
            assert not plans._queue.empty()


class TestStableSettleShapes:
    """take_device_state pads to the capacity ladder so streamed batches
    neither recompile the settle kernel per batch nor break the
    device-resident chain when a prefetched plan interns new pairs."""

    def test_exported_shape_is_capacity_not_used(self):
        store = seeded_store(n=10)
        state, _epoch0 = store.take_device_state(None)
        capacity = store._rel.shape[0]
        assert len(store) == 10
        assert state.reliability.shape[0] == capacity
        assert capacity > len(store)
        # Pad rows read as cold defaults — exactly what a newly interned
        # pair must read as.
        pads = np.asarray(state.exists)[len(store):]
        assert not pads.any()

    def test_chain_survives_interning_within_capacity(self):
        rng = random.Random(7)
        batch_a = random_payloads(rng, 8, universe=10, tag="-a")
        batch_b = random_payloads(rng, 8, universe=10, tag="-b")
        out_a = [rng.random() < 0.5 for _ in range(8)]
        out_b = [rng.random() < 0.5 for _ in range(8)]

        store = TensorReliabilityStore()
        plan_a = build_settlement_plan(store, batch_a)
        settle(store, plan_a, out_a, steps=2, now=20_900.0)
        assert store._pending is not None
        # Plan B interns NEW pairs (within the initial 64-row capacity):
        # the pending chain must hand forward, not sync + rebuild.
        plan_b = build_settlement_plan(store, batch_b)
        assert len(store) <= store._rel.shape[0]
        settle(store, plan_b, out_b, steps=2, now=20_901.0)
        assert len(store._pending_sync) == 2  # A's recipe still deferred

        # Equivalence with the sync-every-time path.
        eager = TensorReliabilityStore()
        plan = build_settlement_plan(eager, batch_a)
        settle(eager, plan, out_a, steps=2, now=20_900.0)
        eager.sync()
        plan = build_settlement_plan(eager, batch_b)
        settle(eager, plan, out_b, steps=2, now=20_901.0)
        eager.sync()
        store.sync()
        assert store.list_sources() == eager.list_sources()


class TestSettleStream:
    """settle_stream: the one-API streamed service loop must equal the
    serial build → settle → flush loop in results, store state, and
    checkpoint file — overlap changes wall clock only."""

    def _batches(self, num_batches=4, markets=9, seed=31):
        rng = random.Random(seed)
        out = []
        for b in range(num_batches):
            payloads = random_payloads(rng, markets, universe=15, tag=f"-s{b}")
            outcomes = [rng.random() < 0.5 for _ in range(markets)]
            out.append((payloads, outcomes))
        return out

    def _serial(self, batches, db, steps=2, now=21_000.0,
                checkpoint_every=1):
        from bayesian_consensus_engine_tpu.pipeline import settle

        store = TensorReliabilityStore()
        results = []
        for i, (payloads, outcomes) in enumerate(batches):
            plan = build_settlement_plan(store, payloads, num_slots="bucket")
            results.append(
                settle(store, plan, outcomes, steps=steps, now=now + i)
            )
            if (i + 1) % checkpoint_every == 0:
                store.flush_to_sqlite(db)
        store.flush_to_sqlite(db)
        return store, results

    def test_matches_serial_loop(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches()
        serial_store, serial_results = self._serial(
            batches, tmp_path / "serial.db"
        )

        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store,
                batches,
                steps=2,
                now=21_000.0,
                db_path=tmp_path / "stream.db",
            )
        )
        assert len(results) == len(serial_results)
        for mine, ref in zip(results, serial_results):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_array_equal(
                mine.consensus, ref.consensus, err_msg="consensus"
            )
        store.sync()
        assert store.list_sources() == serial_store.list_sources()
        assert db_records(tmp_path / "stream.db") == db_records(
            tmp_path / "serial.db"
        )

    def test_checkpoint_every_with_tail_flush(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches(num_batches=3)
        store = TensorReliabilityStore()
        list(
            settle_stream(
                store,
                batches,
                steps=1,
                now=21_010.0,
                db_path=tmp_path / "stream.db",
                checkpoint_every=2,
            )
        )
        # Batch 3 landed after the last periodic flush: the tail flush
        # must still have made the file complete.
        serial_store, _ = self._serial(
            batches, tmp_path / "serial.db", steps=1, now=21_010.0,
            checkpoint_every=2,
        )
        assert db_records(tmp_path / "stream.db") == db_records(
            tmp_path / "serial.db"
        )

    def test_no_db_means_no_flush(self):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        store = TensorReliabilityStore()
        results = list(
            settle_stream(store, self._batches(num_batches=2), now=21_020.0)
        )
        assert len(results) == 2
        assert store._last_flush_path is None

    def test_batch_error_propagates(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        good = self._batches(num_batches=1)[0]
        bad = (
            [
                ("dup", [{"sourceId": "s", "probability": 0.5}]),
                ("dup", [{"sourceId": "t", "probability": 0.5}]),
            ],
            [True, False],
        )
        store = TensorReliabilityStore()
        stream = settle_stream(
            store, [good, bad], now=21_030.0, db_path=tmp_path / "x.db"
        )
        assert next(stream).market_keys == [k for k, _ in good[0]]
        with pytest.raises(ValueError, match="duplicate market ids"):
            next(stream)

    def test_failed_background_flush_surfaces(self, tmp_path, monkeypatch):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        store = TensorReliabilityStore()
        real_builder = store._build_snapshot_writer
        fail_once = {"armed": True}

        def sometimes_broken(*args, **kwargs):
            if fail_once.pop("armed", False):
                def writer():
                    raise RuntimeError("checkpoint disk gone")

                return writer
            return real_builder(*args, **kwargs)

        monkeypatch.setattr(store, "_build_snapshot_writer", sometimes_broken)
        stream = settle_stream(
            store,
            self._batches(num_batches=2),
            now=21_040.0,
            db_path=tmp_path / "x.db",
        )
        next(stream)  # batch 1 settles; its flush is the broken one
        with pytest.raises(RuntimeError, match="checkpoint disk gone"):
            # Batch 2's flush joins the broken one first and re-raises.
            next(stream)

    def test_early_break_still_tail_flushes_and_joins(self, tmp_path):
        """A consumer break (GeneratorExit) must not lose checkpoints: the
        in-flight write is joined and settled batches reach the file."""
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches(num_batches=4)
        db = tmp_path / "stream.db"
        store = TensorReliabilityStore()
        for i, _result in enumerate(
            settle_stream(
                store, batches, steps=1, now=21_050.0, db_path=db,
                checkpoint_every=3,
            )
        ):
            if i == 1:
                break  # two batches settled; no periodic flush happened yet
        serial_store, _ = self._serial(
            batches[:2], tmp_path / "serial.db", steps=1, now=21_050.0
        )
        assert db_records(db) == db_records(tmp_path / "serial.db")


class TestCloseJoinsFlush:
    def test_close_joins_inflight_checkpoint(self, tmp_path, monkeypatch):
        store = seeded_store()
        gate = threading.Event()
        real_builder = store._build_snapshot_writer

        def gated_builder(*args, **kwargs):
            writer = real_builder(*args, **kwargs)

            def slow_writer():
                gate.wait(timeout=30)
                return writer()

            return slow_writer

        monkeypatch.setattr(store, "_build_snapshot_writer", gated_builder)
        handle = store.flush_to_sqlite_async(tmp_path / "ckpt.db")
        # Prove close() BLOCKS on the in-flight write by construction:
        # run it on a helper thread while the writer is still gated.
        closer = threading.Thread(target=store.close)
        closer.start()
        closer.join(timeout=0.3)
        assert closer.is_alive(), "close() returned before the write landed"
        gate.set()
        closer.join(timeout=30)
        assert not closer.is_alive()
        assert handle.done()
        assert len(db_records(tmp_path / "ckpt.db")) == 25

    def test_close_surfaces_background_failure(self, tmp_path, monkeypatch):
        store = seeded_store()

        def broken_builder(*args, **kwargs):
            def writer():
                raise RuntimeError("checkpoint disk gone")

            return writer

        monkeypatch.setattr(store, "_build_snapshot_writer", broken_builder)
        store.flush_to_sqlite_async(tmp_path / "ckpt.db")
        with pytest.raises(RuntimeError, match="checkpoint disk gone"):
            store.close()
        store.close()  # idempotent after the failure surfaced


class TestSettleStreamColumnar:
    def test_columnar_batches_match_dict_batches(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        rng = random.Random(41)
        dict_batches = []
        for b in range(3):
            payloads = random_payloads(rng, 8, universe=12, tag=f"-c{b}")
            outcomes = [rng.random() < 0.5 for _ in range(8)]
            dict_batches.append((payloads, outcomes))

        def to_columns(payloads):
            keys = [market_id for market_id, _ in payloads]
            source_ids, probs, offsets = [], [], [0]
            for _, signals in payloads:
                for signal in signals:
                    source_ids.append(signal["sourceId"])
                    probs.append(signal["probability"])
                offsets.append(len(source_ids))
            return (
                keys,
                source_ids,
                np.asarray(probs, dtype=np.float64),
                np.asarray(offsets, dtype=np.int64),
            )

        dict_store = TensorReliabilityStore()
        dict_results = list(
            settle_stream(
                dict_store, dict_batches, steps=2, now=21_060.0,
                db_path=tmp_path / "dict.db",
            )
        )
        col_store = TensorReliabilityStore()
        col_results = list(
            settle_stream(
                col_store,
                [(to_columns(p), o) for p, o in dict_batches],
                steps=2,
                now=21_060.0,
                db_path=tmp_path / "col.db",
                columnar=True,
            )
        )
        for mine, ref in zip(col_results, dict_results):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_array_equal(mine.consensus, ref.consensus)
        assert db_records(tmp_path / "col.db") == db_records(
            tmp_path / "dict.db"
        )

    def test_stats_reports_per_batch_timings(self, tmp_path):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        rng = random.Random(43)
        batches = [
            (
                random_payloads(rng, 9, universe=12, tag=f"-st{b}"),
                [rng.random() < 0.5 for _ in range(9)],
            )
            for b in range(3)
        ]
        stats = []
        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, batches, steps=1, now=21_070.0,
                db_path=tmp_path / "s.db", checkpoint_every=2, stats=stats,
            )
        )
        assert len(results) == 3
        assert [s["batch"] for s in stats] == [0, 1, 2]
        assert [s["checkpoint_s"] is not None for s in stats] == [
            False, True, False,
        ]
        for s in stats:
            assert s["markets"] == 9
            assert s["plan_wait_s"] >= 0
            assert s["settle_dispatch_s"] >= 0


def stable_topology_batches(num_batches=4, markets=9, universe=12, seed=23,
                            duplicates=False):
    """One persistent (source, market) universe, fresh probabilities and
    outcomes per batch — the reference's daily re-settlement shape and the
    steady state the delta-ingest fast path exists for."""
    rng = random.Random(seed)
    base = []
    for m in range(markets):
        n = rng.randint(1, 4)
        sids = [f"src-{rng.randrange(universe)}" for _ in range(n)]
        if duplicates and n > 1:
            sids[-1] = sids[0]  # same (source, market) twice per market
        base.append((f"mkt-r{m}", sids))
    batches = []
    for _ in range(num_batches):
        payloads = [
            (
                market_id,
                [
                    {"sourceId": sid, "probability": round(rng.random(), 6)}
                    for sid in sids
                ],
            )
            for market_id, sids in base
        ]
        outcomes = [rng.random() < 0.5 for _ in range(markets)]
        batches.append((payloads, outcomes))
    return batches


class TestPlanReuse:
    """The topology-cached delta-ingest fast path: reuse_plans=True must be
    bit-exact with the rebuild path — results, store state, and checkpoint
    BYTES — and any topology change must force a rebuild."""

    def _stream(self, batches, db, reuse, stats=None, mesh=None,
                columnar=False, now=21_300.0):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, batches, steps=2, now=now, db_path=db,
                checkpoint_every=2, stats=stats, reuse_plans=reuse,
                mesh=mesh, columnar=columnar,
            )
        )
        store.sync()
        return store, results

    def _assert_bit_equal(self, tmp_path, batches, mesh=None,
                          columnar=False):
        off_db = tmp_path / "off.db"
        on_db = tmp_path / "on.db"
        off_stats, on_stats = [], []
        off_store, off_results = self._stream(
            batches, off_db, False, off_stats, mesh, columnar
        )
        on_store, on_results = self._stream(
            batches, on_db, True, on_stats, mesh, columnar
        )
        for mine, ref in zip(on_results, off_results):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(ref.consensus)
            )
        assert on_store.list_sources() == off_store.list_sources()
        # The interchange files must be identical to the BYTE: the reuse
        # path fed the exact same rows through the exact same flushes.
        assert on_db.read_bytes() == off_db.read_bytes()
        return off_stats, on_stats

    def test_stable_topology_stream_is_bit_exact_with_rebuild(self,
                                                              tmp_path):
        batches = stable_topology_batches()
        off_stats, on_stats = self._assert_bit_equal(tmp_path, batches)
        # Rebuild path never reuses; fast path misses only batch 0.
        assert [s["plan_reused"] for s in off_stats] == [False] * 4
        assert [s["plan_reused"] for s in on_stats] == [
            False, True, True, True,
        ]

    def test_duplicate_signals_reuse_parity(self, tmp_path):
        # Duplicate (source, market) signals exercise the refresh path's
        # ordered accumulate — the float-summation-order contract.
        batches = stable_topology_batches(duplicates=True)
        _, on_stats = self._assert_bit_equal(tmp_path, batches)
        assert [s["plan_reused"] for s in on_stats] == [
            False, True, True, True,
        ]

    def test_columnar_stream_reuse_parity(self, tmp_path):
        def to_columns(payloads):
            keys = [market_id for market_id, _ in payloads]
            source_ids, probs, offsets = [], [], [0]
            for _, signals in payloads:
                for signal in signals:
                    source_ids.append(signal["sourceId"])
                    probs.append(signal["probability"])
                offsets.append(len(source_ids))
            return (
                keys,
                source_ids,
                np.asarray(probs, dtype=np.float64),
                np.asarray(offsets, dtype=np.int64),
            )

        batches = [
            (to_columns(p), o) for p, o in stable_topology_batches(seed=29)
        ]
        _, on_stats = self._assert_bit_equal(
            tmp_path, batches, columnar=True
        )
        assert [s["plan_reused"] for s in on_stats] == [
            False, True, True, True,
        ]

    def test_sharded_stream_reuse_parity(self, tmp_path):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh

        batches = stable_topology_batches(seed=37)
        _, on_stats = self._assert_bit_equal(
            tmp_path, batches, mesh=make_mesh()  # markets-only: bit-exact
        )
        assert [s["plan_reused"] for s in on_stats] == [
            False, True, True, True,
        ]

    def test_reordered_markets_force_rebuild(self, tmp_path):
        # Same signals, markets permuted in batch 1: per-market float
        # summation order changes, so the fingerprint MUST miss and the
        # stream must stay exact (vs the rebuild path on the same input).
        batches = stable_topology_batches(num_batches=3, seed=41)
        payloads, outcomes = batches[1]
        batches[1] = (list(reversed(payloads)), list(reversed(outcomes)))
        off_stats, on_stats = self._assert_bit_equal(tmp_path, batches)
        assert [s["plan_reused"] for s in on_stats] == [
            # Batch 1's reorder misses, and batch 2 (back in the original
            # order) misses against batch 1's reordered fingerprint.
            False, False, False,
        ]

    def test_reordered_signals_within_market_force_rebuild(self, tmp_path):
        batches = stable_topology_batches(num_batches=3, seed=43)
        payloads, outcomes = batches[1]
        batches[1] = (
            [(mid, list(reversed(signals))) for mid, signals in payloads],
            outcomes,
        )
        _, on_stats = self._assert_bit_equal(tmp_path, batches)
        assert on_stats[1]["plan_reused"] is False

    def test_topology_drift_rebuilds_then_reuses_again(self, tmp_path):
        # A fresh market joining mid-stream (capacity/universe drift) must
        # rebuild that batch; the NEW topology then reuses from there on.
        stable = stable_topology_batches(num_batches=2, seed=47)
        grown = stable_topology_batches(
            num_batches=2, markets=10, seed=47
        )
        batches = stable + grown
        _, on_stats = self._assert_bit_equal(tmp_path, batches)
        assert [s["plan_reused"] for s in on_stats] == [
            False, True, False, True,
        ]


class TestTopologyFingerprint:
    def _columns(self, payloads):
        from bayesian_consensus_engine_tpu.core.batch import (
            columns_from_payloads,
        )

        keys, sids, _probs, offsets = columns_from_payloads(payloads)
        return keys, sids, offsets

    def test_probability_change_keeps_digest(self):
        from bayesian_consensus_engine_tpu.core.batch import (
            topology_fingerprint,
        )

        a = [("m-1", [{"sourceId": "s1", "probability": 0.25},
                      {"sourceId": "s2", "probability": 0.5}])]
        b = [("m-1", [{"sourceId": "s1", "probability": 0.75},
                      {"sourceId": "s2", "probability": 0.125}])]
        assert topology_fingerprint(*self._columns(a)) == \
            topology_fingerprint(*self._columns(b))

    def test_order_and_boundary_sensitivity(self):
        from bayesian_consensus_engine_tpu.core.batch import (
            topology_fingerprint,
        )

        def digest(keys, sids, offsets):
            return topology_fingerprint(
                keys, sids, np.asarray(offsets, dtype=np.int64)
            )

        base = digest(["m1", "m2"], ["a", "b", "c"], [0, 2, 3])
        # Market order, source order, and signal→market assignment all
        # feed the float-summation-order contract: each must change it.
        assert digest(["m2", "m1"], ["a", "b", "c"], [0, 2, 3]) != base
        assert digest(["m1", "m2"], ["b", "a", "c"], [0, 2, 3]) != base
        assert digest(["m1", "m2"], ["a", "b", "c"], [0, 1, 3]) != base
        # Length-delimited ids: shifting bytes between adjacent ids must
        # not collide ("ab","c" vs "a","bc").
        assert digest(["m1"], ["ab", "c"], [0, 2]) != \
            digest(["m1"], ["a", "bc"], [0, 2])
        assert digest(["m1m2"], ["a"], [0, 1]) != \
            digest(["m1", "m2"], ["a"], [0, 0, 1])

    def test_refresh_twin_is_bitwise_equal_to_rebuilt_plan(self):
        from bayesian_consensus_engine_tpu.core.batch import (
            columns_from_payloads,
        )
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan_columnar,
        )

        batches = stable_topology_batches(num_batches=2, seed=53)
        cols = [columns_from_payloads(p) for p, _ in batches]

        store = TensorReliabilityStore()
        plan0 = build_settlement_plan_columnar(
            store, *cols[0], fingerprint=True
        )
        refreshed = plan0.refresh(cols[1][2])

        twin_store = TensorReliabilityStore()
        build_settlement_plan_columnar(twin_store, *cols[0])
        rebuilt = build_settlement_plan_columnar(twin_store, *cols[1])

        np.testing.assert_array_equal(refreshed.probs, rebuilt.probs)
        assert refreshed.binding == rebuilt.binding
        # Topology arrays are SHARED with the parent, not copied.
        assert refreshed.slot_rows is plan0.slot_rows
        assert refreshed.mask is plan0.mask
        assert refreshed.fingerprint == plan0.fingerprint
        assert not refreshed.probs.flags.writeable

    def test_refresh_validates_probability_count(self):
        from bayesian_consensus_engine_tpu.core.batch import (
            columns_from_payloads,
        )
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan_columnar,
        )

        (payloads, _), = stable_topology_batches(num_batches=1, seed=59)
        store = TensorReliabilityStore()
        plan = build_settlement_plan_columnar(
            store, *columns_from_payloads(payloads)
        )
        with pytest.raises(ValueError, match="probabilities"):
            plan.refresh(np.zeros(1))

    def test_refresh_without_metadata_rejected(self):
        import dataclasses

        (payloads, _), = stable_topology_batches(num_batches=1, seed=59)
        store = TensorReliabilityStore()
        plan = build_settlement_plan(store, payloads)
        # dataclasses.replace drops the object.__setattr__ sidecars — the
        # shape of a plan minted before the delta-ingest path existed.
        bare = dataclasses.replace(plan)
        with pytest.raises(ValueError, match="refresh metadata"):
            bare.refresh(np.zeros(4))

    def test_session_refresh_delta_matches_rebuilt_sessions(self):
        """A LONG-LIVED sharded session taking probability-only refreshes
        must equal per-batch rebuilt sessions bit-for-bit (markets-only
        mesh) — the chained device-resident daily re-settlement shape."""
        from bayesian_consensus_engine_tpu.core.batch import (
            columns_from_payloads,
        )
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
            build_settlement_plan_columnar,
        )

        batches = stable_topology_batches(num_batches=3, seed=61)
        cols = [columns_from_payloads(p) for p, _ in batches]
        outcomes = [o for _, o in batches]
        mesh = make_mesh()

        store = TensorReliabilityStore()
        plan = build_settlement_plan_columnar(
            store, *cols[0], num_slots="bucket", fingerprint=True
        )
        session = ShardedSettlementSession(store, plan, mesh)
        results = [session.settle(outcomes[0], steps=2, now=21_400.0)]
        for i in (1, 2):
            plan = plan.refresh(cols[i][2])
            session.refresh(plan)
            results.append(
                session.settle(outcomes[i], steps=2, now=21_400.0 + i)
            )
        session.close()

        ref_store = TensorReliabilityStore()
        ref_results = []
        for i in range(3):
            ref_plan = build_settlement_plan_columnar(
                ref_store, *cols[i], num_slots="bucket"
            )
            with ShardedSettlementSession(
                ref_store, ref_plan, mesh
            ) as ref_session:
                ref_results.append(
                    ref_session.settle(outcomes[i], steps=2, now=21_400.0 + i)
                )
        for mine, ref in zip(results, ref_results):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(ref.consensus)
            )
        assert store.list_sources() == ref_store.list_sources()

    def test_session_refresh_rejects_foreign_plan(self):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
        )

        batches = stable_topology_batches(num_batches=2, seed=67)
        store = TensorReliabilityStore()
        plan = build_settlement_plan(
            store, batches[0][0], num_slots="bucket"
        )
        other = build_settlement_plan(
            store, batches[1][0], num_slots="bucket"
        )
        with ShardedSettlementSession(store, plan, make_mesh()) as session:
            with pytest.raises(ValueError, match="probability-only twin"):
                session.refresh(other)


class TestSettleStreamSharded:
    """settle_stream(mesh=...): the streamed service loop over a device
    mesh must equal the flat stream — bit-identical on a markets-only
    mesh (same reduction tree), and the overlap contract (deferred band
    gathers, background checkpoints) must hold unchanged."""

    def _batches(self, num_batches=4, markets=9, seed=47):
        rng = random.Random(seed)
        out = []
        for b in range(num_batches):
            payloads = random_payloads(rng, markets, universe=15, tag=f"-sh{b}")
            outcomes = [rng.random() < 0.5 for _ in range(markets)]
            out.append((payloads, outcomes))
        return out

    def _flat(self, batches, db, steps=2, now=21_100.0):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, batches, steps=steps, now=now, db_path=db
            )
        )
        store.sync()
        return store, results

    def test_markets_only_mesh_matches_flat_stream_bitwise(self, tmp_path):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches()
        flat_store, flat_results = self._flat(batches, tmp_path / "flat.db")

        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store,
                batches,
                steps=2,
                now=21_100.0,
                db_path=tmp_path / "mesh.db",
                mesh=make_mesh(),  # (8, 1): markets-only
            )
        )
        assert len(results) == len(flat_results)
        for mine, ref in zip(results, flat_results):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), ref.consensus
            )
        store.sync()
        assert store.list_sources() == flat_store.list_sources()
        assert db_records(tmp_path / "mesh.db") == db_records(
            tmp_path / "flat.db"
        )

    def test_two_d_mesh_matches_to_ulp(self, tmp_path):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches(seed=53)
        flat_store, flat_results = self._flat(batches, tmp_path / "flat.db")

        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store,
                batches,
                steps=2,
                now=21_100.0,
                db_path=tmp_path / "mesh.db",
                mesh=make_mesh((4, 2)),  # sources split: psum partials
            )
        )
        for mine, ref in zip(results, flat_results):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_allclose(
                np.asarray(mine.consensus), ref.consensus,
                rtol=2e-6, atol=1e-7,
            )
        store.sync()
        mine, theirs = store.list_sources(), flat_store.list_sources()
        assert len(mine) == len(theirs) > 0
        for a, b in zip(mine, theirs):
            assert (a.source_id, a.market_id) == (b.source_id, b.market_id)
            assert abs(a.reliability - b.reliability) < 1e-6
            assert a.confidence == b.confidence  # host-replayed, both paths
            assert a.updated_at == b.updated_at

    def test_disjoint_batches_never_sync_mid_stream(self):
        """Fresh-market batches touch disjoint rows, so NO per-batch sync
        may happen: every batch's band gather stays deferred (chain
        bounded at 8 — older links apply early), and the store still
        equals the flat stream bit-for-bit after the final sync."""
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches(num_batches=10)
        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, batches, steps=1, now=21_200.0, mesh=make_mesh(),
            )
        )
        assert len(results) == 10
        # All ten stayed deferred up to the bound; none was resolved by a
        # mid-stream sync (a per-batch sync would leave exactly one).
        assert len(store._pending_sync) == 8
        store.sync()
        assert not store._pending_sync

        flat_store = TensorReliabilityStore()
        flat_results = list(
            settle_stream(flat_store, batches, steps=1, now=21_200.0)
        )
        for mine, ref in zip(results, flat_results):
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(ref.consensus)
            )
        flat_store.sync()
        assert store.list_sources() == flat_store.list_sources()

    def test_deferred_chain_bounded_by_held_device_bytes(self, monkeypatch):
        """Big-block chains must apply old links before exhausting HBM:
        with a tiny byte budget the chain stays at one link (older
        gathers resolved early) and results still match the flat stream
        bit-for-bit."""
        import bayesian_consensus_engine_tpu.state.tensor_store as ts

        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        monkeypatch.setattr(ts, "_MAX_DEFERRED_BYTES", 1)
        batches = self._batches(num_batches=4)
        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, batches, steps=1, now=21_230.0, mesh=make_mesh(),
            )
        )
        assert len(store._pending_sync) == 1  # early-applied down to one
        store.sync()

        flat_store = TensorReliabilityStore()
        flat_results = list(
            settle_stream(flat_store, batches, steps=1, now=21_230.0)
        )
        for mine, ref in zip(results, flat_results):
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(ref.consensus)
            )
        flat_store.sync()
        assert store.list_sources() == flat_store.list_sources()

    def test_overlapping_batches_sync_and_stay_exact(self):
        """Re-settling the SAME markets every batch (the daily
        re-settlement shape) overlaps rows, so each batch must resolve
        its predecessor's gather before building — and results must stay
        bit-identical to the flat stream."""
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        rng = random.Random(61)
        payloads = random_payloads(rng, 9, universe=15, tag="-ov")
        batches = [
            (payloads, [rng.random() < 0.5 for _ in range(9)])
            for _ in range(3)
        ]
        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, batches, steps=1, now=21_210.0, mesh=make_mesh(),
            )
        )
        # Overlap forced the per-batch sync: at most the LAST batch's
        # recipe is still pending.
        assert len(store._pending_sync or []) <= 1
        store.sync()

        flat_store = TensorReliabilityStore()
        flat_results = list(
            settle_stream(flat_store, batches, steps=1, now=21_210.0)
        )
        for mine, ref in zip(results, flat_results):
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(ref.consensus)
            )
        flat_store.sync()
        assert store.list_sources() == flat_store.list_sources()

    @pytest.mark.parametrize("use_mesh", [False, True],
                             ids=["flat", "sharded"])
    def test_lazy_checkpoints_lag_then_tail_flush_catches_up(self, tmp_path,
                                                            use_mesh):
        """lazy_checkpoints=True: mid-stream files snapshot only APPLIED
        settlements (no device drain — they lag the yielded batches), and
        the tail flush makes the final file identical to eager mode's."""
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        mesh = make_mesh() if use_mesh else None
        batches = self._batches(num_batches=3)
        db = tmp_path / "lazy.db"
        store = TensorReliabilityStore()
        lagged = None
        for i, _result in enumerate(settle_stream(
            store, batches, steps=1, now=21_220.0, db_path=db,
            mesh=mesh, lazy_checkpoints=True,
        )):
            if i == 1:
                # Batch 1's lazy flush: batch 1's settle is still deferred,
                # so its rows must NOT be in the file yet.
                store._flush_inflight.result()
                lagged = len(db_records(db))
        self._flat(batches[:2], tmp_path / "prefix.db",
                   steps=1, now=21_220.0)
        assert lagged < len(db_records(tmp_path / "prefix.db")), (
            "lazy checkpoint drained the newest deferred settle"
        )
        if use_mesh:
            # Session recipes survive capacity growth, so NOTHING applies
            # mid-stream; the flat chain may legitimately apply older
            # batches when interning outgrows the pending state's capacity.
            assert lagged == 0
        eager_store, _ = self._flat(batches, tmp_path / "eager.db",
                                    steps=1, now=21_220.0)
        assert db_records(db) == db_records(tmp_path / "eager.db")
        store.sync()
        assert store.list_sources() == eager_store.list_sources()

    def test_lazy_checkpoints_never_write_torn_resettled_rows(self,
                                                              tmp_path):
        """Re-settling the SAME markets with lazy checkpoints: the eager
        confidence replay updates (and dirties) host confidences while
        reliabilities/stamps wait on the deferred recipe — the lazy flush
        must exclude those rows ENTIRELY, never pairing a new confidence
        with an old reliability (a state that never existed)."""
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        rng = random.Random(67)
        payloads = random_payloads(rng, 8, universe=10, tag="-torn")
        batches = [
            (payloads, [rng.random() < 0.5 for _ in range(8)])
            for _ in range(3)
        ]

        # Every consistent state a checkpoint may legally show: the store
        # after each fully-applied batch prefix.
        legal_states = []
        prefix_store = TensorReliabilityStore()
        for k in range(len(batches)):
            for _ in settle_stream(
                prefix_store, batches[k:k + 1], steps=1, now=21_240.0 + k,
            ):
                pass
            prefix_store.sync()
            legal_states.append({
                (r.source_id, r.market_id): (r.reliability, r.confidence)
                for r in prefix_store.list_sources()
            })

        db = tmp_path / "lazy.db"
        store = TensorReliabilityStore()
        stream = settle_stream(
            store, batches, steps=1, now=21_240.0, db_path=db,
            lazy_checkpoints=True,
        )
        for _result in stream:
            store._flush_inflight.result()
            for sid, mid, rel, conf, _iso in db_records(db):
                pairs = {state.get((sid, mid)) for state in legal_states}
                assert (rel, conf) in pairs, (
                    f"torn record for ({sid}, {mid}): ({rel}, {conf}) "
                    "matches no fully-applied state"
                )
        store.sync()
        final = {
            (r.source_id, r.market_id): (r.reliability, r.confidence)
            for r in store.list_sources()
        }
        assert final == legal_states[-1]
        assert {
            (sid, mid): (rel, conf)
            for sid, mid, rel, conf, _iso in db_records(db)
        } == legal_states[-1]

    def test_lazy_checkpoint_failure_rollback_composes(self, tmp_path,
                                                       monkeypatch):
        """A failing LAZY flush must roll back like an eager one: its
        written-row selection re-dirties, the deferred rows it excluded
        were never un-dirtied in the first place, and one caller retry
        after the stream aborts re-covers everything settled."""
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches(num_batches=4, seed=71)
        db = tmp_path / "lazy.db"
        store = TensorReliabilityStore()
        real_builder = store._build_snapshot_writer
        calls = {"n": 0}

        def broken_second(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 2:
                def writer():
                    raise RuntimeError("checkpoint disk gone")

                return writer
            return real_builder(*args, **kwargs)

        monkeypatch.setattr(store, "_build_snapshot_writer", broken_second)
        stats: list = []
        with pytest.raises(RuntimeError, match="checkpoint disk gone"):
            for _result in settle_stream(
                store, batches, steps=1, now=21_250.0, db_path=db,
                lazy_checkpoints=True, stats=stats,
            ):
                pass
        settled = len(stats)
        assert settled >= 2
        store.sync()
        store.flush_to_sqlite(db)
        serial_store, _ = self._serial_flat(
            batches[:settled], tmp_path / "serial.db", steps=1, now=21_250.0
        )
        assert db_records(db) == db_records(tmp_path / "serial.db")

    def test_band_gather_stays_deferred_between_batches(self):
        """The mesh path must NOT sync eagerly after each settle: the last
        batch's merge recipe stays pending until a host read resolves it
        (the overlap the per-batch session must preserve)."""
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, self._batches(num_batches=2), steps=1,
                now=21_110.0, mesh=make_mesh(),
            )
        )
        assert len(results) == 2
        assert store._pending_sync, "last batch's recipe was synced eagerly"
        store.sync()
        assert not store._pending_sync

    def test_stats_and_checkpoint_every_on_mesh(self, tmp_path):
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        stats = []
        store = TensorReliabilityStore()
        results = list(
            settle_stream(
                store, self._batches(num_batches=3), steps=1, now=21_120.0,
                db_path=tmp_path / "s.db", checkpoint_every=2, stats=stats,
                mesh=make_mesh(),
            )
        )
        assert len(results) == 3
        assert [s["checkpoint_s"] is not None for s in stats] == [
            False, True, False,
        ]

    def test_band_parameter_validation(self):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        with pytest.raises(ValueError, match="band= requires mesh="):
            next(iter(settle_stream(
                TensorReliabilityStore(), [], band=(0, 8)
            )))
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh

        with pytest.raises(ValueError, match="globally-agreed integer"):
            next(iter(settle_stream(
                TensorReliabilityStore(), [], mesh=make_mesh(),
                band=(0, 8),
            )))
        with pytest.raises(ValueError, match="globally-agreed integer"):
            # num_slots=None (natural K) is per-process too, not just
            # "bucket": processes' plans would disagree on the block shape.
            next(iter(settle_stream(
                TensorReliabilityStore(), [], mesh=make_mesh(),
                band=(0, 8), num_slots=None,
            )))
        # NumPy integers (num_slots from array math) are agreed integers.
        assert list(settle_stream(
            TensorReliabilityStore(), [], mesh=make_mesh(),
            band=(0, 8), num_slots=np.int64(4),
        )) == []
        with pytest.raises(ValueError, match="globally-agreed integer"):
            next(iter(settle_stream(
                TensorReliabilityStore(), [], mesh=make_mesh(),
                band=(0, 8), num_slots=True,  # bool is not an agreed K
            )))

    @pytest.mark.parametrize("use_mesh", [False, True],
                             ids=["flat", "sharded"])
    def test_midstream_flush_failure_loses_no_settled_batch(self, tmp_path,
                                                            monkeypatch,
                                                            use_mesh):
        """A background checkpoint failing mid-stream must surface at the
        next flush, roll its bookkeeping back, and leave every settled
        batch recoverable by a caller retry — the disk-gone contract for
        the composed service loop (failure-agnostic: the same rollback
        path serves disk-full, permissions, or a vanished volume), on the
        flat AND the sharded stream."""
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        mesh = make_mesh() if use_mesh else None
        batches = self._batches(num_batches=4)
        db = tmp_path / "stream.db"
        store = TensorReliabilityStore()
        real_builder = store._build_snapshot_writer
        fail_at = {"calls": 0}

        def broken_second_flush(*args, **kwargs):
            fail_at["calls"] += 1
            if fail_at["calls"] == 2:
                def writer():
                    raise RuntimeError("checkpoint disk gone")

                return writer
            return real_builder(*args, **kwargs)

        monkeypatch.setattr(store, "_build_snapshot_writer",
                            broken_second_flush)
        settled = 0
        stats: list = []
        with pytest.raises(RuntimeError, match="checkpoint disk gone"):
            for _result in settle_stream(
                store, batches, steps=1, now=21_140.0, db_path=db,
                mesh=mesh, stats=stats,
            ):
                settled += 1
        # Batch 2's flush was the broken one; batch 3 settled, then ITS
        # flush joined the failure. Three batches are settled and none may
        # be lost: the rollback re-marked batch 2's rows dirty, so one
        # caller retry must produce the complete checkpoint.
        assert settled == 2  # batch 3's result never yielded (raise first)
        assert len(stats) == 3  # ...but stats counts it: the resume point
        store.sync()
        store.flush_to_sqlite(db)
        serial_store, _ = self._serial_flat(
            batches[:3], tmp_path / "serial.db", steps=1, now=21_140.0
        )
        assert db_records(db) == db_records(tmp_path / "serial.db")

    def test_locked_file_failure_then_recovery(self, tmp_path):
        """The REAL failure path, no monkeypatch: an exclusive SQLite lock
        held by another process makes the native background writer fail
        ("database is locked" after its busy timeout); the stream surfaces
        it, and after the lock clears one retry re-covers everything."""
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        batches = self._batches(num_batches=3)
        db = tmp_path / "stream.db"
        store = TensorReliabilityStore()
        lock = None
        stream = settle_stream(
            store, batches, steps=1, now=21_150.0, db_path=db,
        )
        with pytest.raises(Exception, match="locked"):
            for i, _result in enumerate(stream):
                if i == 0:
                    # Batch 0's checkpoint is in flight or landed; lock the
                    # file before batch 1's flush gets joined by batch 2's.
                    store._flush_inflight.result()  # let flush 0 land first
                    lock = sqlite3.connect(db)
                    lock.execute("PRAGMA locking_mode=EXCLUSIVE")
                    lock.execute("BEGIN EXCLUSIVE")
        assert lock is not None
        lock.rollback()
        lock.close()
        store.sync()
        store.flush_to_sqlite(db)
        serial_store, _ = self._serial_flat(
            batches, tmp_path / "serial.db", steps=1, now=21_150.0
        )
        assert db_records(db) == db_records(tmp_path / "serial.db")

    def _serial_flat(self, batches, db, steps=1, now=21_140.0):
        from bayesian_consensus_engine_tpu.pipeline import settle

        store = TensorReliabilityStore()
        results = []
        for i, (payloads, outcomes) in enumerate(batches):
            plan = build_settlement_plan(store, payloads, num_slots="bucket")
            results.append(
                settle(store, plan, outcomes, steps=steps, now=now + i)
            )
        store.sync()
        store.flush_to_sqlite(db)
        return store, results

    def test_sessions_share_one_compiled_loop_per_mesh(self):
        """Per-batch sessions must reuse ONE jit wrapper per mesh — a fresh
        build_cycle_loop() per session would retrace (and on TPU recompile)
        the sharded cycle on every streamed batch at identical shapes."""
        from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
            build_settlement_plan,
        )

        mesh = make_mesh()
        store = TensorReliabilityStore()
        loops = []
        for b, (payloads, outcomes) in enumerate(self._batches(num_batches=2)):
            plan = build_settlement_plan(store, payloads, num_slots="bucket")
            session = ShardedSettlementSession(store, plan, mesh)
            session.settle(outcomes, steps=1, now=21_130.0 + b)
            loops.append(session._loop)
        assert loops[0] is loops[1]


class TestResidentSessionStream:
    """settle_stream(mesh=...) round 7: ONE long-lived session across
    batches. The persistent-session stream must be byte-identical to the
    per-batch-session stream (``resident_session=False``) and to the flat
    stream on a markets-only mesh — results, store state, journal epochs,
    and SQLite checkpoint bytes — across topology hits (refresh), drift
    (adopt relayout), and capacity-ladder growth; and the crash contract
    (restart from ``batches[len(stats):]`` with a fresh session) must
    survive unchanged."""

    def _mixed_batches(self):
        """Hits, drift, and growth in one stream: two stable-topology
        batches (the refresh steady state), two batches of a DRIFTED
        topology overlapping the first (adopt relayout with rows staying,
        entering, and leaving), and one batch of fresh markets large
        enough to run the store up its capacity ladder."""
        stable = stable_topology_batches(num_batches=2, seed=47)
        drifted = stable_topology_batches(
            num_batches=2, markets=40, universe=30, seed=47
        )
        rng = random.Random(5)
        growth = [(
            random_payloads(rng, 60, universe=40, tag="-grow"),
            [rng.random() < 0.5 for _ in range(60)],
        )]
        return stable + drifted + growth

    @staticmethod
    def _journal_epochs_sans_clock(path):
        """Decoded epoch frames with the wall-clock field masked: the
        byte-for-byte comparable content of a journal (``wall_unix_ts``
        — and the CRC covering it — legitimately differ between two
        runs of identical work)."""
        import struct

        blob = path.read_bytes()
        assert blob[:8] == b"BCEJRNL1"
        hdr = struct.Struct("<QQQQQdQ")
        off = 8
        epochs = []
        while off < len(blob):
            fields = hdr.unpack_from(blob, off)
            (epoch_index, used_after, pair_len, dirty, iso_len,
             _wall_ts, tag) = fields
            payload_len = pair_len + 33 * dirty + iso_len
            start = off + hdr.size
            epochs.append((
                (epoch_index, used_after, pair_len, dirty, iso_len, tag),
                blob[start:start + payload_len],
            ))
            off = start + payload_len + 4  # + crc32
        return epochs

    def _stream(self, batches, tmp_path, name, resident, mesh,
                journal=True, stats=None, now=21_300.0):
        from bayesian_consensus_engine_tpu.state.journal import JournalWriter

        store = TensorReliabilityStore()
        db = tmp_path / f"{name}.db"
        jrnl = tmp_path / f"{name}.jrnl"
        results = list(
            settle_stream(
                store, batches, steps=2, now=now, db_path=db,
                checkpoint_every=2, stats=stats, reuse_plans=True,
                mesh=mesh, resident_session=resident,
                journal=JournalWriter(jrnl) if journal else None,
            )
        )
        store.sync()
        return store, results, db, jrnl

    def test_persistent_equals_per_batch_and_flat_bytes(self, tmp_path):
        batches = self._mixed_batches()
        mesh = make_mesh()  # markets-only: the bit-exact regime
        on_stats, off_stats = [], []
        s_on, r_on, db_on, j_on = self._stream(
            batches, tmp_path, "on", True, mesh, stats=on_stats
        )
        s_off, r_off, db_off, j_off = self._stream(
            batches, tmp_path, "off", False, mesh, stats=off_stats
        )
        s_flat, r_flat, db_flat, j_flat = self._stream(
            batches, tmp_path, "flat", True, None
        )
        # The session was served resident: one start, hits refresh, drift
        # and growth adopt WITHOUT teardown.
        assert [s["session_adopt"] for s in on_stats] == [
            "start", "refresh", "relayout", "refresh", "relayout",
        ]
        assert [s["session_adopt"] for s in off_stats] == [None] * 5
        for mine, ref, flat in zip(r_on, r_off, r_flat):
            assert mine.market_keys == ref.market_keys
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(ref.consensus)
            )
            np.testing.assert_array_equal(
                np.asarray(mine.consensus), np.asarray(flat.consensus)
            )
        assert s_on.list_sources() == s_off.list_sources()
        assert s_on.list_sources() == s_flat.list_sources()
        assert db_on.read_bytes() == db_off.read_bytes()
        assert db_on.read_bytes() == db_flat.read_bytes()
        # Journal EPOCH BYTES: same cadence, same dirty rows, same frame
        # payloads (the wall-clock stamp each epoch carries is the one
        # legitimately run-varying field — masked by the helper).
        epochs_on = self._journal_epochs_sans_clock(j_on)
        assert epochs_on == self._journal_epochs_sans_clock(j_off)
        assert epochs_on == self._journal_epochs_sans_clock(j_flat)

    def test_relayout_never_rebuilds_from_host(self, tmp_path, monkeypatch):
        """The drift batches must be served by the device relayout, not a
        host-state rebuild: ``_build_state`` runs exactly once (batch 0)
        even though the drifted topology OVERLAPS the session's rows."""
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
        )

        builds = []
        real_build = ShardedSettlementSession._build_state

        def counting_build(self, epoch0):
            builds.append(epoch0)
            return real_build(self, epoch0)

        monkeypatch.setattr(
            ShardedSettlementSession, "_build_state", counting_build
        )
        stats = []
        self._stream(
            self._mixed_batches(), tmp_path, "count", True, make_mesh(),
            journal=False, stats=stats,
        )
        assert len(builds) == 1
        assert [s["session_adopt"] for s in stats] == [
            "start", "refresh", "relayout", "refresh", "relayout",
        ]

    def test_resident_counters_and_adopt_phase(self, tmp_path):
        from bayesian_consensus_engine_tpu import obs
        from bayesian_consensus_engine_tpu.obs.timeline import (
            PhaseTimeline,
            recording,
        )

        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        timeline = PhaseTimeline()
        try:
            stats = []
            with recording(timeline):
                self._stream(
                    self._mixed_batches(), tmp_path, "obs", True,
                    make_mesh(), journal=False, stats=stats,
                )
        finally:
            obs.set_metrics_registry(previous)
        export = registry.export()
        assert export["counters"]["stream.session_adopts"] == 2
        # Last batch's active set: 60 fresh markets' rows.
        assert export["gauges"]["stream.resident_rows"] > 0
        # The adopt cost lands in the new canonical phase, inside the
        # additive per-batch breakdown, on exactly the adopting batches.
        adopted = [s["session_adopt"] == "relayout" for s in stats]
        recorded = ["state_adopt" in s.get("phases", {}) for s in stats]
        assert recorded == adopted
        assert timeline.totals().get("state_adopt", 0.0) > 0.0

    def test_crash_resume_with_fresh_session(self, tmp_path, monkeypatch):
        """Kill the resident stream mid-flight (a failing journal epoch
        write), restart from ``batches[len(stats):]`` with a fresh
        session on the same store: the final store, the journal's
        replayed state, and a full SQLite export must equal the
        uninterrupted run's."""
        from bayesian_consensus_engine_tpu.state.journal import (
            JournalWriter,
            replay_journal,
        )

        batches = self._mixed_batches()
        mesh = make_mesh()
        ref_store, _, _, _ = self._stream(
            batches, tmp_path, "uninterrupted", True, mesh
        )

        store = TensorReliabilityStore()
        jrnl = tmp_path / "crash.jrnl"
        real_flush = TensorReliabilityStore.flush_to_journal_async
        calls = {"n": 0}

        def broken_second(self, journal, tag=0):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("journal disk gone")
            return real_flush(self, journal, tag=tag)

        monkeypatch.setattr(
            TensorReliabilityStore, "flush_to_journal_async", broken_second
        )
        stats: list = []
        writer = JournalWriter(jrnl)
        with pytest.raises(RuntimeError, match="journal disk gone"):
            for _result in settle_stream(
                store, batches, steps=2, now=21_300.0,
                checkpoint_every=2, stats=stats, reuse_plans=True,
                mesh=mesh, journal=writer,
            ):
                pass
        writer.close()
        monkeypatch.setattr(
            TensorReliabilityStore, "flush_to_journal_async", real_flush
        )
        settled = len(stats)
        assert 0 < settled < len(batches)
        # Restart: same store, FRESH session (settle_stream builds one),
        # the documented resume point, now advanced by the settled count.
        resume_stats: list = []
        for _result in settle_stream(
            store, batches[settled:], steps=2, now=21_300.0 + settled,
            checkpoint_every=2, stats=resume_stats, reuse_plans=True,
            mesh=mesh, journal=JournalWriter(jrnl, resume=True),
        ):
            pass
        store.sync()
        assert resume_stats[0]["session_adopt"] == "start"
        assert store.list_sources() == ref_store.list_sources()
        # Journal: replaying the crashed-then-resumed journal rebuilds the
        # same live state (epoch tags restart with the resumed stream, so
        # byte-equality is not the contract here — replayed STATE is).
        replayed, _tag = replay_journal(jrnl)
        replayed.sync()
        assert replayed.list_sources() == store.list_sources()
        # SQLite: a fresh full export of each final store, byte-compared.
        (tmp_path / "resumed_full.db").unlink(missing_ok=True)
        store.flush_to_sqlite(tmp_path / "resumed_full.db")
        ref_store.flush_to_sqlite(tmp_path / "ref_full.db")
        assert (tmp_path / "resumed_full.db").read_bytes() == (
            tmp_path / "ref_full.db"
        ).read_bytes()

    def test_two_d_mesh_resident_drift_matches_flat_to_ulp(self, tmp_path):
        """The adopt relayout under a sources-sharded mesh: the resident
        stream's psum re-association stays within the documented ulp
        envelope of the flat stream across drift batches."""
        batches = self._mixed_batches()
        s_mesh, r_mesh, _, _ = self._stream(
            batches, tmp_path, "2d", True, make_mesh((4, 2)), journal=False
        )
        s_flat, r_flat, _, _ = self._stream(
            batches, tmp_path, "2dflat", True, None, journal=False
        )
        for mine, ref in zip(r_mesh, r_flat):
            np.testing.assert_allclose(
                np.asarray(mine.consensus), np.asarray(ref.consensus),
                rtol=2e-6, atol=1e-7,
            )
        mine, theirs = s_mesh.list_sources(), s_flat.list_sources()
        assert len(mine) == len(theirs) > 0
        for a, b in zip(mine, theirs):
            assert (a.source_id, a.market_id) == (b.source_id, b.market_id)
            assert abs(a.reliability - b.reliability) < 1e-6
            assert a.confidence == b.confidence
            assert a.updated_at == b.updated_at

    def test_per_batch_flag_still_available_for_ab(self, tmp_path):
        """resident_session=False is the A/B lever the bench leg uses —
        it must keep the legacy per-batch behaviour observable (a
        session build per batch)."""
        from bayesian_consensus_engine_tpu.pipeline import (
            ShardedSettlementSession,
        )

        batches = self._mixed_batches()[:3]
        builds = []
        real_init = ShardedSettlementSession.__init__

        def counting_init(self, *args, **kwargs):
            builds.append(1)
            return real_init(self, *args, **kwargs)

        import unittest.mock as mock

        with mock.patch.object(
            ShardedSettlementSession, "__init__", counting_init
        ):
            self._stream(
                batches, tmp_path, "ab", False, make_mesh(), journal=False
            )
        assert len(builds) == len(batches)
