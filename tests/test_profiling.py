"""Profiling hooks: annotation pass-through and Nth-call auto-capture."""

import numpy as np

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    build_cycle,
    init_block_state,
)
from bayesian_consensus_engine_tpu.utils.profiling import (
    annotate,
    auto_trace,
    device_memory_stats,
    trace,
)


def _cycle_args(m=8, k=4, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.random((m, k)), jnp.float32),
        jnp.asarray(rng.random((m, k)) < 0.9),
        jnp.asarray(rng.random(m) < 0.5),
        init_block_state(m, k),
        jnp.float32(1.0),
    )


class TestTrace:
    def test_annotation_only_passthrough(self):
        with trace("unit-test-block"):
            out = jnp.sum(jnp.arange(4.0))
        assert float(out) == 6.0

    def test_annotate_decorator(self):
        @annotate("unit-test-fn")
        def double(x):
            return x * 2

        assert float(double(jnp.float32(3.0))) == 6.0


class TestDeviceMemoryStats:
    def test_shape_and_graceful_absence(self):
        # CPU devices expose no stats; the helper must still return the
        # full shape with zero/None placeholders, never raise.
        stats = device_memory_stats()
        assert set(stats) == {
            "device",
            "bytes_in_use",
            "bytes_limit",
            "peak_bytes_in_use",
            "utilisation",
        }
        assert stats["bytes_in_use"] >= 0
        assert stats["utilisation"] is None or 0 <= stats["utilisation"] <= 1


class TestAutoTrace:
    def test_nth_call_captures_profile(self, tmp_path):
        log_dir = tmp_path / "bce-trace"
        cycle = auto_trace(
            build_cycle(mesh=None, donate=False), str(log_dir), every_n=3
        )
        args = _cycle_args()
        plain = build_cycle(mesh=None, donate=False)(*args)
        results = [cycle(*_cycle_args()) for _ in range(3)]

        # Pass-through semantics: every call returns real results.
        np.testing.assert_allclose(
            np.asarray(results[0].consensus), np.asarray(plain.consensus)
        )
        # The 3rd call was captured: the profiler wrote trace artifacts.
        captured = list(log_dir.rglob("*"))
        assert any(p.is_file() for p in captured), captured

    def test_untraced_calls_write_nothing(self, tmp_path):
        log_dir = tmp_path / "bce-trace"
        cycle = auto_trace(
            build_cycle(mesh=None, donate=False), str(log_dir), every_n=5
        )
        for _ in range(3):
            cycle(*_cycle_args())
        assert not log_dir.exists() or not any(log_dir.rglob("*"))

    def test_named_scopes_compile_in_cycle(self):
        # Phase annotations must not alter semantics; the HLO carries them.
        args = _cycle_args(seed=3)
        result = build_cycle(mesh=None, donate=False)(*args)
        assert np.isfinite(np.asarray(result.consensus)).all()
        lowered = jax.jit(
            lambda *a: build_cycle(mesh=None, donate=False)(*a)
        ).lower(*args)
        try:
            hlo = lowered.as_text(debug_info=True)
        except TypeError:
            # Old JAX: as_text() strips location metadata; the scope names
            # survive only in the compiled executable's HLO modules.
            hlo = "\n".join(
                m.to_string()
                for m in lowered.compile().runtime_executable().hlo_modules()
            )
        assert "bce.read_decay" in hlo and "bce.consensus_reduce" in hlo
