"""Sort-based batched tie-break (ops/tiebreak.py) vs the scalar contract.

Same parity methodology as the ring suite (tests/test_ring.py): constructed
hierarchy cases including the reference quirks, then randomized rows checked
row-by-row against DeterministicTieBreaker, then a batch-level cross-check
against the ring path — two independent groupings (sorted segments vs
pairwise ring accumulation) that must agree.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.models.tiebreak import (
    AgentSignal,
    DeterministicTieBreaker,
)
from bayesian_consensus_engine_tpu.ops.tiebreak import batched_tiebreak
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.parallel.ring import build_ring_tiebreak

_LABELS = {0: "unanimous", 1: "weight_density", 2: "prediction_value_smallest"}


def _rows_from_agents(rows, a_total):
    """Pack lists of AgentSignal into padded (M, A) arrays."""
    m = len(rows)
    pred = np.zeros((m, a_total), np.float32)
    weight = np.zeros((m, a_total), np.float32)
    conf = np.zeros((m, a_total), np.float32)
    rel = np.zeros((m, a_total), np.float32)
    valid = np.zeros((m, a_total), bool)
    for i, agents in enumerate(rows):
        for j, agent in enumerate(agents):
            pred[i, j] = agent.prediction
            weight[i, j] = agent.weight
            conf[i, j] = agent.confidence
            rel[i, j] = agent.reliability_score
            valid[i, j] = True
    return tuple(jnp.asarray(x) for x in (pred, weight, conf, rel, valid))


def _run_one(agents, a_total=16):
    result = batched_tiebreak(*_rows_from_agents([agents], a_total))
    return jax.tree.map(lambda x: np.asarray(x)[0], result)


class TestHierarchy:
    def test_density_winner(self):
        agents = [
            AgentSignal("a", 0.7, 0.9, weight=2.0, reliability_score=0.8),
            AgentSignal("b", 0.7, 0.8, weight=2.0, reliability_score=0.6),
            AgentSignal("c", 0.3, 0.7, weight=1.0, reliability_score=0.9),
        ]
        want_pred, want_diag = DeterministicTieBreaker().resolve(list(agents))
        got = _run_one(agents)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert _LABELS[int(got.resolved_by)] == want_diag.tie_resolved_by
        assert int(got.num_groups) == len(want_diag.groups)
        assert got.confidence_variance == pytest.approx(
            want_diag.confidence_variance, abs=1e-5
        )

    def test_reliability_breaks_density_tie_labeled_density(self):
        # Quirk #6: the decision falls to max_reliability but the label
        # stays weight_density (reference: tiebreak.py:126-131).
        agents = [
            AgentSignal("a", 0.6, 0.5, weight=1.0, reliability_score=0.9),
            AgentSignal("b", 0.4, 0.5, weight=1.0, reliability_score=0.2),
        ]
        want_pred, want_diag = DeterministicTieBreaker().resolve(list(agents))
        got = _run_one(agents)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert want_diag.tie_resolved_by == "weight_density"
        assert _LABELS[int(got.resolved_by)] == "weight_density"

    def test_full_tie_smallest_prediction(self):
        agents = [
            AgentSignal("a", 0.8, 0.5, weight=1.0, reliability_score=0.5),
            AgentSignal("b", 0.2, 0.5, weight=1.0, reliability_score=0.5),
        ]
        got = _run_one(agents)
        assert got.prediction == pytest.approx(0.2, abs=1e-6)
        assert _LABELS[int(got.resolved_by)] == "prediction_value_smallest"

    def test_unanimous(self):
        agents = [
            AgentSignal("a", 0.55, 0.5, weight=1.0, reliability_score=0.5),
            AgentSignal("b", 0.55, 0.9, weight=3.0, reliability_score=0.7),
        ]
        got = _run_one(agents)
        assert _LABELS[int(got.resolved_by)] == "unanimous"
        assert int(got.num_groups) == 1

    def test_empty_row_is_nan_padding(self):
        pred, weight, conf, rel, valid = _rows_from_agents(
            [[AgentSignal("a", 0.5, 0.5)], []], a_total=4
        )
        result = batched_tiebreak(pred, weight, conf, rel, valid)
        assert np.asarray(result.prediction)[0] == pytest.approx(0.5)
        assert np.isnan(np.asarray(result.prediction)[1])
        assert int(np.asarray(result.num_groups)[1]) == 0
        assert int(np.asarray(result.resolved_by)[1]) == 0
        assert np.asarray(result.confidence_variance)[1] == 0.0

    def test_duplicate_group_spread_across_lanes(self):
        # Same key in non-adjacent lanes must still be one group after the
        # sort (the dict-grouping semantics the reference has).
        agents = [
            AgentSignal("a", 0.3, 0.5, weight=1.0, reliability_score=0.1),
            AgentSignal("b", 0.9, 0.5, weight=5.0, reliability_score=0.2),
            AgentSignal("c", 0.3, 0.5, weight=3.0, reliability_score=0.9),
        ]
        want_pred, want_diag = DeterministicTieBreaker().resolve(list(agents))
        got = _run_one(agents)
        assert got.prediction == pytest.approx(want_pred, abs=1e-6)
        assert int(got.num_groups) == 2
        want_group = want_diag.groups[round(want_pred, 6)]
        assert got.weight_density == pytest.approx(
            want_group["weight_density"], abs=1e-4
        )
        assert got.max_reliability == pytest.approx(
            want_group["max_reliability"], abs=1e-4
        )


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_rows_match_scalar(self, seed):
        rng = np.random.default_rng(seed)
        m, a = 12, 24
        grid = np.array([0.1, 0.25, 0.5, 0.75, 0.9])
        rows = [
            [
                AgentSignal(
                    f"a{i}-{j}",
                    float(rng.choice(grid)),
                    float(rng.uniform(0, 1)),
                    weight=float(rng.uniform(0.1, 3.0)),
                    reliability_score=float(rng.uniform(0, 1)),
                )
                for j in range(int(rng.integers(1, a)))
            ]
            for i in range(m)
        ]
        result = batched_tiebreak(*_rows_from_agents(rows, a))
        breaker = DeterministicTieBreaker()
        for i, agents in enumerate(rows):
            want_pred, want_diag = breaker.resolve(list(agents))
            assert np.asarray(result.prediction)[i] == pytest.approx(
                want_pred, abs=1e-6
            ), f"row {i}"
            if len(agents) > 1:
                assert (
                    _LABELS[int(np.asarray(result.resolved_by)[i])]
                    == want_diag.tie_resolved_by
                ), f"row {i}"
                assert int(np.asarray(result.num_groups)[i]) == len(
                    want_diag.groups
                ), f"row {i}"
            assert np.asarray(result.confidence_variance)[i] == pytest.approx(
                want_diag.confidence_variance, abs=1e-5
            ), f"row {i}"


class TestAgainstRingPath:
    def test_batch_cross_check(self):
        # Two independent groupings (sorted segments here, pairwise ring
        # accumulation there) over the same batch must agree field-for-field.
        rng = np.random.default_rng(99)
        m, a = 16, 64
        grid = np.array([0.1, 0.3, 0.5, 0.7, 0.9], dtype=np.float32)
        pred = jnp.asarray(rng.choice(grid, (m, a)), jnp.float32)
        weight = jnp.asarray(rng.uniform(0.1, 2.0, (m, a)), jnp.float32)
        conf = jnp.asarray(rng.uniform(0, 1, (m, a)), jnp.float32)
        rel = jnp.asarray(rng.uniform(0, 1, (m, a)), jnp.float32)
        valid = jnp.asarray(rng.random((m, a)) < 0.9)

        sorted_r = batched_tiebreak(pred, weight, conf, rel, valid)
        ring_r = build_ring_tiebreak(make_mesh((2, 4)))(
            pred, weight, conf, rel, valid
        )
        np.testing.assert_allclose(
            np.asarray(sorted_r.prediction), np.asarray(ring_r.prediction),
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(sorted_r.weight_density),
            np.asarray(ring_r.weight_density),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(sorted_r.max_reliability),
            np.asarray(ring_r.max_reliability),
            rtol=1e-6,
        )
        np.testing.assert_array_equal(
            np.asarray(sorted_r.resolved_by), np.asarray(ring_r.resolved_by)
        )
        np.testing.assert_array_equal(
            np.asarray(sorted_r.num_groups), np.asarray(ring_r.num_groups)
        )

    def test_markets_sharded_input_propagates(self):
        # Row-local ops: a markets-sharded input stays sharded, no gather.
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh((8, 1))
        rng = np.random.default_rng(5)
        m, a = 32, 16
        sharding = NamedSharding(mesh, P("markets", None))
        grid = np.array([0.2, 0.5, 0.8], dtype=np.float32)
        args = (
            jax.device_put(rng.choice(grid, (m, a)).astype(np.float32), sharding),
            jax.device_put(rng.uniform(0.1, 2, (m, a)).astype(np.float32), sharding),
            jax.device_put(rng.uniform(0, 1, (m, a)).astype(np.float32), sharding),
            jax.device_put(rng.uniform(0, 1, (m, a)).astype(np.float32), sharding),
            jax.device_put(rng.random((m, a)) < 0.9, sharding),
        )
        result = jax.jit(batched_tiebreak)(*args)
        out_sharding = result.prediction.sharding
        assert out_sharding.is_equivalent_to(
            NamedSharding(mesh, P("markets")), ndim=1
        )
