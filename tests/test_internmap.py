"""Equivalence suite for the native internmap extension.

The C hash (native/internmap.c) must assign rows in first-seen order,
identical to the dict-backed :class:`IdInterner` — these tests drive both
through the same key streams and assert row-for-row parity, plus the
NUL-rejection rule that keeps single-string and pair key spaces disjoint.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from bayesian_consensus_engine_tpu.utils.interning import (
    IdInterner,
    NativePairInterner,
    _load_internmap,
    make_pair_interner,
)

internmap = _load_internmap()

pytestmark = pytest.mark.skipif(
    internmap is None,
    reason="native internmap not built (python native/build.py)",
)


def random_pairs(n: int, n_sources: int, n_markets: int, seed: int = 0):
    rng = random.Random(seed)
    return [
        (f"src-{rng.randrange(n_sources)}", f"mkt-{rng.randrange(n_markets)}")
        for _ in range(n)
    ]


class TestFirstSeenParity:
    def test_single_pairs_match_idinterner(self):
        native = NativePairInterner()
        pure = IdInterner()
        for pair in random_pairs(2000, 40, 30):
            assert native.intern(pair) == pure.intern(pair)
        assert len(native) == len(pure)
        assert native.ids() == pure.ids()

    def test_batch_matches_singles_and_idinterner(self):
        pairs = random_pairs(5000, 60, 50, seed=1)
        sources = [p[0] for p in pairs]
        markets = [p[1] for p in pairs]

        native = NativePairInterner()
        rows_batch = native.intern_arrays(sources, markets)

        pure = IdInterner()
        rows_pure = pure.intern_arrays(sources, markets)

        np.testing.assert_array_equal(rows_batch, rows_pure)
        assert native.ids() == pure.ids()

        # Re-interning the same stream must be pure lookup: identical rows,
        # no growth.
        before = len(native)
        np.testing.assert_array_equal(
            native.intern_arrays(sources, markets), rows_batch
        )
        assert len(native) == before

    def test_growth_past_initial_capacity(self):
        # Initial table capacity is 64 slots; cross several resizes.
        native = NativePairInterner()
        pure = IdInterner()
        pairs = [(f"s{i}", f"m{i}") for i in range(10_000)]
        for pair in pairs:
            assert native.intern(pair) == pure.intern(pair)
        assert len(native) == 10_000
        assert native.id_of(9_999) == ("s9999", "m9999")


class TestLookups:
    def test_lookup_arrays_matches_singletons(self):
        native = NativePairInterner()
        known = random_pairs(500, 20, 20, seed=2)
        native.intern_all(known)
        probe = known[:100] + [("ghost", "mkt"), ("src-0", "nowhere")]
        rows = native.lookup_arrays([p[0] for p in probe], [p[1] for p in probe])
        expected = np.asarray(
            [native.get(p) for p in probe], dtype=np.int32
        )
        np.testing.assert_array_equal(rows, expected)
        assert rows[-1] == -1 and rows[-2] == -1

    def test_lookup_never_inserts(self):
        native = NativePairInterner()
        native.intern(("a", "b"))
        native.lookup_arrays(["x", "y"], ["m", "m"])
        assert native.get(("x", "m")) == -1
        assert len(native) == 1

    def test_lookup_raises_for_unknown(self):
        native = NativePairInterner()
        with pytest.raises(KeyError):
            native.lookup(("never", "seen"))

    def test_contains(self):
        native = NativePairInterner()
        native.intern(("a", "m"))
        assert ("a", "m") in native
        assert ("a", "n") not in native


class TestKeySpaceSeparation:
    """intern("a\\0b") must NOT alias intern_pair("a", "b")."""

    def test_single_key_rejects_nul(self):
        raw = internmap.InternMap()
        with pytest.raises(ValueError, match="NUL"):
            raw.intern("a\0b")
        with pytest.raises(ValueError, match="NUL"):
            raw.intern_batch(["ok", "bad\0key"])

    def test_pair_halves_reject_nul(self):
        native = NativePairInterner()
        with pytest.raises(ValueError, match="NUL"):
            native.intern(("a\0b", "m"))
        with pytest.raises(ValueError, match="NUL"):
            native.intern(("a", "m\0x"))
        with pytest.raises(ValueError, match="NUL"):
            native.intern_arrays(["a", "b\0c"], ["m", "m"])

    def test_nul_reads_are_absent_not_errors(self):
        # Writes reject NUL, but READS must treat a NUL key as simply
        # unknown — matching the IdInterner fallback, so the tensor store's
        # read behaviour does not depend on which backend is built.
        native = NativePairInterner()
        pure = IdInterner()
        native.intern(("a", "m"))
        pure.intern(("a", "m"))
        assert native.get(("a\0b", "m")) == pure.get(("a\0b", "m")) == -1
        assert (("a\0b", "m") in native) == (("a\0b", "m") in pure) is False
        with pytest.raises(KeyError):
            native.lookup(("a\0b", "m"))
        np.testing.assert_array_equal(
            native.lookup_arrays(["a", "a\0b"], ["m", "m"]),
            pure.lookup_arrays(["a", "a\0b"], ["m", "m"]),
        )

    def test_mixed_key_kinds_coexist(self):
        # One raw map can hold both str and pair keys without collision.
        raw = internmap.InternMap()
        assert raw.intern("alpha") == 0
        assert raw.intern_pair("alpha", "beta") == 1
        assert raw.intern("alphabeta") == 2  # concatenation is a distinct key
        assert raw.id_of(0) == "alpha"
        assert raw.id_of(1) == ("alpha", "beta")

    def test_type_errors(self):
        raw = internmap.InternMap()
        with pytest.raises(TypeError):
            raw.intern(42)
        with pytest.raises(TypeError):
            raw.intern_pairs(["a", 3], ["m", "m"])
        with pytest.raises(ValueError):
            raw.intern_pairs(["a"], ["m", "m"])  # length mismatch


class TestFactory:
    def test_make_pair_interner_prefers_native(self):
        interner = make_pair_interner()
        assert isinstance(interner, NativePairInterner)

    def test_items_row_order(self):
        native = NativePairInterner()
        native.intern(("s1", "m"))
        native.intern(("s0", "m"))
        assert native.items() == [(("s1", "m"), 0), (("s0", "m"), 1)]


class TestSortedRows:
    """C memcmp key sort == Python (source, market) tuple sort."""

    def test_randomized_matches_python_sorted(self):
        native = NativePairInterner()
        pairs = list(dict.fromkeys(random_pairs(3000, 80, 60, seed=9)))
        for pair in pairs:
            native.intern(pair)
        rows = np.arange(len(pairs), dtype=np.int32)
        rng = random.Random(1)
        shuffled = rows.copy()
        rng.shuffle(shuffled)
        got = native.sorted_rows(shuffled)
        expect = sorted(range(len(pairs)), key=pairs.__getitem__)
        assert got.tolist() == expect

    def test_unicode_and_prefix_order(self):
        # UTF-8 byte order equals code-point order; the NUL joiner sorts a
        # shorter source before any longer source sharing its prefix.
        native = NativePairInterner()
        pairs = [
            ("ab", "z"), ("a", "é"), ("a", "b"), ("abc", "a"),
            ("é", "a"), ("ζ", "m"), ("a", "bb"), ("aé", "x"),
        ]
        for pair in pairs:
            native.intern(pair)
        got = native.sorted_rows(np.arange(len(pairs), dtype=np.int32))
        assert [pairs[r] for r in got.tolist()] == sorted(pairs)

    def test_out_of_range_row_rejected(self):
        raw = internmap.InternMap()
        raw.intern_pair("a", "b")
        with pytest.raises(IndexError):
            raw.sorted_rows(np.array([0, 5], dtype=np.int32))

    def test_empty(self):
        raw = internmap.InternMap()
        assert bytes(raw.sorted_rows(np.zeros(0, dtype=np.int32))) == b""


@pytest.mark.skipif(
    internmap is None or not internmap.sqlite_writer_available(),
    reason="libsqlite3 runtime not dlopen()able here",
)
class TestFlushSqlite:
    """Direct error-path coverage of the C checkpoint writer (the happy
    paths are pinned against the sqlite3-module implementation in
    tests/test_tensor_store.py::TestNativeFlushParity). Skipped where the
    extension builds but libsqlite3 is absent: flush_sqlite checks runtime
    availability before argument validation."""

    def _map_with_pairs(self):
        raw = internmap.InternMap()
        raw.intern_pair("s", "m")
        raw.intern_pair("t", "m")
        return raw

    def test_single_string_key_rejected(self, tmp_path):
        raw = internmap.InternMap()
        raw.intern("not-a-pair")
        with pytest.raises(ValueError, match="single-string"):
            raw.flush_sqlite(
                str(tmp_path / "x.db"),
                np.array([0], dtype=np.int32),
                np.array([0.5]), np.array([0.25]), [""],
            )

    def test_row_out_of_columns_rejected(self, tmp_path):
        raw = self._map_with_pairs()
        with pytest.raises(IndexError):
            raw.flush_sqlite(
                str(tmp_path / "x.db"),
                np.array([1], dtype=np.int32),
                np.array([0.5]),  # only one column row for row id 1
                np.array([0.25]), ["", ""],
            )

    def test_iso_must_be_list(self, tmp_path):
        raw = self._map_with_pairs()
        with pytest.raises(TypeError, match="list"):
            raw.flush_sqlite(
                str(tmp_path / "x.db"),
                np.array([0], dtype=np.int32),
                np.array([0.5, 0.5]), np.array([0.25, 0.25]),
                ("", ""),
            )

    def test_unwritable_path_raises(self):
        raw = self._map_with_pairs()
        with pytest.raises(RuntimeError, match="sqlite checkpoint"):
            raw.flush_sqlite(
                "/nonexistent-dir/x.db",
                np.array([0], dtype=np.int32),
                np.array([0.5, 0.5]), np.array([0.25, 0.25]), ["", ""],
            )


class TestIndexedPairs:
    """intern_pairs_indexed == intern_pairs on the materialised columns."""

    def test_matches_materialised_pairs(self):
        rng = random.Random(17)
        table_a = [f"src-é{i}" for i in range(40)]
        table_b = [f"mkt-{i}" for i in range(25)]
        codes_a = np.array(
            [rng.randrange(40) for _ in range(3000)], dtype=np.int32)
        codes_b = np.array(
            [rng.randrange(25) for _ in range(3000)], dtype=np.int32)

        indexed = internmap.InternMap()
        got = np.frombuffer(
            indexed.intern_pairs_indexed(table_a, codes_a, table_b, codes_b),
            dtype=np.int32,
        )
        plain = internmap.InternMap()
        want = np.frombuffer(
            plain.intern_pairs(
                [table_a[c] for c in codes_a.tolist()],
                [table_b[c] for c in codes_b.tolist()],
            ),
            dtype=np.int32,
        )
        np.testing.assert_array_equal(got, want)
        assert indexed.ids() == plain.ids()

    def test_out_of_range_code_rejected(self):
        raw = internmap.InternMap()
        with pytest.raises(IndexError, match="out of table range"):
            raw.intern_pairs_indexed(
                ["a"], np.array([1], dtype=np.int32),
                ["m"], np.array([0], dtype=np.int32))

    def test_nul_in_table_rejected(self):
        raw = internmap.InternMap()
        with pytest.raises(ValueError, match="NUL"):
            raw.intern_pairs_indexed(
                ["a\0b"], np.array([0], dtype=np.int32),
                ["m"], np.array([0], dtype=np.int32))

    def test_mismatched_code_lengths_rejected(self):
        raw = internmap.InternMap()
        with pytest.raises(ValueError, match="equal-length"):
            raw.intern_pairs_indexed(
                ["a"], np.array([0, 0], dtype=np.int32),
                ["m"], np.array([0], dtype=np.int32))

    def test_empty(self):
        raw = internmap.InternMap()
        out = raw.intern_pairs_indexed(
            [], np.zeros(0, dtype=np.int32), [], np.zeros(0, dtype=np.int32))
        assert bytes(out) == b""

    def test_unreferenced_table_entry_never_validated(self):
        """A table entry no code references (e.g. a zero-signal market's
        NUL-carrying id) must not raise — matching the per-pair paths."""
        raw = internmap.InternMap()
        rows = raw.intern_pairs_indexed(
            ["ok", "bad\0sid"], np.array([0], dtype=np.int32),
            ["m", 42], np.array([0], dtype=np.int32))
        assert np.frombuffer(rows, dtype=np.int32).tolist() == [0]
        assert raw.id_of(0) == ("ok", "m")


class TestIndexedErrorRecoveryParity:
    def test_pairs_before_a_bad_code_are_interned(self):
        """Chunked batching may not change observable error-recovery state:
        like the per-pair paths, everything BEFORE the bad pair interns."""
        internmap = pytest.importorskip(
            "bayesian_consensus_engine_tpu._native.internmap"
        )
        m = internmap.InternMap()
        a_table = ["s0", "s1", "s2"]
        b_table = ["m0", "m1"]
        a_codes = np.asarray([0, 1, 2, 99], dtype=np.int32)  # 99: bad
        b_codes = np.asarray([0, 1, 0, 1], dtype=np.int32)
        with pytest.raises(IndexError, match="pair 3"):
            m.intern_pairs_indexed(a_table, a_codes, b_table, b_codes)
        assert len(m) == 3
        assert m.ids() == [("s0", "m0"), ("s1", "m1"), ("s2", "m0")]

    def test_bad_pair_in_a_later_chunk(self):
        internmap = pytest.importorskip(
            "bayesian_consensus_engine_tpu._native.internmap"
        )
        m = internmap.InternMap()
        n = 1024 + 7  # crosses the chunk boundary
        a_table = [f"s{i}" for i in range(n)]
        b_table = ["mkt"]
        a_codes = np.arange(n, dtype=np.int32)
        a_codes[-1] = n + 50  # bad code in the second chunk
        b_codes = np.zeros(n, dtype=np.int32)
        with pytest.raises(IndexError):
            m.intern_pairs_indexed(a_table, a_codes, b_table, b_codes)
        assert len(m) == n - 1  # everything before the bad pair interned

    def test_intern_pairs_partial_state_on_error(self):
        internmap = pytest.importorskip(
            "bayesian_consensus_engine_tpu._native.internmap"
        )
        m = internmap.InternMap()
        sources = ["a", "b", "bad\x00id", "c"]
        markets = ["m", "m", "m", "m"]
        with pytest.raises(ValueError):
            m.intern_pairs(sources, markets)
        assert m.ids() == [("a", "m"), ("b", "m")]
