"""Deterministic tie-break hierarchy (weight density → max reliability →
smallest prediction) + diagnostics labeling quirks."""

import pytest

from bayesian_consensus_engine_tpu.models.tiebreak import (
    AgentSignal,
    DeterministicTieBreaker,
    TieBreakDiagnostics,
)


class TestAgentSignal:
    def test_valid(self):
        s = AgentSignal("a1", 0.75, 0.8, 0.9, 0.7)
        assert s.agent_id == "a1"
        assert s.prediction == 0.75
        assert s.weight == 0.9

    def test_defaults(self):
        s = AgentSignal("a1", 0.75, 0.8)
        assert s.weight == 1.0
        assert s.reliability_score == 0.5

    def test_confidence_bounds(self):
        with pytest.raises(ValueError, match="confidence must be in"):
            AgentSignal("a1", 0.5, 1.5)
        with pytest.raises(ValueError, match="confidence must be in"):
            AgentSignal("a1", 0.5, -0.1)

    def test_reliability_bounds(self):
        with pytest.raises(ValueError, match="reliability_score must be in"):
            AgentSignal("a1", 0.5, 0.5, 1.0, 1.5)


class TestResolve:
    def setup_method(self):
        self.breaker = DeterministicTieBreaker()

    def test_empty_raises(self):
        with pytest.raises(ValueError, match="empty agent list"):
            self.breaker.resolve([])

    def test_single_agent(self):
        pred, diag = self.breaker.resolve([AgentSignal("a1", 0.75, 0.8)])
        assert pred == 0.75
        assert diag.method == "single_agent"
        assert diag.tie_resolved_by == "unanimous"
        assert diag.confidence_variance == 0.0
        assert diag.groups == {0.75: {"count": 1}}

    def test_unanimous(self):
        agents = [
            AgentSignal("a1", 0.75, 0.8, 0.9, 0.7),
            AgentSignal("a2", 0.75, 0.75, 0.85, 0.6),
            AgentSignal("a3", 0.75, 0.70, 0.80, 0.5),
        ]
        pred, diag = self.breaker.resolve(agents)
        assert pred == 0.75
        assert diag.tie_resolved_by == "unanimous"
        assert diag.groups[0.75]["count"] == 3

    def test_weight_density_primary(self):
        agents = [
            AgentSignal("a1", 0.75, 0.85, 0.9, 0.82),
            AgentSignal("a2", 0.75, 0.80, 0.85, 0.78),
            AgentSignal("a3", 0.25, 0.70, 0.6, 0.65),
            AgentSignal("a4", 0.25, 0.65, 0.55, 0.70),
            AgentSignal("a5", 0.25, 0.60, 0.50, 0.60),
        ]
        pred, diag = self.breaker.resolve(agents)
        assert pred == 0.75
        assert diag.tie_resolved_by == "weight_density"
        assert diag.groups[0.75]["weight_density"] == 0.875
        assert diag.groups[0.25]["weight_density"] == 0.55

    def test_max_reliability_secondary_still_labeled_weight_density(self):
        """Quirk #6: decision made by max_reliability, label says weight_density."""
        agents = [
            AgentSignal("a1", 0.75, 0.8, 1.0, 0.5),
            AgentSignal("a2", 0.25, 0.8, 1.0, 0.9),
        ]
        pred, diag = self.breaker.resolve(agents)
        assert pred == 0.25
        assert diag.tie_resolved_by == "weight_density"

    def test_smallest_prediction_tertiary(self):
        """Quirk #5: full tie → smallest prediction wins (not lexicographic id)."""
        agents = [
            AgentSignal("a1", 0.75, 0.8, 1.0, 0.9),
            AgentSignal("a2", 0.25, 0.8, 1.0, 0.9),
        ]
        pred, diag = self.breaker.resolve(agents)
        assert pred == 0.25
        assert diag.tie_resolved_by == "prediction_value_smallest"

    def test_grouping_rounds_to_precision(self):
        agents = [
            AgentSignal("a1", 0.7500000001, 0.8),
            AgentSignal("a2", 0.7500000002, 0.7),
        ]
        _pred, diag = self.breaker.resolve(agents)
        assert list(diag.groups) == [0.75]
        assert diag.groups[0.75]["count"] == 2

    def test_custom_precision(self):
        breaker = DeterministicTieBreaker(precision=1)
        agents = [AgentSignal("a1", 0.74, 0.8), AgentSignal("a2", 0.71, 0.9)]
        _pred, diag = breaker.resolve(agents)
        assert list(diag.groups) == [0.7]

    def test_diagnostics_structure(self):
        agents = [
            AgentSignal("a1", 0.75, 0.8, 0.9, 0.7),
            AgentSignal("a2", 0.25, 0.6, 0.5, 0.5),
        ]
        _pred, diag = self.breaker.resolve(agents)
        assert isinstance(diag, TieBreakDiagnostics)
        assert diag.method == "prioritized_weight_density"
        for key in ("count", "weight_density", "avg_confidence", "max_reliability"):
            assert key in diag.groups[0.75]
        assert diag.confidence_variance > 0

    def test_determinism_under_input_permutation(self):
        import itertools

        agents = [
            AgentSignal("a1", 0.3, 0.5, 1.0, 0.4),
            AgentSignal("a2", 0.6, 0.7, 1.0, 0.4),
            AgentSignal("a3", 0.9, 0.6, 1.0, 0.4),
        ]
        winners = {
            self.breaker.resolve(list(perm))[0]
            for perm in itertools.permutations(agents)
        }
        assert winners == {0.3}  # full tie → smallest prediction, any order

    def test_matches_reference_implementation_randomized(self):
        import random
        import sys

        sys.path.insert(0, "/root/reference/src")
        try:
            from bayesian_engine.tiebreak import (
                AgentSignal as RefSignal,
                DeterministicTieBreaker as RefBreaker,
            )
        except ImportError:
            pytest.skip("reference not mounted")
        finally:
            sys.path.remove("/root/reference/src")

        rng = random.Random(123)
        ref_breaker = RefBreaker()
        for _ in range(300):
            n = rng.randint(1, 12)
            raw = [
                (
                    f"a{i}",
                    rng.choice([0.2, 0.5, 0.8, rng.random()]),
                    rng.random(),
                    rng.choice([1.0, rng.random()]),
                    rng.choice([0.5, rng.random()]),
                )
                for i in range(n)
            ]
            ours = self.breaker.resolve([AgentSignal(*a) for a in raw])
            theirs = ref_breaker.resolve([RefSignal(*a) for a in raw])
            assert ours[0] == theirs[0]
            assert ours[1].tie_resolved_by == theirs[1].tie_resolved_by
            assert ours[1].groups == theirs[1].groups
            assert ours[1].confidence_variance == theirs[1].confidence_variance
