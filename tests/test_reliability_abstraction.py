"""Namespaced reliability: market → domain → global → cold-start chain."""

import pytest

from bayesian_consensus_engine_tpu.utils.config import (
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
)
from bayesian_consensus_engine_tpu.state.namespaced import (
    NamespacedReliabilityRecord,
    NamespacedReliabilityStore,
    ReliabilityNamespace,
    ReliabilityProvider,
    domain_market_id,
)


@pytest.fixture
def store():
    with NamespacedReliabilityStore(":memory:") as s:
        yield s


class TestColdStart:
    def test_unknown_source_cold_start(self, store):
        record = store.get_reliability("unknown-source")
        assert record.namespace == ReliabilityNamespace.GLOBAL
        assert record.namespace_value == "cold-start"
        assert record.reliability == DEFAULT_RELIABILITY
        assert record.confidence == DEFAULT_CONFIDENCE
        assert record.is_fallback is True
        assert record.updated_at == ""


class TestFallbackChain:
    def test_global_seeded(self, store):
        store.set_global_reliability("a", 0.8, 0.6)
        record = store.get_reliability("a")
        assert record.reliability == pytest.approx(0.8)
        assert record.confidence == pytest.approx(0.6)
        assert record.namespace == ReliabilityNamespace.GLOBAL
        assert record.is_fallback is True

    def test_market_miss_falls_to_global(self, store):
        store.set_global_reliability("a", 0.75, 0.5)
        record = store.get_reliability("a", market_id="unseen-market")
        assert record.reliability == pytest.approx(0.75)
        assert record.namespace == ReliabilityNamespace.GLOBAL
        assert record.is_fallback is True

    def test_domain_beats_global(self, store):
        store.set_global_reliability("a", 0.75, 0.5)
        store.update_reliability("a", outcome_correct=True, domain="crypto")
        record = store.get_reliability("a", market_id="m-x", domain="crypto")
        assert record.namespace == ReliabilityNamespace.DOMAIN
        assert record.namespace_value == "crypto"
        assert record.is_fallback is True

    def test_market_beats_domain(self, store):
        store.update_reliability("a", outcome_correct=True, domain="crypto")
        store.update_reliability("a", outcome_correct=True, market_id="btc-1")
        record = store.get_reliability("a", market_id="btc-1", domain="crypto")
        assert record.namespace == ReliabilityNamespace.MARKET
        assert record.namespace_value == "btc-1"
        assert record.is_fallback is False

    def test_full_chain_walk(self, store):
        r1 = store.get_reliability("a", market_id="m1", domain="d1")
        assert r1.namespace_value == "cold-start"

        store.set_global_reliability("a", 0.7, 0.5)
        r2 = store.get_reliability("a", market_id="m1", domain="d1")
        assert r2.namespace == ReliabilityNamespace.GLOBAL
        assert r2.reliability == pytest.approx(0.7)

        store.update_reliability("a", outcome_correct=True, domain="d1")
        r3 = store.get_reliability("a", market_id="m1", domain="d1")
        assert r3.namespace == ReliabilityNamespace.DOMAIN

        store.update_reliability("a", outcome_correct=True, market_id="m1")
        r4 = store.get_reliability("a", market_id="m1", domain="d1")
        assert r4.namespace == ReliabilityNamespace.MARKET
        assert r4.namespace_value == "m1"


class TestUpdates:
    def test_domain_update_increases(self, store):
        record = store.update_reliability("a", outcome_correct=True, domain="crypto")
        assert record.reliability > DEFAULT_RELIABILITY
        assert record.namespace == ReliabilityNamespace.DOMAIN

    def test_domain_update_decreases(self, store):
        record = store.update_reliability("a", outcome_correct=False, domain="crypto")
        assert record.reliability < DEFAULT_RELIABILITY

    def test_update_global_flag_double_writes(self, store):
        record = store.update_reliability(
            "a", outcome_correct=True, domain="crypto", update_global=True
        )
        assert record.namespace == ReliabilityNamespace.DOMAIN
        global_record = store.get_reliability("a")
        assert global_record.namespace == ReliabilityNamespace.GLOBAL
        assert global_record.reliability > DEFAULT_RELIABILITY

    def test_no_namespace_updates_global(self, store):
        record = store.update_reliability("a", outcome_correct=True)
        assert record.namespace == ReliabilityNamespace.GLOBAL
        assert record.namespace_value == "global"


class TestStorageLayout:
    def test_domain_synthetic_market_id(self, store):
        assert domain_market_id("crypto") == "__domain__:crypto"
        store.update_reliability("a", outcome_correct=True, domain="crypto")
        raw = store.backing_store.get_reliability("a", "__domain__:crypto")
        assert raw.updated_at != ""

    def test_global_market_id_constant(self, store):
        assert NamespacedReliabilityStore.GLOBAL_MARKET_ID == "__global__"
        store.set_global_reliability("a", 0.9, 0.9)
        raw = store.backing_store.get_reliability("a", "__global__")
        assert raw.reliability == pytest.approx(0.9)


class TestProtocolAndRecord:
    def test_record_frozen(self):
        import dataclasses

        rec = NamespacedReliabilityRecord(
            "a", ReliabilityNamespace.GLOBAL, "global", 0.5, 0.25, "", True
        )
        with pytest.raises(dataclasses.FrozenInstanceError):
            rec.reliability = 0.9  # type: ignore[misc]

    def test_provider_protocol_runtime_checkable(self, store):
        # Declared for parity (reference quirk #11); our store satisfies it.
        assert isinstance(store, ReliabilityProvider)
