"""The executed lint gate itself: scripts/devlint.py rule coverage.

devlint is the lint gate that actually RUNS in this offline environment
(ruff/mypy execute only in hosted CI — they are not installed in the
image), so its rules need the same kind of pinning as any other executed
contract. Each case writes a small file and asserts on the findings.
"""

import importlib.util
import pathlib

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "devlint", pathlib.Path(__file__).resolve().parents[1] / "scripts" / "devlint.py"
)
devlint = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(devlint)


def findings(tmp_path, source: str) -> list[str]:
    f = tmp_path / "case.py"
    f.write_text(source, encoding="utf-8")
    return devlint.check_file(f)


def codes(tmp_path, source: str) -> list[str]:
    return [msg.split()[1] for msg in findings(tmp_path, source)]


class TestFunctionScopeImports:
    def test_unused_function_scope_import_flagged(self, tmp_path):
        src = (
            "def f():\n"
            "    from os.path import join, split\n"
            "    return join('a', 'b')\n"
        )
        msgs = findings(tmp_path, src)
        assert any("F401" in m and "'split'" in m for m in msgs)
        assert not any("'join'" in m for m in msgs)

    def test_alias_used_by_nested_def_not_flagged(self, tmp_path):
        src = (
            "def f():\n"
            "    import json\n"
            "    def g():\n"
            "        return json.dumps({})\n"
            "    return g\n"
        )
        assert "F401" not in codes(tmp_path, src)

    def test_quoted_annotation_counts_as_use(self, tmp_path):
        # ruff resolves string annotations; the gate must not be stricter.
        src = (
            "def f():\n"
            "    import decimal\n"
            "    val: \"decimal.Decimal\" = None\n"
            "    return val\n"
        )
        assert "F401" not in codes(tmp_path, src)

    def test_noqa_suppresses(self, tmp_path):
        src = (
            "def f():\n"
            "    import json  # noqa: F401\n"
            "    return 1\n"
        )
        assert "F401" not in codes(tmp_path, src)


class TestUndefinedNames:
    def test_genuine_undefined_name_flagged(self, tmp_path):
        # The exact bug class an executed F821 gate catches pre-run: a
        # name used in a test/function that nothing ever binds.
        src = (
            "def f():\n"
            "    return DeviceReliabilityState(1, 2)\n"
        )
        msgs = findings(tmp_path, src)
        assert any(
            "F821" in m and "DeviceReliabilityState" in m for m in msgs
        )

    @pytest.mark.parametrize(
        "src",
        [
            # builtins
            "def f(xs):\n    return sorted(len(x) for x in xs)\n",
            # closure over an enclosing local
            "def f():\n    y = 1\n    def g():\n        return y\n    return g\n",
            # module-level name defined AFTER the function (runtime-bound)
            "def f():\n    return HELPER\nHELPER = 3\n",
            # global statement binding
            "def set_it():\n    global COUNT\n    COUNT = 1\n"
            "def get_it():\n    return COUNT\n",
            # class attribute access through self + method cross-calls
            "class C:\n    def a(self):\n        return self.b()\n"
            "    def b(self):\n        return 1\n",
            # comprehension scope reading module binding
            "N = 4\nsquares = [i * i for i in range(N)]\n",
            # conditional import fallback pattern
            "try:\n    import json as codec\nexcept ImportError:\n"
            "    codec = None\nprint(codec)\n",
            # dunder module attributes
            "print(__name__, __file__)\n",
        ],
    )
    def test_bound_or_builtin_names_not_flagged(self, tmp_path, src):
        assert "F821" not in codes(tmp_path, src)

    def test_wildcard_import_skips_file(self, tmp_path):
        src = "from os.path import *\nprint(join('a', 'b'))\n"
        assert "F821" not in codes(tmp_path, src)


class TestWholeRepoClean:
    def test_repo_passes_devlint(self):
        # The gate the CI fallback step runs; keep it green.
        assert devlint.main([]) == 0
