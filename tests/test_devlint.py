"""The executed lint gate itself: scripts/devlint.py rule coverage.

devlint is the lint gate that actually RUNS in this offline environment
(ruff/mypy execute only in hosted CI — they are not installed in the
image), so its rules need the same kind of pinning as any other executed
contract. Each case writes a small file and asserts on the findings.
"""

import importlib.util
import pathlib

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "devlint", pathlib.Path(__file__).resolve().parents[1] / "scripts" / "devlint.py"
)
devlint = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(devlint)


def findings(tmp_path, source: str) -> list[str]:
    f = tmp_path / "case.py"
    f.write_text(source, encoding="utf-8")
    return devlint.check_file(f)


def codes(tmp_path, source: str) -> list[str]:
    return [msg.split()[1] for msg in findings(tmp_path, source)]


class TestFunctionScopeImports:
    def test_unused_function_scope_import_flagged(self, tmp_path):
        src = (
            "def f():\n"
            "    from os.path import join, split\n"
            "    return join('a', 'b')\n"
        )
        msgs = findings(tmp_path, src)
        assert any("F401" in m and "'split'" in m for m in msgs)
        assert not any("'join'" in m for m in msgs)

    def test_alias_used_by_nested_def_not_flagged(self, tmp_path):
        src = (
            "def f():\n"
            "    import json\n"
            "    def g():\n"
            "        return json.dumps({})\n"
            "    return g\n"
        )
        assert "F401" not in codes(tmp_path, src)

    def test_quoted_annotation_counts_as_use(self, tmp_path):
        # ruff resolves string annotations; the gate must not be stricter.
        src = (
            "def f():\n"
            "    import decimal\n"
            "    val: \"decimal.Decimal\" = None\n"
            "    return val\n"
        )
        assert "F401" not in codes(tmp_path, src)

    def test_noqa_suppresses(self, tmp_path):
        src = (
            "def f():\n"
            "    import json  # noqa: F401\n"
            "    return 1\n"
        )
        assert "F401" not in codes(tmp_path, src)


class TestUndefinedNames:
    def test_genuine_undefined_name_flagged(self, tmp_path):
        # The exact bug class an executed F821 gate catches pre-run: a
        # name used in a test/function that nothing ever binds.
        src = (
            "def f():\n"
            "    return DeviceReliabilityState(1, 2)\n"
        )
        msgs = findings(tmp_path, src)
        assert any(
            "F821" in m and "DeviceReliabilityState" in m for m in msgs
        )

    @pytest.mark.parametrize(
        "src",
        [
            # builtins
            "def f(xs):\n    return sorted(len(x) for x in xs)\n",
            # closure over an enclosing local
            "def f():\n    y = 1\n    def g():\n        return y\n    return g\n",
            # module-level name defined AFTER the function (runtime-bound)
            "def f():\n    return HELPER\nHELPER = 3\n",
            # global statement binding
            "def set_it():\n    global COUNT\n    COUNT = 1\n"
            "def get_it():\n    return COUNT\n",
            # class attribute access through self + method cross-calls
            "class C:\n    def a(self):\n        return self.b()\n"
            "    def b(self):\n        return 1\n",
            # comprehension scope reading module binding
            "N = 4\nsquares = [i * i for i in range(N)]\n",
            # conditional import fallback pattern
            "try:\n    import json as codec\nexcept ImportError:\n"
            "    codec = None\nprint(codec)\n",
            # dunder module attributes
            "print(__name__, __file__)\n",
        ],
    )
    def test_bound_or_builtin_names_not_flagged(self, tmp_path, src):
        assert "F821" not in codes(tmp_path, src)

    def test_wildcard_import_skips_file(self, tmp_path):
        src = "from os.path import *\nprint(join('a', 'b'))\n"
        assert "F821" not in codes(tmp_path, src)


class TestWholeRepoClean:
    def test_repo_passes_devlint(self):
        # The gate the CI fallback step runs; keep it green.
        assert devlint.main([]) == 0


# -- whole-program tier: multi-file fixture matrix ----------------------------
#
# check_source(project=…) builds a synthetic multi-file gate set, so the
# cross-module rules can be pinned without writing files into the repo.

from bayesian_consensus_engine_tpu import lint  # noqa: E402
from bayesian_consensus_engine_tpu.lint import config as lint_config  # noqa: E402

PKG = lint_config.PACKAGE


def _ids(src, rel, project=None, select=None):
    return [
        f.rule_id
        for f in lint.check_source(src, rel, project=project, select=select)
    ]


class TestJX110Matrix:
    """The jit wrap and the offending helper live in different modules."""

    _WRAP = (
        f"import jax\nfrom {PKG}.ops.helper import helper\n\n"
        "def build():\n    return jax.jit(helper)\n"
    )

    def test_helper_one_module_away(self):
        helper = "import numpy as np\n\ndef helper(x):\n    return np.asarray(x)\n"
        findings = lint.check_source(
            helper,
            f"{PKG}/ops/helper.py",
            project={f"{PKG}/parallel/wrap.py": self._WRAP},
        )
        assert [f.rule_id for f in findings] == ["JX110"]
        # The finding names the trace chain: wrap site first, helper last.
        assert "parallel/wrap.py:build" in findings[0].message
        assert "ops/helper.py:helper" in findings[0].message

    def test_helper_two_modules_away(self):
        deep = "import numpy as np\n\ndef inner(x):\n    return np.asarray(x)\n"
        mid = (
            f"from {PKG}.ops.deep import inner\n\n"
            "def mid(x):\n    return inner(x)\n"
        )
        wrap = (
            f"import jax\nfrom {PKG}.ops.mid import mid\n\n"
            "def build():\n    return jax.jit(mid)\n"
        )
        findings = lint.check_source(
            deep,
            f"{PKG}/ops/deep.py",
            project={
                f"{PKG}/ops/mid.py": mid,
                f"{PKG}/parallel/wrap.py": wrap,
            },
        )
        assert [f.rule_id for f in findings] == ["JX110"]
        # Full chain: wrap → mid → inner.
        assert "parallel/wrap.py:build" in findings[0].message
        assert "ops/mid.py:mid" in findings[0].message
        assert "ops/deep.py:inner" in findings[0].message

    def test_reexported_name_resolves(self):
        # sharded.py's shape: the wrap imports the name from a module
        # that merely re-exports it; the def lives one layer further.
        impl = "def fn(x):\n    return float(x)\n"
        reexport = (
            f"from {PKG}.ops.impl import fn\n\n__all__ = ['fn']\n"
        )
        wrap = (
            f"import jax\nfrom {PKG}.parallel.facade import fn\n\n"
            "def build():\n    return jax.jit(fn)\n"
        )
        findings = lint.check_source(
            impl,
            f"{PKG}/ops/impl.py",
            project={
                f"{PKG}/parallel/facade.py": reexport,
                f"{PKG}/parallel/wrap.py": wrap,
            },
        )
        assert [f.rule_id for f in findings] == ["JX110"]
        assert "ops/impl.py:fn" in findings[0].message

    def test_noqa_at_helper_line_suppresses(self):
        helper = (
            "import numpy as np\n\ndef helper(x):\n"
            "    return np.asarray(x)  # noqa: JX110\n"
        )
        assert _ids(
            helper,
            f"{PKG}/ops/helper.py",
            project={f"{PKG}/parallel/wrap.py": self._WRAP},
        ) == []

    def test_clean_helper_is_quiet(self):
        helper = "def helper(x):\n    return x * 2.0\n"
        assert _ids(
            helper,
            f"{PKG}/ops/helper.py",
            project={f"{PKG}/parallel/wrap.py": self._WRAP},
        ) == []

    def test_unwrapped_helper_is_quiet(self):
        # Same hazard, but nothing traces the helper: not JX110's business.
        helper = "import numpy as np\n\ndef helper(x):\n    return np.asarray(x)\n"
        nowrap = f"from {PKG}.ops.helper import helper\n\nout = helper(1)\n"
        assert _ids(
            helper,
            f"{PKG}/ops/helper.py",
            project={f"{PKG}/parallel/wrap.py": nowrap},
        ) == []


class TestAS6xxMatrix:
    """Async-safety shapes the per-file tier cannot see."""

    _REL = f"{PKG}/serve/case.py"

    def test_as601_sync_helper_reachable_only_from_async(self):
        src = (
            "import time\n\n"
            "def pack():\n    time.sleep(0.5)\n\n"
            "async def handle():\n    pack()\n"
        )
        findings = lint.check_source(src, self._REL, select=["AS601"])
        assert [f.rule_id for f in findings] == ["AS601"]
        assert "pack" in findings[0].message

    def test_as601_mixed_callers_stay_quiet(self):
        # A helper with any sync caller is legitimately blocking code.
        src = (
            "import time\n\n"
            "def pack():\n    time.sleep(0.5)\n\n"
            "def batch_entry():\n    pack()\n\n"
            "async def handle():\n    pack()\n"
        )
        assert _ids(src, self._REL, select=["AS601"]) == []

    def test_as601_executor_submit_is_not_a_call(self):
        # Handing the helper to an executor is the FIX, not the bug.
        src = (
            "import time\nfrom concurrent.futures import ThreadPoolExecutor\n\n"
            "def pack():\n    time.sleep(0.5)\n\n"
            "async def handle(ex: ThreadPoolExecutor):\n"
            "    ex.submit(pack)\n"
        )
        assert _ids(src, self._REL, select=["AS601"]) == []

    def test_as601_thread_join_in_async_def(self):
        src = (
            "import threading\n\n"
            "async def handle():\n"
            "    t = threading.Thread(target=print)\n"
            "    t.start()\n"
            "    t.join()\n"
        )
        findings = lint.check_source(src, self._REL, select=["AS601"])
        assert [f.rule_id for f in findings] == ["AS601"]

    def test_as602_imported_coroutine_dropped(self):
        conn = "async def send_reply(frame):\n    return frame\n"
        src = (
            f"from {PKG}.serve.conn import send_reply\n\n"
            "async def handle(frame):\n    send_reply(frame)\n"
        )
        findings = lint.check_source(
            src,
            self._REL,
            project={f"{PKG}/serve/conn.py": conn},
            select=["AS602"],
        )
        assert [f.rule_id for f in findings] == ["AS602"]

    def test_as602_task_wrapped_coroutine_is_quiet(self):
        conn = "async def send_reply(frame):\n    return frame\n"
        src = (
            f"import asyncio\nfrom {PKG}.serve.conn import send_reply\n\n"
            "async def handle(frame):\n"
            "    asyncio.create_task(send_reply(frame))\n"
        )
        assert _ids(
            src,
            self._REL,
            project={f"{PKG}/serve/conn.py": conn},
            select=["AS602"],
        ) == []

    def test_as602_self_method_dropped(self):
        src = (
            "class Conn:\n"
            "    async def _send(self):\n        return 1\n"
            "    async def handle(self):\n        self._send()\n"
        )
        findings = lint.check_source(src, self._REL, select=["AS602"])
        assert [f.rule_id for f in findings] == ["AS602"]

    def test_as603_attr_lock_across_await(self):
        src = (
            "import asyncio\nimport threading\n\n"
            "class Conn:\n"
            "    def __init__(self):\n"
            "        self._wl = threading.Lock()\n"
            "    async def write(self, b):\n"
            "        with self._wl:\n"
            "            await asyncio.sleep(0)\n"
        )
        findings = lint.check_source(src, self._REL, select=["AS603"])
        assert [f.rule_id for f in findings] == ["AS603"]

    def test_as603_lock_without_await_is_quiet(self):
        src = (
            "import threading\n\n"
            "class Conn:\n"
            "    def __init__(self):\n"
            "        self._wl = threading.Lock()\n"
            "    async def write(self, b):\n"
            "        with self._wl:\n"
            "            return b\n"
        )
        assert _ids(src, self._REL, select=["AS603"]) == []

    def test_scope_excludes_non_async_tier(self):
        # The same blocking shape in ops/ is not this family's business.
        src = "import time\n\nasync def handle():\n    time.sleep(1)\n"
        assert _ids(src, f"{PKG}/ops/case.py", select=["AS601"]) == []


class TestNewRulesDocumented:
    def test_every_new_id_in_docs(self):
        docs = (
            pathlib.Path(__file__).resolve().parents[1]
            / "docs" / "static-analysis.md"
        ).read_text()
        for rule_id in ("JX110", "AS601", "AS602", "AS603"):
            assert rule_id in docs, f"{rule_id} missing from the catalog"


class TestLintCache:
    """The mtime+size sidecar: warm runs replay byte-identically and
    measurably faster; any relevant change invalidates precisely."""

    def _tree(self, tmp_path, n=24):
        for i in range(n):
            (tmp_path / f"m{i:02d}.py").write_text(
                "import jax\n\n"
                f"def helper_{i}(x):\n    return x + {i}\n\n"
                "@jax.jit\n"
                f"def entry_{i}(x):\n    return helper_{i}(x)\n"
            )
        # One seeded finding so "byte-identical" compares real output.
        (tmp_path / "dirty.py").write_text("x = f'const'\n")

    def test_warm_run_is_faster_and_byte_identical(self, tmp_path):
        import time as _time

        self._tree(tmp_path)
        sidecar = tmp_path / "cache.json"

        t0 = _time.perf_counter()
        n_cold, cold = lint.run(["."], root=tmp_path, cache=sidecar)
        t_cold = _time.perf_counter() - t0

        warm_cache = lint.LintCache(sidecar)
        t0 = _time.perf_counter()
        n_warm, warm = lint.run(["."], root=tmp_path, cache=warm_cache)
        t_warm = _time.perf_counter() - t0

        assert n_warm == n_cold == 25
        assert [f.render() for f in warm] == [f.render() for f in cold]
        assert warm_cache.hits == 25 and warm_cache.misses == 0
        # "Measurably faster": the warm pass is stat+JSON only — even on
        # a loaded box it beats re-parsing 25 files by a wide margin.
        assert t_warm < t_cold / 2, (t_warm, t_cold)

    def test_touched_file_misses_and_updates(self, tmp_path):
        self._tree(tmp_path)
        sidecar = tmp_path / "cache.json"
        lint.run(["."], root=tmp_path, cache=sidecar)

        target = tmp_path / "m00.py"
        target.write_text(target.read_text() + "y = f'const'\n")
        c = lint.LintCache(sidecar)
        _, findings = lint.run(["."], root=tmp_path, cache=c)
        assert c.misses == 1 and c.hits == 24
        assert any(
            f.rule_id == "F541" and f.path.endswith("m00.py")
            for f in findings
        )

    def test_project_findings_keyed_on_gate_digest(self, tmp_path):
        # The correctness property that makes per-file caching safe for
        # whole-program rules: editing the WRAP file must resurface the
        # JX110 finding on the UNCHANGED helper file.
        pkg_dir = tmp_path / PKG / "ops"
        pkg_dir.mkdir(parents=True)
        par_dir = tmp_path / PKG / "parallel"
        par_dir.mkdir(parents=True)
        helper = pkg_dir / "helper.py"
        helper.write_text(
            "import numpy as np\n\ndef helper(x):\n    return np.asarray(x)\n"
        )
        wrap = par_dir / "wrap.py"
        wrap.write_text(
            f"from {PKG}.ops.helper import helper\n\nout = helper\n"
        )
        sidecar = tmp_path / "cache.json"

        _, before = lint.run(["."], root=tmp_path, cache=sidecar)
        assert not any(f.rule_id == "JX110" for f in before)

        wrap.write_text(
            f"import jax\nfrom {PKG}.ops.helper import helper\n\n"
            "out = jax.jit(helper)\n"
        )
        c = lint.LintCache(sidecar)
        _, after = lint.run(["."], root=tmp_path, cache=c)
        jx = [f for f in after if f.rule_id == "JX110"]
        assert len(jx) == 1 and jx[0].path.endswith("helper.py")
        # …while the helper's per-file entry still served from cache.
        assert c.hits >= 1

    def test_select_change_invalidates(self, tmp_path):
        self._tree(tmp_path)
        sidecar = tmp_path / "cache.json"
        lint.run(["."], root=tmp_path, cache=sidecar, select=["F541"])
        c = lint.LintCache(sidecar)
        _, findings = lint.run(["."], root=tmp_path, cache=c)
        # Different select → different header → no stale replay.
        assert c.hits == 0
        assert any(f.rule_id == "F541" for f in findings)
