"""Market orchestration, store queries, and cross-market aggregation."""

import pytest

from bayesian_consensus_engine_tpu.models import (
    CrossMarketAggregator,
    Market,
    MarketId,
    MarketStatus,
    MarketStore,
    SourcePerformance,
)
from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore


class TestMarketId:
    def test_str(self):
        assert str(MarketId("crypto-btc-1")) == "crypto-btc-1"

    def test_repr(self):
        assert repr(MarketId("x")) == "MarketId('x')"

    def test_empty_rejected(self):
        with pytest.raises(ValueError, match="cannot be empty"):
            MarketId("  ")

    def test_category(self):
        assert MarketId("crypto:btc:price").category == "crypto"
        assert MarketId("simple-id").category is None

    def test_parts(self):
        assert MarketId("crypto:btc:price").parts == ["crypto", "btc", "price"]

    def test_matches(self):
        mid = MarketId("crypto:btc:1")
        assert mid.matches("crypto:btc:1")
        assert mid.matches("crypto:*:1")
        assert mid.matches("crypto:*")
        assert mid.matches("*")
        assert not mid.matches("sports:*")
        assert not mid.matches("crypto:btc:2")

    def test_frozen_hashable(self):
        assert {MarketId("a"): 1}[MarketId("a")] == 1


class TestMarket:
    def test_new_market_open_and_empty(self):
        market = Market(id=MarketId("m"))
        assert market.status == MarketStatus.OPEN
        assert market.signals == []
        assert market.created_at != ""

    def test_add_signal(self):
        market = Market(id=MarketId("m"))
        market.add_signal({"sourceId": "a", "probability": 0.7})
        assert len(market.signals) == 1

    def test_add_signal_rejected_when_resolved(self):
        market = Market(id=MarketId("m"))
        market.resolve(True)
        with pytest.raises(ValueError, match="Cannot add signal"):
            market.add_signal({"sourceId": "a", "probability": 0.7})

    def test_empty_consensus_reduced_shape(self):
        """Quirk #8: empty market yields a 4-key doc, not core's empty shape."""
        result = Market(id=MarketId("m")).compute_consensus()
        assert result == {
            "schemaVersion": "1.0.0",
            "consensus": None,
            "confidence": 0.0,
            "marketId": "m",
        }

    def test_consensus_stamped_and_cached(self):
        market = Market(id=MarketId("m"))
        market.add_signal({"sourceId": "a", "probability": 0.7})
        market.add_signal({"sourceId": "b", "probability": 0.8})
        result = market.compute_consensus()
        assert result["consensus"] == pytest.approx(0.75)
        assert result["marketId"] == "m"
        assert market.consensus_result is result

    def test_resolve(self):
        market = Market(id=MarketId("m"))
        market.resolve(True)
        assert market.status == MarketStatus.RESOLVED
        assert market.outcome is True
        assert market.resolved_at is not None

    def test_closed_status_exists(self):
        # Quirk #14: CLOSED is defined; nothing transitions to it automatically.
        assert MarketStatus.CLOSED.value == "closed"


class TestMarketStore:
    def test_create_and_get(self):
        store = MarketStore()
        market = store.create_market(MarketId("m"))
        assert store.get_market(MarketId("m")) is market

    def test_duplicate_create_rejected(self):
        store = MarketStore()
        store.create_market(MarketId("m"))
        with pytest.raises(ValueError, match="already exists"):
            store.create_market(MarketId("m"))

    def test_get_or_create(self):
        store = MarketStore()
        m1 = store.get_or_create(MarketId("m"))
        assert m1.status == MarketStatus.OPEN
        assert store.get_or_create(MarketId("m")) is m1

    def test_add_signal_creates_market(self):
        store = MarketStore()
        store.add_signal(MarketId("m"), {"sourceId": "a", "probability": 0.5})
        assert len(store.get_market(MarketId("m")).signals) == 1

    def test_list_by_status(self):
        store = MarketStore()
        store.create_market(MarketId("open-1"))
        store.create_market(MarketId("resolved-1")).resolve(True)
        open_markets = store.list_markets(status=MarketStatus.OPEN)
        assert [m.id.value for m in open_markets] == ["open-1"]

    def test_list_by_pattern(self):
        store = MarketStore()
        for mid in ("crypto:a", "crypto:b", "sports:a"):
            store.create_market(MarketId(mid))
        assert len(store.list_markets(pattern="crypto:*")) == 2

    def test_compute_all_consensus_without_store(self):
        store = MarketStore()
        store.add_signal(MarketId("m1"), {"sourceId": "a", "probability": 0.6})
        store.add_signal(MarketId("m2"), {"sourceId": "b", "probability": 0.8})
        results = store.compute_all_consensus()
        assert results["m1"]["consensus"] == pytest.approx(0.6)
        assert results["m2"]["consensus"] == pytest.approx(0.8)

    def test_compute_all_consensus_with_reliability(self):
        markets = MarketStore()
        markets.add_signal(MarketId("m"), {"sourceId": "good", "probability": 1.0})
        markets.add_signal(MarketId("m"), {"sourceId": "bad", "probability": 0.0})
        with SQLiteReliabilityStore(":memory:") as rel:
            for _ in range(5):
                rel.update_reliability("good", "m", outcome_correct=True)
                rel.update_reliability("bad", "m", outcome_correct=False)
            results = markets.compute_all_consensus(rel)
        # good ≈ 1.0 reliability, bad ≈ 0.0 → consensus pulled toward 1.0
        assert results["m"]["consensus"] > 0.9

    def test_skips_resolved_markets(self):
        store = MarketStore()
        store.add_signal(MarketId("m1"), {"sourceId": "a", "probability": 0.6})
        store.get_market(MarketId("m1")).resolve(True)
        assert store.compute_all_consensus() == {}

    def test_unknown_backend_rejected(self):
        # A typo'd backend must raise, not silently route to the array path.
        store = MarketStore()
        store.add_signal(MarketId("m1"), {"sourceId": "a", "probability": 0.6})
        with pytest.raises(ValueError, match="unknown backend"):
            store.compute_all_consensus(backend="pyton")


def _resolved_store() -> MarketStore:
    """agent-a right twice; agent-b right once, wrong once."""
    store = MarketStore()
    m1 = store.get_or_create(MarketId("crypto:btc"))
    m1.add_signal({"sourceId": "agent-a", "probability": 0.8})
    m1.add_signal({"sourceId": "agent-b", "probability": 0.7})
    m1.compute_consensus()
    m1.resolve(True)

    m2 = store.get_or_create(MarketId("crypto:eth"))
    m2.add_signal({"sourceId": "agent-a", "probability": 0.9})
    m2.add_signal({"sourceId": "agent-b", "probability": 0.2})
    m2.compute_consensus()
    m2.resolve(True)
    return store


class TestCrossMarketAggregator:
    def test_summarize_sources(self):
        agg = CrossMarketAggregator(_resolved_store())
        perf = agg.summarize_sources()
        assert perf["agent-a"].correct_predictions == 2
        assert perf["agent-a"].accuracy == 1.0
        assert perf["agent-b"].correct_predictions == 1
        assert perf["agent-b"].wrong_predictions == 1
        assert perf["agent-b"].accuracy == 0.5

    def test_summarize_sources_pattern_filter(self):
        agg = CrossMarketAggregator(_resolved_store())
        perf = agg.summarize_sources(patterns=["crypto:btc"])
        assert perf["agent-a"].total_markets == 1

    def test_boundary_probability_counts_as_true(self):
        store = MarketStore()
        m = store.get_or_create(MarketId("m"))
        m.add_signal({"sourceId": "edge", "probability": 0.5})
        m.resolve(True)
        perf = CrossMarketAggregator(store).summarize_sources()
        assert perf["edge"].correct_predictions == 1  # 0.5 >= 0.5 → True

    def test_summarize_category(self):
        agg = CrossMarketAggregator(_resolved_store())
        summary = agg.summarize_category("crypto")
        assert summary["category"] == "crypto"
        assert summary["total_markets"] == 2
        assert summary["resolved"] == 2
        assert summary["open"] == 0

    def test_aggregate_weighted_average(self):
        agg = CrossMarketAggregator(_resolved_store())
        result = agg.aggregate_consensus(["crypto:*"])
        assert result["marketsIncluded"] == 2
        assert result["consensus"] is not None
        assert result["method"] == "weighted_average"

    def test_aggregate_median_upper(self):
        agg = CrossMarketAggregator(_resolved_store())
        result = agg.aggregate_consensus(["crypto:*"], method="median")
        # Two entries → upper median (index len//2 == 1)
        values = sorted(
            m.consensus_result["consensus"]
            for m in _resolved_store().list_markets()
        )
        assert result["consensus"] == pytest.approx(values[1])

    def test_aggregate_majority(self):
        agg = CrossMarketAggregator(_resolved_store())
        result = agg.aggregate_consensus(["crypto:*"], method="majority")
        assert result["method"] == "majority"
        assert 0.0 <= result["consensus"] <= 1.0

    def test_aggregate_unknown_method(self):
        agg = CrossMarketAggregator(_resolved_store())
        with pytest.raises(ValueError, match="Unknown aggregation method"):
            agg.aggregate_consensus(["*"], method="mode")

    def test_aggregate_no_matches(self):
        agg = CrossMarketAggregator(MarketStore())
        result = agg.aggregate_consensus(["nothing:*"])
        assert result["consensus"] is None
        assert result["marketsIncluded"] == 0

    def test_aggregate_markets_without_cached_consensus(self):
        store = MarketStore()
        store.create_market(MarketId("m"))  # no consensus computed
        result = CrossMarketAggregator(store).aggregate_consensus(["*"])
        assert result["consensus"] is None
        assert result["marketsIncluded"] == 1


class TestSourcePerformance:
    def test_accuracy(self):
        perf = SourcePerformance("a", 10, 7, 3, 0.7)
        assert perf.accuracy == 0.7

    def test_zero_judged_accuracy(self):
        assert SourcePerformance("a", 0, 0, 0, 0.5).accuracy == 0.0
