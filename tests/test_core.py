"""Validation contract + scalar consensus engine behaviour.

Covers the reference's core test surface (reference: tests/test_core.py) plus
additional engine-semantics cases the golden fixtures rely on: duplicate
averaging, sorted-source determinism, cold-start listing, zero-weight path.
"""

import pytest

from bayesian_consensus_engine_tpu.core import (
    SCHEMA_VERSION,
    ValidationError,
    compute_consensus,
    validate_input_payload,
)


def _valid_payload() -> dict:
    return {
        "schemaVersion": SCHEMA_VERSION,
        "marketId": "market-1",
        "signals": [
            {"sourceId": "agent-a", "probability": 0.6},
            {"sourceId": "agent-b", "probability": 0.4},
        ],
    }


class TestValidation:
    def test_accepts_valid_payload(self):
        validate_input_payload(_valid_payload())

    def test_missing_schema_version_message(self):
        payload = _valid_payload()
        del payload["schemaVersion"]
        with pytest.raises(ValidationError) as exc:
            validate_input_payload(payload)
        assert str(exc.value) == "schemaVersion is required"

    def test_schema_version_mismatch(self):
        payload = _valid_payload()
        payload["schemaVersion"] = "2.0.0"
        with pytest.raises(ValidationError) as exc:
            validate_input_payload(payload)
        assert "schemaVersion must be" in str(exc.value)

    def test_market_id_required_and_non_empty(self):
        payload = _valid_payload()
        payload["marketId"] = "   "
        with pytest.raises(ValidationError, match="marketId must be a non-empty string"):
            validate_input_payload(payload)
        del payload["marketId"]
        with pytest.raises(ValidationError, match="marketId is required"):
            validate_input_payload(payload)

    def test_signals_must_be_array(self):
        payload = _valid_payload()
        payload["signals"] = {"sourceId": "a"}
        with pytest.raises(ValidationError, match="signals must be an array"):
            validate_input_payload(payload)

    def test_signal_must_be_object(self):
        payload = _valid_payload()
        payload["signals"] = ["not-a-dict"]
        with pytest.raises(ValidationError, match=r"signals\[0\] must be an object"):
            validate_input_payload(payload)

    def test_source_id_non_empty(self):
        payload = _valid_payload()
        payload["signals"][1]["sourceId"] = ""
        with pytest.raises(ValidationError, match=r"signals\[1\].sourceId must be a non-empty string"):
            validate_input_payload(payload)

    def test_probability_out_of_range(self):
        payload = _valid_payload()
        payload["signals"][0]["probability"] = 1.2
        with pytest.raises(ValidationError) as exc:
            validate_input_payload(payload)
        assert "must be between 0 and 1" in str(exc.value)

    def test_probability_must_be_number(self):
        payload = _valid_payload()
        payload["signals"][0]["probability"] = "0.5"
        with pytest.raises(ValidationError, match=r"signals\[0\].probability must be a number"):
            validate_input_payload(payload)

    def test_validation_error_is_value_error(self):
        assert issubclass(ValidationError, ValueError)


class TestEmptySignals:
    def test_empty_shape(self):
        result = compute_consensus([])
        assert result == {
            "schemaVersion": SCHEMA_VERSION,
            "consensus": None,
            "confidence": 0.0,
            "sourceWeights": [],
            "normalization": {"totalWeight": 0.0, "sourceCount": 0},
            "diagnostics": {"status": "no_signals", "sources": 0},
        }

    def test_empty_result_is_fresh_per_call(self):
        a = compute_consensus([])
        a["diagnostics"]["dryRun"] = True
        a["sourceWeights"].append({"x": 1})
        b = compute_consensus([])
        assert "dryRun" not in b["diagnostics"]
        assert b["sourceWeights"] == []


class TestConsensusMath:
    def test_cold_start_equal_weights(self):
        result = compute_consensus(
            [
                {"sourceId": "a", "probability": 0.6},
                {"sourceId": "b", "probability": 0.8},
            ]
        )
        assert result["consensus"] == pytest.approx(0.7)
        assert result["confidence"] == pytest.approx(0.25)
        assert result["normalization"]["totalWeight"] == pytest.approx(1.0)
        assert result["diagnostics"]["coldStartSources"] == ["a", "b"]

    def test_reliability_weighting(self):
        result = compute_consensus(
            [
                {"sourceId": "good", "probability": 1.0},
                {"sourceId": "bad", "probability": 0.0},
            ],
            {
                "good": {"reliability": 0.9, "confidence": 0.8},
                "bad": {"reliability": 0.1, "confidence": 0.2},
            },
        )
        assert result["consensus"] == pytest.approx(0.9)
        assert result["confidence"] == pytest.approx((0.8 * 0.9 + 0.2 * 0.1) / 1.0)
        assert result["diagnostics"]["coldStartSources"] == []

    def test_duplicate_signals_averaged_per_source(self):
        result = compute_consensus(
            [
                {"sourceId": "a", "probability": 0.2},
                {"sourceId": "a", "probability": 0.4},
                {"sourceId": "b", "probability": 0.9},
            ]
        )
        # a's signals average to 0.3 before weighting; equal weights → 0.6
        assert result["consensus"] == pytest.approx(0.6)
        assert result["diagnostics"]["sources"] == 3
        assert result["diagnostics"]["uniqueSources"] == 2

    def test_source_weights_sorted_by_id(self):
        result = compute_consensus(
            [
                {"sourceId": "zeta", "probability": 0.5},
                {"sourceId": "alpha", "probability": 0.5},
                {"sourceId": "mid", "probability": 0.5},
            ]
        )
        ids = [w["sourceId"] for w in result["sourceWeights"]]
        assert ids == ["alpha", "mid", "zeta"]

    def test_zero_total_weight_yields_null_consensus(self):
        result = compute_consensus(
            [{"sourceId": "a", "probability": 0.7}],
            {"a": {"reliability": 0.0, "confidence": 0.5}},
        )
        assert result["consensus"] is None
        assert result["confidence"] == 0.0
        assert result["sourceWeights"][0]["normalizedWeight"] == 0.0

    def test_partial_reliability_entry_fills_defaults(self):
        # Present-but-partial entries use defaults for missing keys yet are
        # NOT cold-start (reference semantics: membership test on the dict,
        # core.py:167-170).
        result = compute_consensus(
            [{"sourceId": "a", "probability": 0.5}],
            {"a": {}},
        )
        assert result["sourceWeights"][0]["weight"] == 0.5
        assert result["diagnostics"]["coldStartSources"] == []

    def test_summation_semantics_match_builtin_sum(self):
        # Regression for a 1-ulp drift: the weighted reductions must use
        # builtin sum() (Neumaier-compensated on CPython >= 3.12), while
        # totalWeight accumulates naively — the exact mix the reference uses
        # (reference: core.py:116,120,135-144).
        import random

        rng = random.Random(7)
        sigs = [
            {"sourceId": f"s{i % 9}", "probability": rng.random()} for i in range(40)
        ]
        rel = {f"s{i}": {"reliability": rng.random(), "confidence": rng.random()}
               for i in range(9)}
        result = compute_consensus(sigs, rel)

        by_source: dict[str, list[float]] = {}
        for s in sigs:
            by_source.setdefault(s["sourceId"], []).append(s["probability"])
        ordered = sorted(by_source)
        total_weight = 0.0
        for sid in ordered:
            total_weight += rel[sid]["reliability"]
        expected = sum(
            (sum(by_source[sid]) / len(by_source[sid])) * rel[sid]["reliability"]
            for sid in ordered
        ) / total_weight
        assert result["consensus"] == expected  # exact, not approx

    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError, match="unknown backend"):
            compute_consensus([{"sourceId": "a", "probability": 0.5}], backend="cuda")
