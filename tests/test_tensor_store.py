"""Tensor-store specifics: batch ops, SQLite checkpoint round-trip, device tier.

(The shared record-API semantics battery runs in test_reliability.py against
both backends.)
"""

import random
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from bayesian_consensus_engine_tpu.state import (
    ReliabilityRecord,
    SQLiteReliabilityStore,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    DeviceReliabilityState,
    TensorReliabilityStore,
)
from bayesian_consensus_engine_tpu.utils.timeconv import iso_to_days


def _populated(n_sources=7, n_markets=5, seed=3) -> TensorReliabilityStore:
    rng = random.Random(seed)
    store = TensorReliabilityStore()
    for s in range(n_sources):
        for m in range(n_markets):
            if rng.random() < 0.6:
                for _ in range(rng.randint(1, 4)):
                    store.update_reliability(f"s{s}", f"m{m}", rng.random() < 0.5)
    return store


class TestBatchGet:
    def test_matches_scalar_reads(self):
        store = _populated()
        pairs = [(f"s{s}", f"m{m}") for s in range(8) for m in range(6)]  # incl. unknown
        rel, conf, exists = store.batch_get_reliability(pairs)
        for i, (sid, mid) in enumerate(pairs):
            record = store.get_reliability(sid, mid)
            assert rel[i] == record.reliability
            assert conf[i] == record.confidence
            assert exists[i] == (record.updated_at != "")

    def test_decayed_batch_matches_scalar_at_same_instant(self):
        store = TensorReliabilityStore()
        now = datetime(2026, 7, 1, tzinfo=timezone.utc)
        for age, sid in ((0, "fresh"), (30, "month"), (400, "ancient")):
            stamp = (now - timedelta(days=age)).isoformat()
            store.put_record(ReliabilityRecord(sid, "m", 0.8, 0.5, stamp))
        pairs = [("fresh", "m"), ("month", "m"), ("ancient", "m"), ("ghost", "m")]
        rel, _conf, exists = store.batch_get_reliability(
            pairs, apply_decay=True, now=iso_to_days(now.isoformat())
        )
        assert rel[0] == pytest.approx(0.8)            # no elapsed time
        assert rel[1] == pytest.approx(0.45)           # one half-life to floor
        assert rel[2] == pytest.approx(0.10, abs=1e-3) # pinned at floor
        assert rel[3] == 0.5 and not exists[3]         # cold start

    def test_batch_get_never_allocates(self):
        store = TensorReliabilityStore()
        store.batch_get_reliability([("ghost", "m")] * 3)
        assert store.list_sources() == []


class TestBatchUpdate:
    def test_matches_scalar_update_loop(self):
        rng = random.Random(11)
        scalar_store = TensorReliabilityStore()
        batch_store = TensorReliabilityStore()
        # Unique pairs per round (duplicates have documented last-wins semantics).
        pairs = [(f"s{i}", f"m{i % 3}") for i in range(20)]
        for _round in range(4):
            corrects = [rng.random() < 0.5 for _ in pairs]
            for (sid, mid), ok in zip(pairs, corrects):
                scalar_store.update_reliability(sid, mid, ok)
            batch_store.batch_update_reliability(pairs, corrects)
        for sid, mid in pairs:
            a = scalar_store.get_reliability(sid, mid)
            b = batch_store.get_reliability(sid, mid)
            assert a.reliability == b.reliability
            assert a.confidence == b.confidence

    def test_shared_timestamp_within_batch(self):
        store = TensorReliabilityStore()
        store.batch_update_reliability([("a", "m"), ("b", "m")], [True, False])
        records = store.list_sources()
        assert records[0].updated_at == records[1].updated_at != ""

    def test_scale_10k_pairs(self):
        store = TensorReliabilityStore()
        pairs = [(f"s{i}", f"m{i % 100}") for i in range(10_000)]
        store.batch_update_reliability(pairs, [True] * len(pairs))
        rel, _conf, exists = store.batch_get_reliability(pairs)
        assert exists.all()
        assert np.allclose(rel, 0.6)


class TestSQLiteRoundTrip:
    def test_flush_and_reload_identical(self, tmp_path):
        store = _populated()
        db = tmp_path / "ckpt.db"
        written = store.flush_to_sqlite(db)
        assert written == len(store.list_sources())
        reloaded = TensorReliabilityStore.from_sqlite(db)
        assert reloaded.list_sources() == store.list_sources()

    def test_checkpoint_readable_by_sqlite_backend(self, tmp_path):
        store = _populated()
        db = tmp_path / "ckpt.db"
        store.flush_to_sqlite(db)
        with SQLiteReliabilityStore(db) as sqlite_store:
            assert sqlite_store.list_sources() == store.list_sources()

    def test_sqlite_written_by_reference_semantics_loads(self, tmp_path):
        db = tmp_path / "ref.db"
        with SQLiteReliabilityStore(db) as sqlite_store:
            sqlite_store.update_reliability("a", "m", True)
            expected = sqlite_store.list_sources()
        tensor_store = TensorReliabilityStore.from_sqlite(db)
        assert tensor_store.list_sources() == expected


class TestDeviceTier:
    def test_device_state_round_trip_unchanged(self):
        store = _populated()
        before = store.list_sources()
        state, epoch0 = store.device_state()
        store.absorb(state, epoch0)
        assert store.list_sources() == before  # byte-identical sidecar preserved

    def test_device_state_values_match_host(self):
        store = _populated()
        state, _epoch0 = store.device_state()
        for i, (sid, mid) in enumerate(store._pairs.ids()):
            record = store.get_reliability(sid, mid)
            assert float(state.reliability[i]) == pytest.approx(
                record.reliability, rel=1e-6
            )
            assert bool(state.exists[i]) == (record.updated_at != "")

    def test_absorb_updated_rows_get_fresh_timestamps(self):
        import jax.numpy as jnp

        store = TensorReliabilityStore()
        store.update_reliability("a", "m", True)
        old_iso = store.get_reliability("a", "m").updated_at
        state, epoch0 = store.device_state()
        bumped = state._replace(
            reliability=jnp.full_like(state.reliability, 0.9),
            updated_days=state.updated_days + 1.0,
        )
        store.absorb(bumped, epoch0)
        record = store.get_reliability("a", "m")
        assert record.reliability == pytest.approx(0.9, rel=1e-6)
        assert record.updated_at != old_iso
        assert iso_to_days(record.updated_at) > iso_to_days(old_iso)

    def test_device_cache_invalidated_on_write(self):
        store = _populated()
        state1, _ = store.device_state()
        store.update_reliability("new-source", "new-market", True)
        state2, _ = store.device_state()
        assert len(state2.reliability) == len(state1.reliability) + 1


class TestCrossBackendEquivalence:
    def test_same_history_same_records_modulo_timestamps(self):
        rng = random.Random(42)
        sqlite_store = SQLiteReliabilityStore(":memory:")
        tensor_store = TensorReliabilityStore()
        for _ in range(120):
            sid, mid = f"s{rng.randint(0, 5)}", f"m{rng.randint(0, 3)}"
            ok = rng.random() < 0.5
            sqlite_store.update_reliability(sid, mid, ok)
            tensor_store.update_reliability(sid, mid, ok)
        a = sqlite_store.list_sources()
        b = tensor_store.list_sources()
        assert [(r.source_id, r.market_id, r.reliability, r.confidence) for r in a] == [
            (r.source_id, r.market_id, r.reliability, r.confidence) for r in b
        ]
        sqlite_store.close()


class TestIncrementalFlush:
    """Dirty-row checkpointing: flush cost scales with touched rows.

    Reference semantics: each update UPSERTs only the row it changed
    (reference: reliability.py:221-231); a full-store rewrite per checkpoint
    was the round-2 e2e bottleneck.
    """

    def _seeded(self, n=50):
        store = TensorReliabilityStore()
        store.batch_update_reliability(
            [(f"s{i}", f"m{i % 7}") for i in range(n)], [True] * n
        )
        return store

    def test_second_flush_writes_only_dirty_rows(self, tmp_path):
        db = tmp_path / "ckpt.db"
        store = self._seeded()
        assert store.flush_to_sqlite(db) == 50  # first flush: full
        store.update_reliability("s3", "m3", False)
        store.update_reliability("s9", "m2", True)
        assert store.flush_to_sqlite(db) == 2  # same target: dirty only
        # The file equals a full flush of the same state.
        reloaded = TensorReliabilityStore.from_sqlite(db)
        assert reloaded.list_sources() == store.list_sources()

    def test_new_target_falls_back_to_full(self, tmp_path):
        store = self._seeded()
        store.flush_to_sqlite(tmp_path / "a.db")
        store.update_reliability("s1", "m1", True)
        # Different file: auto mode must write the complete store.
        assert store.flush_to_sqlite(tmp_path / "b.db") == 50
        reloaded = TensorReliabilityStore.from_sqlite(tmp_path / "b.db")
        assert reloaded.list_sources() == store.list_sources()

    def test_forced_incremental_to_wrong_target_raises(self, tmp_path):
        store = self._seeded()
        store.flush_to_sqlite(tmp_path / "a.db")
        with pytest.raises(ValueError, match="incomplete checkpoint"):
            store.flush_to_sqlite(tmp_path / "b.db", incremental=True)

    def test_resume_from_sqlite_flushes_incrementally(self, tmp_path):
        """Load → settle-ish update → flush back: only the delta is written."""
        db = tmp_path / "ckpt.db"
        self._seeded().flush_to_sqlite(db)
        resumed = TensorReliabilityStore.from_sqlite(db)
        resumed.update_reliability("s11", "m4", True)
        assert resumed.flush_to_sqlite(db) == 1
        assert (
            TensorReliabilityStore.from_sqlite(db).list_sources()
            == resumed.list_sources()
        )

    def test_absorb_marks_only_changed_rows_dirty(self, tmp_path):
        db = tmp_path / "ckpt.db"
        store = self._seeded()
        store.flush_to_sqlite(db)
        state, epoch0 = store.device_state()
        # Mutate exactly one row on the "device"; absorb back.
        import numpy as np

        rel = np.asarray(state.reliability).copy()
        days = np.asarray(state.updated_days).copy()
        rel[7] = 0.123
        days[7] = days[7] + 1.0
        store.absorb(
            DeviceReliabilityState(
                rel, np.asarray(state.confidence), days, np.asarray(state.exists)
            ),
            epoch0,
        )
        assert store.flush_to_sqlite(db) == 1

    def test_deleted_target_falls_back_to_full(self, tmp_path):
        """A rotated/removed checkpoint file must get a full rewrite, not a
        silently-truncated delta."""
        db = tmp_path / "ckpt.db"
        store = self._seeded()
        store.flush_to_sqlite(db)
        db.unlink()
        store.update_reliability("s1", "m1", True)
        assert store.flush_to_sqlite(db) == 50  # full, despite same path
        reloaded = TensorReliabilityStore.from_sqlite(db)
        assert reloaded.list_sources() == store.list_sources()

    def test_retired_row_deleted_from_checkpoint(self, tmp_path):
        """A row whose device exists flag flipped False (absorb of a
        mutated device state — no kernel does it, but the API allows it)
        must be DELETED by the next incremental flush, not stranded."""
        db = tmp_path / "ckpt.db"
        store = self._seeded(10)
        store.flush_to_sqlite(db)
        state, epoch0 = store.device_state()
        exists = np.asarray(state.exists).copy()
        exists[4] = False
        store.absorb(
            DeviceReliabilityState(
                np.asarray(state.reliability),
                np.asarray(state.confidence),
                np.asarray(state.updated_days),
                exists,
            ),
            epoch0,
        )
        store.flush_to_sqlite(db)  # incremental
        reloaded = TensorReliabilityStore.from_sqlite(db)
        assert reloaded.list_sources() == store.list_sources()
        assert len(reloaded.list_sources()) == 9

    def test_memory_db_never_incremental(self):
        store = self._seeded()
        assert store.flush_to_sqlite(":memory:") == 50
        assert store.flush_to_sqlite(":memory:") == 50  # still full


class TestNativeFlushParity:
    """The C checkpoint writer (internmap.flush_sqlite over dlopen()ed
    libsqlite3) against the sqlite3-module path: identical records, identical
    key order, deterministic bytes. The native path is what flush_to_sqlite
    auto-selects when the C interner is built, so forcing the fallback pins
    the two implementations against each other."""

    def _randomized(self, n=400, seed=13):
        rng = random.Random(seed)
        store = TensorReliabilityStore()
        # Unicode + prefix-colliding ids probe the memcmp-order claim
        # (UTF-8 byte order == code-point order; NUL sorts below all).
        alphabet = ["a", "ab", "abc", "src-é", "src-éx", "zz", "ζeta"]
        for _ in range(n):
            sid = f"{rng.choice(alphabet)}{rng.randrange(40)}"
            mid = f"m{rng.choice(alphabet)}{rng.randrange(25)}"
            store.update_reliability(sid, mid, rng.random() < 0.5)
        return store

    def _force_python_flush(self, monkeypatch):
        from bayesian_consensus_engine_tpu.utils import interning

        monkeypatch.setattr(
            interning.NativePairInterner,
            "sqlite_writer_available",
            lambda self: False,
        )

    def test_native_matches_python_path(self, tmp_path, monkeypatch):
        store = self._randomized()
        native_db = tmp_path / "native.db"
        store.flush_to_sqlite(native_db)
        python_db = tmp_path / "python.db"
        self._force_python_flush(monkeypatch)
        store.flush_to_sqlite(python_db)

        native_records = TensorReliabilityStore.from_sqlite(native_db).list_sources()
        python_records = TensorReliabilityStore.from_sqlite(python_db).list_sources()
        assert native_records == python_records
        import sqlite3

        schemas = []
        for db in (native_db, python_db):
            with sqlite3.connect(db) as conn:
                # Key order inside the files matches (same physical row walk).
                walk = conn.execute(
                    "SELECT source_id, market_id FROM sources"
                ).fetchall()
                assert walk == sorted(walk)
                schemas.append(
                    conn.execute(
                        "SELECT type, name, sql FROM sqlite_master ORDER BY name"
                    ).fetchall()
                )
        # The C writer's embedded schema must track sqlite_store.py's: a
        # column/default/constraint drift between the duplicated SQL
        # literals shows up here as differing CREATE statements.
        def normalize(rows):
            # sql is None for the PK's auto-index row.
            return [(t, n, " ".join(s.split()) if s else s) for t, n, s in rows]

        assert normalize(schemas[0]) == normalize(schemas[1])

    def test_incremental_native_matches_python(self, tmp_path, monkeypatch):
        def run(tmp, forced):
            store = self._randomized(seed=29)
            db = tmp / ("py.db" if forced else "nat.db")
            store.flush_to_sqlite(db)
            store.update_reliability("aa", "m1", True)
            store.update_reliability("zz9", "mab3", False)
            wrote = store.flush_to_sqlite(db)
            return wrote, TensorReliabilityStore.from_sqlite(db).list_sources()

        n_wrote, n_records = run(tmp_path, forced=False)
        self._force_python_flush(monkeypatch)
        p_wrote, p_records = run(tmp_path, forced=True)
        assert n_wrote == p_wrote == 2
        assert [
            (r.source_id, r.market_id, r.reliability, r.confidence)
            for r in n_records
        ] == [
            (r.source_id, r.market_id, r.reliability, r.confidence)
            for r in p_records
        ]

    def test_repeated_full_flush_bytes_identical(self, tmp_path):
        store = self._randomized(seed=7)
        a, b = tmp_path / "a.db", tmp_path / "b.db"
        store.flush_to_sqlite(a)
        # Reset flush bookkeeping so the second flush is full again.
        store._last_flush_path = None
        store._dirty[: len(store)] = True
        store.flush_to_sqlite(b)
        assert a.read_bytes() == b.read_bytes()


class TestBatchFailureConsistency:
    def test_mid_batch_intern_failure_keeps_sidecars_synced(self):
        """A NUL id mid-batch must not desync interner rows from sidecars."""
        store = TensorReliabilityStore()
        try:
            store.batch_update_reliability(
                [("a", "m"), ("b\0bad", "m")], [True, True]
            )
        except ValueError:
            pass  # native interner rejects NUL ids mid-batch
        # Rows interned before the failure must be fully usable afterwards.
        record = store.update_reliability("a", "m", True)
        assert record.updated_at != ""
        assert store.get_reliability("a", "m").reliability == record.reliability
        assert len(store.list_sources()) == 1


class TestPendingOverlaps:
    """The store-level contract behind the streamed service's
    skip-the-sync fast path: ``pending_overlaps(rows)`` says whether
    deferred settlements must merge before *rows* can be read raw, and
    ``host_rows(..., sync=False)`` / ``epoch_origin(sync=False)`` read
    without resolving them."""

    def _store_with_recipe(self):
        import jax.numpy as jnp

        store = _populated()
        touched = np.asarray([0, 2, 4], dtype=np.int64)
        before = store._rel[touched].copy()
        store.defer_settle_recipe(
            touched,
            jnp.asarray([0.9, 0.8, 0.7], dtype=jnp.float32),
            store.epoch_origin(),
            np.float32(5.0),
        )
        return store, touched, before

    def test_no_deferral_means_no_overlap(self):
        store = _populated()
        assert not store.pending_overlaps(np.asarray([0, 1, 2]))

    def test_recipe_rows_overlap_and_others_do_not(self):
        store, touched, _ = self._store_with_recipe()
        assert store.pending_overlaps(np.asarray([2]))
        assert store.pending_overlaps(np.asarray([7, 4]))
        assert not store.pending_overlaps(np.asarray([1, 3, 5]))
        # Still deferred: the query itself must not resolve anything.
        assert store._pending_sync

    def test_flat_pending_state_always_overlaps(self):
        store = _populated()
        state, epoch0 = store.take_device_state(None)
        store.defer_absorb(state, epoch0)
        assert store.pending_overlaps(np.asarray([0]))

    def test_unsynced_host_rows_exact_for_disjoint_stale_for_touched(self):
        store, touched, before = self._store_with_recipe()
        exact = store._rel[np.asarray([1, 3])].copy()
        rel, _conf, _days, _exists = store.host_rows(
            np.asarray([1, 3]), sync=False
        )
        np.testing.assert_array_equal(rel, exact)
        assert store._pending_sync  # unresolved
        # Touched rows read STALE without sync...
        stale, *_ = store.host_rows(touched, sync=False)
        np.testing.assert_array_equal(stale, before)
        # ...and exact with the default (which resolves the recipe).
        synced, *_ = store.host_rows(touched)
        np.testing.assert_allclose(synced, [0.9, 0.8, 0.7], atol=1e-6)
        assert not store._pending_sync

    def test_unsynced_epoch_origin_lower_bounds_caller_rows(self):
        store, touched, _ = self._store_with_recipe()
        unsynced = store.epoch_origin(sync=False)
        days = store._days[: len(store)]
        live = days[days > 0]
        assert unsynced <= live.min() - 1.0 + 1e-9
        assert store._pending_sync  # still deferred

    def test_lazy_flush_excludes_deferred_rows_and_keeps_them_dirty(
        self, tmp_path
    ):
        """resolve_pending=False writes only APPLIED truth: rows behind a
        deferred recipe are excluded whole (their eagerly-replayed
        confidences must not pair with stale reliabilities) and stay
        dirty so the next resolving flush covers them."""
        store, touched, _before = self._store_with_recipe()
        db = tmp_path / "lazy.db"
        handle = store.flush_to_sqlite_async(db, resolve_pending=False)
        written = handle.result()
        used = len(store)
        assert written == used - len(touched)
        assert store._pending_sync  # still deferred
        assert store._dirty[touched].all()  # kept for the next flush
        import sqlite3

        with sqlite3.connect(db) as conn:
            in_file = {
                (sid, mid) for sid, mid in conn.execute(
                    "SELECT source_id, market_id FROM sources"
                )
            }
        deferred_ids = {store._pairs.id_of(int(r)) for r in touched}
        assert not (in_file & deferred_ids)
        # The resolving flush completes the file.
        store.flush_to_sqlite(db)
        with sqlite3.connect(db) as conn:
            count = conn.execute("SELECT COUNT(*) FROM sources").fetchone()[0]
        assert count == used
        assert not store._pending_sync


class TestDeltaSync:
    """The round-6 delta device→host sync: with a flat pending state and
    recipe-bounded dirty set, _sync_pending fetches ONE union-of-touched
    take and merges through the same row merge as a full sync — the host
    arrays (values, stamps, ISO strings, and BOTH dirty ledgers) must be
    byte-identical to the full-column sync, and a journal epoch built
    after either sync must be byte-identical too."""

    @staticmethod
    def _chained_settles(store):
        """Two chained settles: duplicate signals in batch 1, new
        interning (plus a row overlap) in batch 2 — the union-take path
        with accumulated distinct-plan recipes."""
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan,
            settle,
        )

        batch1 = [
            ("m0", [
                {"sourceId": "a", "probability": 0.9},
                {"sourceId": "a", "probability": 0.4},  # duplicate signal
                {"sourceId": "b", "probability": 0.3},
            ]),
            ("m1", [{"sourceId": "a", "probability": 0.7}]),
        ]
        plan1 = build_settlement_plan(store, batch1)
        settle(store, plan1, [True, False], steps=2, now=21_000.0)
        batch2 = [
            ("m2", [
                {"sourceId": "c", "probability": 0.6},  # new interning
                {"sourceId": "a", "probability": 0.2},
            ]),
            ("m0", [{"sourceId": "b", "probability": 0.8}]),  # overlap
        ]
        plan2 = build_settlement_plan(store, batch2)
        settle(store, plan2, [True, True], steps=1, now=21_001.0)

    @staticmethod
    def _host_state(store):
        used = len(store)
        return (
            store._rel[:used].tobytes(),
            store._conf[:used].tobytes(),
            store._days[:used].tobytes(),
            store._exists[:used].tobytes(),
            list(store._iso[:used]),
            store._dirty[:used].tobytes(),
            store._journal_dirty[:used].tobytes(),
        )

    def _twin_stores(self):
        delta, full = TensorReliabilityStore(), TensorReliabilityStore()
        self._chained_settles(delta)
        self._chained_settles(full)
        assert delta._pending is not None and delta._pending_sync
        # Force the full-column sync on the twin: dropping the recipes
        # leaves only the recipe-less flat-pending path.
        full._pending_sync = None
        delta.sync()
        full.sync()
        return delta, full

    def test_delta_sync_host_arrays_byte_identical_to_full(self):
        delta, full = self._twin_stores()
        assert len(delta) == len(full)
        for mine, theirs in zip(
            self._host_state(delta), self._host_state(full)
        ):
            assert mine == theirs

    def test_journal_epoch_after_delta_sync_byte_identical(
        self, tmp_path, monkeypatch
    ):
        from bayesian_consensus_engine_tpu.state import journal as jmod
        from bayesian_consensus_engine_tpu.state.journal import (
            JournalWriter,
        )

        delta, full = self._twin_stores()
        monkeypatch.setattr(jmod.time, "time", lambda: 1_234.5)
        with JournalWriter(tmp_path / "delta.jrnl") as writer:
            delta.flush_to_journal(writer, tag=7)
        with JournalWriter(tmp_path / "full.jrnl") as writer:
            full.flush_to_journal(writer, tag=7)
        assert (
            (tmp_path / "delta.jrnl").read_bytes()
            == (tmp_path / "full.jrnl").read_bytes()
        )

    def test_delta_sync_counts_union_rows(self):
        from bayesian_consensus_engine_tpu import obs

        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            store = TensorReliabilityStore()
            self._chained_settles(store)
            store.sync()
        finally:
            obs.set_metrics_registry(previous)
        # Union of the two settles' touched rows: 3 + 3 with one overlap.
        assert registry.export()["counters"]["store.delta_sync_rows"] == 5


class TestInterchangeFingerprint:
    """Incremental interchange exports verify the target still carries
    OUR last export (content fingerprint) before upserting a delta; a
    foreign write or rotation falls back to a full rewrite."""

    def _seeded(self, n=30):
        store = TensorReliabilityStore()
        store.batch_update_reliability(
            [(f"s{i}", f"m{i % 7}") for i in range(n)], [True] * n
        )
        return store

    def test_untouched_target_stays_incremental(self, tmp_path):
        db = tmp_path / "x.db"
        store = self._seeded()
        assert store.flush_to_sqlite(db) == 30
        store.update_reliability("s3", "m3", False)
        assert store.flush_to_sqlite(db) == 1

    def test_foreign_write_falls_back_to_full(self, tmp_path):
        import sqlite3
        import time

        db = tmp_path / "x.db"
        store = self._seeded()
        store.flush_to_sqlite(db)
        time.sleep(0.01)  # ensure the foreign mtime is distinguishable
        with sqlite3.connect(db) as conn:
            conn.execute(
                "INSERT OR REPLACE INTO sources VALUES"
                " ('zz', 'zz', 0.1, 0.1, 'then')"
            )
        store.update_reliability("s3", "m3", False)
        # Auto mode: fingerprint mismatch → the complete store, not the
        # 1-row delta; forcing incremental refuses outright.
        with pytest.raises(ValueError, match="fingerprint"):
            store.flush_to_sqlite(db, incremental=True)
        assert store.flush_to_sqlite(db) == 30

    def test_rotated_target_falls_back_to_full(self, tmp_path):
        db = tmp_path / "x.db"
        store = self._seeded()
        store.flush_to_sqlite(db)
        other = self._seeded(n=5)
        other.flush_to_sqlite(tmp_path / "other.db")
        (tmp_path / "other.db").replace(db)  # rotation: same path, other file
        store.update_reliability("s3", "m3", False)
        assert store.flush_to_sqlite(db) == 30

    def test_async_flush_chain_keeps_fingerprint_current(self, tmp_path):
        db = tmp_path / "x.db"
        store = self._seeded()
        store.flush_to_sqlite_async(db).result()
        store.update_reliability("s3", "m3", False)
        # The async write recorded the post-write fingerprint: a clean
        # follow-up flush is still a delta.
        assert store.flush_to_sqlite(db) == 1

    def test_delta_export_db_equals_full_export(self, tmp_path):
        """The acceptance pin: an incremental re-export to the baseline
        file is ROW-FOR-ROW identical to a fresh full export (and to a
        second full export — dump comparison covers values and keys)."""
        import sqlite3

        def dump(path):
            with sqlite3.connect(path) as conn:
                return conn.execute(
                    "SELECT source_id, market_id, reliability, confidence,"
                    " updated_at FROM sources"
                    " ORDER BY source_id, market_id"
                ).fetchall()

        store = self._seeded()
        delta_db = tmp_path / "delta.db"
        store.flush_to_sqlite(delta_db)  # baseline: full export
        # Touch a subset (including a retired row) then delta-export.
        store.update_reliability("s1", "m1", True)
        store.update_reliability("s9", "m2", False)
        written = store.flush_to_sqlite(delta_db)
        assert 0 < written < 30  # genuinely a delta write
        full_db = tmp_path / "full.db"
        store.flush_to_sqlite(full_db)  # fresh full export of same state
        assert dump(delta_db) == dump(full_db)
