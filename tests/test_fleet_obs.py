"""Live telemetry plane (round 16): exporter, fleet merge, burn-rate
health.

The contracts under pin (ISSUE 14 acceptance):

* **Deterministic wire bytes** — ``/metrics`` is rendered from
  ``MetricsRegistry.export()`` with sorted names/labels/buckets: two
  registries that saw the same observations produce identical BYTES
  regardless of registration order (DT203 on the wire).
* **Write-only exporter** — a server scraping mid-settle moves no
  settlement byte: stream results, SQLite checkpoint bytes, and journal
  heads are identical with the exporter running vs absent, and the
  serve path's journal epochs (sans wall clock) + SQLite bytes are too.
* **Fleet-merge determinism** — two observers folding the same snapshot
  set (any order) produce identical fleet-view and ``/metrics`` bytes;
  expected-but-missing hosts are EXPLICIT (``hosts_absent``), higher
  epochs supersede, same-epoch conflicts and bucket-layout mismatches
  refuse.
* **Burn-rate health** — the verdict is a pure function of the
  classified outcome sequence (fixed windows, fixed thresholds);
  burning requires fast AND slow windows over threshold; ``degraded``
  outranks ``burning``; recovery returns to ``healthy``.
* **Serve wiring** — ``ConsensusService(health=)`` feeds every
  SLO-classified outcome to the monitor, ``start_telemetry`` serves the
  live plane, and ``AdmissionConfig(shed_when_burning=True)`` turns the
  burning verdict into an admission decision (off by default — the
  admission sequence is unchanged).
"""

import asyncio
import hashlib
import json
import os
import struct
import tempfile
import threading
import urllib.error
import urllib.request  # noqa: F811

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from bayesian_consensus_engine_tpu import obs
from bayesian_consensus_engine_tpu.obs import export as obs_export
from bayesian_consensus_engine_tpu.obs import fleet as obs_fleet
from bayesian_consensus_engine_tpu.obs import health as obs_health
from bayesian_consensus_engine_tpu.serve import (
    AdmissionConfig,
    ConsensusService,
    Overloaded,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 22_300.0


def _get(url, timeout=5.0):
    """GET → (status, parsed-JSON-or-text); 503 bodies are answers."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            raw = r.read()
            status = r.status
    except urllib.error.HTTPError as exc:
        raw = exc.read()
        status = exc.code
    try:
        return status, json.loads(raw)
    except ValueError:
        return status, raw.decode()


def _registry_ab(order):
    """One registry fed the same observations in the given site order."""
    registry = obs.MetricsRegistry()
    sites = {
        "c": lambda: registry.counter("serve.requests").inc(3),
        "g": lambda: registry.gauge("stream.intern_wait_s").set(0.25),
        "h": lambda: registry.histogram(
            "serve.latency_total_s"
        ).observe(0.003),
    }
    for key in order:
        sites[key]()
    return registry


class TestPrometheusRender:
    def test_bytes_independent_of_registration_order(self):
        a = obs_export.render_prometheus(_registry_ab("cgh").export())
        b = obs_export.render_prometheus(_registry_ab("hgc").export())
        assert a == b
        assert a.encode() == b.encode()

    def test_counter_gauge_histogram_shapes(self):
        text = obs_export.render_prometheus(_registry_ab("cgh").export())
        lines = text.splitlines()
        assert "# TYPE bce_serve_requests counter" in lines
        assert "bce_serve_requests 3" in lines
        assert "bce_stream_intern_wait_s 0.25" in lines
        # Histogram: cumulative buckets, +Inf, _sum, _count.
        buckets = [
            line for line in lines
            if line.startswith("bce_serve_latency_total_s_bucket")
        ]
        assert buckets[-1] == (
            'bce_serve_latency_total_s_bucket{le="+Inf"} 1'
        )
        counts = [int(line.rsplit(" ", 1)[1]) for line in buckets]
        assert counts == sorted(counts)  # cumulative never decreases
        assert "bce_serve_latency_total_s_count 1" in lines
        assert any(
            line.startswith("bce_serve_latency_total_s_sum ")
            for line in lines
        )

    def test_names_sorted(self):
        text = obs_export.render_prometheus(_registry_ab("cgh").export())
        type_lines = [
            line.split()[2] for line in text.splitlines()
            if line.startswith("# TYPE")
        ]
        # Sorted within each metric kind (counters, then gauges, then
        # histograms) — the render contract the fleet fold relies on.
        assert type_lines == [
            "bce_serve_requests",
            "bce_stream_intern_wait_s",
            "bce_serve_latency_total_s",
        ]

    def test_empty_export_renders_empty(self):
        assert obs_export.render_prometheus(
            obs.MetricsRegistry().export()
        ) == ""


class TestTelemetryServer:
    def test_endpoints_and_scrape_accounting(self):
        registry = _registry_ab("cgh")
        with obs_export.TelemetryServer(
            registry=registry, host_id=7, epoch=3
        ) as server:
            status, text = _get(server.url + "/metrics")
            assert status == 200
            assert "bce_serve_requests 3" in text
            status, snap = _get(server.url + "/snapshot")
            assert status == 200
            assert snap["host_id"] == 7 and snap["epoch"] == 3
            assert snap["metrics"]["counters"]["serve.requests"] == 3
            status, payload = _get(server.url + "/healthz")
            assert status == 200
            assert payload == {
                "ok": True, "verdict": "healthy", "detail": None,
            }
            status, _ = _get(server.url + "/nope")
            assert status == 404
            # Scrapes self-account on the pinned layout.
            export = registry.export()
            assert export["counters"]["export.scrapes"] >= 3
            hist = export["histograms"]["export.scrape_latency_s"]
            assert tuple(hist["bounds"]) == (
                obs_export.SCRAPE_LATENCY_BOUNDS
            )
            assert hist["count"] >= 3

    def test_healthz_tracks_the_monitor(self):
        monitor = obs_health.HealthMonitor(
            objective_goodput=0.9,
            windows=(obs_health.BurnWindow(4, 16, 2.0),),
        )
        with obs_export.TelemetryServer(
            registry=obs.MetricsRegistry(), health=monitor
        ) as server:
            status, payload = _get(server.url + "/healthz")
            assert (status, payload["verdict"]) == (200, "healthy")
            for _ in range(16):
                monitor.record("violated")
            status, payload = _get(server.url + "/healthz")
            assert (status, payload["verdict"]) == (503, "burning")
            assert payload["ok"] is False
            assert payload["detail"]["windows"][0]["burning"] is True
            monitor.set_degraded("host 1 absent")
            status, payload = _get(server.url + "/healthz")
            assert (status, payload["verdict"]) == (503, "degraded")
            monitor.clear_degraded()
            for _ in range(16):
                monitor.record("met")
            status, payload = _get(server.url + "/healthz")
            assert (status, payload["verdict"]) == (200, "healthy")

    def test_set_epoch_moves_the_snapshot_tag(self):
        with obs_export.TelemetryServer(
            registry=obs.MetricsRegistry(), host_id=1, epoch=0
        ) as server:
            _, snap = _get(server.url + "/snapshot")
            assert snap["epoch"] == 0
            server.set_epoch(4)  # recovery adopted a degraded view
            _, snap = _get(server.url + "/snapshot")
            assert snap["epoch"] == 4

    def test_snapshot_carries_trace_ring_depths(self):
        tracer = obs.Tracer()
        tracer.batch_event(0, "batch")
        tracer.request_event(0, "enqueue")
        with obs_export.TelemetryServer(
            registry=obs.MetricsRegistry(), tracer=tracer
        ) as server:
            _, snap = _get(server.url + "/snapshot")
        assert snap["trace"]["enabled"] is True
        assert snap["trace"]["ring_depths"] == {"driver": 1, "service": 1}


class TestHealthMonitor:
    def _monitor(self, fast=4, slow=16, threshold=2.0, target=0.9):
        return obs_health.HealthMonitor(
            objective_goodput=target,
            windows=(obs_health.BurnWindow(fast, slow, threshold),),
        )

    def test_verdict_is_pure_function_of_outcome_sequence(self):
        trace = (
            ["met"] * 20 + ["violated"] * 16 + ["met"] * 16
        )
        runs = []
        for _ in range(2):
            monitor = self._monitor()
            verdicts = []
            for outcome in trace:
                monitor.record(outcome)
                verdicts.append(monitor.verdict()["verdict"])
            runs.append(verdicts)
        assert runs[0] == runs[1]
        assert "burning" in runs[0]          # the violation burst fires
        assert runs[0][-1] == "healthy"      # ...and the met tail clears

    def test_burning_requires_fast_and_slow(self):
        monitor = self._monitor(fast=4, slow=16, threshold=2.0)
        for _ in range(12):
            monitor.record("met")
        # 4 violations: fast window (4) is all-error (burn 10) but the
        # slow window holds 4/16 = burn 2.5 >= 2 — both over, burning.
        for _ in range(4):
            monitor.record("violated")
        assert monitor.burning is True
        # One met resets the fast window below threshold: not burning,
        # even though the slow window still carries the errors.
        for _ in range(4):
            monitor.record("met")
        assert monitor.burning is False
        state = monitor.verdict()["windows"][0]
        assert state["fast_burn"] < state["threshold"]
        assert state["slow_burn"] > 0

    def test_every_non_met_outcome_burns_budget(self):
        for outcome in ("violated", "shed", "rejected", "failed"):
            monitor = self._monitor(fast=2, slow=4, threshold=1.0)
            for _ in range(4):
                monitor.record(outcome)
            assert monitor.burning is True, outcome

    def test_degraded_outranks_burning(self):
        monitor = self._monitor()
        for _ in range(16):
            monitor.record("violated")
        monitor.set_degraded("adopting band 1")
        verdict = monitor.verdict()
        assert verdict["verdict"] == "degraded"
        assert verdict["burning"] is True  # both facts visible
        monitor.clear_degraded()
        assert monitor.verdict()["verdict"] == "burning"

    def test_gauges_and_pinned_burn_histogram(self):
        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            monitor = self._monitor(fast=4, slow=16)
            for _ in range(8):
                monitor.record("violated")
        finally:
            obs.set_metrics_registry(previous)
        export = registry.export()
        assert export["gauges"]["health.burning"] == 1.0
        assert export["gauges"]["health.burn_rate_fast"] == (
            pytest.approx(10.0)
        )
        hist = export["histograms"]["health.burn_rate"]
        assert tuple(hist["bounds"]) == obs_health.BURN_RATE_BOUNDS
        assert hist["count"] > 0

    def test_validation(self):
        with pytest.raises(ValueError, match="objective_goodput"):
            obs_health.HealthMonitor(objective_goodput=1.0)
        with pytest.raises(ValueError, match="slow window"):
            obs_health.BurnWindow(8, 8, 1.0)
        with pytest.raises(ValueError, match="outcome"):
            self._monitor().record("mystery")


def _snap(host, epoch, counters=None, gauges=None, hist=None):
    registry = obs.MetricsRegistry()
    for name, n in (counters or {}).items():
        registry.counter(name).inc(n)
    for name, value in (gauges or {}).items():
        registry.gauge(name).set(value)
    for name, values in (hist or {}).items():
        h = registry.histogram(name, bounds=(0.01, 0.1, 1.0))
        for value in values:
            h.observe(value)
    return obs_fleet.snapshot_host(host, epoch, registry)


class TestFleetMerge:
    def test_any_fold_order_same_bytes(self):
        snaps = [
            _snap(2, 1, {"serve.requests": 5}, {"stream.intern_wait_s": 1.0},
                  {"lat": [0.05]}),
            _snap(0, 1, {"serve.requests": 7}, {"stream.intern_wait_s": 2.0},
                  {"lat": [0.5, 0.02]}),
            _snap(5, 1, {"serve.requests": 1}, {}, {"lat": [0.05]}),
        ]
        views = [
            obs_fleet.merge_fleet(order, expected_hosts=[0, 2, 5])
            for order in (snaps, list(reversed(snaps)),
                          [snaps[1], snaps[2], snaps[0]])
        ]
        as_json = {obs_fleet.fleet_to_json(v) for v in views}
        assert len(as_json) == 1
        rendered = {obs_fleet.render_fleet_prometheus(v) for v in views}
        assert len(rendered) == 1

    def test_counters_sum_gauges_stay_per_host(self):
        view = obs_fleet.merge_fleet(
            [
                _snap(0, 0, {"serve.requests": 5}, {"depth": 2.0}),
                _snap(1, 0, {"serve.requests": 7}, {"depth": 3.0}),
            ]
        )
        assert view["counters"]["serve.requests"] == 12
        assert view["gauges"]["depth"] == {"0": 2.0, "1": 3.0}
        text = obs_fleet.render_fleet_prometheus(view)
        assert 'bce_depth{host="0"} 2.0' in text
        assert 'bce_depth{host="1"} 3.0' in text
        assert "bce_serve_requests 12" in text

    def test_histograms_merge_by_bucket_sum(self):
        view = obs_fleet.merge_fleet(
            [
                _snap(0, 0, hist={"lat": [0.05, 0.5]}),
                _snap(1, 0, hist={"lat": [0.05]}),
            ]
        )
        assert view["histograms"]["lat"]["count"] == 3
        assert view["histograms"]["lat"]["counts"] == [0, 2, 1, 0]

    def test_histogram_layout_mismatch_refuses(self):
        a = _snap(0, 0, hist={"lat": [0.05]})
        registry = obs.MetricsRegistry()
        registry.histogram("lat", bounds=(1.0, 2.0)).observe(1.5)
        b = obs_fleet.snapshot_host(1, 0, registry)
        with pytest.raises(ValueError, match="layouts differ"):
            obs_fleet.merge_fleet([a, b])

    def test_absent_hosts_are_explicit(self):
        view = obs_fleet.merge_fleet(
            [_snap(0, 1), _snap(2, 1)], expected_hosts=[0, 1, 2, 3]
        )
        assert view["hosts_absent"] == [1, 3]
        text = obs_fleet.render_fleet_prometheus(view)
        assert "bce_fleet_hosts_absent 2" in text

    def test_higher_epoch_supersedes_same_epoch_conflict_refuses(self):
        stale = _snap(0, 0, {"serve.requests": 1})
        fresh = _snap(0, 2, {"serve.requests": 9})
        view = obs_fleet.merge_fleet([stale, fresh])
        assert view["counters"]["serve.requests"] == 9
        assert view["epoch"] == 2
        conflicting = _snap(0, 2, {"serve.requests": 10})
        with pytest.raises(ValueError, match="conflicting"):
            obs_fleet.merge_fleet([fresh, conflicting])
        # ...but an identical duplicate (the same scrape seen twice) is
        # not a conflict.
        assert obs_fleet.merge_fleet(
            [fresh, obs_fleet.snapshot_from_json(
                obs_fleet.snapshot_to_json(fresh)
            )]
        )["counters"]["serve.requests"] == 9

    def test_conflict_refusal_is_order_independent(self):
        # A conflict at a SUPERSEDED epoch still refuses, wherever the
        # superseding snapshot sits in the sequence — otherwise two
        # observers of the same set could disagree (one refuses, one
        # folds), which is exactly the split the refusal exists to stop.
        a = _snap(0, 3, {"c": 1})
        b = _snap(0, 3, {"c": 2})   # conflicts with a at epoch 3
        c = _snap(0, 5, {"c": 9})   # supersedes both
        for order in ([a, b, c], [a, c, b], [c, a, b], [b, c, a]):
            with pytest.raises(ValueError, match="conflicting"):
                obs_fleet.merge_fleet(order)

    def test_wire_roundtrip(self):
        snap = _snap(3, 2, {"c": 1}, {"g": 0.5}, {"lat": [0.05]})
        back = obs_fleet.snapshot_from_json(obs_fleet.snapshot_to_json(snap))
        assert back == snap


def _qos_snap(host, epoch, qos):
    return obs_fleet.HostSnapshot(
        host_id=host, epoch=epoch,
        metrics={"counters": {}, "gauges": {}, "histograms": {}},
        qos=qos,
    )


def _qos_block(slo_s=0.05, counts=None, pending=0, burning=False):
    return {
        "slo_s": slo_s,
        "counts": dict(counts or {}),
        "pending": pending,
        "burning": burning,
    }


class TestQosFleetMerge:
    """Round 17: class-labeled QoS series fold under the same sorted-
    deterministic discipline — conflicting class vocabularies refuse
    like histogram layout mismatches."""

    def test_counts_sum_goodput_recomputed(self):
        view = obs_fleet.merge_fleet([
            _qos_snap(0, 1, {
                "premium": _qos_block(0.05, {"met": 8, "violated": 2}),
            }),
            _qos_snap(1, 1, {
                "premium": _qos_block(
                    0.05, {"met": 6, "shed": 4}, pending=3, burning=True,
                ),
            }),
        ])
        premium = view["qos"]["premium"]
        assert premium["counts"] == {"met": 14, "shed": 4, "violated": 2}
        assert premium["offered"] == 20
        assert premium["goodput_within_slo"] == 0.7
        # Pending stays a per-host series; burning names hosts, never
        # averages.
        assert premium["pending"] == {"0": 0, "1": 3}
        assert premium["hosts_burning"] == [1]

    def test_fold_order_independent_bytes(self):
        snaps = [
            _qos_snap(2, 1, {"a": _qos_block(0.1, {"met": 1}),
                             "b": _qos_block(1.0, {"shed": 2})}),
            _qos_snap(0, 1, {"a": _qos_block(0.1, {"met": 4}),
                             "b": _qos_block(1.0, {"met": 1})}),
        ]
        views = [
            obs_fleet.merge_fleet(order)
            for order in (snaps, list(reversed(snaps)))
        ]
        assert len({obs_fleet.fleet_to_json(v) for v in views}) == 1
        assert len({
            obs_fleet.render_fleet_prometheus(v) for v in views
        }) == 1

    def test_class_vocabulary_mismatch_refuses(self):
        with pytest.raises(ValueError, match="vocabularies differ"):
            obs_fleet.merge_fleet([
                _qos_snap(0, 1, {"premium": _qos_block()}),
                _qos_snap(1, 1, {"gold": _qos_block()}),
            ])

    def test_slo_disagreement_is_a_vocabulary_mismatch(self):
        with pytest.raises(ValueError, match="vocabularies differ"):
            obs_fleet.merge_fleet([
                _qos_snap(0, 1, {"premium": _qos_block(slo_s=0.05)}),
                _qos_snap(1, 1, {"premium": _qos_block(slo_s=5.0)}),
            ])

    def test_hosts_without_qos_contribute_nothing(self):
        view = obs_fleet.merge_fleet([
            _qos_snap(0, 1, {"premium": _qos_block(0.05, {"met": 3})}),
            _snap(1, 1, {"serve.requests": 5}),
        ])
        assert view["qos"]["premium"]["counts"] == {"met": 3}
        no_qos = obs_fleet.merge_fleet([_snap(0, 1), _snap(1, 1)])
        assert "qos" not in no_qos

    def test_same_epoch_qos_conflict_refuses(self):
        a = _qos_snap(0, 1, {"premium": _qos_block(0.05, {"met": 3})})
        b = _qos_snap(0, 1, {"premium": _qos_block(0.05, {"met": 4})})
        with pytest.raises(ValueError, match="conflicting"):
            obs_fleet.merge_fleet([a, b])

    def test_wire_roundtrip_preserves_qos(self):
        snap = _qos_snap(3, 2, {"premium": _qos_block(0.05, {"met": 1})})
        back = obs_fleet.snapshot_from_json(
            obs_fleet.snapshot_to_json(snap)
        )
        assert back == snap

    def test_rendered_class_series(self):
        view = obs_fleet.merge_fleet([
            _qos_snap(0, 1, {
                "premium": _qos_block(0.05, {"met": 3, "violated": 1}),
            }),
        ])
        text = obs_fleet.render_fleet_prometheus(view)
        assert 'bce_qos_offered{class="premium"} 4' in text
        assert 'bce_qos_goodput_within_slo{class="premium"} 0.75' in text

    def test_service_snapshot_carries_qos_block(self, tmp_path):
        """End to end: a QoS service's exporter serves the per-class
        block on /snapshot, and the fleet lift picks it up."""
        from bayesian_consensus_engine_tpu.serve import QosClass

        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        try:
            store = TensorReliabilityStore()

            async def main():
                service = ConsensusService(
                    store, steps=1, now=21_900.0, max_batch=8,
                    max_delay_s=None,
                    qos=[QosClass("premium", 3600.0, 64),
                         QosClass("besteffort", 3600.0, 64)],
                )
                telemetry = service.start_telemetry(port=0)
                future = service.submit(
                    "m-1", [("s-1", 0.7)], True, qos_class="premium"
                )
                await service.drain()
                await future
                status, payload = obs_export.scrape_endpoint(
                    telemetry.url + "/snapshot"
                )
                await service.close()
                return status, payload

            status, payload = asyncio.run(main())
            assert status == 200
            assert sorted(payload["qos"]) == ["besteffort", "premium"]
            assert payload["qos"]["premium"]["counts"]["met"] == 1
            lifted = obs_fleet.snapshot_from_wire(payload)
            assert lifted.qos["premium"]["slo_s"] == 3600.0
            view = obs_fleet.merge_fleet([lifted])
            assert view["qos"]["premium"]["offered"] == 1
        finally:
            obs.set_metrics_registry(previous)


class TestExporterByteParity:
    """The acceptance bar: settlement bytes are identical with the
    exporter running (and being scraped, hard) vs absent — write-only
    obs holds end to end on the wire."""

    def _stream(self, with_exporter):
        from bayesian_consensus_engine_tpu.pipeline import settle_stream

        def batches():
            rng = np.random.default_rng(11)
            for b in range(3):
                payloads = [
                    (
                        f"m{b}-{i}",
                        [
                            {"sourceId": f"s{j}",
                             "probability": float(rng.random())}
                            for j in range(3)
                        ],
                    )
                    for i in range(6)
                ]
                yield payloads, (rng.random(6) < 0.5).tolist()

        store = TensorReliabilityStore()
        previous = obs.set_metrics_registry(obs.MetricsRegistry())
        server = scraper = None
        stop = threading.Event()
        try:
            if with_exporter:
                server = obs_export.TelemetryServer().start()
                url = server.url

                def scrape_loop():
                    while not stop.is_set():
                        for endpoint in ("/metrics", "/snapshot",
                                         "/healthz"):
                            try:
                                _get(url + endpoint, timeout=1.0)
                            except Exception:
                                pass

                scraper = threading.Thread(target=scrape_loop, daemon=True)
                scraper.start()
            with tempfile.TemporaryDirectory() as tmp:
                db = os.path.join(tmp, "ckpt.db")
                journal = os.path.join(tmp, "ckpt.jrnl")
                results = [
                    result.by_market()
                    for result in settle_stream(
                        store, batches(), steps=2, now=NOW,
                        db_path=db, journal=journal, checkpoint_every=2,
                    )
                ]
                store.sync()
                db_digest = hashlib.sha256(
                    open(db, "rb").read()
                ).hexdigest()
        finally:
            stop.set()
            if scraper is not None:
                scraper.join(timeout=5.0)
            if server is not None:
                server.close()
            obs.set_metrics_registry(previous)
        return results, db_digest

    def test_stream_bytes_identical_scraped_vs_unexported(self):
        res_plain, db_plain = self._stream(False)
        res_scraped, db_scraped = self._stream(True)
        assert res_scraped == res_plain
        assert db_scraped == db_plain


def _journal_epochs_sans_clock(path):
    """Decoded epoch frames with the wall-clock field masked (the
    tests/test_serve.py helper, trimmed)."""
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4  # + crc32
    return epochs


def _serve_trace(n=12, width=4):
    return [
        (f"m-{i % width}", [("s", 0.5 + 0.01 * i)], i % 2 == 0)
        for i in range(n)
    ]


def _run_service(tmp_path, name, **kwargs):
    """Submit the standard trace, drain, close; returns the service."""

    async def main():
        service = ConsensusService(
            TensorReliabilityStore(), steps=2, now=NOW, max_batch=4,
            max_delay_s=None, checkpoint_every=2,
            journal=tmp_path / f"{name}.jrnl",
            db_path=tmp_path / f"{name}.db",
            **kwargs,
        )
        async with service:
            futures = [
                service.submit(market, signals, outcome)
                for market, signals, outcome in _serve_trace()
            ]
            await service.drain()
        for future in futures:
            future.result()
        return service

    return asyncio.run(main())


class TestServiceTelemetry:
    def test_health_fed_and_served_live(self, tmp_path):
        monitor = obs_health.HealthMonitor(
            objective_goodput=0.9,
            windows=(obs_health.BurnWindow(8, 32, 2.0),),
        )
        scraped = {}

        async def main():
            service = ConsensusService(
                TensorReliabilityStore(), steps=2, now=NOW, max_batch=4,
                max_delay_s=None, slo=3600.0, health=monitor,
            )
            server = service.start_telemetry(host_id=3, epoch=1)
            assert service.start_telemetry() is server  # idempotent
            async with service:
                futures = [
                    service.submit(market, signals, outcome)
                    for market, signals, outcome in _serve_trace()
                ]
                await service.drain()
                for future in futures:
                    future.result()
                scraped["healthz"] = _get(server.url + "/healthz")
                scraped["snapshot"] = _get(server.url + "/snapshot")
                scraped["url"] = server.url
            return service, server

        service, server = asyncio.run(main())
        # Every SLO-classified outcome reached the monitor.
        verdict = monitor.verdict()
        assert verdict["recorded"] == len(_serve_trace())
        assert verdict["verdict"] == "healthy"
        status, payload = scraped["healthz"]
        assert (status, payload["verdict"]) == (200, "healthy")
        _status, snap = scraped["snapshot"]
        assert (snap["host_id"], snap["epoch"]) == (3, 1)
        # close() shut the exporter down with the service.
        with pytest.raises((OSError, urllib.error.URLError)):
            urllib.request.urlopen(scraped["url"] + "/healthz", timeout=0.5)

    def test_health_requires_slo(self):
        monitor = obs_health.HealthMonitor(objective_goodput=0.9)
        with pytest.raises(ValueError, match="slo"):
            ConsensusService(
                TensorReliabilityStore(), health=monitor
            )

    def test_shed_when_burning_is_an_admission_input(self, tmp_path):
        monitor = obs_health.HealthMonitor(
            objective_goodput=0.9,
            windows=(obs_health.BurnWindow(2, 4, 1.0),),
        )
        for _ in range(4):
            monitor.record("violated")
        assert monitor.burning is True
        recorded_before = monitor.verdict()["recorded"]

        async def main():
            service = ConsensusService(
                TensorReliabilityStore(), steps=1, now=NOW, max_batch=4,
                max_delay_s=None, slo=3600.0, health=monitor,
                admission=AdmissionConfig(
                    max_pending=64, policy="reject",
                    shed_when_burning=True, burn_probe_every=2,
                ),
            )
            futures = []
            async with service:
                with pytest.raises(Overloaded):
                    service.submit("m-0", [("s", 0.5)], True)
                # Probe admission (every 2nd burn arrival here): the
                # monitor keeps seeing real outcomes, so a recovered
                # service can CLEAR its burning verdict instead of
                # rejecting everything forever.
                futures.append(service.submit("m-1", [("s", 0.5)], True))
                with pytest.raises(Overloaded):
                    service.submit("m-2", [("s", 0.5)], True)
                futures.append(service.submit("m-3", [("s", 0.5)], True))
                await service.drain()
                for future in futures:
                    future.result()
            return service

        service = asyncio.run(main())
        counts = service.goodput()["counts"]
        # Refusals are SLO-accounted like any other rejection...
        assert counts["rejected"] == 2
        assert counts["met"] == 2
        # ...but burn-DRIVEN refusals never feed the monitor (no
        # feedback loop): it saw only the two probed completions.
        assert monitor.verdict()["recorded"] == recorded_before + 2

    def test_probes_let_burning_clear(self, tmp_path):
        # The full loop: trip burning, then let probed traffic (all
        # met) wash the windows — the verdict must return to healthy
        # even though every non-probe arrival is being refused.
        monitor = obs_health.HealthMonitor(
            objective_goodput=0.9,
            windows=(obs_health.BurnWindow(2, 4, 1.0),),
        )
        for _ in range(4):
            monitor.record("violated")
        assert monitor.burning is True

        async def main():
            service = ConsensusService(
                TensorReliabilityStore(), steps=1, now=NOW, max_batch=1,
                max_delay_s=None, slo=3600.0, health=monitor,
                admission=AdmissionConfig(
                    max_pending=64, policy="reject",
                    shed_when_burning=True, burn_probe_every=2,
                ),
            )
            async with service:
                submitted = 0
                while monitor.burning and submitted < 64:
                    try:
                        future = service.submit(
                            "m-0", [("s", 0.5)], True
                        )
                    except Overloaded:
                        pass
                    else:
                        await future
                    submitted += 1
            return submitted

        submitted = asyncio.run(main())
        assert monitor.burning is False
        assert submitted < 64  # it actually converged, not timed out

    def test_burning_without_the_flag_changes_nothing(self, tmp_path):
        monitor = obs_health.HealthMonitor(
            objective_goodput=0.9,
            windows=(obs_health.BurnWindow(2, 4, 1.0),),
        )
        for _ in range(4):
            monitor.record("violated")
        service = _run_service(
            tmp_path, "burning_default", slo=3600.0, health=monitor,
        )
        counts = service.goodput()["counts"]
        assert counts["rejected"] == 0 and counts["shed"] == 0
        assert counts["met"] == len(_serve_trace())

    def test_serve_bytes_identical_with_exporter_scraping(self, tmp_path):
        plain = _run_service(tmp_path, "plain", slo=3600.0)
        del plain

        monitor = obs_health.HealthMonitor(objective_goodput=0.9)
        stop = threading.Event()
        scraper = None

        async def main():
            service = ConsensusService(
                TensorReliabilityStore(), steps=2, now=NOW, max_batch=4,
                max_delay_s=None, checkpoint_every=2,
                journal=tmp_path / "scraped.jrnl",
                db_path=tmp_path / "scraped.db",
                slo=3600.0, health=monitor,
            )
            server = service.start_telemetry()
            url = server.url

            def scrape_loop():
                while not stop.is_set():
                    for endpoint in ("/metrics", "/snapshot", "/healthz"):
                        try:
                            _get(url + endpoint, timeout=1.0)
                        except Exception:
                            pass

            nonlocal scraper
            scraper = threading.Thread(target=scrape_loop, daemon=True)
            scraper.start()
            async with service:
                futures = [
                    service.submit(market, signals, outcome)
                    for market, signals, outcome in _serve_trace()
                ]
                await service.drain()
                for future in futures:
                    future.result()
            return service

        try:
            asyncio.run(main())
        finally:
            stop.set()
            if scraper is not None:
                scraper.join(timeout=5.0)
        assert _journal_epochs_sans_clock(
            tmp_path / "scraped.jrnl"
        ) == _journal_epochs_sans_clock(tmp_path / "plain.jrnl")
        assert (tmp_path / "scraped.db").read_bytes() == (
            tmp_path / "plain.db"
        ).read_bytes()
