"""Process-level CLI integration: exit codes, stdout JSON, stderr messages.

Mirrors the reference's subprocess test style (reference:
tests/test_integration.py, tests/test_dry_run.py): drive
``python -m bayesian_consensus_engine_tpu.cli`` end-to-end, assert state only
through the public surface (a second CLI process), never by DB peeking.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = str(Path(__file__).resolve().parents[1])


def run_cli(args: list[str], stdin_payload: dict | None = None):
    return subprocess.run(
        [sys.executable, "-m", "bayesian_consensus_engine_tpu.cli", *args],
        capture_output=True,
        text=True,
        input=json.dumps(stdin_payload) if stdin_payload is not None else None,
        cwd=REPO_ROOT,
    )


def _payload(signals=None) -> dict:
    return {
        "schemaVersion": "1.0.0",
        "marketId": "market-1",
        "signals": signals
        if signals is not None
        else [{"sourceId": "agent-a", "probability": 0.5}],
    }


class TestLegacyMode:
    def test_input_file(self, tmp_path: Path):
        f = tmp_path / "in.json"
        f.write_text(json.dumps(_payload()), encoding="utf-8")
        proc = run_cli(["--input", str(f)])
        assert proc.returncode == 0
        out = json.loads(proc.stdout)
        assert out["schemaVersion"] == "1.0.0"
        assert out["consensus"] == 0.5

    def test_stdin(self):
        proc = run_cli([], stdin_payload=_payload())
        assert proc.returncode == 0
        assert json.loads(proc.stdout)["consensus"] == 0.5

    def test_missing_schema_version_exits_1(self):
        bad = _payload()
        del bad["schemaVersion"]
        proc = run_cli([], stdin_payload=bad)
        assert proc.returncode == 1
        assert "schemaVersion is required" in proc.stderr

    def test_malformed_json_exits_1(self):
        proc = subprocess.run(
            [sys.executable, "-m", "bayesian_consensus_engine_tpu.cli"],
            capture_output=True,
            text=True,
            input="{not json",
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 1
        assert "Validation error" in proc.stderr

    def test_dry_run_stamps_diagnostics(self):
        proc = run_cli(["--dry-run"], stdin_payload=_payload())
        assert json.loads(proc.stdout)["diagnostics"]["dryRun"] is True


class TestConsensusSubcommand:
    def test_stdin(self):
        proc = run_cli(["consensus"], stdin_payload=_payload())
        assert proc.returncode == 0
        assert json.loads(proc.stdout)["consensus"] == 0.5

    def test_subcommand_input_flag(self, tmp_path: Path):
        f = tmp_path / "in.json"
        f.write_text(json.dumps(_payload()), encoding="utf-8")
        proc = run_cli(["consensus", "--input", str(f)])
        assert proc.returncode == 0

    def test_golden_fixture_byte_exact_via_cli(self):
        fixture = json.loads(
            (Path(REPO_ROOT) / "tests/fixtures/golden_regression.json").read_text()
        )
        proc = run_cli(["consensus"], stdin_payload=fixture["input"])
        assert proc.returncode == 0
        assert proc.stdout == json.dumps(fixture["expectedOutput"], indent=2) + "\n"

    def test_backend_jax_golden_byte_exact_x64(self, tmp_path: Path):
        # End-to-end --backend jax through a real CLI process. Env-var JAX
        # overrides are dead on this host (sitecustomize pins the platform at
        # interpreter startup), so the subprocess pins CPU + x64 via
        # jax.config before main() — argv, stdin, stdout, and exit code are
        # the real surface. Under x64 the batched path must reproduce the
        # golden fixture byte-for-byte through the dispatch.
        fixture = json.loads(
            (Path(REPO_ROOT) / "tests/fixtures/golden_regression.json").read_text()
        )
        launcher = tmp_path / "cli_jax_launcher.py"
        launcher.write_text(
            "import sys\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            f"sys.path.insert(0, {REPO_ROOT!r})\n"
            "from bayesian_consensus_engine_tpu.cli import main\n"
            "main()\n",
            encoding="utf-8",
        )
        proc = subprocess.run(
            [sys.executable, str(launcher), "--backend", "jax", "consensus"],
            capture_output=True,
            text=True,
            input=json.dumps(fixture["input"]),
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout == json.dumps(fixture["expectedOutput"], indent=2) + "\n"

    def test_backend_jax_default_f32_close(self, tmp_path: Path):
        # Without x64 the jax backend runs f32: same document shape, floats
        # within f32 resolution of the scalar answer.
        launcher = tmp_path / "cli_jax_f32.py"
        launcher.write_text(
            "import sys\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            f"sys.path.insert(0, {REPO_ROOT!r})\n"
            "from bayesian_consensus_engine_tpu.cli import main\n"
            "main()\n",
            encoding="utf-8",
        )
        payload = _payload(
            [
                {"sourceId": "a", "probability": 0.61},
                {"sourceId": "b", "probability": 0.34},
            ]
        )
        proc = subprocess.run(
            [sys.executable, str(launcher), "--backend", "jax"],
            capture_output=True,
            text=True,
            input=json.dumps(payload),
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert out["consensus"] == pytest.approx(0.475, rel=1e-6)
        assert out["diagnostics"]["uniqueSources"] == 2

    def test_db_reliability_lookup(self, tmp_path: Path):
        db = tmp_path / "rel.db"
        # Build reliability through the public surface: report outcomes.
        for _ in range(3):
            run_cli([
                "--db", str(db), "report-outcome",
                "--source-id", "good", "--market-id", "market-1", "--correct",
            ])
            run_cli([
                "--db", str(db), "report-outcome",
                "--source-id", "bad", "--market-id", "market-1",
            ])
        payload = _payload(
            [
                {"sourceId": "good", "probability": 1.0},
                {"sourceId": "bad", "probability": 0.0},
            ]
        )
        proc = run_cli(["--db", str(db), "consensus"], stdin_payload=payload)
        out = json.loads(proc.stdout)
        assert out["consensus"] > 0.7  # good outweighs bad
        assert out["diagnostics"]["coldStartSources"] == []


class TestReportOutcome:
    def test_requires_db(self):
        proc = run_cli(["report-outcome", "--source-id", "a", "--market-id", "m"])
        assert proc.returncode == 1
        assert "--db is required" in proc.stderr

    def test_correct_outcome(self, tmp_path: Path):
        proc = run_cli([
            "--db", str(tmp_path / "r.db"), "report-outcome",
            "--source-id", "a", "--market-id", "m", "--correct",
        ])
        assert proc.returncode == 0
        out = json.loads(proc.stdout)
        assert out["sourceId"] == "a"
        assert out["marketId"] == "m"
        assert out["reliability"] == 0.6
        assert out["dryRun"] is False

    def test_incorrect_outcome(self, tmp_path: Path):
        proc = run_cli([
            "--db", str(tmp_path / "r.db"), "report-outcome",
            "--source-id", "a", "--market-id", "m",
        ])
        assert json.loads(proc.stdout)["reliability"] == 0.4


class TestDryRun:
    def test_dry_run_report_outcome_persists_nothing(self, tmp_path: Path):
        db = tmp_path / "r.db"
        proc = run_cli([
            "--db", str(db), "--dry-run", "report-outcome",
            "--source-id", "a", "--market-id", "m", "--correct",
        ])
        assert proc.returncode == 0
        out = json.loads(proc.stdout)
        assert out["dryRun"] is True
        assert out["reliability"] > 0.5
        # Zero writes — verified through the public surface.
        listing = run_cli(["--db", str(db), "list-sources"])
        assert json.loads(listing.stdout)["count"] == 0

    def test_without_dry_run_persists(self, tmp_path: Path):
        db = tmp_path / "r.db"
        run_cli([
            "--db", str(db), "report-outcome",
            "--source-id", "a", "--market-id", "m", "--correct",
        ])
        listing = run_cli(["--db", str(db), "list-sources"])
        assert json.loads(listing.stdout)["count"] == 1


class TestListSources:
    def test_requires_db(self):
        proc = run_cli(["list-sources"])
        assert proc.returncode == 1
        assert "--db is required" in proc.stderr

    def test_empty_db(self, tmp_path: Path):
        proc = run_cli(["--db", str(tmp_path / "r.db"), "list-sources"])
        out = json.loads(proc.stdout)
        assert out == {"sources": [], "count": 0}

    def test_market_filter(self, tmp_path: Path):
        db = tmp_path / "r.db"
        run_cli(["--db", str(db), "report-outcome", "--source-id", "a",
                 "--market-id", "m-1", "--correct"])
        run_cli(["--db", str(db), "report-outcome", "--source-id", "a",
                 "--market-id", "m-2", "--correct"])
        proc = run_cli(["--db", str(db), "list-sources", "--market-id", "m-1"])
        out = json.loads(proc.stdout)
        assert out["count"] == 1
        assert out["sources"][0]["marketId"] == "m-1"


class TestJournalExport:
    """Additive maintenance subcommand: replay a settle_stream durability
    journal and export the reference-compatible SQLite file — the
    crash-recovery path without writing Python."""

    def _journal(self, tmp_path: Path) -> Path:
        from bayesian_consensus_engine_tpu.pipeline import settle_stream
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        batches = [
            (
                [
                    (
                        f"jx-b{b}-m{m}",
                        [{"sourceId": f"s{m % 3}", "probability": 0.25 * (m % 4)}],
                    )
                    for m in range(5)
                ],
                [bool(m % 2) for m in range(5)],
            )
            for b in range(2)
        ]
        jrnl = tmp_path / "svc.jrnl"
        store = TensorReliabilityStore()
        for _result in settle_stream(
            store, batches, steps=1, now=21_800.0, journal=jrnl
        ):
            pass
        store.sync()
        self._live = store.list_sources()
        return jrnl

    def test_export_then_list_sources_round_trip(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        db = tmp_path / "out.db"
        proc = run_cli(["--db", str(db), "journal-export", str(jrnl)])
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert out["epochTag"] == 1
        assert out["rows"] == len(self._live)
        assert out["exportedTo"] == str(db)
        assert out["dryRun"] is False
        # State asserted through the public surface: a second CLI process.
        listing = run_cli(["--db", str(db), "list-sources"])
        assert listing.returncode == 0
        got = json.loads(listing.stdout)["sources"]
        assert [
            (s["sourceId"], s["marketId"], s["reliability"], s["confidence"])
            for s in got
        ] == [
            (r.source_id, r.market_id, r.reliability, r.confidence)
            for r in self._live
        ]

    def test_dry_run_reports_without_writing(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        db = tmp_path / "never.db"
        proc = run_cli(
            ["--db", str(db), "--dry-run", "journal-export", str(jrnl)]
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert out["exportedTo"] is None and out["dryRun"] is True
        assert not db.exists()

    def test_dry_run_needs_no_db(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        proc = run_cli(["--dry-run", "journal-export", str(jrnl)])
        assert proc.returncode == 0, proc.stderr
        assert json.loads(proc.stdout)["rows"] > 0

    def test_existing_target_refused(self, tmp_path: Path):
        # The export must EQUAL the recovered journal state; an existing
        # file would UPSERT-merge stale rows in, so it is refused.
        jrnl = self._journal(tmp_path)
        db = tmp_path / "pre.db"
        db.write_bytes(b"anything")
        proc = run_cli(["--db", str(db), "journal-export", str(jrnl)])
        assert proc.returncode == 1
        assert "already exists" in proc.stderr
        assert db.read_bytes() == b"anything"

    def test_missing_db_errors(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        proc = run_cli(["journal-export", str(jrnl)])
        assert proc.returncode == 1
        assert "Error: --db is required for journal-export" in proc.stderr

    def test_bad_journal_errors(self, tmp_path: Path):
        bad = tmp_path / "not.jrnl"
        bad.write_bytes(b"NOTAJRNL")
        proc = run_cli(["--db", str(tmp_path / "x.db"), "journal-export", str(bad)])
        assert proc.returncode == 1
        assert "Error:" in proc.stderr


class TestReplay:
    """The counterfactual replay subcommand: re-drive a recorded
    journal's trace sidecar under K altered configs without writing
    Python. The sweep semantics live in tests/test_replay.py; this pins
    the CLI surface — table + JSON shapes, the lane-0 digest witness,
    the --db export, --strict, and the config-spec error paths."""

    def _journal(self, tmp_path: Path) -> Path:
        import numpy as np

        from bayesian_consensus_engine_tpu.cluster.recover import (
            store_digest,
        )
        from bayesian_consensus_engine_tpu.pipeline import settle_stream
        from bayesian_consensus_engine_tpu.state.tensor_store import (
            TensorReliabilityStore,
        )

        rng = np.random.default_rng(7)
        batches = []
        for b in range(2):
            counts = rng.integers(1, 4, 6)
            keys = [f"rp-b{b}-m{m}" if m % 2 else f"rp-m{m}" for m in range(6)]
            sids = [f"s{v}" for v in rng.integers(0, 4, int(counts.sum()))]
            probs = rng.random(int(counts.sum()))
            offsets = np.concatenate([[0], np.cumsum(counts)]).astype(
                np.int64
            )
            outcomes = (rng.random(6) < 0.5).tolist()
            batches.append(((keys, sids, probs, offsets), outcomes))
        jrnl = tmp_path / "rp.jrnl"
        store = TensorReliabilityStore()
        for _result in settle_stream(
            store, batches, steps=1, now=21_800.0,
            journal=jrnl, trace=str(jrnl) + ".trace", columnar=True,
        ):
            pass
        self._live_digest = store_digest(store)
        return jrnl

    def test_json_sweep_lane0_is_the_live_run(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        proc = run_cli([
            "replay", str(jrnl),
            "--configs", "half_life_days=12,base_learning_rate=0.05",
            "--json",
        ])
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert out["batches"] == 2
        # The byte-contract witness: lane 0 rebuilt the recorded run.
        assert out["digest"] == self._live_digest
        assert len(out["lanes"]) == 2
        assert out["lanes"][0]["delta"] == {}
        assert out["lanes"][1]["delta"] == {
            "half_life_days": 12.0, "base_learning_rate": 0.05,
        }
        for lane in out["lanes"]:
            assert lane["marketsSettled"] == 12

    def test_table_diffs_each_lane_against_recorded(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        proc = run_cli(["replay", str(jrnl), "--configs", "band_z=1.25"])
        assert proc.returncode == 0, proc.stderr
        assert "recorded" in proc.stdout
        assert "band_z=1.25" in proc.stdout
        assert "brier" in proc.stdout  # the recorded->lane trailer

    def test_db_exports_lane0_state(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        db = tmp_path / "lane0.db"
        proc = run_cli(["--db", str(db), "replay", str(jrnl), "--json"])
        assert proc.returncode == 0, proc.stderr
        assert json.loads(proc.stdout)["exportedTo"] == str(db)
        listed = run_cli(["--db", str(db), "list-sources"])
        assert listed.returncode == 0
        assert json.loads(listed.stdout)["count"] > 0
        # A fresh interchange file only: an existing target refuses.
        proc = run_cli(["--db", str(db), "replay", str(jrnl)])
        assert proc.returncode == 1
        assert "already exists" in proc.stderr

    def test_strict_refuses_a_torn_tail(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        with open(jrnl, "r+b") as f:
            f.truncate(jrnl.stat().st_size - 9)
        torn = run_cli(["replay", str(jrnl), "--strict"])
        assert torn.returncode == 1
        assert "Error:" in torn.stderr and "durable" in torn.stderr
        # Non-strict replays to the last joined epoch.
        proc = run_cli(["replay", str(jrnl), "--json"])
        assert proc.returncode == 0, proc.stderr
        assert json.loads(proc.stdout)["batches"] == 1

    def test_bad_config_spec_errors(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        proc = run_cli(["replay", str(jrnl), "--configs", "nope=1"])
        assert proc.returncode == 1
        assert "Error:" in proc.stderr and "half_life_days" in proc.stderr

    def test_graph_lane_is_python_api_only(self, tmp_path: Path):
        jrnl = self._journal(tmp_path)
        proc = run_cli(["replay", str(jrnl), "--configs", "graph_steps=2"])
        assert proc.returncode == 1
        assert "MarketGraph" in proc.stderr


class TestBankVerbs:
    """``bce-tpu bank export|merge|show`` — the shippable autotune bank
    round-trip at the process level (round 20)."""

    def _entry(self, **over):
        entry = {
            "knob": "settle_kernel",
            "shape_key": [16, 256, 2],
            "generation": "tpu-v5e",
            "choice": "pallas",
            "default": "xla",
            "beat_default": True,
            "timings_s": {"pallas": 1.0, "xla": 2.0},
        }
        entry.update(over)
        return entry

    def _cache(self, tmp_path: Path) -> Path:
        # A tuner cache as ShapeTuner persists it: key is the JSON of
        # [knob, shape_key, device_kind].
        cache = tmp_path / "tune.json"
        key = json.dumps(["settle_kernel", [16, 256, 2], "TPU v5e"])
        cache.write_text(json.dumps({key: {
            "choice": "pallas", "default": "xla", "beat_default": True,
            "timings_s": {"pallas": 1.0, "xla": 2.0},
        }}))
        return cache

    def test_export_show_round_trip(self, tmp_path: Path):
        cache = self._cache(tmp_path)
        out = tmp_path / "v5e.bank.json"
        proc = run_cli([
            "bank", "export", "--cache", str(cache), "-o", str(out)
        ])
        assert proc.returncode == 0, proc.stderr
        payload = json.loads(out.read_text())
        assert payload["schema"] == "bce-autotune-bank/v1"
        (entry,) = payload["entries"]
        assert entry["generation"] == "tpu-v5e"
        show = run_cli(["bank", "show", str(out)])
        assert show.returncode == 0, show.stderr
        assert "1 verdicts" in show.stdout
        assert "beat default" in show.stdout

    def test_export_empty_cache_errors(self, tmp_path: Path):
        cache = tmp_path / "empty.json"
        cache.write_text("{}")
        proc = run_cli(["bank", "export", "--cache", str(cache)])
        assert proc.returncode == 1
        assert "no adjudicated verdicts" in proc.stderr

    def test_merge_refuses_verdict_flip(self, tmp_path: Path):
        a = tmp_path / "a.bank.json"
        b = tmp_path / "b.bank.json"
        a.write_text(json.dumps(
            {"schema": "bce-autotune-bank/v1", "entries": [self._entry()]}
        ))
        b.write_text(json.dumps({
            "schema": "bce-autotune-bank/v1",
            "entries": [self._entry(choice="xla", beat_default=False)],
        }))
        merged = tmp_path / "m.bank.json"
        proc = run_cli([
            "bank", "merge", str(a), str(b), "-o", str(merged)
        ])
        assert proc.returncode == 1
        assert "verdict flip" in proc.stderr
        assert not merged.exists()
        # Agreeing banks merge fine.
        ok = run_cli(["bank", "merge", str(a), str(a), "-o", str(merged)])
        assert ok.returncode == 0, ok.stderr
        assert len(json.loads(merged.read_text())["entries"]) == 1

    def test_show_rejects_drifted_schema(self, tmp_path: Path):
        bad = tmp_path / "bad.bank.json"
        bad.write_text(json.dumps(
            {"schema": "bce-autotune-bank/v0", "entries": []}
        ))
        proc = run_cli(["bank", "show", str(bad)])
        assert proc.returncode == 1
        assert "schema" in proc.stderr
