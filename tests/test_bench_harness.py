"""The driver bench's orchestration harness (bench.py).

Round 3 lost its driver-recorded perf number because one hung
``jax.devices()`` during tunnel bring-up took the whole bench process with
it. These tests pin the round-4 contract: no leg failure mode — hang,
crash, or backend outage — may cost more than that leg's entry in extras,
and the final line is ALWAYS one valid JSON object (exit code 0 whenever
any headline leg measured a number).

The subprocess tests use dedicated ``selftest*`` legs that never import
jax, so they are fast and hermetic; the orchestration tests inject a fake
leg runner; one end-to-end test drives real subprocess legs on the CPU
backend at ``--fast`` shapes.
"""

import json

import pytest

import bench


class TestLegSubprocess:
    def test_selftest_roundtrip(self):
        res = bench.run_leg_subprocess("selftest", timeout=60)
        assert res["ok"] is True
        assert res["value"] == {"hello": 1}
        # Every leg subprocess reports its wall clock and an additive
        # phase breakdown (obs/timeline.py): named spans + untracked
        # remainder summing to wall_s within 5%.
        assert res["wall_s"] >= 0
        assert abs(sum(res["phases"].values()) - res["wall_s"]) <= (
            0.05 * max(res["wall_s"], 1e-3)
        )

    def test_hang_is_killed(self):
        res = bench.run_leg_subprocess("selftest_hang", timeout=3)
        assert res["ok"] is False
        assert "timeout after 3s" in res["error"]

    def test_crash_is_reported_not_raised(self):
        res = bench.run_leg_subprocess("selftest_crash", timeout=60)
        assert res["ok"] is False
        assert "rc=3" in res["error"]

    def test_unknown_leg_fails_cleanly(self):
        res = bench.run_leg_subprocess("no_such_leg", timeout=60)
        assert res == {"ok": False, "error": "unknown leg 'no_such_leg'"}

    def test_ledger_records_leg_with_loadavg_and_repeat(self, tmp_path):
        from bayesian_consensus_engine_tpu.obs.ledger import read_ledger

        ledger = tmp_path / "run.jsonl"
        res = bench.run_leg_subprocess(
            "selftest", timeout=60, ledger=str(ledger)
        )
        assert res["ok"] is True
        (record,) = read_ledger(ledger)
        assert record["leg"] == "selftest"
        assert record["repeat"] == 0
        assert "loadavg_1m" in record["host"]
        assert record["extras"]["wall_s"] >= 0
        assert "phases" in record


class TestHeadlineDurability:
    """VERDICT r5 #4: the round's headline must survive a front-truncated
    tail capture — compact last line + atomic --out."""

    def test_headline_line_final_bytes_carry_value_and_unit(self):
        payload, _ = bench.compose(_full_results(), [], {}, 1.0)
        line = bench.headline_line(payload)
        parsed = json.loads(line)
        assert parsed["value"] == payload["value"]
        assert parsed["unit"] == payload["unit"]
        assert parsed["vs_baseline"] == payload["vs_baseline"]
        # Key order is the durability contract: value/unit close the line,
        # so any capture holding the tail bytes holds the number.
        assert list(parsed)[-2:] == ["value", "unit"]
        assert line.rstrip().endswith('"unit": "cycles/sec"}')

    def test_main_prints_compact_headline_last_and_writes_out(
        self, tmp_path, monkeypatch, capsys
    ):
        payload, _ = bench.compose(_full_results(), [], {}, 1.0)
        monkeypatch.setattr(
            bench, "orchestrate", lambda **kwargs: (payload, 0)
        )
        monkeypatch.setattr(bench, "lint_gate", lambda skip: None)
        out_path = tmp_path / "driver.json"
        rc = bench.main(["--out", str(out_path), "--no-lint"])
        assert rc == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert json.loads(lines[-2]) == payload  # full record
        compact = json.loads(lines[-1])  # durable headline, LAST
        assert compact["headline"] is True
        assert compact["value"] == payload["value"]
        # --out holds the full record, atomically written.
        assert json.loads(out_path.read_text()) == payload
        assert not list(tmp_path.glob("*.tmp.*"))


class TestProbeBackoff:
    def test_retries_until_success(self):
        calls = []
        sleeps = []

        def run_leg(name, fast=False):
            calls.append(name)
            if len(calls) < 3:
                return {"ok": False, "error": "UNAVAILABLE: tunnel down"}
            return {"ok": True, "value": {"platform": "tpu", "devices": 1}}

        info, attempts, err = bench.probe_with_backoff(
            run_leg, budget_s=600, sleeper=sleeps.append
        )
        assert info == {"platform": "tpu", "devices": 1}
        assert attempts == 3
        assert err is None
        # Exponential backoff between attempts.
        assert sleeps == [15, 30]

    def test_budget_exhaustion_reports_last_error(self):
        def run_leg(name, fast=False):
            return {"ok": False, "error": "UNAVAILABLE: still down"}

        info, attempts, err = bench.probe_with_backoff(
            run_leg, budget_s=0, sleeper=lambda s: None
        )
        assert info is None
        assert attempts == 1
        assert err == "UNAVAILABLE: still down"


def _ok(value):
    return {"ok": True, "value": value}


def _fail(msg="boom"):
    return {"ok": False, "error": msg}


def _full_results(compact=7200.0, f32=2200.0):
    return {
        "headline_f32": _ok(f32),
        "compact": _ok(compact),
        "compact_fit": _ok(compact * 1.5),
        "dispatch_rtt": _ok(96.0),
        "stream_probe": _ok(400.0),
        "north_star_band": _ok(
            {
                "workload": "125056 markets x 10000 slots",
                "u16_probs": {
                    "marginal_ms_per_step": 14.31,
                    "band_sustained_cycles_per_sec": 69.9,
                },
                "projected_v5e8_1m_x_10k_u16_cycles_per_sec": 69.9,
            }
        ),
        "north_star_f32": _ok(
            {
                "workload": "62528 markets x 10000 slots, f32 probs",
                "marginal_ms_per_step": 8.94,
                "band_sustained_cycles_per_sec": 111.9,
                "projected_v5e16_1m_x_10k_f32_cycles_per_sec": 111.9,
            }
        ),
        "large_k": _ok({"flat_loop_cycles_per_sec": 233.0}),
        "e2e_pipeline": _ok({"cycles_per_sec_amortised": 0.4}),
        "tiebreak_10k_agents": _ok({"ring_markets_per_sec": 1142.0}),
        "pallas_ab": _ok({"xla_cycles_per_sec": 887.0, "pallas_tile2048_cycles_per_sec": 620.0, "verdict": "xla_wins_1m16 (887.0 vs 620.0)"}),
    }


class TestCompose:
    def test_healthy_run(self):
        payload, rc = bench.compose(
            _full_results(), [], {"platform": "tpu", "devices": 1}, 100.0
        )
        assert rc == 0
        assert payload["value"] == 7200.0
        assert payload["vs_baseline"] == round(
            7200.0 / bench.REFERENCE_BASELINE_CYCLES_PER_SEC, 1
        )
        extras = payload["extras"]
        assert extras["headline_source"] == "compact_int8_loop"
        assert "degraded" not in extras
        # Probe-normalised comparison is done in-JSON (VERDICT r3 #5).
        assert extras["normalised_vs_probe"]["headline_cycles_per_gbs"] == round(
            7200.0 / 400.0, 3
        )
        # BASELINE-shaped metric rides along every run, u16-labelled (the
        # f32 band does not fit one chip; its anchor is north_star_f32).
        assert (
            extras["baseline_shape"]["projected_v5e8_u16_cycles_per_sec"]
            == 69.9
        )
        assert (
            extras["north_star_f32"][
                "projected_v5e16_1m_x_10k_f32_cycles_per_sec"
            ]
            == 111.9
        )
        assert extras["harness"]["legs"]["compact"] == "ok"
        json.dumps(payload)  # driver contract: serializable

    def test_f32_wins_when_faster(self):
        payload, _ = bench.compose(
            _full_results(compact=1000.0, f32=2000.0), [], {}, 1.0
        )
        assert payload["extras"]["headline_source"] == "f32_fast_loop"
        assert payload["value"] == 2000.0

    def test_dispatch_fit_from_two_points(self):
        # compact at 1600 steps = 8000 c/s, at 400 steps = 4000 c/s:
        # t_big=0.2s, t_small=0.1s -> marginal = 0.1/1200 s/step.
        results = _full_results(compact=8000.0)
        results["compact_fit"] = _ok(4000.0)
        payload, _ = bench.compose(results, [], {}, 1.0)
        fit = payload["extras"]["compact_dispatch_fit"]
        assert fit["sustained_cycles_per_sec"] == round(12000.0, 1)
        assert fit["fixed_dispatch_ms"] == round(
            (0.1 - 400 * (0.1 / 1200)) * 1e3, 1
        )

    def test_degenerate_fit_is_reported_not_negative(self):
        results = _full_results(compact=4000.0)
        results["compact_fit"] = _ok(1000.0)  # t_big == t_small == 0.4s
        payload, _ = bench.compose(results, [], {}, 1.0)
        assert "degenerate" in payload["extras"]["compact_dispatch_fit"]

    def test_partial_failure_costs_only_that_leg(self):
        results = _full_results()
        results["large_k"] = _fail("timeout after 1200s (killed)")
        del results["pallas_ab"]
        payload, rc = bench.compose(results, [], {}, 1.0)
        assert rc == 0
        assert payload["value"] == 7200.0
        assert "timeout" in payload["extras"]["large_k"]
        assert payload["extras"]["pallas_ab"] == (
            "failed: not run"
        )
        json.dumps(payload)

    def test_e2e_stream_cpu_absent_on_healthy_runs(self):
        """The fallback-only leg must not pollute healthy records with a
        'failed: not run' entry — absent means 'was never scheduled'."""
        payload, _ = bench.compose(
            _full_results(), [], {"platform": "tpu", "devices": 1}, 100.0
        )
        assert "e2e_stream_cpu" not in payload["extras"]
        results = _full_results()
        results["e2e_stream_cpu"] = _ok({"eager": {"wall_s": 20.0}})
        payload, _ = bench.compose(results, [], {}, 1.0)
        assert payload["extras"]["e2e_stream_cpu"] == {
            "eager": {"wall_s": 20.0}
        }

    def test_cpu_fallback_headline(self):
        results = {
            "headline_f32": _fail("timeout after 900s (killed)"),
            "compact": _fail("timeout after 700s (killed)"),
            "headline_f32_cpu": _ok(3.5),
            "compact_cpu": _ok(5.0),
        }
        payload, rc = bench.compose(
            results, ["tpu backend unavailable after 5 probe attempts"],
            None, 700.0,
        )
        assert rc == 0
        assert payload["value"] == 5.0
        assert payload["extras"]["headline_source"] == (
            "compact_int8_loop_cpu_fallback"
        )
        assert "CPU-backend fallback" in payload["metric"]
        assert payload["extras"]["degraded"]
        json.dumps(payload)

    def test_total_failure_still_valid_json_rc1(self):
        payload, rc = bench.compose({}, ["everything is down"], None, 5.0)
        assert rc == 1
        assert payload["value"] == 0.0
        assert payload["vs_baseline"] == 0.0
        assert "no headline leg succeeded" in payload["extras"]["degraded"]
        json.dumps(payload)

    def test_forced_cpu_never_masquerades_as_tpu(self):
        payload, rc = bench.compose(
            _full_results(), [], {"platform": "cpu", "devices": 1}, 10.0,
            forced_cpu=True,
        )
        assert rc == 0
        assert "--cpu" in payload["metric"]
        assert any("--cpu" in d for d in payload["extras"]["degraded"])

    def test_fast_mode_suppresses_production_derived_numbers(self):
        payload, _ = bench.compose(_full_results(), [], {}, 1.0, fast=True)
        # The fit formula and slot throughput hardcode production step
        # counts/shapes; a --fast run must not fabricate them.
        assert payload["extras"]["compact_dispatch_fit"] == "n/a (--fast shapes)"
        assert payload["extras"]["per_slot_throughput"] == {}


class TestPallasAdjudication:
    """bench_pallas_ab's decision logic, with the measurement functions
    stubbed (the real kernels need the TPU backend)."""

    def _run(self, monkeypatch, xla=(887.0, 900.0), pallas2048=620.0,
             auto_tile=1024, pallas_auto=700.0, large_k_error=None,
             onepass=720.0, onepass_error=None,
             bp=(300.0, 480.0), bp_error=None):
        xla_values = iter(xla)
        monkeypatch.setattr(
            bench, "bench_headline", lambda *a, **k: next(xla_values)
        )

        def fake_rate(markets, slots, steps, tile):
            if slots == bench.LARGE_K_SLOTS:
                if large_k_error is not None:
                    raise large_k_error
                return 50.0
            return pallas2048 if tile == 2048 else pallas_auto

        def fake_onepass_rate(markets, slots, steps):
            if onepass_error is not None:
                raise onepass_error
            return onepass

        def fake_bp_rate(markets, degree, max_steps, kind):
            if kind == "pallas" and bp_error is not None:
                raise bp_error
            return bp[0] if kind == "xla" else bp[1]

        monkeypatch.setattr(bench, "_pallas_rate", fake_rate)
        monkeypatch.setattr(bench, "_onepass_rate", fake_onepass_rate)
        monkeypatch.setattr(bench, "_bp_rate", fake_bp_rate)
        monkeypatch.setattr(
            bench, "_bp_autotune_decision",
            lambda m, s: {
                "choice": "xla", "default": "xla", "beat_default": False,
            },
        )
        monkeypatch.setattr(
            "bayesian_consensus_engine_tpu.ops.pallas_cycle._tuned_tile",
            lambda m, k: auto_tile,
        )
        return bench.bench_pallas_ab(num_markets=4096, slots=8,
                                     timed_steps=200)

    def test_xla_win_verdict(self, monkeypatch):
        out = self._run(monkeypatch)
        assert out["verdict"].startswith("xla_wins_1m16 (900.0 vs 700.0")
        assert out["autotuned_tile"] == 1024
        assert out["pallas_16k10k_cycles_per_sec"] == 50.0

    def test_pallas_win_verdict_uses_best_of_both(self, monkeypatch):
        out = self._run(monkeypatch, xla=(500.0, 480.0), pallas_auto=650.0)
        assert out["verdict"].startswith("pallas_wins_1m16 (650.0 vs 500.0")

    def test_auto_tile_2048_reuses_the_fixed_measurement(self, monkeypatch):
        out = self._run(monkeypatch, auto_tile=2048, pallas_auto=999.0)
        # Same tile: the auto number must BE the fixed-tile number, not a
        # separate (drift-prone) re-measurement.
        assert out["pallas_auto_cycles_per_sec"] == 620.0

    def test_large_k_infeasibility_is_data_not_a_crash(self, monkeypatch):
        out = self._run(
            monkeypatch, large_k_error=RuntimeError("VMEM OOM: 51MB > 16MB")
        )
        assert "pallas_16k10k_cycles_per_sec" not in out
        assert out["pallas_16k10k"].startswith("infeasible: RuntimeError")
        assert out["verdict"]  # the 1M×16 verdict still renders

    def test_onepass_arm_adjudicated_against_best_xla(self, monkeypatch):
        # Round 14: the third bracket arm. A one-pass rate above the
        # best XLA pass is a decisive win (the kernel computes MORE per
        # sweep); below, XLA keeps the verdict.
        out = self._run(monkeypatch, onepass=950.0)
        assert out["onepass_settle_cycles_per_sec"] == 950.0
        assert out["onepass_verdict"].startswith(
            "onepass_wins_1m16 (950.0 vs 900.0"
        )
        out = self._run(monkeypatch, onepass=720.0)
        assert out["onepass_verdict"].startswith(
            "xla_wins_onepass_1m16 (900.0 vs 720.0"
        )

    def test_onepass_infeasibility_is_data_not_a_crash(self, monkeypatch):
        out = self._run(
            monkeypatch, onepass_error=RuntimeError("Mosaic lowering")
        )
        assert "onepass_settle_cycles_per_sec" not in out
        assert out["onepass_settle"].startswith("infeasible: RuntimeError")
        assert "onepass_verdict" not in out
        assert out["verdict"]

    def test_bp_arm_adjudicates_the_sweep_routes(self, monkeypatch):
        # Round 19: the fourth bracket arm is its own apples-to-apples
        # pair (same workload, same depth), and the tuner's fused-route
        # verdict rides the JSON.
        out = self._run(monkeypatch, bp=(300.0, 480.0))
        assert out["bp_xla_sweeps_per_sec"] == 300.0
        assert out["bp_pallas_sweeps_per_sec"] == 480.0
        assert out["bp_verdict"].startswith("bp_kernel_wins (480.0 vs 300.0")
        assert out["bp_autotune_decision"]["default"] == "xla"
        out = self._run(monkeypatch, bp=(480.0, 300.0))
        assert out["bp_verdict"].startswith("xla_wins_bp (480.0 vs 300.0")

    def test_bp_infeasibility_is_data_not_a_crash(self, monkeypatch):
        out = self._run(
            monkeypatch, bp_error=RuntimeError("VMEM OOM: 24MB > 16MB")
        )
        assert out["bp_xla_sweeps_per_sec"] == 300.0
        assert "bp_pallas_sweeps_per_sec" not in out
        assert out["bp_sweep"].startswith("infeasible: RuntimeError")
        assert "bp_verdict" not in out
        assert out["verdict"]  # the settle bracket still renders


class TestOrchestrate:
    def _runner(self, canned, log):
        def run_leg(name, timeout=None, fast=False, cpu=False):
            log.append((name, cpu))
            return canned.get(name, _fail(f"no canned result for {name}"))

        return run_leg

    def test_healthy_path_runs_device_legs_in_priority_order(self, monkeypatch):
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "4800")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "10")
        canned = {"probe": _ok({"platform": "tpu", "devices": 1})}
        canned.update(_full_results())
        log = []
        payload, rc = bench.orchestrate(
            run_leg=self._runner(canned, log), sleeper=lambda s: None
        )
        assert rc == 0
        assert [name for name, _ in log] == ["probe"] + bench.DEVICE_LEG_ORDER
        assert "degraded" not in payload["extras"]

    def test_dead_backend_falls_back_to_cpu(self, monkeypatch):
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "4800")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "0")
        canned = {
            "headline_f32_cpu": _ok(3.5),
            "compact_cpu": _ok(5.0),
        }
        log = []
        payload, rc = bench.orchestrate(
            run_leg=self._runner(canned, log), sleeper=lambda s: None
        )
        assert rc == 0
        assert payload["value"] == 5.0
        # Device legs were never attempted; CPU legs ran with cpu=True.
        assert ("headline_f32_cpu", True) in log
        assert all(name == "probe" or name.endswith("_cpu") for name, _ in log)
        assert any(
            "tpu backend unavailable" in d
            for d in payload["extras"]["degraded"]
        )

    def test_global_budget_skips_late_legs(self, monkeypatch):
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "0")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "10")
        canned = {"probe": _ok({"platform": "tpu", "devices": 1})}
        log = []
        payload, rc = bench.orchestrate(
            run_leg=self._runner(canned, log), sleeper=lambda s: None
        )
        # Probe ran, every leg was skipped — still valid JSON out.
        assert [name for name, _ in log] == ["probe"]
        assert rc == 1
        for leg, status in payload["extras"]["harness"]["legs"].items():
            if not leg.endswith("_cpu"):
                assert "skipped: global budget" in status

    def test_device_headline_failure_appends_cpu_fallback(self, monkeypatch):
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "4800")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "10")
        canned = {"probe": _ok({"platform": "tpu", "devices": 1}),
                  "compact_cpu": _ok(5.0)}
        log = []
        payload, rc = bench.orchestrate(
            run_leg=self._runner(canned, log), sleeper=lambda s: None
        )
        assert rc == 0
        assert payload["value"] == 5.0
        assert any(
            "CPU-backend fallback appended" in d
            for d in payload["extras"]["degraded"]
        )


class TestStableTopologyLeg:
    """The delta-ingest A/B leg (``e2e_stream_stable_topology``) at --fast
    shapes: the steady-state re-settlement workload runs both with and
    without plan reuse and reports the hit/miss accounting the per-batch
    ``stats`` dicts carry. Bit-parity of the two paths is pinned by
    tests/test_overlap.py; this pins the LEG's contract (shape of the
    JSON, reuse engaging at all)."""

    def test_fast_leg_reports_reuse_accounting(self):
        result = bench.run_leg_inprocess(
            "e2e_stream_stable_topology", fast=True
        )
        fast_kwargs = bench.LEGS["e2e_stream_stable_topology"][2]
        batches = fast_kwargs["batches"]
        for side in ("no_reuse", "reuse"):
            for key in (
                "wall_s", "amortised_1m_cycles_per_sec", "ingest_wait_s",
                "settle_dispatch_s", "checkpoint_s", "plan_reuse_hits",
                "plan_reuse_misses",
            ):
                assert key in result[side], (side, key)
        # Rebuild path never reuses; the fast path misses only batch 0
        # (one topology for the whole stream).
        assert result["no_reuse"]["plan_reuse_hits"] == 0
        assert result["no_reuse"]["plan_reuse_misses"] == batches
        assert result["reuse"]["plan_reuse_hits"] == batches - 1
        assert result["reuse"]["plan_reuse_misses"] == 1
        assert result["reuse_speedup"] > 0
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_stream_stable_topology" in bench.LEGS
        assert "e2e_stream_stable_topology" in bench.DEVICE_LEG_ORDER


class TestDeltaDurabilityLeg:
    """The round-6 durability A/B leg (``e2e_stream_delta``) at --fast
    shapes: sync-full vs async-delta journal epochs on the stable-
    topology workload, plus the full-then-delta interchange export pair.
    Byte-parity of the two durability modes is pinned by
    tests/test_journal.py::TestAsyncEpochs; this pins the LEG's contract
    (JSON shape, the serial checkpoint win, the O(dirty) export, and the
    journal-wait attribution being visible)."""

    def test_fast_leg_reports_durability_ab(self):
        result = bench.run_leg_inprocess("e2e_stream_delta", fast=True)
        for side in ("sync_full", "async_delta"):
            for key in (
                "wall_s", "amortised_1m_cycles_per_sec", "checkpoint_s",
                "journal_fsync_s", "journal_async_wait_s",
                "interchange_full_s", "interchange_full_rows",
                "interchange_delta_s", "interchange_delta_rows", "phases",
            ):
                assert key in result[side], (side, key)
        sync_full, async_delta = result["sync_full"], result["async_delta"]
        # The headline: async-delta's serial in-loop checkpoint cost is
        # strictly below sync-full's (the fsync left the loop).
        assert async_delta["checkpoint_s"] < sync_full["checkpoint_s"]
        assert result["checkpoint_serial_speedup"] > 1
        # Sync mode fsyncs in-loop (the phase is visible); async mode's
        # in-loop share is the join wait instead.
        assert sync_full["journal_fsync_s"] > 0
        assert sync_full["journal_async_wait_s"] == 0
        assert "journal_async_wait" in async_delta["phases"]
        assert async_delta["journal_fsync_s"] == 0
        # Interchange: the re-export to the baseline file is O(dirty).
        for side in (sync_full, async_delta):
            assert side["interchange_full_rows"] == result["store_rows"]
            assert (
                0 < side["interchange_delta_rows"]
                < side["interchange_full_rows"]
            )
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_stream_delta" in bench.LEGS
        assert "e2e_stream_delta" in bench.DEVICE_LEG_ORDER


class TestIngestLeg:
    """The ISSUE-8 packer A/B/C (``e2e_ingest``) at --fast shapes:
    pure-Python twin stack vs native columnar grouping vs the zero-copy
    coded intake, each a full plan build onto a fresh store. Packer
    byte-parity is pinned by tests/test_fastpack.py; this pins the LEG
    contract (JSON shape, per-variant min-of-N bands, the
    ``signals_per_sec`` headline, and the 4M-signal scaling fields the
    acceptance bar quotes)."""

    def test_fast_leg_reports_packer_abc(self):
        result = bench.run_leg_inprocess("e2e_ingest", fast=True)
        for side in ("python", "native_columnar", "zero_copy"):
            for key in ("wall_s", "signals_per_sec", "wall_s_band",
                        "repeats"):
                assert key in result[side], (side, key)
            lo, hi = result[side]["wall_s_band"]
            assert lo <= hi
            assert result[side]["wall_s"] == lo
        assert result["signals"] > 0
        assert (
            result["signals_per_sec"]
            == result["native_columnar"]["signals_per_sec"]
        )
        assert result["native_speedup"] > 0
        assert (
            result["wall_s_per_4m_band"][0] == result["wall_s_per_4m_signals"]
        )
        assert isinstance(result["sub_second_4m"], bool)
        json.dumps(result)

    def test_fast_leg_reports_drift_act(self):
        """Act 3 (round 15): the drifting-topology packs over the
        epoch-persistent pair table — per-variant intern_s +
        delta_pairs, the full-mode floor beside each, the in-act
        delta==full row-assignment coda, and the scaled acceptance
        fields."""
        result = bench.run_leg_inprocess("e2e_ingest", fast=True)
        drift = result["drift"]
        for side in ("stable", "drift1", "drift25"):
            out = drift[side]
            for key in ("wall_s", "intern_s", "delta_pairs",
                        "matched_pairs", "intern_cold_s",
                        "intern_full_s", "delta_parity", "wall_s_band",
                        "repeats"):
                assert key in out, (side, key)
            assert out["delta_parity"] is True
            assert side in result["drift_intern_s_per_4m"]
        # The stable re-pack is the pair-fingerprint O(1) tier; the
        # drifted packs intern strictly fewer pairs than the batch.
        assert drift["stable"]["fingerprint_hit"] is True
        assert drift["stable"]["delta_pairs"] == 0
        assert 0 < drift["drift1"]["delta_pairs"] < result["signals"]
        assert (
            drift["drift1"]["delta_pairs"]
            < drift["drift25"]["delta_pairs"]
        )
        assert isinstance(result["sub_100ms_drift_4m"], bool)
        assert isinstance(result["sub_half_s_cold_4m"], bool)
        assert result["cold_intern_s_per_4m"] > 0
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_ingest" in bench.LEGS
        assert "e2e_ingest" in bench.DEVICE_LEG_ORDER


class TestRingMemoryLeg:
    """ISSUE-9's ``e2e_ring_memory`` at --fast shapes: the chunked vs
    unchunked tie-break A/B with its AOT ``memory_analysis()`` capture
    (``compiled_temp_bytes``/``arg_bytes``), the no-losing-trial fold,
    and the fused co-resident program's footprint next to the two
    programs it replaces. Bit-parity of the paths is pinned by
    tests/test_ring.py; this pins the LEG contract."""

    def test_fast_leg_reports_memory_ab(self):
        result = bench.run_leg_inprocess("e2e_ring_memory", fast=True)
        for side in ("unchunked", "chunked"):
            for key in ("wall_s", "markets_per_sec", "compiled_temp_bytes",
                        "arg_bytes", "wall_s_band", "repeats"):
                assert key in result[side], (side, key)
        # The diet: chunked temps strictly below unchunked, same args.
        assert (
            result["chunked"]["compiled_temp_bytes"]
            < result["unchunked"]["compiled_temp_bytes"]
        )
        assert (
            result["chunked"]["arg_bytes"]
            == result["unchunked"]["arg_bytes"]
        )
        assert result["temp_ratio"] > 1
        assert isinstance(result["no_losing_trial"], bool)
        fused = result["fused_coresident"]
        for key in ("fused_temp_bytes", "separate_cycle_temp_bytes",
                    "separate_tiebreak_temp_bytes", "fused_arg_bytes",
                    "separate_arg_bytes", "session_fused_dispatch_s"):
            assert key in fused, key
        # One program per chip: the fused program takes the block ONCE —
        # its argument footprint undercuts the two separate programs'.
        assert fused["fused_arg_bytes"] < fused["separate_arg_bytes"]
        # Round 14: the one-pass read capture rides the leg (the ≤0.5×
        # acceptance engages at the full co-resident shape, where the
        # kernel grid tiles the markets axis).
        onepass = result["onepass"]
        for key in ("multi_pass_read_bytes", "one_pass_read_bytes",
                    "read_ratio", "single_pass_halves_reads",
                    "grid_tiles"):
            assert key in onepass, key
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_ring_memory" in bench.LEGS
        assert "e2e_ring_memory" in bench.DEVICE_LEG_ORDER


class TestAnalyticsLeg:
    """ISSUE-10's ``e2e_analytics`` at --fast shapes: bands-only vs
    fused-resident vs +graph-sweep with the AOT co-residency argument
    capture. Bit-parity of the paths is pinned by tests/test_analytics.py;
    this pins the LEG contract."""

    def test_fast_leg_reports_coresidency_ab(self):
        result = bench.run_leg_inprocess("e2e_analytics", fast=True)
        for variant in ("bands_only", "fused_resident", "fused_graph"):
            for key in ("wall_s", "markets_per_sec", "compiled_temp_bytes",
                        "arg_bytes", "wall_s_band", "repeats"):
                assert key in result[variant], (variant, key)
        # The acceptance bar: dispatching bands inside the fused
        # resident program costs ≤ half the arg bytes of a separate
        # bands program after settle (measured marginal ≈ an outcomes
        # vector — the block rides once).
        assert result["fused_halves_band_args"] is True
        assert (
            result["bands_marginal_arg_bytes"]
            <= result["bands_separate_arg_bytes"] / 2
        )
        # Whole-pipeline reading recorded alongside (fused program vs
        # settle + separate bands programs).
        assert result["fused_arg_bytes"] < result["separate_arg_bytes"]
        assert 0 < result["coresident_arg_ratio"] < 1
        # The graph sweep's marginal arguments are the tiny neighbour
        # blocks, never a second copy of the state.
        assert (
            result["sweep_marginal_arg_bytes"]
            < result["fused_arg_bytes"] / 10
        )
        # Round 14: the one-pass read capture rides the leg (the ≤0.5×
        # acceptance engages at the full shape, where the kernel grid
        # tiles the markets axis — grid_tiles is recorded so the reader
        # can tell which regime the ratio came from).
        onepass = result["onepass"]
        for key in ("multi_pass_read_bytes", "one_pass_read_bytes",
                    "read_ratio", "single_pass_halves_reads",
                    "tile_markets", "grid_tiles"):
            assert key in onepass, key
        assert onepass["one_pass_read_bytes"] <= (
            onepass["multi_pass_read_bytes"] * 1.05
        )
        # The live co-resident session act ran (it is what records the
        # `analytics` phase span into the leg's breakdown).
        assert result["session_fused_dispatch_s"] > 0
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_analytics" in bench.LEGS
        assert "e2e_analytics" in bench.DEVICE_LEG_ORDER
        assert "e2e_analytics" in bench.compose(
            {}, [], None, 0.0
        )[0]["extras"]


class TestOnepassLeg:
    """ISSUE-12's ``e2e_onepass`` at --fast shapes: the multi-pass XLA
    fused program vs the one-pass settlement kernel on identical
    operands, with the per-settle HBM bytes-read capture off the AOT
    executables that ran. Bit-parity of the two routes is pinned by
    tests/test_pallas_settle.py; this pins the LEG contract."""

    def test_fast_leg_reports_read_ab(self):
        result = bench.run_leg_inprocess("e2e_onepass", fast=True)
        for side in ("multi_pass", "one_pass"):
            for key in ("wall_s", "markets_per_sec", "arg_bytes",
                        "compiled_temp_bytes", "hbm_read_bytes",
                        "wall_s_band", "repeats"):
                assert key in result[side], (side, key)
        # Identical operands → identical argument bytes; the read story
        # is in the temps. At the --fast one-tile shape the interpret
        # program degenerates to the XLA program (ratio ~1, recorded as
        # onepass_tiled=False); the ≤0.5× acceptance engages at the
        # full tiled shapes (onepass_tiled=True — the ring/analytics
        # legs' full captures measure 0.146/0.271).
        assert (
            result["one_pass"]["arg_bytes"]
            == result["multi_pass"]["arg_bytes"]
        )
        assert result["read_ratio"] > 0
        assert isinstance(result["single_pass_halves_reads"], bool)
        assert result["onepass_tiled"] == (result["grid_tiles"] > 1)
        assert result["grid_tiles"] * result["tile_markets"] >= 256
        # Round 20: the sources-sharded arm — a (2, 4) mesh needs 8
        # devices; under the test harness (8 forced CPU devices) it runs
        # live and records the per-shard-vs-unsharded read diet, and on
        # a smaller fleet it records the infeasibility as data. Either
        # way the arm is present and JSON-serialisable.
        sharded = result["sharded_sources"]
        if isinstance(sharded, str):
            assert sharded.startswith("infeasible")
        else:
            for side in ("multi_pass", "one_pass"):
                assert sharded[side]["per_shard_read_bytes"] > 0
            assert sharded["read_ratio"] > 0
            assert sharded["program_read_ratio"] > 0
            assert sharded["one_pass_read_bytes"] == (
                sharded["one_pass"]["per_shard_read_bytes"]
            )
            assert sharded["multi_pass_read_bytes"] == (
                sharded["unsharded_multi_pass"]["hbm_read_bytes"]
            )
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_onepass" in bench.LEGS
        assert "e2e_onepass" in bench.DEVICE_LEG_ORDER
        assert "e2e_onepass" in bench.compose(
            {}, [], None, 0.0
        )[0]["extras"]


class TestOverlapAdjudication:
    """The re-adjudicated e2e_overlap leg (VERDICT r5 #2): min-of-N
    alternating repeats, per-repeat load, a band, and a documented
    decision rule — no more single-capture sign flips."""

    def test_fast_leg_reports_repeats_band_and_decision(self):
        result = bench.run_leg_inprocess("e2e_overlap", fast=True)
        trials = bench.LEGS["e2e_overlap"][2]["trials"]
        assert len(result["repeats"]) == 2 * trials  # two flows per trial
        for repeat in result["repeats"]:
            assert repeat["flow"] in ("serial", "overlapped")
            assert "loadavg_1m" in repeat
            assert repeat["s"] > 0
        lo, hi = result["speedup_band"]
        assert lo <= hi
        assert result["decision"] in ("wins", "loses", "wash")
        assert "decision_rule" in result
        # min-of-N headline is consistent with the recorded repeats
        # (repeats are rounded for the record; compare loosely).
        serial = min(
            r["s"] for r in result["repeats"] if r["flow"] == "serial"
        )
        overlapped = min(
            r["s"] for r in result["repeats"] if r["flow"] == "overlapped"
        )
        assert result["speedup"] == pytest.approx(
            serial / overlapped, rel=0.02
        )
        json.dumps(result)


class TestObsOverheadLeg:
    """The obs A/B leg: the streamed service with observability off vs
    fully on (timeline + metrics + per-batch phases)."""

    def test_fast_leg_reports_ratio_and_phase_decomposition(self):
        from bayesian_consensus_engine_tpu.obs.timeline import PHASES

        result = bench.run_leg_inprocess("obs_overhead", fast=True)
        assert result["obs_off_wall_s"] > 0
        assert result["obs_on_wall_s"] > 0
        assert result["overhead_ratio"] == pytest.approx(
            result["obs_on_wall_s"] / result["obs_off_wall_s"], rel=0.02
        )
        # The round-9 tracing leg of the same contract: the traced run
        # recorded batch span chains and reports its own ratio (the ≤1%
        # assertion rides as trace_within_1pct, adjudicated at
        # production shapes like within_1pct).
        assert result["obs_trace_wall_s"] > 0
        assert result["trace_overhead_ratio"] == pytest.approx(
            result["obs_trace_wall_s"] / result["obs_on_wall_s"], rel=0.02
        )
        assert "trace_within_1pct" in result
        assert result["trace_events"] > 0
        # The enabled run decomposes into the canonical phase names.
        assert result["phases"]
        assert set(result["phases"]) <= set(PHASES)
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "obs_overhead" in bench.LEGS
        assert "obs_overhead" in bench.DEVICE_LEG_ORDER


@pytest.mark.slow
class TestEndToEndFast:
    def test_fast_cpu_run_produces_driver_json(self, monkeypatch):
        """Real subprocess legs, tiny shapes, CPU backend, trimmed leg set."""
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "280")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "60")
        monkeypatch.setattr(
            bench, "DEVICE_LEG_ORDER", ["headline_f32", "compact"]
        )
        payload, rc = bench.orchestrate(fast=True, cpu=True)
        assert rc == 0, payload
        assert payload["value"] > 0
        assert payload["extras"]["harness"]["legs"]["headline_f32"] == "ok"
        assert payload["extras"]["harness"]["probe"]["platform"] == "cpu"
        # A forced-CPU run must self-identify (review finding, round 4).
        assert "--cpu" in payload["metric"]
        json.dumps(payload)


class TestCircuitBreaker:
    def test_two_consecutive_timeouts_break_remaining_device_legs(
        self, monkeypatch
    ):
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "4800")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "10")
        canned = {
            "probe": {"ok": True, "value": {"platform": "tpu", "devices": 1}},
            "headline_f32": {"ok": False, "error": "timeout after 900s (killed)"},
            "compact": {"ok": False, "error": "timeout after 700s (killed)"},
            "headline_f32_cpu": {"ok": True, "value": 3.5},
            "compact_cpu": {"ok": True, "value": 5.0},
        }
        log = []

        def run_leg(name, timeout=None, fast=False, cpu=False):
            log.append(name)
            return canned.get(name, {"ok": False, "error": "unexpected"})

        payload, rc = bench.orchestrate(
            run_leg=run_leg, sleeper=lambda s: None
        )
        # Only the first two device legs actually ran; the rest were
        # circuit-broken without burning their timeouts, and the CPU
        # fallback still secured the headline.
        assert log == ["probe", "headline_f32", "compact",
                       "headline_f32_cpu", "compact_cpu",
                       "e2e_stream_cpu"]
        assert rc == 0
        assert payload["value"] == 5.0
        legs = payload["extras"]["harness"]["legs"]
        assert "circuit-broken" in legs["north_star_band"]
        assert any(
            "circuit-broken" in d for d in payload["extras"]["degraded"]
        )

    def test_success_resets_the_breaker(self, monkeypatch):
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "4800")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "10")
        canned = {"probe": {"ok": True, "value": {"platform": "tpu"}}}
        canned.update(_full_results())
        # One timeout between successes must not accumulate.
        canned["compact_fit"] = {"ok": False, "error": "timeout after 500s"}
        canned["stream_probe"] = {"ok": False, "error": "timeout after 400s"}
        log = []

        def run_leg(name, timeout=None, fast=False, cpu=False):
            log.append(name)
            return canned.get(name, {"ok": False, "error": "unexpected"})

        payload, rc = bench.orchestrate(
            run_leg=run_leg, sleeper=lambda s: None
        )
        assert rc == 0
        # dispatch_rtt succeeded between the two timeouts: breaker reset,
        # every device leg was attempted.
        assert log.count("pallas_ab") == 1
        assert "degraded" not in payload["extras"]

    def test_fast_crash_mentioning_timeout_does_not_trip(self, monkeypatch):
        """Only the harness's own kill message counts: a quick crash whose
        stderr tail mentions 'timeout' burned no budget."""
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "4800")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "10")
        canned = {"probe": _ok({"platform": "tpu"})}
        canned.update(_full_results())
        canned["headline_f32"] = _fail(
            "leg process died rc=1: RPC timeout watchdog fired"
        )
        canned["compact_fit"] = _fail(
            "leg process died rc=1: RPC timeout watchdog fired"
        )
        log = []

        def run_leg(name, timeout=None, fast=False, cpu=False):
            log.append(name)
            return canned.get(name, _fail("unexpected"))

        payload, rc = bench.orchestrate(
            run_leg=run_leg, sleeper=lambda s: None
        )
        assert rc == 0
        assert log.count("pallas_ab") == 1  # nothing was circuit-broken
        assert "degraded" not in payload["extras"]

    def test_trailing_timeouts_do_not_claim_a_trip(self, monkeypatch):
        """Timeouts on the LAST two legs reach the threshold after the
        loop: nothing was skipped, so degraded must not say it was."""
        monkeypatch.setenv("BCE_BENCH_BUDGET_S", "4800")
        monkeypatch.setenv("BCE_BENCH_PROBE_BUDGET_S", "10")
        canned = {"probe": _ok({"platform": "tpu"})}
        canned.update(_full_results())
        canned["pallas_ab"] = _fail("timeout after 1500s (killed)")
        canned["dryrun_multichip"] = _fail("timeout after 1500s (killed)")

        def run_leg(name, timeout=None, fast=False, cpu=False):
            return canned.get(name, _fail("unexpected"))

        payload, rc = bench.orchestrate(
            run_leg=run_leg, sleeper=lambda s: None
        )
        assert rc == 0
        assert "degraded" not in payload["extras"]


class TestResidentSessionLeg:
    """The round-7 persistent-session A/B leg (``e2e_stream_resident``)
    at --fast shapes: per-batch vs resident sharded streaming over the
    two-act (steady + drift) workload. Byte-parity of the two shapes is
    pinned by tests/test_overlap.py::TestResidentSessionStream; this
    pins the LEG's contract (JSON shape, the adopt accounting, the
    min-of-N band fields)."""

    def test_fast_leg_reports_resident_ab(self):
        result = bench.run_leg_inprocess("e2e_stream_resident", fast=True)
        for side in ("per_batch", "resident"):
            for key in (
                "wall_s", "wall_s_band", "repeats",
                "amortised_1m_cycles_per_sec",
                "dispatch_s_per_batch_act1", "dispatch_s_per_batch_act2",
                "adopt_s", "session_adopts", "session_modes",
                "plan_reuse_hits", "phases",
            ):
                assert key in result[side], (side, key)
        per_batch, resident = result["per_batch"], result["resident"]
        fast_kwargs = bench.LEGS["e2e_stream_resident"][2]
        batches = fast_kwargs["batches"]
        # The resident run holds ONE session: a start, hits served by
        # refresh, exactly one adopt at the act boundary.
        assert resident["session_modes"][0] == "start"
        assert resident["session_adopts"] == 1
        assert resident["session_modes"].count("relayout") == 1
        assert resident["adopt_s"] > 0
        # Legacy shape: no session bookkeeping at all.
        assert per_batch["session_modes"] == [None] * batches
        assert per_batch["session_adopts"] == 0
        # Scaling with rows CHANGED, not store size, is a production-
        # shape claim (at --fast sizes both windows are dominated by
        # per-dispatch noise); the smoke pins that both windows exist
        # and were measured.
        assert resident["dispatch_s_per_batch_act1"] > 0
        assert resident["dispatch_s_per_batch_act2"] > 0
        # Min-of-N band fields are coherent.
        lo, hi = resident["wall_s_band"]
        assert lo <= resident["wall_s"] <= hi
        assert result["resident_speedup"] > 0
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_stream_resident" in bench.LEGS
        assert "e2e_stream_resident" in bench.DEVICE_LEG_ORDER


class TestStreamLegBands:
    """VERDICT r5 #6: every e2e_stream* leg reports min-of-N bands and
    routes per-repeat records through the run ledger like e2e_overlap."""

    def test_stream_leg_records_repeats_to_ledger(self, tmp_path):
        from bayesian_consensus_engine_tpu.obs.ledger import (
            RunLedger,
            min_of_repeats,
            read_ledger,
        )

        ledger_path = tmp_path / "stream.jsonl"
        old = bench._LEDGER
        bench._LEDGER = RunLedger(ledger_path, backend="cpu")
        try:
            fast_kwargs = bench.LEGS["e2e_stream_stable_topology"][2]
            result = bench.bench_e2e_stream_stable_topology(
                **{**fast_kwargs, "trials": 2}
            )
        finally:
            bench._LEDGER.close()
            bench._LEDGER = old
        records = read_ledger(ledger_path)
        for variant in ("no_reuse", "reuse"):
            band = min_of_repeats(
                records, f"e2e_stream_stable_topology.{variant}"
            )
            assert band is not None and band["n"] == 2
            assert band["unit"] == "s"
            lo, hi = result[variant]["wall_s_band"]
            assert band["min"] == pytest.approx(lo, abs=0.01)
            assert band["max"] == pytest.approx(hi, abs=0.01)
        # Every repeat carried its pre-run loadavg for attribution.
        assert all(
            "loadavg_1m_before" in r["extras"] for r in records
        )

    def test_all_stream_legs_take_trials(self):
        import inspect

        for leg in ("e2e_stream", "e2e_stream_stable_topology",
                    "e2e_stream_delta", "e2e_stream_resident"):
            fn = bench.LEGS[leg][0]
            assert "trials" in inspect.signature(fn).parameters, leg


class TestNetServeLeg:
    """The round-17 front-door leg (``e2e_netserve``) at --fast shapes:
    mixed-class overload over the REAL socket transport. The wire byte
    parity, robustness, and shed determinism live in tests/test_net.py;
    this pins the LEG's contract (JSON shape, per-class goodput in the
    leg JSON and the ledger, the premium-holds/best-effort-sheds
    acceptance pair)."""

    def test_fast_leg_reports_per_class_goodput(self, tmp_path):
        from bayesian_consensus_engine_tpu.obs.ledger import (
            RunLedger,
            read_ledger,
            render,
            summarize,
        )

        ledger_path = tmp_path / "netserve.jsonl"
        old = bench._LEDGER
        bench._LEDGER = RunLedger(ledger_path, backend="cpu")
        try:
            result = bench.run_leg_inprocess("e2e_netserve", fast=True)
        finally:
            bench._LEDGER.close()
            bench._LEDGER = old
        for act in ("closed_loop", "overload_mixed"):
            side = result[act]
            for key in (
                "wall_s", "wall_s_band", "repeats", "served", "refused",
                "throughput_rps", "batches", "connections",
                "wire_errors", "p50_ms", "p99_ms", "premium",
                "besteffort", "ingest_wait_s", "intern_s",
            ):
                assert key in side, (act, key)
            for cls in ("premium", "besteffort"):
                assert set(side[cls]) == {
                    "offered", "counts", "goodput_within_slo",
                }
            assert side["wire_errors"] == 0
            # The load actually travelled the socket transport.
            assert side["connections"] >= 1
        # The acceptance pair: premium holds at its closed-loop band
        # while best-effort absorbed the overload as explicit policy.
        assert result["premium_holds"] is True
        assert result["besteffort_sheds"] is True
        assert result["besteffort_refused"] > 0
        overload = result["overload_mixed"]
        be_counts = overload["besteffort"]["counts"]
        assert be_counts["shed"] + be_counts["rejected"] > 0
        json.dumps(result)
        # Per-class accounting reached the ledger and folds into the
        # stats table's qos follow-up line.
        records = read_ledger(ledger_path)
        bands = summarize(records)
        overload_leg = "e2e_netserve.overload_mixed.latency"
        assert overload_leg in bands
        band = bands[overload_leg]
        assert sorted(band["qos"]) == ["besteffort", "premium"]
        assert band["qos"]["besteffort"]["slo_violations"] > 0
        assert band["qos"]["premium"]["goodput_within_slo"] is not None
        table = render(records)
        assert "premium: goodput" in table


class TestKillSoakLeg:
    """The round-13 failure-as-steady-state leg (``e2e_kill_soak``) at
    --fast shapes: a REAL worker SIGKILL mid-stream over the shared-
    nothing banded cluster, adjudicated on recovered goodput. The
    in-process recovery contracts are pinned by tests/test_cluster.py;
    this pins the LEG contract (JSON shape, the acceptance fields, the
    ledger recovery/goodput records the stats table reads)."""

    def test_fast_leg_reports_recovered_goodput(self, tmp_path):
        from bayesian_consensus_engine_tpu.obs.ledger import (
            RunLedger,
            read_ledger,
            summarize,
        )

        ledger_path = tmp_path / "kill.jsonl"
        old = bench._LEDGER
        bench._LEDGER = RunLedger(ledger_path, backend="cpu")
        try:
            result = bench.run_leg_inprocess("e2e_kill_soak", fast=True)
        finally:
            bench._LEDGER.close()
            bench._LEDGER = old
        for key in (
            "wall_s", "goodput_within_slo", "recovery_s", "adopt_s",
            "rows_adopted", "requests_offered", "slo",
            "resident_fallbacks_steady", "resident_fallbacks_survivor",
            "survivor_adopt_modes", "byte_equal_store",
            "byte_equal_sqlite", "survivor_journal_self_contained",
            "every_batch_durable", "soak_ok",
            "health_timeline", "health_transitions_ok",
            "healthz_polls", "healthz_poll_ok", "fleet",
        ):
            assert key in result, key
        # The acceptance bars: the kill was recovered (a dead-band batch
        # re-settled), every offered batch eventually made durable, the
        # stream NEVER fell back to teardown+rebuild — before or during
        # recovery — and the degraded-mesh byte contract held live.
        assert result["soak_ok"] is True
        assert result["recovery_s"] > 0
        assert result["every_batch_durable"] is True
        assert result["resident_fallbacks_steady"] == 0
        assert result["resident_fallbacks_survivor"] == 0
        assert result["byte_equal_store"] is True
        assert result["byte_equal_sqlite"] is True
        assert result["survivor_journal_self_contained"] is True
        # Goodput is the honest fraction: met / offered with the crash-
        # eaten traffic counting against.
        assert 0.0 < result["goodput_within_slo"] <= 1.0
        assert sum(result["slo"]["counts"].values()) == (
            result["requests_offered"]
        )
        # Recovery rode the resident adopt, not a rebuild.
        assert "relayout" in result["survivor_adopt_modes"]
        assert not any(
            m.startswith("rebuild") for m in result["survivor_adopt_modes"]
        )
        # Round 16: the recovery was observable WHILE it happened — the
        # survivor's /healthz timeline left healthy and returned to it
        # across the kill window, the endpoint answered over the wire,
        # and the fleet merge named the dead host as explicitly absent
        # (deterministically, any fold order).
        assert result["health_transitions_ok"] is True
        verdicts = {e["verdict"] for e in result["health_timeline"]}
        assert "healthy" in verdicts
        assert verdicts & {"degraded", "burning"}
        assert result["healthz_poll_ok"] is True
        assert result["healthz_polls"] > 0
        assert result["fleet"] is not None
        assert result["fleet"]["hosts_absent"] == [result["killed_host"]]
        assert result["fleet"]["deterministic"] is True
        json.dumps(result)
        # The ledger record carries the recovery story the stats table
        # renders: goodput (extras.slo) + the recovery_s fold.
        records = read_ledger(ledger_path)
        band = summarize(records)["e2e_kill_soak"]
        assert band["recovery_s"] == pytest.approx(
            result["recovery_s"], rel=1e-6
        )
        assert band["goodput_within_slo"] == pytest.approx(
            result["goodput_within_slo"], rel=1e-6
        )

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_kill_soak" in bench.LEGS
        assert "e2e_kill_soak" in bench.DEVICE_LEG_ORDER
        assert "e2e_kill_soak" in bench.compose(
            {}, [], None, 0.0
        )[0]["extras"]


class TestServeLeg:
    """The round-8 serving-latency leg (``e2e_serve``) at --fast shapes:
    closed-loop, open-loop (Poisson), and bounded-overload acts over the
    coalescing front end. Byte-parity of the serving path is pinned by
    tests/test_serve.py; this pins the LEG's contract (JSON shape, the
    latency quantiles, the bounded-overload claim, ledger latency
    records)."""

    def test_fast_leg_reports_latency_bands(self, tmp_path):
        from bayesian_consensus_engine_tpu.obs.ledger import read_ledger

        ledger_path = tmp_path / "serve.jsonl"
        old = bench._LEDGER
        from bayesian_consensus_engine_tpu.obs.ledger import RunLedger

        bench._LEDGER = RunLedger(ledger_path, backend="cpu")
        try:
            result = bench.run_leg_inprocess("e2e_serve", fast=True)
        finally:
            bench._LEDGER.close()
            bench._LEDGER = old
        for act in ("closed_loop", "open_loop", "overload"):
            side = result[act]
            for key in (
                "wall_s", "wall_s_band", "repeats", "requests_offered",
                "served", "rejected", "shed", "batches", "mean_batch_fill",
                "throughput_rps", "p50_ms", "p99_ms", "dispatch_p50_ms",
                "dispatch_p99_ms", "max_pending_seen",
                "goodput_within_slo", "slo", "hbm_bytes_in_use",
                "hbm_peak_bytes",
            ):
                assert key in side, (act, key)
            assert side["p50_ms"] is not None
            assert side["p99_ms"] >= side["p50_ms"]
            # SLO accounting covers the whole act: every offered request
            # ends in exactly one outcome bucket.
            assert 0.0 <= side["goodput_within_slo"] <= 1.0
            assert sum(side["slo"]["counts"].values()) == (
                side["requests_offered"]
            )
        # Unconstrained acts serve everything they were offered.
        assert result["closed_loop"]["served"] == (
            result["closed_loop"]["requests_offered"]
        )
        assert result["closed_loop"]["rejected"] == 0
        # The overload act actually overloaded — and stayed bounded.
        overload = result["overload"]
        assert overload["rejected"] > 0
        assert overload["max_pending_seen"] <= 64
        assert result["overload_bounded"] is True
        json.dumps(result)
        # Per-request distributions reached the ledger, and the stats
        # renderer folds them into p50/p99 columns.
        from bayesian_consensus_engine_tpu.obs.ledger import (
            render,
            summarize,
        )

        records = read_ledger(ledger_path)
        bands = summarize(records)
        latency_legs = [
            leg for leg in bands if leg.endswith(".latency")
        ]
        assert len(latency_legs) == 3
        for leg in latency_legs:
            assert bands[leg]["p50"] is not None
            assert bands[leg]["p99"] is not None
            # The SLO accounting reached the ledger and merged into the
            # goodput band (the overload act's headline metric).
            assert bands[leg]["goodput_within_slo"] is not None
            assert 0.0 <= bands[leg]["goodput_within_slo"] <= 1.0
        overload_leg = "e2e_serve.overload.latency"
        assert overload_leg in bands
        overload_records = [
            r for r in records if r.get("leg") == overload_leg
        ]
        assert all(
            "counts" in r["extras"]["slo"] for r in overload_records
        )
        header = render(records).splitlines()[0]
        assert "p99" in header and "goodput" in header

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_serve" in bench.LEGS
        assert "e2e_serve" in bench.DEVICE_LEG_ORDER
        assert "trials" in __import__("inspect").signature(
            bench.LEGS["e2e_serve"][0]
        ).parameters


class TestReplaySweepLeg:
    """The round-18 counterfactual-replay leg (``e2e_replay_sweep``) at
    --fast shapes: one vmapped K-lane sweep A/B'd against K sequential
    single-config replays over the same recorded trace. The replay
    semantics (byte contract, torn tails, determinism) are pinned by
    tests/test_replay.py; this pins the LEG contract — the JSON shape,
    the acceptance fields, and the ``replay_batches_per_s`` ledger
    extras record the stats table's replay column reads."""

    def test_fast_leg_reports_sweep_vs_sequential(self, tmp_path):
        from bayesian_consensus_engine_tpu.obs.ledger import (
            RunLedger,
            read_ledger,
            summarize,
        )

        ledger_path = tmp_path / "replay.jsonl"
        old = bench._LEDGER
        bench._LEDGER = RunLedger(ledger_path, backend="cpu")
        try:
            result = bench.run_leg_inprocess("e2e_replay_sweep", fast=True)
        finally:
            bench._LEDGER.close()
            bench._LEDGER = old
        for key in (
            "workload", "sweep", "sequential", "wall_s", "sweep_speedup",
            "speedup_ok", "replay_batches_per_s", "byte_equal_store",
            "run_twice_identical", "lane0_brier_mean",
        ):
            assert key in result, key
        # The acceptance bars the fast shape CAN hold: the rebuilt
        # lane-0 store byte-equals the live run and the sweep is
        # run-twice deterministic. The ≥6x speedup bar is only asserted
        # at the full 16-config shape (speedup_ok is None under 16).
        assert result["byte_equal_store"] is True
        assert result["run_twice_identical"] is True
        assert result["speedup_ok"] is None
        assert result["sweep"]["wall_s"] > 0
        assert result["sequential"]["wall_s"] > 0
        assert result["replay_batches_per_s"] > 0
        assert result["sweep"]["lane0_markets_settled"] == (
            result["sequential"]["lane0_markets_settled"]
        )
        json.dumps(result)
        # The ledger rows carry the throughput the stats table renders:
        # min-across-repeats of extras.replay_batches_per_s.
        records = read_ledger(ledger_path)
        band = summarize(records)["e2e_replay_sweep"]
        assert band["replay_batches_per_s"] == pytest.approx(
            result["replay_batches_per_s"], rel=1e-6
        )

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_replay_sweep" in bench.LEGS
        assert "e2e_replay_sweep" in bench.DEVICE_LEG_ORDER
        assert "e2e_replay_sweep" in bench.compose(
            {}, [], None, 0.0
        )[0]["extras"]


class TestInferLeg:
    """The round-18 inference leg (``e2e_infer``) at --fast shapes:
    fixed-depth vs adaptive moment sweeps over sparse and dense graphs
    through the fused settle+analytics program. The sweep semantics
    (bit parity, determinism, early-exit) are pinned by
    tests/test_infer.py; this pins the LEG contract — the JSON shape,
    the acceptance fields, and the ``bp_iters`` ledger extras record
    the stats table's iters column reads."""

    def test_fast_leg_reports_adaptive_vs_fixed(self, tmp_path):
        from bayesian_consensus_engine_tpu.obs.ledger import (
            RunLedger,
            read_ledger,
            summarize,
        )

        ledger_path = tmp_path / "infer.jsonl"
        old = bench._LEDGER
        bench._LEDGER = RunLedger(ledger_path, backend="cpu")
        try:
            result = bench.run_leg_inprocess("e2e_infer", fast=True)
        finally:
            bench._LEDGER.close()
            bench._LEDGER = old
        for key in (
            "workload", "fixed_sparse", "adaptive_sparse", "fixed_dense",
            "adaptive_dense", "wall_s", "bp_iters",
            "adaptive_saves_sweeps", "sparse_fewer_sweeps",
            "adaptive_matches_fixed", "xla_sweep", "pallas_sweep",
            "sweep_read_capture",
        ):
            assert key in result, key
        # The acceptance bars hold at every shape: the sparse graph
        # settles under the static bound and in fewer sweeps than the
        # dense one, at outputs matching the fixed-depth sweep; the
        # fixed variants always pay the full depth.
        assert result["adaptive_saves_sweeps"] is True
        assert result["sparse_fewer_sweeps"] is True
        assert result["adaptive_matches_fixed"] is True
        assert result["bp_iters"] == (
            result["adaptive_sparse"]["iters_run"]
        )
        assert result["fixed_sparse"]["iters_run"] > result["bp_iters"]
        assert result["adaptive_sparse"]["wall_s"] > 0
        # Round 19: the kernel arm races the standalone dense sweep
        # both ways off the same AOT executables and captures their
        # bytes-read floors; the ratio fields are the shared one-pass
        # capture shape plus this leg's own ≤0.6 bar.
        capture = result["sweep_read_capture"]
        assert capture["multi_pass_read_bytes"] > 0
        assert capture["one_pass_read_bytes"] > 0
        assert capture["read_ratio"] > 0
        assert capture["sweep_read_leq_0p6"] == (
            capture["read_ratio"] <= 0.6
        )
        for name in ("xla_sweep", "pallas_sweep"):
            assert result[name]["wall_s"] > 0
            assert result[name]["sweeps_per_sec"] > 0
            assert result[name]["hbm_read_bytes"] > 0
        json.dumps(result)
        # The ledger rows carry the trip count the stats table renders:
        # min-across-repeats of extras.bp_iters — and, round 19, the
        # kernel sweep's bytes-read floor as the leg's hbm_read column.
        records = read_ledger(ledger_path)
        band = summarize(records)["e2e_infer"]
        assert band["bp_iters"] == result["bp_iters"]
        assert band["hbm_read_bytes"] == (
            result["pallas_sweep"]["hbm_read_bytes"]
        )

    def test_leg_is_registered_for_device_runs(self):
        assert "e2e_infer" in bench.LEGS
        assert "e2e_infer" in bench.DEVICE_LEG_ORDER
        assert "e2e_infer" in bench.compose({}, [], None, 0.0)[0]["extras"]


class TestDryrunMultichipLeg:
    """The scaled virtual-mesh leg (VERDICT r5 #3): the north-star band
    over 8 virtual devices with a REAL psum epilogue, parity-asserted
    inside the leg itself. The full 8 × 16k × 10k shape runs in
    tests/test_multichip_scale.py (slow) and as the production leg; the
    --fast shape smoke-tests the same code path here."""

    def test_fast_leg_runs_scaled_band_with_real_psum(self):
        result = bench.run_leg_inprocess("dryrun_multichip", fast=True)
        assert result["devices"] == 8
        assert result["mesh_shape"] == [4, 2]
        assert result["psum_replica_groups"].startswith("real")
        assert result["step_ms"] > 0
        assert result["parity"].startswith("allclose")
        assert result["ring_tiebreak_ms"] > 0
        json.dumps(result)

    def test_leg_is_registered_for_device_runs(self):
        assert "dryrun_multichip" in bench.LEGS
        assert "dryrun_multichip" in bench.DEVICE_LEG_ORDER
